"""Evidence pool + store (ref: evidence/pool.go, store.go).

Holds verified-but-uncommitted DuplicateVoteEvidence for inclusion in blocks;
marks committed; ages out beyond ConsensusParams.Evidence.MaxAge.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.db.kv import DB
from tendermint_tpu.state.services import EvidencePool as EvidencePoolIface
from tendermint_tpu.state.validation import verify_evidence
from tendermint_tpu.types import DuplicateVoteEvidence

_PENDING = b"evp:"
_COMMITTED = b"evc:"


def _key(ev: DuplicateVoteEvidence) -> bytes:
    return b"%016d:%s" % (ev.height, ev.hash().hex().encode())


class EvidenceStore:
    """Priority (pending) + lookup (committed) records (ref store.go)."""

    def __init__(self, db: DB):
        self._db = db

    def add_new_evidence(self, ev: DuplicateVoteEvidence) -> bool:
        k = _key(ev)
        if self._db.has(_PENDING + k) or self._db.has(_COMMITTED + k):
            return False
        self._db.set_sync(_PENDING + k, ev.marshal())
        return True

    def pending_evidence(self, max_count: int = -1) -> List[DuplicateVoteEvidence]:
        out = []
        for k, v in self._db.iterator(_PENDING, _PENDING + b"\xff"):
            out.append(DuplicateVoteEvidence.unmarshal(v))
            if 0 <= max_count <= len(out):
                break
        return out

    def mark_evidence_as_committed(self, ev: DuplicateVoteEvidence) -> None:
        k = _key(ev)
        self._db.delete(_PENDING + k)
        self._db.set(_COMMITTED + k, b"1")

    def is_committed(self, ev: DuplicateVoteEvidence) -> bool:
        return self._db.has(_COMMITTED + _key(ev))

    def prune_before(self, height: int) -> None:
        end = _PENDING + b"%016d" % height
        for k, _ in list(self._db.iterator(_PENDING, end)):
            self._db.delete(k)


class EvidencePool(EvidencePoolIface):
    def __init__(self, state_db: DB, evidence_db: DB, state, logger=None):
        self._state_db = state_db
        self.store = EvidenceStore(evidence_db)
        self._state = state
        self._mtx = threading.Lock()
        self.evidence_list = CList()  # for the gossip reactor
        import logging

        self.logger = logger or logging.getLogger("tm.evidence")
        for ev in self.store.pending_evidence():
            self.evidence_list.push_back(ev)

    @property
    def state(self):
        with self._mtx:
            return self._state

    def pending_evidence(self, max_bytes: int = -1) -> List[DuplicateVoteEvidence]:
        if max_bytes < 0:
            return self.store.pending_evidence()
        # crude per-item budget mirroring MaxEvidenceBytes accounting
        max_count = max(0, max_bytes // 512)
        return self.store.pending_evidence(max_count)

    def add_evidence(self, ev: DuplicateVoteEvidence) -> None:
        """Verify against historical validators, persist, enqueue for gossip
        (pool.go:91)."""
        with self._mtx:
            state = self._state
        verify_evidence(self._state_db, state, ev)
        if not self.store.add_new_evidence(ev):
            return  # duplicate
        self.logger.info("verified new evidence height=%d addr=%s",
                         ev.height, ev.address.hex())
        self.evidence_list.push_back(ev)

    def update(self, block, state) -> None:
        """Mark block evidence committed; age out old (pool.go Update)."""
        with self._mtx:
            self._state = state
        for ev in block.evidence.evidence:
            self.store.mark_evidence_as_committed(ev)
        max_age = state.consensus_params.evidence.max_age
        if block.height > max_age:
            self.store.prune_before(block.height - max_age)
        # committed or aged-out evidence leaves the gossip list on EVERY
        # update (ref pool.go removeEvidence — not gated on age)
        el = self.evidence_list.front()
        while el is not None:
            nxt = el.next()
            if (
                el.value.height <= block.height - max_age
                or self.store.is_committed(el.value)
            ):
                self.evidence_list.remove(el)
            el = nxt

    def is_committed(self, ev: DuplicateVoteEvidence) -> bool:
        return self.store.is_committed(ev)
