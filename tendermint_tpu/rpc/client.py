"""RPC client library (ref: rpc/client/ — HTTP client + event/WS client,
used by the reference's tools and integration tests).

``HTTPClient`` — JSON-RPC over HTTP, one method per core route.
``WSEventClient`` — the /websocket endpoint: subscribe to event-bus queries
and iterate events (client-side RFC 6455 with masked frames).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import queue
import socket
import struct
import threading
from typing import Any, Dict, Iterator, List, Optional

from tendermint_tpu.rpc.websocket import (
    MessageReader,
    OP_CLOSE,
    OP_PING,
    OP_PONG,
    OP_TEXT,
    accept_key,
    make_frame,
)


class RPCClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


def _parse_laddr(addr: str) -> tuple:
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://"):]
    if addr.startswith("http://"):
        addr = addr[len("http://"):]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class HTTPClient:
    """rpc/client/httpclient.go — every method returns the route's result
    dict or raises RPCClientError."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self.host, self.port = _parse_laddr(addr)
        self.timeout = timeout

    def call(self, method: str, **params) -> Any:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            body = json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
            )
            conn.request(
                "POST", "/", body=body, headers={"Content-Type": "application/json"}
            )
            resp = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        if "error" in resp and resp["error"]:
            err = resp["error"]
            raise RPCClientError(err.get("code", -1), err.get("message", ""))
        return resp.get("result")

    # -- info ---------------------------------------------------------------
    def status(self) -> dict:
        return self.call("status")

    def health(self) -> dict:
        return self.call("health")

    def genesis(self) -> dict:
        return self.call("genesis")

    def net_info(self) -> dict:
        return self.call("net_info")

    def block(self, height: Optional[int] = None) -> dict:
        return self.call("block", **({"height": height} if height else {}))

    def commit(self, height: Optional[int] = None) -> dict:
        return self.call("commit", **({"height": height} if height else {}))

    def validators(self, height: Optional[int] = None) -> dict:
        return self.call("validators", **({"height": height} if height else {}))

    def dump_consensus_state(self) -> dict:
        return self.call("dump_consensus_state")

    def consensus_state(self) -> dict:
        return self.call("consensus_state")

    def consensus_params(self, height: Optional[int] = None) -> dict:
        return self.call(
            "consensus_params", **({"height": height} if height else {})
        )

    def blockchain(self, min_height: int = 0, max_height: int = 0) -> dict:
        return self.call("blockchain", minHeight=min_height, maxHeight=max_height)

    def block_results(self, height: Optional[int] = None) -> dict:
        return self.call("block_results", **({"height": height} if height else {}))

    def dial_seeds(self, seeds: list) -> dict:
        return self.call("dial_seeds", seeds=seeds)

    def dial_peers(self, peers: list, persistent: bool = False) -> dict:
        return self.call("dial_peers", peers=peers, persistent=persistent)

    def unsafe_flush_mempool(self) -> dict:
        return self.call("unsafe_flush_mempool")

    # -- debug dumps (unsafe-gated server side) -----------------------------
    def dump_trace(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_trace", **({"limit": limit} if limit is not None else {})
        )

    def trace_reset(self, enable=None, capacity: Optional[int] = None) -> dict:
        params = {}
        if enable is not None:
            params["enable"] = enable
        if capacity is not None:
            params["capacity"] = capacity
        return self.call("trace_reset", **params)

    def dump_profile(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_profile", **({"limit": limit} if limit is not None else {})
        )

    def dump_flight(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_flight", **({"limit": limit} if limit is not None else {})
        )

    def flight_reset(self, enable=None, capacity: Optional[int] = None) -> dict:
        params = {}
        if enable is not None:
            params["enable"] = enable
        if capacity is not None:
            params["capacity"] = capacity
        return self.call("flight_reset", **params)

    def dump_critpath(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_critpath", **({"limit": limit} if limit is not None else {})
        )

    def critpath_reset(self, capacity: Optional[int] = None) -> dict:
        return self.call(
            "critpath_reset",
            **({"capacity": capacity} if capacity is not None else {}),
        )

    def dump_quorum(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_quorum", **({"limit": limit} if limit is not None else {})
        )

    def quorum_reset(self, capacity: Optional[int] = None) -> dict:
        return self.call(
            "quorum_reset",
            **({"capacity": capacity} if capacity is not None else {}),
        )

    def dump_telemetry(self, limit: Optional[int] = None) -> dict:
        return self.call(
            "dump_telemetry", **({"limit": limit} if limit is not None else {})
        )

    def telemetry_reset(self, capacity: Optional[int] = None) -> dict:
        return self.call(
            "telemetry_reset",
            **({"capacity": capacity} if capacity is not None else {}),
        )

    def dump_device_health(self) -> dict:
        return self.call("dump_device_health")

    def device_breaker_reset(self, reprobe: Optional[bool] = None) -> dict:
        return self.call(
            "device_breaker_reset",
            **({"reprobe": reprobe} if reprobe is not None else {}),
        )

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        return self.call("unconfirmed_txs", limit=limit)

    def num_unconfirmed_txs(self) -> dict:
        return self.call("num_unconfirmed_txs")

    # -- txs ----------------------------------------------------------------
    def broadcast_tx_async(self, tx: bytes) -> dict:
        return self.call("broadcast_tx_async", tx=base64.b64encode(tx).decode())

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        return self.call("broadcast_tx_sync", tx=base64.b64encode(tx).decode())

    def broadcast_tx_commit(self, tx: bytes) -> dict:
        return self.call("broadcast_tx_commit", tx=base64.b64encode(tx).decode())

    def tx(self, tx_hash: str, prove: bool = False) -> dict:
        return self.call("tx", hash=tx_hash, prove=prove)

    def tx_search(self, query: str, page: int = 1, per_page: int = 30) -> dict:
        return self.call("tx_search", query=query, page=page, per_page=per_page)

    # -- abci ---------------------------------------------------------------
    def abci_info(self) -> dict:
        return self.call("abci_info")

    def abci_query(self, path: str = "", data: bytes = b"", height: int = 0) -> dict:
        return self.call(
            "abci_query", path=path, data=data.hex(), height=height
        )

    def metrics_text(self) -> str:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            conn.request("GET", "/metrics")
            return conn.getresponse().read().decode()
        finally:
            conn.close()


class WSEventClient:
    """Client side of the /websocket subscribe endpoint."""

    def __init__(self, addr: str, timeout: float = 10.0):
        host, port = _parse_laddr(addr)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (
            f"GET /websocket HTTP/1.1\r\nHost: {host}:{port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
        )
        self._sock.sendall(req.encode())
        self._rfile = self._sock.makefile("rb")
        status = self._rfile.readline()
        if b"101" not in status:
            raise ConnectionError(f"websocket upgrade refused: {status!r}")
        while self._rfile.readline() not in (b"\r\n", b""):
            pass
        self._reader = MessageReader(self._rfile)
        self._next_id = 0
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._acks: "queue.Queue[dict]" = queue.Queue()
        self._closed = threading.Event()
        threading.Thread(target=self._recv_loop, name="ws-client-recv", daemon=True).start()

    # -- frame IO -------------------------------------------------------------
    def _send_json(self, obj) -> None:
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        n = len(payload)
        head = bytes([0x80 | OP_TEXT])
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 1 << 16:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self._sock.sendall(head + mask + masked)

    def _recv_loop(self) -> None:
        try:
            while not self._closed.is_set():
                msg = self._reader.next()
                if msg is None:
                    return
                opcode, payload = msg
                if opcode == OP_PING:
                    self._sock.sendall(make_frame(OP_PONG, payload))
                    continue
                if opcode == OP_CLOSE:
                    return
                if opcode != OP_TEXT:
                    continue
                obj = json.loads(payload)
                if isinstance(obj.get("id"), str) and obj["id"].endswith("#event"):
                    self._events.put(obj)
                else:
                    self._acks.put(obj)
        except OSError:
            pass
        finally:
            self._closed.set()

    # -- API -------------------------------------------------------------------
    def subscribe(self, query: str, timeout: float = 10.0) -> None:
        self._next_id += 1
        self._send_json(
            {"jsonrpc": "2.0", "id": self._next_id, "method": "subscribe",
             "params": {"query": query}}
        )
        ack = self._acks.get(timeout=timeout)
        if ack.get("error"):
            raise RPCClientError(
                ack["error"].get("code", -1), ack["error"].get("message", "")
            )

    def unsubscribe(self, query: str, timeout: float = 10.0) -> None:
        self._next_id += 1
        self._send_json(
            {"jsonrpc": "2.0", "id": self._next_id, "method": "unsubscribe",
             "params": {"query": query}}
        )
        self._acks.get(timeout=timeout)

    def next_event(self, timeout: Optional[float] = None) -> dict:
        """The next pushed event's result {query, data:{type, value, tags}}."""
        return self._events.get(timeout=timeout)["result"]

    def events(self, timeout: float = 1.0) -> Iterator[dict]:
        while not self._closed.is_set():
            try:
                yield self.next_event(timeout=timeout)
            except queue.Empty:
                return

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
