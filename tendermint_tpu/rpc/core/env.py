"""RPC core handlers — read node state, broadcast txs
(ref: rpc/core/ routes at rpc/core/routes.go:9-41; wiring node/node.go:618).

Every handler returns JSON-able dicts.  Errors raise RPCError(code, message).
"""

from __future__ import annotations

import base64
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.types.events import EVENT_TX, TX_HASH_KEY, query_for_event


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class RPCEnv:
    """The handler table; method names match the reference routes."""

    def __init__(self, node):
        self.node = node

    # info ------------------------------------------------------------------
    def health(self) -> dict:
        return {}

    def status(self) -> dict:
        return self.node.status()

    def genesis(self) -> dict:
        import json

        return {"genesis": json.loads(self.node.genesis_doc.to_json())}

    def block(self, height: Optional[int] = None) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no block for height {h}")
        block = bs.load_block(h)
        return {
            "block_meta": {
                "block_id": {
                    "hash": meta.block_id.hash.hex().upper(),
                    "parts": {
                        "total": meta.block_id.parts_header.total,
                        "hash": meta.block_id.parts_header.hash.hex().upper(),
                    },
                },
                "header": _header_json(meta.header),
            },
            "block": {
                "header": _header_json(block.header),
                "data": {"txs": [_b64(bytes(t)) for t in block.data.txs]},
                "last_commit": {
                    "block_id": {"hash": block.last_commit.block_id.hash.hex().upper()},
                    "precommits_count": sum(
                        1 for pc in block.last_commit.precommits if pc
                    ),
                },
            },
        }

    def commit(self, height: Optional[int] = None) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no commit for height {h}")
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        return {
            "signed_header": {
                "header": _header_json(meta.header),
                "commit": {
                    "block_id": {"hash": commit.block_id.hash.hex().upper()},
                    "precommits_count": sum(1 for pc in commit.precommits if pc),
                },
            },
            "canonical": bs.load_block_commit(h) is not None,
        }

    def lite_full_commit(self, height: Optional[int] = None) -> dict:
        """Codec-exact light-client material: header+commit+valsets as b64
        marshal bytes (what lite/proxy's RPCProvider consumes; JSON field
        re-serialization could never be hash-exact)."""
        from tendermint_tpu.encoding.codec import Writer
        from tendermint_tpu.state import store as sm_store

        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        if meta is None or commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        try:
            vals = sm_store.load_validators(self.node.state_db, h)
            next_vals = sm_store.load_validators(self.node.state_db, h + 1)
        except Exception as e:
            raise RPCError(-32603, f"no validators for {h}: {e}")
        w = Writer()
        meta.header.encode(w)
        return {
            "height": h,
            "header": _b64(w.build()),
            "commit": _b64(commit.marshal()),
            "validators": _b64(vals.marshal()),
            "next_validators": _b64(next_vals.marshal()),
        }

    def validators(self, height: Optional[int] = None) -> dict:
        from tendermint_tpu.state import store as sm_store

        h = int(height) if height else self.node.block_store.height() + 1
        vals = sm_store.load_validators(self.node.state_db, h)
        return {
            "block_height": h,
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": v.pub_key.to_json_obj(),
                    "voting_power": v.voting_power,
                    "accum": v.accum,
                }
                for v in vals.validators
            ],
        }

    def dump_consensus_state(self) -> dict:
        rs = self.node.consensus_state.get_round_state()
        return {
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": rs.step.name,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
                "proposal": str(rs.proposal) if rs.proposal else None,
            }
        }

    def net_info(self) -> dict:
        sw = getattr(self.node, "switch", None)
        peers = []
        if sw is not None:
            for p in sw.peers.list():
                ni = p.node_info
                peers.append(
                    {
                        "node_info": {
                            "id": ni.id,
                            "listen_addr": ni.listen_addr,
                            "network": ni.network,
                            "moniker": ni.moniker,
                        },
                        "is_outbound": p.outbound,
                        "remote_ip": p.socket_addr.host if p.socket_addr else "",
                    }
                )
        return {"listening": sw is not None, "peers": peers, "n_peers": len(peers)}

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.node.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": self.node.mempool.size(),
            "txs": [_b64(t) for t in txs],
        }

    def num_unconfirmed_txs(self) -> dict:
        return {"n_txs": self.node.mempool.size()}

    # tx --------------------------------------------------------------------
    def broadcast_tx_async(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        self.node.mempool.check_tx(raw)
        import hashlib

        return {"code": 0, "data": "", "log": "", "hash": hashlib.sha256(raw).hexdigest().upper()}

    def broadcast_tx_sync(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        done: "queue.Queue" = queue.Queue()
        self.node.mempool.check_tx(raw, callback=done.put)
        try:
            res = done.get(timeout=10)
        except queue.Empty:
            raise RPCError(-32603, "CheckTx timed out")
        import hashlib

        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "hash": hashlib.sha256(raw).hexdigest().upper(),
        }

    def broadcast_tx_commit(self, tx: str) -> dict:
        """Subscribe to the tx event, CheckTx, wait for commit
        (rpc/core/mempool.go:152)."""
        raw = base64.b64decode(tx)
        import hashlib

        tx_hash = hashlib.sha256(raw).hexdigest().upper()
        bus = self.node.event_bus
        sub_id = f"broadcast-{tx_hash}-{time.monotonic_ns()}"
        sub = bus.subscribe(
            sub_id, f"{query_for_event(EVENT_TX)} AND {TX_HASH_KEY} = '{tx_hash}'"
        )
        try:
            done: "queue.Queue" = queue.Queue()
            self.node.mempool.check_tx(raw, callback=done.put)
            try:
                check_res = done.get(timeout=10)
            except queue.Empty:
                raise RPCError(-32603, "CheckTx timed out")
            if check_res.code != abci.CODE_TYPE_OK:
                return {
                    "check_tx": _tx_res_json(check_res),
                    "deliver_tx": {},
                    "hash": tx_hash,
                    "height": 0,
                }
            try:
                msg = sub.get(timeout=30)
            except queue.Empty:
                raise RPCError(-32603, "timed out waiting for tx to be committed")
            ev = msg.data
            return {
                "check_tx": _tx_res_json(check_res),
                "deliver_tx": _tx_res_json(ev.result),
                "hash": tx_hash,
                "height": ev.height,
            }
        finally:
            try:
                bus.unsubscribe_all(sub_id)
            except Exception:
                pass

    def tx(self, hash: str, prove: bool = False) -> dict:
        raw_hash = bytes.fromhex(hash)
        r = self.node.tx_indexer.get(raw_hash)
        if r is None:
            raise RPCError(-32603, f"tx ({hash}) not found")
        return {
            "hash": hash.upper(),
            "height": r.height,
            "index": r.index,
            "tx_result": _tx_res_json(r.result),
            "tx": _b64(r.tx),
        }

    def tx_search(self, query: str, prove: bool = False, page: int = 1,
                  per_page: int = 30) -> dict:
        results = self.node.tx_indexer.search(query)
        page, per_page = int(page), int(per_page)
        start = (page - 1) * per_page
        sel = results[start : start + per_page]
        return {
            "txs": [
                {
                    "hash": r.hash().hex().upper(),
                    "height": r.height,
                    "index": r.index,
                    "tx_result": _tx_res_json(r.result),
                    "tx": _b64(r.tx),
                }
                for r in sel
            ],
            "total_count": len(results),
        }

    # abci ------------------------------------------------------------------
    def abci_query(self, path: str = "", data: str = "", height: int = 0,
                   prove: bool = False) -> dict:
        res = self.node.proxy_app.query.query_sync(
            abci.RequestQuery(
                data=bytes.fromhex(data) if data else b"",
                path=path,
                height=int(height),
                prove=bool(prove),
            )
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "key": _b64(res.key),
                "value": _b64(res.value),
                "height": res.height,
            }
        }

    # debug / profiling ------------------------------------------------------
    def _require_unsafe(self) -> None:
        """unsafe_* routes are operator tools, gated on config.rpc.unsafe
        (the reference registers its unsafe routes conditionally,
        rpc/core/routes.go:43)."""
        if not self.node.config.rpc.unsafe:
            raise RPCError(-32601, "unsafe RPC routes are disabled (rpc.unsafe)")

    def unsafe_dump_threads(self) -> dict:
        """Stack dump of every live thread — the pprof-goroutine analogue
        (ref: pprof server at node/node.go:474-479)."""
        self._require_unsafe()
        import sys as _sys
        import traceback

        frames = _sys._current_frames()
        out = {}
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            out[f"{t.name} (daemon={t.daemon})"] = (
                traceback.format_stack(frame) if frame is not None else []
            )
        return {"n_threads": len(out), "stacks": out}

    def unsafe_start_profiler(self, dir: str = "/tmp/tm_tpu_trace") -> dict:
        """Start a JAX profiler trace (xprof-compatible; SURVEY §5 —
        device-time attribution for the batched verify dispatches)."""
        self._require_unsafe()
        import jax

        jax.profiler.start_trace(dir)
        return {"tracing": True, "dir": dir}

    def unsafe_stop_profiler(self) -> dict:
        self._require_unsafe()
        import jax

        jax.profiler.stop_trace()
        return {"tracing": False}

    def abci_info(self) -> dict:
        res = self.node.proxy_app.query.info_sync(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "last_block_height": res.last_block_height,
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }


def _header_json(h) -> dict:
    return {
        "chain_id": h.chain_id,
        "height": h.height,
        "time_ns": h.time_ns,
        "num_txs": h.num_txs,
        "total_txs": h.total_txs,
        "last_block_id": {"hash": h.last_block_id.hash.hex().upper()},
        "app_hash": h.app_hash.hex().upper(),
        "data_hash": h.data_hash.hex().upper(),
        "validators_hash": h.validators_hash.hex().upper(),
        "proposer_address": h.proposer_address.hex().upper(),
    }


def _tx_res_json(res) -> dict:
    if res is None:
        return {}
    return {
        "code": res.code,
        "data": _b64(res.data),
        "log": res.log,
        "gas_wanted": res.gas_wanted,
        "gas_used": res.gas_used,
        "tags": [
            {"key": _b64(kv.key), "value": _b64(kv.value)} for kv in res.tags
        ],
    }
