"""RPC core handlers — read node state, broadcast txs
(ref: rpc/core/ routes at rpc/core/routes.go:9-41; wiring node/node.go:618).

Every handler returns JSON-able dicts.  Errors raise RPCError(code, message).
"""

from __future__ import annotations

import base64
import contextlib
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.mempool.mempool import MempoolFullError, TxInCacheError
from tendermint_tpu.types.events import EVENT_TX, TX_HASH_KEY, query_for_event


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


# broadcast_tx_* shed under overload: explicit, immediately distinguishable
# from a generic internal error so clients can back off instead of retrying
ERR_MEMPOOL_OVERLOADED = -32001


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class RPCEnv:
    """The handler table; method names match the reference routes."""

    def __init__(self, node):
        self.node = node
        self._broadcast_mtx = threading.Lock()
        self._broadcast_in_flight = 0
        self.broadcast_shed: Dict[str, int] = {}
        self._feed_mtx = threading.Lock()
        self._feed = None  # lazy shared planner LaneFeed (commit verify)

    # load-shedding: broadcast_tx_* share one bounded in-flight budget; at
    # the cap new submissions fail fast with a mempool-overloaded error
    # instead of queueing unboundedly behind CheckTx / commit waits
    @contextlib.contextmanager
    def _broadcast_slot(self, route: str):
        cfg = getattr(self.node, "config", None)
        budget = getattr(cfg.rpc, "broadcast_max_in_flight", 0) if cfg else 0
        with self._broadcast_mtx:
            if budget > 0 and self._broadcast_in_flight >= budget:
                self.broadcast_shed[route] = self.broadcast_shed.get(route, 0) + 1
                m = getattr(self.node, "metrics", None)
                if m is not None:
                    m.mempool_qos_shed_total.add(1.0, (route,))
                raise RPCError(
                    ERR_MEMPOOL_OVERLOADED,
                    f"mempool overloaded: {self._broadcast_in_flight} "
                    f"broadcast_tx requests in flight (budget {budget})",
                )
            self._broadcast_in_flight += 1
        try:
            yield
        finally:
            with self._broadcast_mtx:
                self._broadcast_in_flight -= 1

    def _check_tx_guarded(self, raw: bytes, callback=None) -> None:
        """check_tx with mempool admission errors mapped to explicit RPC
        errors (a full pool is overload, a cache hit is a client dup)."""
        try:
            self.node.mempool.check_tx(raw, callback=callback)
        except MempoolFullError as e:
            raise RPCError(ERR_MEMPOOL_OVERLOADED, f"mempool overloaded: {e}")
        except TxInCacheError as e:
            raise RPCError(-32603, str(e))

    # info ------------------------------------------------------------------
    def health(self) -> dict:
        """Empty when healthy and no watchdog; with the liveness watchdog
        running it carries the compact stall summary so `curl /health` is
        enough to see a stuck chain."""
        wd = getattr(self.node, "watchdog", None)
        if wd is None:
            return {}
        return wd.status()

    def status(self) -> dict:
        return self.node.status()

    def genesis(self) -> dict:
        import json

        return {"genesis": json.loads(self.node.genesis_doc.to_json())}

    def block(self, height: Optional[int] = None) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no block for height {h}")
        block = bs.load_block(h)
        return {
            "block_meta": {
                "block_id": {
                    "hash": meta.block_id.hash.hex().upper(),
                    "parts": {
                        "total": meta.block_id.parts_header.total,
                        "hash": meta.block_id.parts_header.hash.hex().upper(),
                    },
                },
                "header": _header_json(meta.header),
            },
            "block": {
                "header": _header_json(block.header),
                "data": {"txs": [_b64(bytes(t)) for t in block.data.txs]},
                "last_commit": {
                    "block_id": {"hash": block.last_commit.block_id.hash.hex().upper()},
                    "precommits_count": sum(
                        1 for pc in block.last_commit.precommits if pc
                    ),
                },
            },
        }

    BLOCKCHAIN_INFO_LIMIT = 20  # reference blocks.go:60 const limit

    def blockchain(self, minHeight: int = 0, maxHeight: int = 0) -> dict:
        """Block metas for [minHeight, maxHeight], newest first, capped at 20
        (ref BlockchainInfo rpc/core/blocks.go:66 + filterMinMax)."""
        bs = self.node.block_store
        store_height = bs.height()
        min_h, max_h = int(minHeight), int(maxHeight)
        if min_h < 0 or max_h < 0:
            raise RPCError(-32602, "heights must be non-negative")
        if min_h == 0:
            min_h = 1
        max_h = store_height if max_h == 0 else min(store_height, max_h)
        min_h = max(min_h, max_h - self.BLOCKCHAIN_INFO_LIMIT + 1)
        if min_h > max_h:
            raise RPCError(
                -32603, f"min height {min_h} can't be greater than max height {max_h}"
            )
        metas = []
        for h in range(max_h, min_h - 1, -1):
            meta = bs.load_block_meta(h)
            if meta is None:
                continue
            metas.append(
                {
                    "block_id": {
                        "hash": meta.block_id.hash.hex().upper(),
                        "parts": {
                            "total": meta.block_id.parts_header.total,
                            "hash": meta.block_id.parts_header.hash.hex().upper(),
                        },
                    },
                    "header": _header_json(meta.header),
                }
            )
        return {"last_height": store_height, "block_metas": metas}

    def block_results(self, height: Optional[int] = None) -> dict:
        """ABCI results (DeliverTx, EndBlock) recorded for a height
        (ref BlockResults rpc/core/blocks.go:353; responses saved per height
        in the state store like state/store.go:204)."""
        from tendermint_tpu.state import store as sm_store

        bs = self.node.block_store
        h = int(height) if height else bs.height()
        if h < 1 or h > bs.height():
            raise RPCError(-32603, f"height {h} is not available")
        try:
            resp = sm_store.load_abci_responses(self.node.state_db, h)
        except Exception as e:
            raise RPCError(-32603, f"no results for height {h}: {e}")
        end_block = resp.end_block
        return {
            "height": h,
            "results": {
                "DeliverTx": [_tx_res_json(r) for r in (resp.deliver_tx or [])],
                "EndBlock": {
                    "validator_updates": [
                        {
                            "pub_key": vu.pub_key.to_json_obj()
                            if hasattr(vu.pub_key, "to_json_obj")
                            else _b64(vu.pub_key),
                            "power": vu.power,
                        }
                        for vu in (end_block.validator_updates if end_block else [])
                    ],
                    "tags": [
                        {"key": _b64(kv.key), "value": _b64(kv.value)}
                        for kv in (end_block.tags if end_block else [])
                    ],
                },
            },
        }

    def consensus_state(self) -> dict:
        """Compact live round state — the RoundStateSimple form
        (ref ConsensusState rpc/core/consensus.go:261)."""
        cs = self.node.consensus_state
        rs = cs.get_round_state()
        votes = None
        if rs.votes is not None:
            votes = []
            for r in range(rs.round + 1):
                pv = rs.votes.prevotes(r)
                pc = rs.votes.precommits(r)
                votes.append(
                    {
                        "round": r,
                        "prevotes_bit_array": str(pv.bit_array()) if pv else "",
                        "precommits_bit_array": str(pc.bit_array()) if pc else "",
                    }
                )
        proposal_hash = (
            rs.proposal_block.hash() if rs.proposal_block is not None else None
        )
        locked_hash = rs.locked_block.hash() if rs.locked_block is not None else None
        valid_hash = rs.valid_block.hash() if rs.valid_block is not None else None
        return {
            "round_state": {
                "height/round/step": f"{rs.height}/{rs.round}/{int(rs.step)}",
                "start_time": rs.start_time,
                "proposal_block_hash": proposal_hash.hex().upper() if proposal_hash else "",
                "locked_block_hash": locked_hash.hex().upper() if locked_hash else "",
                "valid_block_hash": valid_hash.hex().upper() if valid_hash else "",
                "height_vote_set": votes,
            }
        }

    def consensus_params(self, height: Optional[int] = None) -> dict:
        """Consensus parameters at a height from the state store
        (ref ConsensusParams rpc/core/consensus.go:299)."""
        from tendermint_tpu.state import store as sm_store

        h = int(height) if height else self.node.block_store.height() + 1
        try:
            params = sm_store.load_consensus_params(self.node.state_db, h)
        except Exception as e:
            raise RPCError(-32603, f"no consensus params for height {h}: {e}")
        return {
            "block_height": h,
            "consensus_params": {
                "block_size": {
                    "max_bytes": params.block_size.max_bytes,
                    "max_gas": params.block_size.max_gas,
                },
                "evidence": {"max_age": params.evidence.max_age},
            },
        }

    def _lane_feed(self):
        """Shared planner LaneFeed serving RPC commit-verification bursts:
        concurrent /commit?verify=1 and /validators?verify=1 queries park
        their signature rows here and fold into ONE lane-packed planner
        dispatch (verify_windows semantics, breaker + host-fallback guard
        unchanged) instead of each paying a serial per-signature loop."""
        with self._feed_mtx:
            if self._feed is None:
                from tendermint_tpu.parallel.planner import LaneFeed

                self._feed = LaneFeed(profile_kind="rpc_lane_feed")
            return self._feed

    def _verify_stored_commit(self, h: int) -> dict:
        """Verify the stored commit at height h against its validator set
        through the shared LaneFeed; returns JSON-able verdict facts."""
        from tendermint_tpu.parallel.planner import rows_from_commit
        from tendermint_tpu.state import store as sm_store
        from tendermint_tpu.types.validator_set import CommitError

        bs = self.node.block_store
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        if commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        try:
            vals = sm_store.load_validators(self.node.state_db, h)
        except Exception as e:
            raise RPCError(-32603, f"no validators for {h}: {e}")
        try:
            pubkeys, msgs, sigs, powers = vals.collect_commit_sigs(
                self.node.genesis_doc.chain_id, commit.block_id, h, commit
            )
        except CommitError as e:
            return {"verified": False, "reason": str(e)}
        vrow, prow = rows_from_commit(
            commit.precommits, pubkeys, msgs, sigs, powers
        )
        ticket = self._lane_feed().submit(
            vrow, prow, vals.total_voting_power()
        )
        try:
            v = ticket.result(60.0)
        except TimeoutError:
            raise RPCError(-32603, f"commit verification timed out for {h}")
        return {
            "verified": bool(v.committed),
            "sigs_ok": bool(v.sigs_ok),
            "tally": int(v.tally),
            "total_power": int(vals.total_voting_power()),
            # realized aggregation of the dispatch this row rode in
            "batch_rows": int(v.batch_rows),
            "batch_lanes": int(v.batch_lanes),
        }

    def commit(self, height: Optional[int] = None, verify=None) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no commit for height {h}")
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        out = {
            "signed_header": {
                "header": _header_json(meta.header),
                "commit": {
                    "block_id": {"hash": commit.block_id.hash.hex().upper()},
                    "precommits_count": sum(1 for pc in commit.precommits if pc),
                },
            },
            "canonical": bs.load_block_commit(h) is not None,
        }
        if verify:
            out["verification"] = self._verify_stored_commit(h)
        return out

    def lite_full_commit(self, height: Optional[int] = None) -> dict:
        """Codec-exact light-client material: header+commit+valsets as b64
        marshal bytes (what lite/proxy's RPCProvider consumes; JSON field
        re-serialization could never be hash-exact)."""
        from tendermint_tpu.encoding.codec import Writer
        from tendermint_tpu.state import store as sm_store

        bs = self.node.block_store
        h = int(height) if height else bs.height()
        meta = bs.load_block_meta(h)
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        if meta is None or commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        try:
            vals = sm_store.load_validators(self.node.state_db, h)
            next_vals = sm_store.load_validators(self.node.state_db, h + 1)
        except Exception as e:
            raise RPCError(-32603, f"no validators for {h}: {e}")
        w = Writer()
        meta.header.encode(w)
        return {
            "height": h,
            "header": _b64(w.build()),
            "commit": _b64(commit.marshal()),
            "validators": _b64(vals.marshal()),
            "next_validators": _b64(next_vals.marshal()),
        }

    def validators(self, height: Optional[int] = None, verify=None) -> dict:
        from tendermint_tpu.state import store as sm_store

        h = int(height) if height else self.node.block_store.height() + 1
        vals = sm_store.load_validators(self.node.state_db, h)
        out = {
            "block_height": h,
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": v.pub_key.to_json_obj(),
                    "voting_power": v.voting_power,
                    "accum": v.accum,
                }
                for v in vals.validators
            ],
        }
        if verify:
            # prove the set actually signed: verify the stored commit AT
            # this height (signed by exactly this valset) through the
            # shared LaneFeed
            out["verification"] = self._verify_stored_commit(h)
        return out

    def dump_consensus_state(self) -> dict:
        rs = self.node.consensus_state.get_round_state()
        out = {
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": rs.step.name,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
                "proposal": str(rs.proposal) if rs.proposal else None,
            }
        }
        wd = getattr(self.node, "watchdog", None)
        if wd is not None:
            out["stall"] = wd.report() or wd.status()
        return out

    def statesync(self) -> dict:
        """Snapshot restore / serving progress (chunks applied, backfill
        window, hand-off height) from the statesync reactor."""
        reactor = getattr(self.node, "statesync_reactor", None)
        if reactor is None:
            return {"enabled": False}
        return reactor.progress()

    def frontend_status(self) -> dict:
        """Light-client frontend serving stats (cache hit state, aggregator
        dispatch/occupancy counters) when [frontend] enable is on."""
        fe = getattr(self.node, "frontend", None)
        if fe is None:
            return {"enabled": False}
        out = {"enabled": True}
        out.update(fe.stats())
        return out

    def net_info(self) -> dict:
        sw = getattr(self.node, "switch", None)
        peers = []
        if sw is not None:
            for p in sw.peers.list():
                ni = p.node_info
                peers.append(
                    {
                        "node_info": {
                            "id": ni.id,
                            "listen_addr": ni.listen_addr,
                            "network": ni.network,
                            "moniker": ni.moniker,
                        },
                        "is_outbound": p.outbound,
                        "remote_ip": p.socket_addr.host if p.socket_addr else "",
                    }
                )
        return {"listening": sw is not None, "peers": peers, "n_peers": len(peers)}

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.node.mempool.reap_max_txs(int(limit))
        return {
            "n_txs": self.node.mempool.size(),
            "txs": [_b64(t) for t in txs],
        }

    def num_unconfirmed_txs(self) -> dict:
        return {"n_txs": self.node.mempool.size()}

    # tx --------------------------------------------------------------------
    def broadcast_tx_async(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        with self._broadcast_slot("async"):
            self._check_tx_guarded(raw)
        import hashlib

        return {"code": 0, "data": "", "log": "", "hash": hashlib.sha256(raw).hexdigest().upper()}

    def broadcast_tx_sync(self, tx: str) -> dict:
        raw = base64.b64decode(tx)
        with self._broadcast_slot("sync"):
            done: "queue.Queue" = queue.Queue()
            self._check_tx_guarded(raw, callback=done.put)
            try:
                res = done.get(timeout=10)
            except queue.Empty:
                raise RPCError(-32603, "CheckTx timed out")
        import hashlib

        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "hash": hashlib.sha256(raw).hexdigest().upper(),
        }

    def broadcast_tx_commit(self, tx: str) -> dict:
        """Subscribe to the tx event, CheckTx, wait for commit
        (rpc/core/mempool.go:152).  The in-flight slot is claimed BEFORE the
        event-bus subscription, so a shed request never leaks a
        subscription (and never holds one while rejected)."""
        raw = base64.b64decode(tx)
        import hashlib

        tx_hash = hashlib.sha256(raw).hexdigest().upper()
        with self._broadcast_slot("commit"):
            bus = self.node.event_bus
            sub_id = f"broadcast-{tx_hash}-{time.monotonic_ns()}"
            sub = bus.subscribe(
                sub_id, f"{query_for_event(EVENT_TX)} AND {TX_HASH_KEY} = '{tx_hash}'"
            )
            try:
                done: "queue.Queue" = queue.Queue()
                self._check_tx_guarded(raw, callback=done.put)
                try:
                    check_res = done.get(timeout=10)
                except queue.Empty:
                    raise RPCError(-32603, "CheckTx timed out")
                if check_res.code != abci.CODE_TYPE_OK:
                    return {
                        "check_tx": _tx_res_json(check_res),
                        "deliver_tx": {},
                        "hash": tx_hash,
                        "height": 0,
                    }
                try:
                    msg = sub.get(timeout=30)
                except queue.Empty:
                    raise RPCError(-32603, "timed out waiting for tx to be committed")
                ev = msg.data
                return {
                    "check_tx": _tx_res_json(check_res),
                    "deliver_tx": _tx_res_json(ev.result),
                    "hash": tx_hash,
                    "height": ev.height,
                }
            finally:
                try:
                    bus.unsubscribe_all(sub_id)
                except Exception:
                    pass

    def tx(self, hash: str, prove: bool = False) -> dict:
        raw_hash = bytes.fromhex(hash)
        r = self.node.tx_indexer.get(raw_hash)
        if r is None:
            raise RPCError(-32603, f"tx ({hash}) not found")
        return {
            "hash": hash.upper(),
            "height": r.height,
            "index": r.index,
            "tx_result": _tx_res_json(r.result),
            "tx": _b64(r.tx),
        }

    def tx_search(self, query: str, prove: bool = False, page: int = 1,
                  per_page: int = 30) -> dict:
        results = self.node.tx_indexer.search(query)
        page, per_page = int(page), int(per_page)
        start = (page - 1) * per_page
        sel = results[start : start + per_page]
        return {
            "txs": [
                {
                    "hash": r.hash().hex().upper(),
                    "height": r.height,
                    "index": r.index,
                    "tx_result": _tx_res_json(r.result),
                    "tx": _b64(r.tx),
                }
                for r in sel
            ],
            "total_count": len(results),
        }

    # abci ------------------------------------------------------------------
    def abci_query(self, path: str = "", data: str = "", height: int = 0,
                   prove: bool = False) -> dict:
        res = self.node.proxy_app.query.query_sync(
            abci.RequestQuery(
                data=bytes.fromhex(data) if data else b"",
                path=path,
                height=int(height),
                prove=bool(prove),
            )
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "key": _b64(res.key),
                "value": _b64(res.value),
                "height": res.height,
            }
        }

    # debug / profiling ------------------------------------------------------
    def _require_unsafe(self) -> None:
        """unsafe_* routes are operator tools, gated on config.rpc.unsafe
        (the reference registers its unsafe routes conditionally,
        rpc/core/routes.go:43)."""
        if not self.node.config.rpc.unsafe:
            raise RPCError(-32601, "unsafe RPC routes are disabled (rpc.unsafe)")

    @staticmethod
    def _parse_addr_list(v) -> list:
        """JSON list or comma-separated string of id@host:port addresses."""
        if isinstance(v, str):
            v = [s for s in v.split(",") if s.strip()]
        return list(v or [])

    def _dial_addrs(self, items, label: str, persistent: bool) -> dict:
        """Shared body of dial_seeds/dial_peers (ref rpc/core/net.go:42,59)."""
        self._require_unsafe()
        from tendermint_tpu.p2p.netaddress import NetAddress

        sw = getattr(self.node, "switch", None)
        if sw is None:
            raise RPCError(-32603, "p2p switch not running")
        items = self._parse_addr_list(items)
        if not items:
            raise RPCError(-32602, f"no {label} provided")
        try:
            addrs = [NetAddress.parse(s) for s in items]
        except Exception as e:
            raise RPCError(-32602, f"bad {label} address: {e}")
        sw.dial_peers_async(addrs, persistent=persistent)
        return {"log": f"Dialing {label} in progress. See /net_info for details"}

    def dial_seeds(self, seeds=None) -> dict:
        return self._dial_addrs(seeds, "seeds", persistent=False)

    def dial_peers(self, peers=None, persistent: bool = False) -> dict:
        return self._dial_addrs(peers, "peers", persistent=bool(persistent))

    def unsafe_flush_mempool(self) -> dict:
        """Drop every pending tx (ref UnsafeFlushMempool
        rpc/core/mempool.go:264, routes.go:47)."""
        self._require_unsafe()
        self.node.mempool.flush()
        return {}

    def dump_trace(self, limit=None) -> dict:
        """Snapshot the span-tracer ring as Chrome trace-event JSON (load at
        chrome://tracing or ui.perfetto.dev).  Gated like the unsafe_*
        routes — the dump leaks internal timings and thread names.

        limit=N keeps only the newest N events (thread-name "M" metadata is
        always kept) so a full 8192-span ring can't blow up a WS frame.  The
        `anchor` pairs a wall-clock and a perf-counter reading taken at dump
        time: trace timestamps are perf_counter-based (process-local), and
        trace_merge.py needs the pair to place them on a wall timeline."""
        self._require_unsafe()
        import time as _time

        from tendermint_tpu.libs import trace

        out = trace.chrome_trace()
        events = out.get("traceEvents", [])
        meta = [e for e in events if e.get("ph") == "M"]
        spans = [e for e in events if e.get("ph") != "M"]
        total = len(spans)
        truncated = False
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
            if total > limit:
                spans = spans[total - limit:]  # export is oldest-first
                truncated = True
        out["traceEvents"] = meta + spans
        out["enabled"] = trace.enabled()
        out["dropped"] = trace.dropped()
        out["total_events"] = total
        out["truncated"] = truncated
        out["anchor"] = {
            "wall_ns": _time.time_ns(),
            "perf_ns": _time.perf_counter_ns(),
        }
        return out

    def trace_reset(self, enable=None, capacity=None) -> dict:
        """Clear the span-tracer ring; optionally flip the tracer on/off
        (enable=true/false) and resize the ring (capacity=N)."""
        self._require_unsafe()
        from tendermint_tpu.libs import trace

        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        trace.reset(capacity)
        if enable is not None:
            if bool(enable):
                trace.enable()
            else:
                trace.disable()
        return {
            "enabled": trace.enabled(),
            "capacity": trace.get_tracer().capacity,
        }

    def dump_profile(self, limit=None) -> dict:
        """Snapshot the device-dispatch cost ledger: per-window rows of
        host pack / compile / device run seconds, bytes shipped, and lane
        occupancy (libs/profile.py).  Gated like dump_trace — the ledger
        leaks internal timings.  limit=N keeps the newest N entries (the
        aggregate ledger always covers the full ring)."""
        self._require_unsafe()
        from tendermint_tpu.libs.profile import get_profiler

        p = get_profiler()
        entries = p.entries()
        total = len(entries)
        truncated = False
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
            if total > limit:
                entries = entries[total - limit:]  # oldest-first
                truncated = True
        return {
            "ledger": p.ledger(),
            "entries": entries,
            "total_entries": total,
            "truncated": truncated,
            "dropped": p.dropped,
            # health events (breaker transitions, audits, fallbacks) ride
            # their own ring — high-churn dispatch entries can't evict them
            "events": p.events(),
            "events_dropped": p.events_dropped,
        }

    def dump_device_health(self) -> dict:
        """Device verify-path health: circuit-breaker snapshot (state,
        counters, transition history), guard config knobs, the installed
        default verifier's identity, and the profiler's breaker/audit/
        fallback event ring (libs/breaker.py).  Gated like dump_trace —
        device health and timings are operator telemetry."""
        self._require_unsafe()
        from tendermint_tpu.crypto.batch import verifier_info
        from tendermint_tpu.libs.breaker import get_device_breaker, guard_config
        from tendermint_tpu.libs.profile import get_profiler

        p = get_profiler()
        events = [
            e for e in p.events()
            if e["kind"] in ("breaker", "audit_mismatch", "device_fallback")
        ]
        return {
            "breaker": get_device_breaker().snapshot(),
            "config": guard_config().as_dict(),
            "verifier": verifier_info(),
            "events": events,
            "events_dropped": p.events_dropped,
        }

    def device_breaker_reset(self, reprobe=None) -> dict:
        """Operator reset of the device circuit breaker — the ONLY way out
        of the quarantined state (a device that disagreed with the host
        oracle must not be re-admitted by timers).  reprobe=true also drops
        the lazy default verifier and the TPU liveness cache so device
        selection reruns from scratch (pays a full probe timeout if the
        device is still dead)."""
        self._require_unsafe()
        from tendermint_tpu.crypto import batch as _batch
        from tendermint_tpu.libs.breaker import get_device_breaker

        br = get_device_breaker()
        br.reset()
        if reprobe is not None and bool(reprobe):
            _batch.reprobe(force=True)
        return {
            "breaker": br.snapshot(),
            "verifier": _batch.verifier_info(),
        }

    def dump_flight(self, limit=None) -> dict:
        """Snapshot the consensus flight recorder: per-height lifecycle
        records (consensus/flight.py) plus the current watchdog stall
        report.  limit=N keeps the newest N height records.  Gated like
        dump_trace — per-peer vote attribution leaks topology."""
        self._require_unsafe()
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
        out = self.node.consensus_state.flight.snapshot(limit)
        wd = getattr(self.node, "watchdog", None)
        out["stall"] = wd.report() if wd is not None else None
        return out

    def dump_critpath(self, limit=None) -> dict:
        """Snapshot the per-height critical-path analyzer: commit-latency
        waterfalls (libs/critpath.py) with per-phase seconds, the dominant
        phase, and rolling per-phase p50/p99.  limit=N keeps the newest N
        height waterfalls.  Gated like dump_flight — it is derived from the
        same lifecycle stamps."""
        self._require_unsafe()
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
        cs = self.node.consensus_state
        out = cs.critpath.snapshot(limit)
        # waterfalls only accrue while the flight recorder stamps heights
        out["flight_enabled"] = cs.flight.enabled
        if not out["node_id"]:
            out["node_id"] = cs.flight.node_id
        return out

    def dump_quorum(self, limit=None) -> dict:
        """Snapshot the quorum-formation analyzer: per-height completion
        curves (time-to-1/3/2/3 with the pivotal validator named),
        gossip first-sighting/duplicate counts, and batch-flush
        attribution (libs/quorumtrace.py).  limit=N keeps the newest N
        height records.  Gated like dump_flight — per-peer vote
        attribution leaks topology."""
        self._require_unsafe()
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
        cs = self.node.consensus_state
        out = cs.quorumtrace.snapshot(limit)
        # curves only accrue while the flight recorder stamps journeys
        out["flight_enabled"] = cs.flight.enabled
        if not out["node_id"]:
            out["node_id"] = cs.flight.node_id
        return out

    def quorum_reset(self, capacity=None) -> dict:
        """Clear the quorum-formation record ring and its rolling
        time-to-quorum percentile windows; optionally resize the ring
        (capacity=N)."""
        self._require_unsafe()
        qt = self.node.consensus_state.quorumtrace
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        qt.reset(capacity)
        return {"capacity": qt.capacity}

    def critpath_reset(self, capacity=None) -> dict:
        """Clear the critical-path waterfall ring and its rolling phase
        percentile windows; optionally resize the ring (capacity=N)."""
        self._require_unsafe()
        cp = self.node.consensus_state.critpath
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        cp.reset(capacity)
        return {"capacity": cp.capacity}

    def dump_telemetry(self, limit=None) -> dict:
        """Snapshot the telemetry spool's in-memory ring (newest periodic
        snapshots plus spool health; libs/telemetry.py) — the live
        counterpart of reading the on-disk spool segments offline.
        limit=N keeps the newest N snapshots.  Gated like dump_flight —
        snapshots embed eviction/ledger internals."""
        self._require_unsafe()
        if limit is not None:
            limit = int(limit)
            if limit < 0:
                raise RPCError(-32602, "limit must be >= 0")
        spool = getattr(self.node, "telemetry_spool", None)
        if spool is None:
            raise RPCError(
                -32603,
                "telemetry spool not running "
                "(instrumentation.telemetry_spool)",
            )
        return spool.snapshot(limit)

    def telemetry_reset(self, capacity=None) -> dict:
        """Clear the telemetry spool's in-memory snapshot ring and health
        counters; optionally resize the ring (capacity=N).  The on-disk
        spool segments are history and are NOT touched."""
        self._require_unsafe()
        spool = getattr(self.node, "telemetry_spool", None)
        if spool is None:
            raise RPCError(
                -32603,
                "telemetry spool not running "
                "(instrumentation.telemetry_spool)",
            )
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        return spool.reset(capacity)

    def dump_mempool_qos(self) -> dict:
        """Per-peer mempool admission ledger (token levels, drops by
        reason, mute state), lane occupancy, and the RPC broadcast
        load-shed counters — the dump_consensus_state of the ingestion
        path.  Gated like dump_trace: per-peer traffic accounting leaks
        topology."""
        self._require_unsafe()
        reactor = getattr(self.node, "mempool_reactor", None)
        qos = (
            reactor.qos_snapshot()
            if reactor is not None and hasattr(reactor, "qos_snapshot")
            else {"enabled": False, "peers": {}}
        )
        mp = self.node.mempool
        cfg = getattr(self.node, "config", None)
        with self._broadcast_mtx:
            rpc = {
                "in_flight": self._broadcast_in_flight,
                "budget": getattr(cfg.rpc, "broadcast_max_in_flight", 0)
                if cfg else 0,
                "shed": dict(self.broadcast_shed),
            }
        return {
            "qos": qos,
            "mempool": {
                "size": mp.size(),
                "max_size": getattr(mp, "_max_size", None),
                "lane_sizes": mp.lane_sizes()
                if hasattr(mp, "lane_sizes") else [],
            },
            "rpc": rpc,
        }

    def flight_reset(self, enable=None, capacity=None) -> dict:
        """Clear the flight-recorder ring; optionally flip it on/off
        (enable=true/false) and resize the ring (capacity=N)."""
        self._require_unsafe()
        flight = self.node.consensus_state.flight
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        flight.reset(capacity)
        if enable is not None:
            if bool(enable):
                flight.enable()
            else:
                flight.disable()
        return {"enabled": flight.enabled, "capacity": flight.capacity}

    def profile_reset(self, capacity=None) -> dict:
        """Clear the dispatch-cost ledger; optionally resize the ring
        (capacity=N)."""
        self._require_unsafe()
        from tendermint_tpu.libs.profile import get_profiler

        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise RPCError(-32602, "capacity must be >= 1")
        get_profiler().reset(capacity)
        return {}

    def unsafe_dump_threads(self) -> dict:
        """Stack dump of every live thread — the pprof-goroutine analogue
        (ref: pprof server at node/node.go:474-479)."""
        self._require_unsafe()
        import sys as _sys
        import traceback

        frames = _sys._current_frames()
        out = {}
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            out[f"{t.name} (daemon={t.daemon})"] = (
                traceback.format_stack(frame) if frame is not None else []
            )
        return {"n_threads": len(out), "stacks": out}

    def unsafe_start_profiler(self, dir: str = "/tmp/tm_tpu_trace") -> dict:
        """Start a JAX profiler trace (xprof-compatible; SURVEY §5 —
        device-time attribution for the batched verify dispatches)."""
        self._require_unsafe()
        import jax

        jax.profiler.start_trace(dir)
        return {"tracing": True, "dir": dir}

    def unsafe_stop_profiler(self) -> dict:
        self._require_unsafe()
        import jax

        jax.profiler.stop_trace()
        return {"tracing": False}

    # reference route-name aliases (routes.go:49-51): the CPU profiler maps
    # to the JAX/xprof trace (device+host timelines), the heap profile to a
    # tracemalloc snapshot
    def unsafe_start_cpu_profiler(self, filename: str = "/tmp/tm_tpu_trace") -> dict:
        return self.unsafe_start_profiler(dir=filename)

    def unsafe_stop_cpu_profiler(self) -> dict:
        return self.unsafe_stop_profiler()

    def unsafe_write_heap_profile(self, filename: str = "tm_tpu_heap.txt") -> dict:
        """Top allocation sites by live bytes (pprof WriteHeapProfile's
        role; tracemalloc is the Python-native equivalent).

        `filename` is a bare name resolved under the system temp dir — an
        RPC caller must not get an arbitrary-file-overwrite primitive out of
        a profiling route (rpc.unsafe gating alone is thin: operators do
        enable it to profile)."""
        self._require_unsafe()
        import tempfile
        import tracemalloc

        base = os.path.basename(filename)
        if base != filename or base in ("", ".", ".."):
            raise ValueError(
                "heap profile filename must be a bare file name "
                "(written under the node's profile directory)"
            )
        # node-owned 0700 subdir + O_NOFOLLOW: a world-writable /tmp must
        # not let another local user plant a symlink where we write
        prof_dir = os.path.join(
            tempfile.gettempdir(), f"tm-tpu-profiles-{os.getuid()}"
        )
        os.makedirs(prof_dir, mode=0o700, exist_ok=True)
        os.chmod(prof_dir, 0o700)
        filename = os.path.join(prof_dir, base)
        fd = os.open(
            filename,
            os.O_WRONLY | os.O_CREAT | os.O_TRUNC | os.O_NOFOLLOW,
            0o600,
        )

        started_here = False
        if not tracemalloc.is_tracing():
            # no baseline: start now so a SECOND call sees real traffic
            tracemalloc.start()
            started_here = True
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")[:100]
        with os.fdopen(fd, "w") as f:
            for st in stats:
                f.write(f"{st.size}B in {st.count} blocks: {st.traceback}\n")
        return {
            "filename": filename,
            "top_entries": len(stats),
            "tracing_started_now": started_here,
        }

    def unsafe_stop_heap_profiler(self) -> dict:
        """Turn allocation tracing back off — tracemalloc taxes every
        allocation, so a validator must be able to disable it without a
        restart after grabbing profiles."""
        self._require_unsafe()
        import tracemalloc

        was = tracemalloc.is_tracing()
        tracemalloc.stop()
        return {"was_tracing": was}

    def abci_info(self) -> dict:
        res = self.node.proxy_app.query.info_sync(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "last_block_height": res.last_block_height,
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }


def _header_json(h) -> dict:
    return {
        "chain_id": h.chain_id,
        "height": h.height,
        "time_ns": h.time_ns,
        "num_txs": h.num_txs,
        "total_txs": h.total_txs,
        "last_block_id": {"hash": h.last_block_id.hash.hex().upper()},
        "app_hash": h.app_hash.hex().upper(),
        "data_hash": h.data_hash.hex().upper(),
        "validators_hash": h.validators_hash.hex().upper(),
        "proposer_address": h.proposer_address.hex().upper(),
    }


def _tx_res_json(res) -> dict:
    if res is None:
        return {}
    return {
        "code": res.code,
        "data": _b64(res.data),
        "log": res.log,
        "gas_wanted": res.gas_wanted,
        "gas_used": res.gas_used,
        "tags": [
            {"key": _b64(kv.key), "value": _b64(kv.value)} for kv in res.tags
        ],
    }
