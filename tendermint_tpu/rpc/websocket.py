"""WebSocket endpoint: JSON-RPC subscribe/unsubscribe over RFC 6455
(ref: rpc/lib/server/ws_handler.go + the subscribe routes at
rpc/core/routes.go:11, events.go).

Hand-rolled frame layer (no external websocket dependency): handshake,
masked client frames, text/ping/pong/close opcodes. Each connection runs a
reader loop (JSON-RPC requests) and pushes event-bus matches back as
notifications with id "<request id>#event", the reference's convention.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading
from typing import Any, Dict, Optional

_WS_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1(client_key.encode() + _WS_GUID).digest()
    return base64.b64encode(digest).decode()


OP_CONT = 0x0


def read_frame(rfile) -> Optional[tuple]:
    """One raw frame: (fin, opcode, payload), or None on EOF."""
    hdr = rfile.read(2)
    if len(hdr) < 2:
        return None
    fin_op, mask_len = hdr[0], hdr[1]
    fin = bool(fin_op & 0x80)
    opcode = fin_op & 0x0F
    masked = bool(mask_len & 0x80)
    length = mask_len & 0x7F
    if length == 126:
        (length,) = struct.unpack(">H", rfile.read(2))
    elif length == 127:
        (length,) = struct.unpack(">Q", rfile.read(8))
    if length > 1 << 20:
        return None  # refuse absurd frames
    mask = rfile.read(4) if masked else b""
    payload = rfile.read(length)
    if len(payload) < length:
        return None
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return fin, opcode, payload


class MessageReader:
    """Reassembles RFC 6455 fragmentation (FIN=0 TEXT/BINARY + continuation
    frames). Control frames (ping/pong/close) may legally interleave with a
    fragmented message and are returned immediately — the partial fragment
    buffer lives on the instance, surviving across ``next()`` calls."""

    def __init__(self, rfile):
        self._rfile = rfile
        self._buffer = bytearray()
        self._buffered_op: Optional[int] = None

    def next(self) -> Optional[tuple]:
        """(opcode, payload) of the next complete message, or None on
        EOF/protocol error."""
        while True:
            frame = read_frame(self._rfile)
            if frame is None:
                return None
            fin, opcode, payload = frame
            if opcode in (OP_CLOSE, OP_PING, OP_PONG):
                return opcode, payload  # control frames are never fragmented
            if opcode == OP_CONT:
                if self._buffered_op is None:
                    return None  # continuation with nothing to continue
                self._buffer.extend(payload)
                if fin:
                    op, out = self._buffered_op, bytes(self._buffer)
                    self._buffered_op = None
                    self._buffer = bytearray()
                    return op, out
                continue
            if fin and self._buffered_op is None:
                return opcode, payload  # the common unfragmented case
            if self._buffered_op is not None:
                return None  # new data frame while a fragment is open
            self._buffered_op = opcode
            self._buffer.extend(payload)


def read_message(rfile) -> Optional[tuple]:
    """One-shot convenience for unfragmented streams (tests/clients).
    Sessions must hold a MessageReader so fragment state survives interleaved
    control frames."""
    return MessageReader(rfile).next()


def make_frame(opcode: int, payload: bytes) -> bytes:
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < 1 << 16:
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


# -- event JSON ----------------------------------------------------------------


def event_to_json(msg) -> Dict[str, Any]:
    """Serialize a pubsub Message into the reference's {type, value} shape."""
    from tendermint_tpu.rpc.core.env import _header_json, _tx_res_json
    from tendermint_tpu.types import events as ev

    data = msg.data
    if isinstance(data, ev.EventDataNewBlock):
        block = data.block
        value = {
            "block": {
                "header": _header_json(block.header),
                "data": {
                    "txs": [
                        base64.b64encode(bytes(t)).decode() for t in block.data.txs
                    ]
                },
            }
        }
        typ = "NewBlock"
    elif isinstance(data, ev.EventDataNewBlockHeader):
        value = {"header": _header_json(data.header)}
        typ = "NewBlockHeader"
    elif isinstance(data, ev.EventDataTx):
        value = {
            "TxResult": {
                "height": data.height,
                "index": data.index,
                "tx": base64.b64encode(bytes(data.tx)).decode(),
                "result": _tx_res_json(data.result),
            }
        }
        typ = "Tx"
    elif isinstance(data, ev.EventDataVote):
        v = data.vote
        value = {
            "Vote": {
                "height": v.height,
                "round": v.round,
                "type": int(v.vote_type),
                "validator_index": v.validator_index,
            }
        }
        typ = "Vote"
    elif isinstance(data, ev.EventDataRoundState):
        value = {"height": data.height, "round": data.round, "step": data.step}
        typ = "RoundState"
    elif isinstance(data, ev.EventDataValidatorSetUpdates):
        value = {"n_updates": len(data.validator_updates)}
        typ = "ValidatorSetUpdates"
    else:
        value = {"repr": repr(data)}
        typ = type(data).__name__
    return {"type": typ, "value": value, "tags": dict(msg.tags)}


# -- per-connection session --------------------------------------------------------


class WSSession:
    """One websocket client: JSON-RPC requests in, responses + event
    notifications out (ws_handler.go wsConnection)."""

    def __init__(self, handler, event_bus, logger):
        self.rfile = handler.rfile
        self.wfile = handler.wfile
        self.bus = event_bus
        self.logger = logger
        self._wmtx = threading.Lock()
        self._client_id = f"ws-{id(self):x}"
        self._subs: Dict[str, tuple] = {}  # query str -> (Subscription, req_id)
        self._closed = threading.Event()

    # -- frame IO -----------------------------------------------------------------
    def _send_json(self, obj) -> bool:
        data = json.dumps(obj).encode()
        try:
            with self._wmtx:
                self.wfile.write(make_frame(OP_TEXT, data))
                self.wfile.flush()
            return True
        except OSError:
            self._closed.set()
            return False

    # -- main loop ---------------------------------------------------------------
    def run(self) -> None:
        reader = MessageReader(self.rfile)
        try:
            while not self._closed.is_set():
                msg = reader.next()
                if msg is None:
                    break
                opcode, payload = msg
                if opcode == OP_CLOSE:
                    with self._wmtx:
                        try:
                            self.wfile.write(make_frame(OP_CLOSE, payload[:2]))
                            self.wfile.flush()
                        except OSError:
                            pass
                    break
                if opcode == OP_PING:
                    with self._wmtx:
                        self.wfile.write(make_frame(OP_PONG, payload))
                        self.wfile.flush()
                    continue
                if opcode != OP_TEXT:
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    self._send_json(
                        {"jsonrpc": "2.0", "id": None,
                         "error": {"code": -32700, "message": "parse error"}}
                    )
                    continue
                self._handle(req)
        finally:
            self._closed.set()
            try:
                self.bus.unsubscribe_all(self._client_id)
            except Exception:
                pass

    def _handle(self, req: dict) -> None:
        method = req.get("method", "")
        params = req.get("params") or {}
        req_id = req.get("id")
        start_pump = None
        try:
            if method == "subscribe":
                start_pump = self._subscribe(params["query"], req_id)
                result: Any = {}
            elif method == "unsubscribe":
                self._unsubscribe(params["query"])
                result = {}
            elif method == "unsubscribe_all":
                for q in list(self._subs):
                    self._unsubscribe(q)
                result = {}
            else:
                self._send_json(
                    {"jsonrpc": "2.0", "id": req_id,
                     "error": {"code": -32601, "message": f"unknown ws method {method!r}"}}
                )
                return
            self._send_json({"jsonrpc": "2.0", "id": req_id, "result": result})
            if start_pump is not None:
                # pump starts only AFTER the ack frame is on the wire, so the
                # client never sees an event before its subscribe response
                start_pump()
        except Exception as e:
            self._send_json(
                {"jsonrpc": "2.0", "id": req_id,
                 "error": {"code": -32603, "message": str(e)}}
            )

    # -- subscriptions ----------------------------------------------------------
    def _subscribe(self, query: str, req_id):
        if query in self._subs:
            raise ValueError(f"already subscribed to {query!r}")
        sub = self.bus.subscribe(self._client_id, query, maxsize=100)
        self._subs[query] = (sub, req_id)

        def start():
            threading.Thread(
                target=self._pump, args=(sub, query, req_id),
                name="ws-pump", daemon=True,
            ).start()

        return start

    def _unsubscribe(self, query: str) -> None:
        if query not in self._subs:
            raise ValueError(f"not subscribed to {query!r}")
        sub, _ = self._subs.pop(query)
        sub.cancelled.set()
        try:
            self.bus.unsubscribe(self._client_id, query)
        except Exception:
            pass

    def _pump(self, sub, query: str, req_id) -> None:
        import queue as q

        while not self._closed.is_set() and not sub.cancelled.is_set():
            try:
                msg = sub.get(timeout=0.2)
            except q.Empty:
                continue
            payload = {
                "jsonrpc": "2.0",
                "id": f"{req_id}#event",
                "result": {"query": query, "data": event_to_json(msg)},
            }
            data = json.dumps(payload).encode()
            try:
                with self._wmtx:
                    # cancellation is flagged BEFORE the unsubscribe ack is
                    # written (same lock): re-checking here guarantees no
                    # event frame ever follows the ack
                    if sub.cancelled.is_set():
                        return
                    self.wfile.write(make_frame(OP_TEXT, data))
                    self.wfile.flush()
            except (OSError, ValueError):
                # ValueError: writing to a file the handler already closed —
                # a racing client disconnect, same meaning as a broken pipe
                self._closed.set()
                return
