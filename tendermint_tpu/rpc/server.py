"""JSON-RPC 2.0 over HTTP (+ GET URI routes) (ref: rpc/lib/server/).

POST / with {"jsonrpc":"2.0","method":...,"params":...} or GET /<method>?arg=v
— the same dual surface the reference exposes.  Handlers come from
rpc.core.env.RPCEnv; public callables become routes (reflection dispatch like
rpc/lib/server's func-signature routing).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tendermint_tpu.libs import trace
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.rpc.core.env import RPCEnv, RPCError


def _parse_laddr(laddr: str):
    if laddr.startswith("tcp://"):
        laddr = laddr[len("tcp://"):]
    host, port = laddr.rsplit(":", 1)
    return host or "0.0.0.0", int(port)


class RPCServer(BaseService):
    def __init__(self, laddr: str, env: RPCEnv):
        super().__init__("rpc.Server")
        self.laddr = laddr
        self.env = env
        self._httpd: Optional[ThreadingHTTPServer] = None

    @property
    def bound_port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def on_start(self) -> None:
        env = self.env
        logger = self.logger

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("rpc: " + fmt, *args)

            def _send(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _call(self, method: str, params: dict, req_id):
                fn = getattr(env, method, None)
                if fn is None or method.startswith("_") or not callable(fn):
                    return self._send(
                        _err(req_id, -32601, f"method {method!r} not found")
                    )
                try:
                    with trace.span("rpc.dispatch", method=method):
                        result = fn(**params)
                    self._send({"jsonrpc": "2.0", "id": req_id, "result": result})
                except RPCError as e:
                    self._send(_err(req_id, e.code, e.message))
                except TypeError as e:
                    self._send(_err(req_id, -32602, f"invalid params: {e}"))
                except Exception as e:
                    logger.error("rpc %s failed: %s", method, e)
                    self._send(_err(req_id, -32603, str(e)))

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._send(_err(None, -32700, "parse error"), 400)
                method = req.get("method", "")
                params = req.get("params") or {}
                if isinstance(params, list):
                    return self._send(
                        _err(req.get("id"), -32602, "positional params unsupported")
                    )
                self._call(method, params, req.get("id"))

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                method = parsed.path.strip("/")
                if method == "websocket":
                    return self._upgrade_websocket()
                if method == "metrics":
                    reg = getattr(env.node, "metrics", None)
                    if reg is None:
                        # 200 + comment, not 404: scrapers must be able to
                        # tell "instrumentation off" from "no such route"
                        body = (
                            b"# metrics disabled "
                            b"(instrumentation.prometheus = false)\n"
                        )
                    else:
                        body = reg.registry.expose_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if method == "":
                    # route listing, like the reference's index page
                    routes = sorted(
                        m for m in dir(env)
                        if not m.startswith("_") and callable(getattr(env, m))
                    )
                    return self._send({"jsonrpc": "2.0", "result": {"routes": routes}})
                params = {
                    k: _coerce(v[0])
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                self._call(method, params, -1)

            def _upgrade_websocket(self):
                """RFC 6455 handshake, then hand the socket to a WSSession
                (the reference's /websocket endpoint, ws_handler.go)."""
                from tendermint_tpu.rpc.websocket import WSSession, accept_key

                key = self.headers.get("Sec-WebSocket-Key")
                upgrade = (self.headers.get("Upgrade") or "").lower()
                if key is None or upgrade != "websocket":
                    return self._send(_err(None, -32600, "not a websocket upgrade"), 400)
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", accept_key(key))
                self.end_headers()
                self.close_connection = True
                WSSession(self, env.node.event_bus, logger).run()

        host, port = _parse_laddr(self.laddr)
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        self.logger.info("RPC listening on %s", self.laddr)

    def on_stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


def _coerce(v: str):
    """GET query params arrive as strings; unquote the reference's conventions:
    0x-prefixed hex stays string, quoted strings unquote, ints parse."""
    if v.startswith('"') and v.endswith('"'):
        return v[1:-1]
    if v in ("true", "false"):
        return v == "true"
    try:
        return int(v)
    except ValueError:
        return v


def _err(req_id, code, message):
    return {
        "jsonrpc": "2.0",
        "id": req_id,
        "error": {"code": code, "message": message},
    }
