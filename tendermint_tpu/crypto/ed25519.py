"""Host-side Ed25519 with the *exact* accept/reject semantics of the Go reference.

The reference (crypto/ed25519/ed25519.go:151) delegates to golang.org/x/crypto/ed25519,
whose Verify has several non-RFC-8032 quirks that define our bit-exactness contract
(BASELINE.md "accept/reject parity"):

  * only the top 3 bits of s are checked (``sig[63]&224 != 0`` rejects), so scalars
    s in [L, 2^253) are ACCEPTED — stricter libraries (OpenSSL) reject them;
  * point decompression loads y as a 255-bit little-endian integer reduced mod p —
    non-canonical encodings (y >= p) are ACCEPTED;
  * the final check is a raw 32-byte comparison of the canonical encoding of
    R' = [s]B - [h]A against sig[:32] (so a non-canonical R in the signature can
    only match itself, never the canonical re-encoding).

This module provides:
  * ``verify`` — the oracle implementing exactly the above (pure-python bigint path,
    with a fast-path through the `cryptography` package when inputs are in the
    canonical zone where both libraries agree);
  * ``sign`` / key generation — RFC 8032 standard (identical to Go's Sign);
  * curve constants and reference point arithmetic reused by tests of the TPU kernel
    (tendermint_tpu/ops/ed25519_verify.py).

Key layout mirrors the reference: PrivKey = 64 bytes (seed || pubkey),
PubKey = 32 bytes, Signature = 64 bytes, Address = SHA256(pubkey)[:20]
(crypto/ed25519/ed25519.go:138, crypto/tmhash/hash.go:62).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

try:  # fast host path for sign + canonical-zone verify
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_CRYPTOGRAPHY = True
except Exception:  # pragma: no cover
    _HAVE_CRYPTOGRAPHY = False

# ---------------------------------------------------------------------------
# Curve constants (edwards25519: -x^2 + y^2 = 1 + d x^2 y^2 over GF(2^255-19))
# ---------------------------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # resolved below


def _decompress_xy(s: bytes) -> Optional[Tuple[int, int]]:
    """Mirror of Go's ExtendedGroupElement.FromBytes: returns affine (x, y) or None.

    Accepts non-canonical y (reduced mod p); sign bit selects the x parity.
    """
    y_raw = int.from_bytes(s, "little")
    sign = (y_raw >> 255) & 1
    y = (y_raw & ((1 << 255) - 1)) % P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root x = u v^3 (u v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow((u * pow(v, 7, P)) % P, (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if (vxx - u) % P != 0:
        if (vxx + u) % P != 0:
            return None
        x = (x * SQRT_M1) % P
    if (x & 1) != sign:
        x = (P - x) % P
    return (x, y)


_B_PT = _decompress_xy(_BY.to_bytes(32, "little"))
assert _B_PT is not None
# base point B: y = 4/5, x even (sign bit clear in the canonical encoding)
B_AFFINE = _B_PT[0]
del _B_PT

# ---------------------------------------------------------------------------
# Extended-coordinate point arithmetic with the complete addition law.
# (a = -1 is a square mod p and d is non-square, so the law is complete for
#  every point on the curve, including low-order/adversarial points.)
# ---------------------------------------------------------------------------

# point = (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z
IDENT = (0, 1, 1, 0)


def _to_extended(pt: Tuple[int, int]) -> Tuple[int, int, int, int]:
    x, y = pt
    return (x, y, 1, (x * y) % P)


def pt_add(p1, p2):
    """add-2008-hwcd-3 (complete for a=-1, d non-square)."""
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = ((Y1 - X1) * (Y2 - X2)) % P
    Bv = ((Y1 + X1) * (Y2 + X2)) % P
    C = (T1 * D2 % P) * T2 % P
    Dv = (Z1 * 2 * Z2) % P
    E = (Bv - A) % P
    F = (Dv - C) % P
    G = (Dv + C) % P
    H = (Bv + A) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def pt_double(p1):
    """dbl-2008-hwcd, valid for all inputs."""
    X1, Y1, Z1, _ = p1
    A = (X1 * X1) % P
    Bv = (Y1 * Y1) % P
    C = (2 * Z1 * Z1) % P
    H = (A + Bv) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - Bv) % P
    F = (C + G) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def pt_scalar_mult(pt, k: int):
    acc = IDENT
    base = pt
    while k:
        if k & 1:
            acc = pt_add(acc, base)
        base = pt_double(base)
        k >>= 1
    return acc


def pt_encode(p1) -> bytes:
    X, Y, Z, _ = p1
    zi = pow(Z, P - 2, P)
    x = (X * zi) % P
    y = (Y * zi) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


B_EXT = _to_extended((B_AFFINE, _BY))

# ---------------------------------------------------------------------------
# Verify / sign
# ---------------------------------------------------------------------------


def _verify_pure(public_key: bytes, message: bytes, sig: bytes) -> bool:
    """Literal mirror of golang.org/x/crypto/ed25519.Verify."""
    if len(public_key) != 32 or len(sig) != 64:
        return False
    if sig[63] & 224 != 0:
        return False
    A = _decompress_xy(public_key)
    if A is None:
        return False
    # negate A (Go negates X and T after FromBytes)
    neg_a = ((P - A[0]) % P, A[1])
    h = int.from_bytes(
        hashlib.sha512(sig[:32] + public_key + message).digest(), "little"
    ) % L
    s = int.from_bytes(sig[32:], "little")
    r_check = pt_add(
        pt_scalar_mult(_to_extended(neg_a), h), pt_scalar_mult(B_EXT, s)
    )
    return pt_encode(r_check) == sig[:32]


def _in_canonical_zone(public_key: bytes, sig: bytes) -> bool:
    """True when stricter RFC-8032 verifiers (OpenSSL) agree with the Go semantics:
    s < L, and both the pubkey y and the R y-coordinate are canonical (< p)."""
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    y_pub = int.from_bytes(public_key, "little") & ((1 << 255) - 1)
    y_r = int.from_bytes(sig[:32], "little") & ((1 << 255) - 1)
    return y_pub < P and y_r < P


def verify(public_key: bytes, message: bytes, sig: bytes) -> bool:
    """Go-exact single verify. Fast path through OpenSSL when inputs are canonical."""
    if len(public_key) != 32 or len(sig) != 64 or sig[63] & 224 != 0:
        return False
    if _HAVE_CRYPTOGRAPHY and _in_canonical_zone(public_key, sig):
        try:
            Ed25519PublicKey.from_public_bytes(public_key).verify(sig, message)
            return True
        except InvalidSignature:
            return False
        except ValueError:
            # e.g. pubkey decompression failure — fall back to oracle semantics
            return _verify_pure(public_key, message, sig)
    return _verify_pure(public_key, message, sig)


def sign(private_key: bytes, message: bytes) -> bytes:
    """RFC 8032 sign; private_key is the 64-byte Go layout (seed || pubkey)."""
    if len(private_key) != 64:
        raise ValueError("ed25519 private key must be 64 bytes (seed || pubkey)")
    seed = private_key[:32]
    if _HAVE_CRYPTOGRAPHY:
        return Ed25519PrivateKey.from_private_bytes(seed).sign(message)
    return _sign_pure(seed, message)


def _sign_pure(seed: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    A_enc = pt_encode(pt_scalar_mult(B_EXT, a))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % L
    R_enc = pt_encode(pt_scalar_mult(B_EXT, r))
    k = int.from_bytes(hashlib.sha512(R_enc + A_enc + message).digest(), "little") % L
    s = (r + k * a) % L
    return R_enc + s.to_bytes(32, "little")


def pubkey_from_seed(seed: bytes) -> bytes:
    if _HAVE_CRYPTOGRAPHY:
        from cryptography.hazmat.primitives import serialization

        return (
            Ed25519PrivateKey.from_private_bytes(seed)
            .public_key()
            .public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw
            )
        )
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return pt_encode(pt_scalar_mult(B_EXT, a))


def gen_privkey(seed: Optional[bytes] = None) -> bytes:
    """64-byte private key (seed || pubkey), mirroring Go's NewKeyFromSeed layout."""
    if seed is None:
        seed = os.urandom(32)
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    return seed + pubkey_from_seed(seed)
