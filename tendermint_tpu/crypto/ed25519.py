"""Host-side Ed25519 with the *exact* accept/reject semantics of the Go reference.

The reference (crypto/ed25519/ed25519.go:151) delegates to golang.org/x/crypto/ed25519,
whose Verify has several non-RFC-8032 quirks that define our bit-exactness contract
(BASELINE.md "accept/reject parity"):

  * only the top 3 bits of s are checked (``sig[63]&224 != 0`` rejects), so scalars
    s in [L, 2^253) are ACCEPTED — stricter libraries (OpenSSL) reject them;
  * point decompression loads y as a 255-bit little-endian integer reduced mod p —
    non-canonical encodings (y >= p) are ACCEPTED;
  * the final check is a raw 32-byte comparison of the canonical encoding of
    R' = [s]B - [h]A against sig[:32] (so a non-canonical R in the signature can
    only match itself, never the canonical re-encoding).

This module provides:
  * ``verify`` — the oracle implementing exactly the above (pure-python bigint path,
    with a fast-path through the `cryptography` package when inputs are in the
    canonical zone where both libraries agree);
  * ``sign`` / key generation — RFC 8032 standard (identical to Go's Sign);
  * curve constants and reference point arithmetic reused by tests of the TPU kernel
    (tendermint_tpu/ops/ed25519_verify.py).

Key layout mirrors the reference: PrivKey = 64 bytes (seed || pubkey),
PubKey = 32 bytes, Signature = 64 bytes, Address = SHA256(pubkey)[:20]
(crypto/ed25519/ed25519.go:138, crypto/tmhash/hash.go:62).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

try:  # fast host path for sign + canonical-zone verify
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_CRYPTOGRAPHY = True
except Exception:  # pragma: no cover
    _HAVE_CRYPTOGRAPHY = False

# ---------------------------------------------------------------------------
# Curve constants (edwards25519: -x^2 + y^2 = 1 + d x^2 y^2 over GF(2^255-19))
# ---------------------------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # resolved below


def _decompress_xy(s: bytes) -> Optional[Tuple[int, int]]:
    """Mirror of Go's ExtendedGroupElement.FromBytes: returns affine (x, y) or None.

    Accepts non-canonical y (reduced mod p); sign bit selects the x parity.
    """
    y_raw = int.from_bytes(s, "little")
    sign = (y_raw >> 255) & 1
    y = (y_raw & ((1 << 255) - 1)) % P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root x = u v^3 (u v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow((u * pow(v, 7, P)) % P, (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if (vxx - u) % P != 0:
        if (vxx + u) % P != 0:
            return None
        x = (x * SQRT_M1) % P
    if (x & 1) != sign:
        x = (P - x) % P
    return (x, y)


_B_PT = _decompress_xy(_BY.to_bytes(32, "little"))
assert _B_PT is not None
# base point B: y = 4/5, x even (sign bit clear in the canonical encoding)
B_AFFINE = _B_PT[0]
del _B_PT

# ---------------------------------------------------------------------------
# Extended-coordinate point arithmetic with the complete addition law.
# (a = -1 is a square mod p and d is non-square, so the law is complete for
#  every point on the curve, including low-order/adversarial points.)
# ---------------------------------------------------------------------------

# point = (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z
IDENT = (0, 1, 1, 0)


def _to_extended(pt: Tuple[int, int]) -> Tuple[int, int, int, int]:
    x, y = pt
    return (x, y, 1, (x * y) % P)


def pt_add(p1, p2):
    """add-2008-hwcd-3 (complete for a=-1, d non-square)."""
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = ((Y1 - X1) * (Y2 - X2)) % P
    Bv = ((Y1 + X1) * (Y2 + X2)) % P
    C = (T1 * D2 % P) * T2 % P
    Dv = (Z1 * 2 * Z2) % P
    E = (Bv - A) % P
    F = (Dv - C) % P
    G = (Dv + C) % P
    H = (Bv + A) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def pt_double(p1):
    """dbl-2008-hwcd, valid for all inputs."""
    X1, Y1, Z1, _ = p1
    A = (X1 * X1) % P
    Bv = (Y1 * Y1) % P
    C = (2 * Z1 * Z1) % P
    H = (A + Bv) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - Bv) % P
    F = (C + G) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def pt_scalar_mult(pt, k: int):
    acc = IDENT
    base = pt
    while k:
        if k & 1:
            acc = pt_add(acc, base)
        base = pt_double(base)
        k >>= 1
    return acc


def pt_encode(p1) -> bytes:
    X, Y, Z, _ = p1
    zi = pow(Z, P - 2, P)
    x = (X * zi) % P
    y = (Y * zi) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


B_EXT = _to_extended((B_AFFINE, _BY))

# ---------------------------------------------------------------------------
# Verify / sign
# ---------------------------------------------------------------------------


def _verify_pure(public_key: bytes, message: bytes, sig: bytes) -> bool:
    """Literal mirror of golang.org/x/crypto/ed25519.Verify."""
    if len(public_key) != 32 or len(sig) != 64:
        return False
    if sig[63] & 224 != 0:
        return False
    A = _decompress_xy(public_key)
    if A is None:
        return False
    # negate A (Go negates X and T after FromBytes)
    neg_a = ((P - A[0]) % P, A[1])
    h = int.from_bytes(
        hashlib.sha512(sig[:32] + public_key + message).digest(), "little"
    ) % L
    s = int.from_bytes(sig[32:], "little")
    r_check = pt_add(
        pt_scalar_mult(_to_extended(neg_a), h), pt_scalar_mult(B_EXT, s)
    )
    return pt_encode(r_check) == sig[:32]


def _in_canonical_zone(public_key: bytes, sig: bytes) -> bool:
    """True when stricter RFC-8032 verifiers (OpenSSL) agree with the Go semantics:
    s < L, and both the pubkey y and the R y-coordinate are canonical (< p)."""
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    y_pub = int.from_bytes(public_key, "little") & ((1 << 255) - 1)
    y_r = int.from_bytes(sig[:32], "little") & ((1 << 255) - 1)
    return y_pub < P and y_r < P


def verify(public_key: bytes, message: bytes, sig: bytes) -> bool:
    """Go-exact single verify. Fast path through OpenSSL when inputs are canonical."""
    if len(public_key) != 32 or len(sig) != 64 or sig[63] & 224 != 0:
        return False
    if _HAVE_CRYPTOGRAPHY and _in_canonical_zone(public_key, sig):
        try:
            Ed25519PublicKey.from_public_bytes(public_key).verify(sig, message)
            return True
        except InvalidSignature:
            return False
        except ValueError:
            # e.g. pubkey decompression failure — fall back to oracle semantics
            return _verify_pure(public_key, message, sig)
    return _verify_pure(public_key, message, sig)


def _is_identity(pt) -> bool:
    X, Y, Z, _ = pt
    return X % P == 0 and (Y - Z) % P == 0


# [d * 2^(8w)]B for every window w and byte digit d — makes any [k]B cost
# <= 32 additions with zero doublings.  ~8k point ops to build, built once
# per process the first time a batch verification runs.
_B_TABLE = None


def _b_table():
    global _B_TABLE
    if _B_TABLE is None:
        table = []
        base = B_EXT
        for _ in range(32):
            row = [None] * 256
            acc = base
            for d in range(1, 256):
                row[d] = acc
                acc = pt_add(acc, base)
            table.append(row)
            base = acc  # [256 * 2^(8w)]B == [2^(8(w+1))]B
        _B_TABLE = table
    return _B_TABLE


def _mul_b(k: int):
    """[k]B off the precomputed window table (k reduced mod L upstream)."""
    table = _b_table()
    acc = None
    w = 0
    while k:
        d = k & 0xFF
        if d:
            p = table[w][d]
            acc = p if acc is None else pt_add(acc, p)
        k >>= 8
        w += 1
    return IDENT if acc is None else acc


def _msm(pairs):
    """Pippenger multi-scalar multiplication: sum of [k]P over (k, P) pairs.

    Bucket width picked from the pair count; scalars of different widths
    (128-bit RLC coefficients vs 252-bit hash scalars) only pay for the
    windows they occupy.  Bucket aggregation multiplies the running sum
    across gaps of empty buckets instead of walking them one by one, so
    sparse windows (small batches) stay cheap."""
    pairs = [(k, p) for k, p in pairs if k]
    if not pairs:
        return IDENT
    n = len(pairs)
    c = 4 if n < 32 else 5 if n < 128 else 6 if n < 512 else 7 if n < 2048 else 8
    maxbits = max(k.bit_length() for k, _ in pairs)
    nwin = (maxbits + c - 1) // c
    mask = (1 << c) - 1
    acc = IDENT
    for w in range(nwin - 1, -1, -1):
        if not _is_identity(acc):
            for _ in range(c):
                acc = pt_double(acc)
        shift = w * c
        buckets = {}
        for k, p in pairs:
            d = (k >> shift) & mask
            if d:
                b = buckets.get(d)
                buckets[d] = p if b is None else pt_add(b, p)
        if not buckets:
            continue
        # window_sum = sum(d * bucket[d]); running-sum over the nonzero
        # buckets in descending d, bridging gaps with [gap]running
        running = None
        window_sum = None
        prev_d = None
        for d in sorted(buckets, reverse=True):
            if running is not None:
                gap = prev_d - d
                stride = running if gap == 1 else pt_scalar_mult(running, gap)
                window_sum = (stride if window_sum is None
                              else pt_add(window_sum, stride))
            running = (buckets[d] if running is None
                       else pt_add(running, buckets[d]))
            prev_d = d
        stride = running if prev_d == 1 else pt_scalar_mult(running, prev_d)
        window_sum = stride if window_sum is None else pt_add(window_sum, stride)
        acc = window_sum if _is_identity(acc) else pt_add(acc, window_sum)
    return acc


def _rlc_holds(parsed) -> bool:
    """One random-linear-combination check over pre-parsed signatures:
    sum_i z_i ([s_i]B - [h_i]A_i - R_i) == identity with random 128-bit
    z_i drawn after the signatures are fixed.  The shared base point rides
    the window table as a single [sum z_i s_i]B, not an MSM column."""
    s_b = 0
    pairs = []
    for _, neg_a, neg_r, h, s in parsed:
        z = int.from_bytes(os.urandom(16), "little") or 1
        s_b = (s_b + z * s) % L
        pairs.append(((z * h) % L, neg_a))
        pairs.append((z, neg_r))
    acc = _msm(pairs)
    return _is_identity(pt_add(acc, _mul_b(s_b)))


def _leaf_verify(item) -> bool:
    """Exact single-signature check for a parsed item: [s]B - [h]A == R as
    group elements.  Parsing already pinned R's encoding to its canonical
    bytes, where group equality coincides with Go's encoded-byte compare;
    the B term rides the window table and the compare cross-multiplies, so
    a leaf costs roughly half a full serial verify."""
    _, neg_a, neg_r, h, s = item
    t = pt_add(_mul_b(s), pt_scalar_mult(neg_a, h))
    x_r, y_r = (P - neg_r[0]) % P, neg_r[1]
    X, Y, Z, _ = t
    return (X - x_r * Z) % P == 0 and (Y - y_r * Z) % P == 0


_CHUNK = 32  # localization chunk: one failed RLC re-checks N/32 groups


def _resolve_batch(parsed, out) -> None:
    """Verdict strategy tuned for the vote-storm shape: almost always the
    whole flush is clean (one RLC), occasionally a few bad signatures hide
    in it.  On failure, chunk RLCs localize the dirty spans in one more
    sweep and only their members pay an exact leaf check — plain bisection
    re-pays the full MSM per level and measures slower than serial once a
    few percent of lanes are bad."""
    if not parsed:
        return
    if _rlc_holds(parsed):
        for item in parsed:
            out[item[0]] = True
        return
    for lo in range(0, len(parsed), _CHUNK):
        chunk = parsed[lo: lo + _CHUNK]
        if len(chunk) > 4 and _rlc_holds(chunk):
            for item in chunk:
                out[item[0]] = True
            continue
        for item in chunk:
            out[item[0]] = _leaf_verify(item)


# negated-A extended points keyed by raw pubkey bytes (None = key does not
# decompress).  A validator's key is decompressed once per process, not once
# per flush — at 0.15ms per decompression that is a measurable slice of a
# clean flush.  Points are immutable tuples, so sharing across threads and
# batches is safe; the bound just caps a pathological stream of fresh keys.
_A_NEG_CACHE: dict = {}
_A_NEG_CACHE_MAX = 16384


def _parse_batch(items, compute_h: bool = True) -> Tuple[list, list]:
    """Parse [(public_key, message, sig), ...] into RLC-ready rows with the
    Go accept/reject edges applied on the host: rows with bad lengths, a set
    top-3-bit in s, an undecompressable A or R, or a non-canonical R
    encoding stay False in ``out`` (exactly as ``verify`` rejects them) and
    never reach the MSM.  Returns ``(parsed, out)`` where ``parsed`` holds
    ``(i, neg_a, neg_r, h, s)`` extended-point rows and ``out`` is the
    all-False verdict list the resolver scatters into.  ``compute_h=False``
    leaves h as 0 for callers that hash on-device (the Pallas SHA-512
    prologue) and substitute their own values."""
    out = [False] * len(items)
    parsed = []
    a_cache = _A_NEG_CACHE  # validators recur across votes, rounds AND flushes
    if len(a_cache) > _A_NEG_CACHE_MAX:
        a_cache.clear()
    for i, (pub, msg, sig) in enumerate(items):
        pub, sig = bytes(pub), bytes(sig)
        if len(pub) != 32 or len(sig) != 64 or sig[63] & 224 != 0:
            continue
        if pub in a_cache:
            neg_a = a_cache[pub]
        else:
            A = _decompress_xy(pub)
            neg_a = None if A is None else _to_extended(
                ((P - A[0]) % P, A[1]))
            a_cache[pub] = neg_a
        if neg_a is None:
            continue
        R = _decompress_xy(sig[:32])
        if R is None:
            continue
        # Go's final check is a raw byte compare against the CANONICAL
        # re-encoding of R' — an R encoding that differs from its own
        # canonical form (y >= p, or a stray sign bit on x == 0) can never
        # match, whatever the curve math says
        if (R[1] | ((R[0] & 1) << 255)).to_bytes(32, "little") != sig[:32]:
            continue
        if compute_h:
            h = int.from_bytes(
                hashlib.sha512(sig[:32] + pub + bytes(msg)).digest(), "little"
            ) % L
        else:
            h = 0
        s = int.from_bytes(sig[32:], "little") % L  # [s]B == [s mod L]B
        neg_r = _to_extended(((P - R[0]) % P, R[1]))
        parsed.append((i, neg_a, neg_r, h, s))
    return parsed, out


def verify_batch(items) -> list:
    """Batch verification of [(public_key, message, sig), ...] with the
    same accept/reject semantics as ``verify`` on every element.

    One random-linear-combination + Pippenger multi-scalar multiplication
    costs ~10x fewer point operations per signature than independent
    verifies, which is the whole throughput story of the vote micro-batch
    on hosts without an accelerator or OpenSSL.  Invalid signatures are
    localized by recursive bisection, so per-item verdicts are exact (a
    false accept needs a 2^-128 RLC collision).  When the `cryptography`
    fast path is available it wins per-signature and we just ride it."""
    if _HAVE_CRYPTOGRAPHY:
        return [verify(p, m, s) for p, m, s in items]
    parsed, out = _parse_batch(items)
    _resolve_batch(parsed, out)
    return out


def sign(private_key: bytes, message: bytes) -> bytes:
    """RFC 8032 sign; private_key is the 64-byte Go layout (seed || pubkey)."""
    if len(private_key) != 64:
        raise ValueError("ed25519 private key must be 64 bytes (seed || pubkey)")
    seed = private_key[:32]
    if _HAVE_CRYPTOGRAPHY:
        return Ed25519PrivateKey.from_private_bytes(seed).sign(message)
    return _sign_pure(seed, message)


def _sign_pure(seed: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    A_enc = pt_encode(pt_scalar_mult(B_EXT, a))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(), "little") % L
    R_enc = pt_encode(pt_scalar_mult(B_EXT, r))
    k = int.from_bytes(hashlib.sha512(R_enc + A_enc + message).digest(), "little") % L
    s = (r + k * a) % L
    return R_enc + s.to_bytes(32, "little")


def pubkey_from_seed(seed: bytes) -> bytes:
    if _HAVE_CRYPTOGRAPHY:
        from cryptography.hazmat.primitives import serialization

        return (
            Ed25519PrivateKey.from_private_bytes(seed)
            .public_key()
            .public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw
            )
        )
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return pt_encode(pt_scalar_mult(B_EXT, a))


def gen_privkey(seed: Optional[bytes] = None) -> bytes:
    """64-byte private key (seed || pubkey), mirroring Go's NewKeyFromSeed layout."""
    if seed is None:
        seed = os.urandom(32)
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    return seed + pubkey_from_seed(seed)
