"""Pure-python secp256k1 ECDSA matching the reference's btcec semantics.

Reference crypto/secp256k1/secp256k1.go:
  * Sign: deterministic-k (RFC 6979) ECDSA over SHA256(msg), serialized as DER,
    with the canonical low-s rule (btcec forces s <= N/2);
  * VerifyBytes: parse compressed pubkey + DER signature, reject non-canonical
    (high-s) signatures, verify over SHA256(msg).

This is the host oracle / non-hot path; batched TPU ecrecover-style verification
is a later ops/ kernel (BASELINE.json configs[3]).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional, Tuple

# curve parameters
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_HALF_N = N // 2


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


# Jacobian coordinates for speed
def _jadd(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return None  # point at infinity
        return _jdouble(p1)
    H = (U2 - U1) % P
    R = (S2 - S1) % P
    HH = H * H % P
    HHH = H * HH % P
    V = U1 * HH % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - S1 * HHH) % P
    Z3 = H * Z1 * Z2 % P
    return (X3, Y3, Z3)


def _jdouble(p1):
    if p1 is None:
        return None
    X1, Y1, Z1 = p1
    if Y1 == 0:
        return None
    YY = Y1 * Y1 % P
    S = 4 * X1 * YY % P
    M = 3 * X1 * X1 % P  # a = 0
    X3 = (M * M - 2 * S) % P
    Y3 = (M * (S - X3) - 8 * YY * YY) % P
    Z3 = 2 * Y1 * Z1 % P
    return (X3, Y3, Z3)


def _jmul(point, k: int):
    acc = None
    base = point
    while k:
        if k & 1:
            acc = _jadd(acc, base)
        base = _jdouble(base)
        k >>= 1
    return acc


def _to_affine(p1) -> Optional[Tuple[int, int]]:
    if p1 is None:
        return None
    X, Y, Z = p1
    zi = _inv(Z, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


_G = (Gx, Gy, 1)


def decompress_pubkey(data: bytes) -> Optional[Tuple[int, int]]:
    if len(data) != 33 or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


def compress_point(x: int, y: int) -> bytes:
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def pubkey_compressed(privkey: bytes) -> bytes:
    d = int.from_bytes(privkey, "big")
    if not 0 < d < N:
        raise ValueError("invalid secp256k1 private key")
    x, y = _to_affine(_jmul(_G, d))
    return compress_point(x, y)


def gen_privkey(seed: bytes | None = None) -> bytes:
    while True:
        cand = seed if seed is not None else os.urandom(32)
        seed = None
        d = int.from_bytes(cand, "big")
        if 0 < d < N:
            return cand


def privkey_from_secret(secret: bytes) -> bytes:
    """reference GenPrivKeySecp256k1: SHA256(secret), with validity fixup."""
    cand = hashlib.sha256(secret).digest()
    return gen_privkey(cand)


# ---------------------------------------------------------------------------
# RFC 6979 deterministic nonce
# ---------------------------------------------------------------------------


def _rfc6979_k(privkey: bytes, digest: bytes) -> int:
    holen = 32
    x = privkey
    h1 = digest
    V = b"\x01" * holen
    K = b"\x00" * holen
    K = hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 0 < k < N:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


# ---------------------------------------------------------------------------
# DER encode/decode (strict, as btcec emits/parses)
# ---------------------------------------------------------------------------


def _der_int(v: int) -> bytes:
    b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if b[0] & 0x80:
        b = b"\x00" + b
    return b"\x02" + bytes([len(b)]) + b


def der_encode_sig(r: int, s: int) -> bytes:
    body = _der_int(r) + _der_int(s)
    return b"\x30" + bytes([len(body)]) + body


def der_decode_sig(sig: bytes) -> Optional[Tuple[int, int]]:
    try:
        if len(sig) < 8 or sig[0] != 0x30 or sig[1] != len(sig) - 2:
            return None
        i = 2
        if sig[i] != 0x02:
            return None
        rl = sig[i + 1]
        r = int.from_bytes(sig[i + 2 : i + 2 + rl], "big")
        i += 2 + rl
        if i >= len(sig) or sig[i] != 0x02:
            return None
        sl = sig[i + 1]
        if i + 2 + sl != len(sig):
            return None
        s = int.from_bytes(sig[i + 2 :], "big")
        return (r, s)
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# sign / verify
# ---------------------------------------------------------------------------


def sign(privkey: bytes, digest: bytes) -> bytes:
    """ECDSA over a 32-byte digest; deterministic k; low-s canonical; DER."""
    d = int.from_bytes(privkey, "big")
    if not 0 < d < N:
        raise ValueError("invalid secp256k1 private key")
    e = int.from_bytes(digest, "big")
    while True:
        k = _rfc6979_k(privkey, digest)
        R = _to_affine(_jmul(_G, k))
        r = R[0] % N
        if r == 0:
            digest = hashlib.sha256(digest).digest()
            continue
        s = _inv(k, N) * (e + r * d) % N
        if s == 0:
            digest = hashlib.sha256(digest).digest()
            continue
        if s > _HALF_N:  # canonical low-s (btcec)
            s = N - s
        return der_encode_sig(r, s)


def verify(pubkey: bytes, digest: bytes, sig: bytes) -> bool:
    Q = decompress_pubkey(pubkey)
    if Q is None:
        return False
    parsed = der_decode_sig(sig)
    if parsed is None:
        return False
    r, s = parsed
    if not (0 < r < N and 0 < s < N):
        return False
    if s > _HALF_N:  # reject non-canonical high-s (malleability)
        return False
    e = int.from_bytes(digest, "big")
    w = _inv(s, N)
    u1 = e * w % N
    u2 = r * w % N
    pt = _jadd(_jmul(_G, u1), _jmul((Q[0], Q[1], 1), u2))
    aff = _to_affine(pt)
    if aff is None:
        return False
    return aff[0] % N == r
