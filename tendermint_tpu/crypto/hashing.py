"""Host-side hash functions.

Mirrors reference crypto/tmhash/hash.go (SHA-256 with 20-byte truncated form) and the
RIPEMD160 use in crypto/secp256k1/secp256k1.go:121 (bitcoin-style addresses).
Hot batched hashing lives on TPU in tendermint_tpu/ops; these are the host oracles.
"""

from __future__ import annotations

import hashlib
import struct

HASH_SIZE = 32
TRUNCATED_SIZE = 20  # reference crypto/tmhash/hash.go:27


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def tmhash(data: bytes) -> bytes:
    """reference crypto/tmhash/hash.go:19 Sum — full SHA-256."""
    return hashlib.sha256(data).digest()


def tmhash_truncated(data: bytes) -> bytes:
    """reference crypto/tmhash/hash.go:62 SumTruncated — first 20 bytes of SHA-256."""
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]


# ---------------------------------------------------------------------------
# RIPEMD160 — pure-python fallback; OpenSSL 3 ships it behind the legacy
# provider so hashlib.new('ripemd160') often raises. Needed only for
# secp256k1 bitcoin-style addresses (not a hot path).
# ---------------------------------------------------------------------------

def _has_openssl_ripemd() -> bool:
    try:
        hashlib.new("ripemd160")
        return True
    except Exception:
        return False


_HAS_OPENSSL_RIPEMD = _has_openssl_ripemd()


def ripemd160(data: bytes) -> bytes:
    if _HAS_OPENSSL_RIPEMD:
        h = hashlib.new("ripemd160")
        h.update(data)
        return h.digest()
    return _ripemd160_py(data)


# -- pure python RIPEMD-160 (spec: Dobbertin, Bosselaers, Preneel 1996) -----

_RL = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8],
    [3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12],
    [1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2],
    [4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13],
]
_RR = [
    [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12],
    [6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2],
    [15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13],
    [8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14],
    [12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11],
]
_SL = [
    [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8],
    [7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12],
    [11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5],
    [11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12],
    [9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6],
]
_SR = [
    [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6],
    [9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11],
    [9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5],
    [15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8],
    [8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11],
]
_KL = [0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E]
_KR = [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000]


def _rol(x: int, n: int) -> int:
    x &= 0xFFFFFFFF
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _rmd_f(j: int, x: int, y: int, z: int) -> int:
    if j == 0:
        return x ^ y ^ z
    if j == 1:
        return (x & y) | (~x & z)
    if j == 2:
        return (x | ~y) ^ z
    if j == 3:
        return (x & z) | (y & ~z)
    return x ^ (y | ~z)


def _ripemd160_py(data: bytes) -> bytes:
    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    # padding, little-endian bit length
    msg = data + b"\x80"
    while len(msg) % 64 != 56:
        msg += b"\x00"
    msg += struct.pack("<Q", (8 * len(data)) & 0xFFFFFFFFFFFFFFFF)
    for off in range(0, len(msg), 64):
        x = struct.unpack("<16I", msg[off : off + 64])
        al, bl, cl, dl, el = h
        ar, br, cr, dr, er = h
        for rnd in range(5):
            for i in range(16):
                t = _rol(
                    (al + _rmd_f(rnd, bl, cl, dl) + x[_RL[rnd][i]] + _KL[rnd]) & 0xFFFFFFFF,
                    _SL[rnd][i],
                ) + el
                al, el, dl, cl, bl = el, dl, _rol(cl, 10), bl, t & 0xFFFFFFFF
                t = _rol(
                    (ar + _rmd_f(4 - rnd, br, cr, dr) + x[_RR[rnd][i]] + _KR[rnd]) & 0xFFFFFFFF,
                    _SR[rnd][i],
                ) + er
                ar, er, dr, cr, br = er, dr, _rol(cr, 10), br, t & 0xFFFFFFFF
        t = (h[1] + cl + dr) & 0xFFFFFFFF
        h[1] = (h[2] + dl + er) & 0xFFFFFFFF
        h[2] = (h[3] + el + ar) & 0xFFFFFFFF
        h[3] = (h[4] + al + br) & 0xFFFFFFFF
        h[4] = (h[0] + bl + cr) & 0xFFFFFFFF
        h[0] = t
    return struct.pack("<5I", *h)
