"""Pure-Python fallback primitives for the Station-to-Station transport.

The p2p secret connection (`p2p/conn/secret_connection.py`) and the
XChaCha20-Poly1305 AEAD normally ride the `cryptography` package's C
implementations.  Environments without that wheel (minimal containers, the
simulation harness's CI image) previously lost the whole encrypted
transport to an ImportError; this module supplies drop-in replacements for
exactly the surface those callers use:

* ``X25519PrivateKey`` / ``X25519PublicKey`` — RFC 7748 curve25519
  Diffie-Hellman (Montgomery ladder over GF(2^255-19));
* ``ChaCha20Poly1305`` — RFC 8439 AEAD (ChaCha20 stream cipher keyed
  Poly1305 one-time MAC, IETF 96-bit nonce);
* ``HKDF`` + ``hashes.SHA256`` — RFC 5869 extract-and-expand over the
  stdlib's hmac/hashlib;
* ``InvalidTag`` — raised on AEAD authentication failure, mirroring
  ``cryptography.exceptions.InvalidTag``.

Everything here is validated against the RFC test vectors in
``tests/test_sts_fallback.py``.  Python-speed crypto is 2-3 orders of
magnitude slower than the C path — fine for a handshake and for tests, not
for a production data plane; callers keep preferring `cryptography` when
it is importable.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct

__all__ = [
    "ChaCha20Poly1305",
    "HKDF",
    "InvalidTag",
    "X25519PrivateKey",
    "X25519PublicKey",
    "hashes",
    "x25519_scalarmult",
]


class InvalidTag(Exception):
    """AEAD authentication failed (ciphertext or AAD was tampered with)."""


# ---------------------------------------------------------------------------
# X25519 (RFC 7748 §5)
# ---------------------------------------------------------------------------

_P = 2**255 - 19
_A24 = 121665
_BASE_POINT = (9).to_bytes(32, "little")


def _clamp_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("X25519 scalar must be 32 bytes")
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("X25519 u-coordinate must be 32 bytes")
    # mask the unused top bit (RFC 7748 §5: "MUST mask the most significant
    # bit of the final byte")
    b = bytearray(u)
    b[31] &= 127
    return int.from_bytes(bytes(b), "little")


def x25519_scalarmult(k: bytes, u: bytes) -> bytes:
    """RFC 7748 X25519(k, u): constant-structure Montgomery ladder."""
    k_int = _clamp_scalar(k)
    x1 = _decode_u(u) % _P
    x2, z2 = 1, 0
    x3, z3 = x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k_int >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = da + cb
        x3 = x3 * x3 % _P
        z3 = da - cb
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


class X25519PublicKey:
    """Mirrors cryptography's X25519PublicKey surface used by the STS code."""

    def __init__(self, data: bytes):
        if len(data) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._data = bytes(data)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._data


class X25519PrivateKey:
    def __init__(self, data: bytes):
        if len(data) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._data = bytes(data)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        return cls(data)

    def private_bytes_raw(self) -> bytes:
        return self._data

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(x25519_scalarmult(self._data, _BASE_POINT))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        shared = x25519_scalarmult(
            self._data, peer_public_key.public_bytes_raw()
        )
        # contributory-behavior check, same as cryptography/OpenSSL: a
        # small-order peer point yields the all-zero secret
        if not any(shared):
            raise ValueError("X25519 exchange produced an all-zero secret")
        return shared


# ---------------------------------------------------------------------------
# ChaCha20 (RFC 8439 §2.3) and Poly1305 (§2.5)
# ---------------------------------------------------------------------------

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_MASK = 0xFFFFFFFF


def chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    """One 64-byte ChaCha20 keystream block (RFC 8439 §2.3)."""
    if len(key) != 32:
        raise ValueError("chacha20: key must be 32 bytes")
    if len(nonce) != 12:
        raise ValueError("chacha20: nonce must be 12 bytes")
    init = list(_SIGMA) + list(struct.unpack("<8I", key)) + [
        counter & _MASK
    ] + list(struct.unpack("<3I", nonce))
    v = init[:]

    def qr(a, b, c, d):
        v[a] = (v[a] + v[b]) & _MASK
        x = v[d] ^ v[a]
        v[d] = ((x << 16) | (x >> 16)) & _MASK
        v[c] = (v[c] + v[d]) & _MASK
        x = v[b] ^ v[c]
        v[b] = ((x << 12) | (x >> 20)) & _MASK
        v[a] = (v[a] + v[b]) & _MASK
        x = v[d] ^ v[a]
        v[d] = ((x << 8) | (x >> 24)) & _MASK
        v[c] = (v[c] + v[d]) & _MASK
        x = v[b] ^ v[c]
        v[b] = ((x << 7) | (x >> 25)) & _MASK

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    return struct.pack("<16I", *((v[i] + init[i]) & _MASK for i in range(16)))


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    """Encrypt/decrypt `data` with the keystream starting at `counter`."""
    out = bytearray(len(data))
    for block_i in range((len(data) + 63) // 64):
        ks = chacha20_block(key, counter + block_i, nonce)
        off = block_i * 64
        chunk = data[off : off + 64]
        out[off : off + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, ks)
        )
    return bytes(out)


_P1305 = (1 << 130) - 5


def poly1305_mac(key: bytes, msg: bytes) -> bytes:
    """RFC 8439 §2.5 one-time authenticator; `key` is r||s (32 bytes)."""
    if len(key) != 32:
        raise ValueError("poly1305: key must be 32 bytes")
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block + b"\x01", "little")
        acc = (acc + n) * r % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"" if rem == 0 else b"\x00" * (16 - rem)


class ChaCha20Poly1305:
    """RFC 8439 §2.8 AEAD, mirroring cryptography's ChaCha20Poly1305 API."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _mac_key(self, nonce: bytes) -> bytes:
        return chacha20_block(self._key, 0, nonce)[:32]

    def _tag(self, nonce: bytes, aad: bytes, ciphertext: bytes) -> bytes:
        mac_data = (
            aad + _pad16(aad)
            + ciphertext + _pad16(ciphertext)
            + struct.pack("<QQ", len(aad), len(ciphertext))
        )
        return poly1305_mac(self._mac_key(nonce), mac_data)

    def encrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("ChaCha20Poly1305 nonce must be 12 bytes")
        aad = associated_data or b""
        ciphertext = chacha20_xor(self._key, 1, nonce, data)
        return ciphertext + self._tag(nonce, aad, ciphertext)

    def decrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("ChaCha20Poly1305 nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the Poly1305 tag")
        aad = associated_data or b""
        ciphertext, tag = data[:-16], data[-16:]
        expected = self._tag(nonce, aad, ciphertext)
        if not _hmac.compare_digest(tag, expected):
            raise InvalidTag("message authentication failed")
        return chacha20_xor(self._key, 1, nonce, ciphertext)


# ---------------------------------------------------------------------------
# HKDF (RFC 5869) over stdlib hmac/hashlib
# ---------------------------------------------------------------------------

class _SHA256:
    name = "sha256"
    digest_size = 32


class hashes:  # noqa: N801 - mirrors the cryptography module-as-namespace
    SHA256 = _SHA256


class HKDF:
    """RFC 5869 extract-and-expand; only SHA-256 is needed here."""

    def __init__(self, algorithm, length: int, salt, info):
        if getattr(algorithm, "name", None) != "sha256":
            raise ValueError("fallback HKDF supports SHA-256 only")
        if length > 255 * 32:
            raise ValueError("HKDF output length too large")
        self._length = length
        self._salt = salt if salt is not None else b"\x00" * 32
        self._info = info or b""
        self._used = False

    def derive(self, key_material: bytes) -> bytes:
        if self._used:
            raise RuntimeError("HKDF instances can only be used once")
        self._used = True
        prk = _hmac.new(self._salt, key_material, hashlib.sha256).digest()
        okm = b""
        t = b""
        counter = 1
        while len(okm) < self._length:
            t = _hmac.new(
                prk, t + self._info + bytes([counter]), hashlib.sha256
            ).digest()
            okm += t
            counter += 1
        return okm[: self._length]
