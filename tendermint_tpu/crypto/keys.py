"""PubKey/PrivKey interfaces and concrete key types.

Mirrors reference crypto/crypto.go:22-34 (PubKey: Address/Bytes/VerifyBytes/Equals,
PrivKey: Bytes/Sign/PubKey/Equals) with a JSON registry in place of amino routes.
"""

from __future__ import annotations

import base64
import hmac as _hmac
import json
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Dict, Type

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.crypto import secp256k1 as _secp
from tendermint_tpu.crypto.hashing import ripemd160, sha256, tmhash_truncated

ADDRESS_SIZE = 20


class PubKey(ABC):
    type_name: str = ""

    @abstractmethod
    def address(self) -> bytes: ...

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def verify_bytes(self, msg: bytes, sig: bytes) -> bool: ...

    def equals(self, other: "PubKey") -> bool:
        return type(self) is type(other) and _hmac.compare_digest(
            self.bytes(), other.bytes()
        )

    def __eq__(self, other):  # convenience for tests/dict keys
        return isinstance(other, PubKey) and self.equals(other)

    def __hash__(self):
        return hash((self.type_name, self.bytes()))

    # -- JSON round-trip (replaces amino interface encoding) ----------------
    def to_json_obj(self) -> dict:
        return {
            "type": self.type_name,
            "value": base64.b64encode(self.bytes()).decode(),
        }


class PrivKey(ABC):
    type_name: str = ""

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    def equals(self, other: "PrivKey") -> bool:
        return type(self) is type(other) and _hmac.compare_digest(
            self.bytes(), other.bytes()
        )

    def to_json_obj(self) -> dict:
        return {
            "type": self.type_name,
            "value": base64.b64encode(self.bytes()).decode(),
        }


# ---------------------------------------------------------------------------
# Ed25519 (reference crypto/ed25519/ed25519.go)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PubKeyEd25519(PubKey):
    data: bytes  # 32 bytes
    type_name = "tendermint/PubKeyEd25519"

    def __post_init__(self):
        if len(self.data) != 32:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def address(self) -> bytes:
        # reference crypto/ed25519/ed25519.go:138 — SHA256(pubkey)[:20];
        # memoized: address() sits under every valset sort/lookup
        addr = self.__dict__.get("_addr")
        if addr is None:
            addr = tmhash_truncated(self.data)
            object.__setattr__(self, "_addr", addr)
        return addr

    def bytes(self) -> bytes:
        return self.data

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64:
            return False
        return _ed.verify(self.data, msg, sig)

    def __hash__(self):
        return hash(self.data)


@dataclass(frozen=True)
class PrivKeyEd25519(PrivKey):
    data: bytes  # 64 bytes: seed || pubkey
    type_name = "tendermint/PrivKeyEd25519"

    def __post_init__(self):
        if len(self.data) != 64:
            raise ValueError("ed25519 privkey must be 64 bytes")

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        return _ed.sign(self.data, msg)

    def pub_key(self) -> PubKeyEd25519:
        return PubKeyEd25519(self.data[32:])

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKeyEd25519":
        return PrivKeyEd25519(_ed.gen_privkey(seed))

    @staticmethod
    def from_secret(secret: bytes) -> "PrivKeyEd25519":
        """reference GenPrivKeyFromSecret: seed = SHA256(secret)."""
        return PrivKeyEd25519(_ed.gen_privkey(sha256(secret)))


# ---------------------------------------------------------------------------
# secp256k1 (reference crypto/secp256k1/secp256k1.go)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PubKeySecp256k1(PubKey):
    data: bytes  # 33-byte compressed point
    type_name = "tendermint/PubKeySecp256k1"

    def __post_init__(self):
        if len(self.data) != 33:
            raise ValueError("secp256k1 pubkey must be 33 bytes (compressed)")

    def address(self) -> bytes:
        # bitcoin-style: RIPEMD160(SHA256(pubkey)) — secp256k1.go:121
        return ripemd160(sha256(self.data))

    def bytes(self) -> bytes:
        return self.data

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        # message is SHA256-premixed; signature is DER, low-s enforced
        # (secp256k1.go:140-153)
        return _secp.verify(self.data, sha256(msg), sig)

    def __hash__(self):
        return hash(self.data)


@dataclass(frozen=True)
class PrivKeySecp256k1(PrivKey):
    data: bytes  # 32 bytes
    type_name = "tendermint/PrivKeySecp256k1"

    def __post_init__(self):
        if len(self.data) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        # reference signs SHA256(msg) and emits DER (secp256k1.go:58-67)
        return _secp.sign(self.data, sha256(msg))

    def pub_key(self) -> PubKeySecp256k1:
        return PubKeySecp256k1(_secp.pubkey_compressed(self.data))

    @staticmethod
    def generate(seed: bytes | None = None) -> "PrivKeySecp256k1":
        return PrivKeySecp256k1(_secp.gen_privkey(seed))

    @staticmethod
    def from_secret(secret: bytes) -> "PrivKeySecp256k1":
        return PrivKeySecp256k1(_secp.privkey_from_secret(secret))


# ---------------------------------------------------------------------------
# Registry (amino-route replacement)
# ---------------------------------------------------------------------------

_PUBKEY_TYPES: Dict[str, Type[PubKey]] = {
    PubKeyEd25519.type_name: PubKeyEd25519,
    PubKeySecp256k1.type_name: PubKeySecp256k1,
}
_PRIVKEY_TYPES: Dict[str, Type[PrivKey]] = {
    PrivKeyEd25519.type_name: PrivKeyEd25519,
    PrivKeySecp256k1.type_name: PrivKeySecp256k1,
}


def pubkey_from_json_obj(obj: dict) -> PubKey:
    cls = _PUBKEY_TYPES[obj["type"]]
    return cls(base64.b64decode(obj["value"]))


def privkey_from_json_obj(obj: dict) -> PrivKey:
    cls = _PRIVKEY_TYPES[obj["type"]]
    return cls(base64.b64decode(obj["value"]))


def pubkey_to_json(pk: PubKey) -> str:
    return json.dumps(pk.to_json_obj())
