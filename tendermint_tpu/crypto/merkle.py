"""Merkle simple tree + proofs (host path).

Mirrors reference crypto/merkle/simple_tree.go:23 and simple_proof.go:70 in
capability. Structural deviation (documented, intentional — we are not
amino-wire-compatible, SURVEY.md §7.2): we use RFC-6962-style domain separation
(0x00 leaf prefix, 0x01 inner prefix, empty tree = SHA256("")) which prevents
second-preimage attacks that the reference's bare concatenation is exposed to,
and we split at the largest power of two (balanced trees compile better onto the
TPU batched-hash kernel in ops/). Proof verification matches this layout.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"

# Native SHA-256/merkle (crypto/_hash_native.c, SHA-NI when the CPU has it) —
# the whole tree walks in one C call instead of 2n Python-level hash calls.
# Pure-Python definitions below remain the reference implementation/fallback.
from tendermint_tpu.encoding.native import load_ext as _load_ext

_native = _load_ext(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "_hash_native.c"),
    "tendermint_tpu.crypto._hash_native",
)


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _py_leaf_hash(leaf: bytes) -> bytes:
    return _hash(_LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _hash(_INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """largest power of two strictly less than n"""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def _py_hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of a list of byte slices (cf. SimpleHashFromByteSlices)."""
    n = len(items)
    if n == 0:
        return _hash(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(
        _py_hash_from_byte_slices(items[:k]), _py_hash_from_byte_slices(items[k:])
    )


if _native is not None:
    leaf_hash = _native.leaf_hash
    hash_from_byte_slices = _native.merkle_root
else:
    leaf_hash = _py_leaf_hash
    hash_from_byte_slices = _py_hash_from_byte_slices


def hash_from_map(m: dict) -> bytes:
    """Root over sorted key/value pairs (cf. merkle/simple_map.go)."""
    items = [
        leaf_kv(k if isinstance(k, bytes) else str(k).encode(), v)
        for k, v in sorted(m.items())
    ]
    return hash_from_byte_slices(items)


def leaf_kv(key: bytes, value: bytes) -> bytes:
    return len(key).to_bytes(4, "big") + key + value


@dataclass
class SimpleProof:
    """Inclusion proof (cf. reference merkle/simple_proof.go:16)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root(self) -> Optional[bytes]:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total <= 0 or not (0 <= self.index < self.total):
            return False
        if self.leaf_hash != leaf_hash(leaf):
            return False
        return self.compute_root() == root

    def encode(self, w) -> None:
        w.uvarint(self.total).uvarint(self.index).bytes(self.leaf_hash)
        w.uvarint(len(self.aunts))
        for a in self.aunts:
            w.bytes(a)

    MAX_AUNTS = 128  # tree depth bound (2^128 leaves is unreachable); caps
    # attacker-controlled allocation on the gossip decode path

    @classmethod
    def decode(cls, r) -> "SimpleProof":
        total = r.uvarint()
        index = r.uvarint()
        lh = r.bytes()
        n = r.uvarint()
        if n > cls.MAX_AUNTS:
            raise ValueError(f"proof claims {n} aunts (max {cls.MAX_AUNTS})")
        aunts = [r.bytes() for _ in range(n)]
        return cls(total=total, index=index, leaf_hash=lh, aunts=aunts)


def _compute_from_aunts(
    index: int, total: int, lh: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> tuple[bytes, List[SimpleProof]]:
    """Build root + per-leaf proofs (cf. SimpleProofsFromByteSlices)."""
    lhs = (
        _native.leaf_hashes(list(items))
        if _native is not None
        else [leaf_hash(it) for it in items]
    )
    return proofs_from_leaf_hashes(lhs)


def proofs_from_leaf_hashes(lhs: Sequence[bytes]) -> tuple[bytes, List[SimpleProof]]:
    """Root + proofs when the leaf hashes are already computed (the part-set
    path hashes chunks natively straight off the block buffer)."""
    n = len(lhs)
    proofs = [SimpleProof(total=n, index=i, leaf_hash=lhs[i]) for i in range(n)]

    def build(lo: int, hi: int) -> bytes:
        cnt = hi - lo
        if cnt == 0:
            return _hash(b"")
        if cnt == 1:
            return lhs[lo]
        k = _split_point(cnt)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            proofs[i].aunts.append(right)
        for i in range(lo + k, hi):
            proofs[i].aunts.append(left)
        return inner_hash(left, right)

    root = build(0, n)
    # aunts were appended root-last during recursion unwinding; they are built
    # leaf-up already because recursion appends at each level after subcalls.
    return root, proofs
