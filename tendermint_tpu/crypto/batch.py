"""The BatchVerifier boundary — the seam where bulk signature verification leaves
the host control plane and lands on TPU.

The reference (v0.26.2) has NO batch interface; its one call-site shape is
``PubKey.VerifyBytes(msg, sig) bool`` (crypto/crypto.go:22-27), invoked serially
from types/validator_set.go:281-296 (commit verify), types/vote.go:102 (per-vote),
state/validation.go:102 and blockchain/reactor.go:306 (fast sync). This module
introduces the batch boundary those call sites feed (SURVEY.md north star):
callers collect (pubkey, msg, sig) tuples for a height — or a whole fast-sync
window of heights — and dispatch them in ONE call.

Backends:
  * HostBatchVerifier  — serial host loop (CPU oracle; always available).
  * TPUBatchVerifier   — device path. On a real TPU it dispatches the fused
    Pallas pipeline (ops/ed25519_pallas); on CPU or when a mesh is given it
    uses the portable XLA kernel (ops/ed25519_verify, shard_map-able).
    Non-ed25519 items (secp256k1, multisig) fall back to host.

Accept/reject is bit-exact across backends (tests/test_ops_ed25519.py).
"""

from __future__ import annotations

import threading
import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.crypto.keys import PubKey, PubKeyEd25519
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.metrics import get_verify_metrics


def _record_dispatch(backend: str, algo: str, n: int, t0: float, ok,
                     first: bool = False) -> None:
    """One VerifyMetrics record per batch dispatch (size, latency, rejects).
    Telemetry must never take down the verify path."""
    try:
        get_verify_metrics().record_dispatch(
            backend, algo, n, time.perf_counter() - t0,
            rejects=n - int(np.count_nonzero(ok)), first=first,
        )
    except Exception:
        pass


class SigItem(NamedTuple):
    """One signature-verification work item. (NamedTuple, not dataclass:
    tens of thousands are created per fast-sync window and tuple
    construction is several times cheaper.)"""

    pubkey: bytes  # raw 32-byte ed25519 key (or PubKey for generic items)
    msg: bytes
    sig: bytes


class HostBatchVerifier:
    """Serial host verification — the oracle backend."""

    name = "host"

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host", algo="ed25519",
                        n=len(items)):
            ok = np.array(
                [_ed.verify(it.pubkey, it.msg, it.sig) for it in items],
                dtype=bool,
            )
        _record_dispatch("host", "ed25519", len(items), t0, ok)
        return ok

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        """Parallel-sequence form of verify_ed25519 — the hot callers
        (verify_generic's homogeneous fast path) already hold the three
        columns, and building |window|x|valset| SigItems was a measured
        slice of the fast-sync host ceiling."""
        t0 = time.perf_counter()
        verify = _ed.verify
        with trace.span("verify.dispatch", backend="host", algo="ed25519",
                        n=len(pubs)):
            ok = np.fromiter(
                (verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)),
                dtype=bool, count=len(pubs),
            )
        _record_dispatch("host", "ed25519", len(pubs), t0, ok)
        return ok

    def verify_secp256k1(self, items: Sequence[SigItem]) -> np.ndarray:
        """items carry (33B compressed pubkey, RAW msg, DER sig); the SHA-256
        premix (secp256k1.go:140) happens here."""
        from tendermint_tpu.crypto import secp256k1 as _secp
        from tendermint_tpu.crypto.hashing import sha256

        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host", algo="secp256k1",
                        n=len(items)):
            ok = np.array(
                [_secp.verify(it.pubkey, sha256(it.msg), it.sig) for it in items],
                dtype=bool,
            )
        _record_dispatch("host", "secp256k1", len(items), t0, ok)
        return ok


def _find_tpu_device():
    """The real chip, if reachable (even when the default backend is CPU).

    Never performs jax device discovery in-process before a subprocess
    liveness probe has passed: on a wedged tunnel, discovery HANGS rather
    than erroring, which would freeze a validator at its first commit
    verify.  libs/tpu_probe holds the probe + cache; a dead verdict also
    pins this process to the CPU platform so the XLA fallback stays safe."""
    from tendermint_tpu.libs.tpu_probe import safe_tpu_device

    return safe_tpu_device()


class TPUBatchVerifier:
    """Batched device verification.

    backend: "pallas" (fused kernel, needs a real TPU), "xla" (portable,
    mesh-shardable), or None = pick pallas when a TPU is reachable and no
    mesh was requested.
    """

    name = "tpu"

    def __init__(self, mesh=None, backend: Optional[str] = None):
        self._mesh = mesh
        self._tpu = None
        if backend is None:
            self._tpu = _find_tpu_device() if mesh is None else None
            backend = "pallas" if self._tpu is not None else "xla"
        elif backend == "pallas":
            self._tpu = _find_tpu_device()
            if self._tpu is None:
                raise RuntimeError("pallas backend requires a reachable TPU")
        elif backend == "xla" and mesh is None:
            # The XLA fallback touches jax at first dispatch; on a dead
            # tunnel that discovery would hang, so probe now (cached) and
            # pin the CPU platform when the chip is unreachable.  A caller
            # passing a mesh already performed discovery to build it.
            from tendermint_tpu.libs.tpu_probe import pin_cpu_platform, tpu_alive

            if not tpu_alive():
                pin_cpu_platform()
        self.backend = backend
        # deferred imports: keep jax out of pure-host users
        if backend == "pallas":
            from tendermint_tpu.ops import ed25519_pallas as kernel
        else:
            from tendermint_tpu.ops import ed25519_verify as kernel
        self._kernel = kernel
        # algos that have dispatched at least once on this verifier — the
        # first dispatch pays compile/upload and lands in compile_seconds
        self._warm: set = set()

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        if len(items) == 0:
            return np.zeros((0,), dtype=bool)
        return self.verify_ed25519_raw(
            [it.pubkey for it in items],
            [it.msg for it in items],
            [it.sig for it in items],
        )

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        """Column form of verify_ed25519 (see HostBatchVerifier's note)."""
        if len(pubs) == 0:
            return np.zeros((0,), dtype=bool)
        t0 = time.perf_counter()
        first = "ed25519" not in self._warm
        with trace.span("verify.dispatch", backend=self.backend,
                        algo="ed25519", n=len(pubs)):
            pubs_a = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(
                len(pubs), 32
            )
            sigs_a = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(
                len(sigs), 64
            )
            if self.backend == "pallas":
                import jax

                dev = None if jax.default_backend() == "tpu" else self._tpu
                ok = self._kernel.verify_batch(pubs_a, msgs, sigs_a, device=dev)
            else:
                ok = self._kernel.verify_batch(
                    pubs_a, msgs, sigs_a, mesh=self._mesh
                )
        ok = np.asarray(ok, dtype=bool)
        self._warm.add("ed25519")
        _record_dispatch(self.backend, "ed25519", len(pubs), t0, ok, first=first)
        return ok

    def verify_secp256k1(self, items: Sequence[SigItem]) -> np.ndarray:
        """Batched ECDSA on device. The pallas backend dispatches the fused
        windowed-Straus kernel (ops/secp256k1_pallas) on the real chip;
        otherwise the portable XLA kernel (mesh/shard_map-able) runs."""
        if len(items) == 0:
            return np.zeros((0,), dtype=bool)
        from tendermint_tpu.crypto.hashing import sha256

        t0 = time.perf_counter()
        first = "secp256k1" not in self._warm
        with trace.span("verify.dispatch", backend=self.backend,
                        algo="secp256k1", n=len(items)):
            pubs = [it.pubkey for it in items]
            digs = [sha256(it.msg) for it in items]
            sigs = [it.sig for it in items]
            if self.backend == "pallas":
                import jax

                from tendermint_tpu.ops import secp256k1_pallas as _skp

                dev = None if jax.default_backend() == "tpu" else self._tpu
                ok = _skp.verify_batch(pubs, digs, sigs, device=dev)
            else:
                from tendermint_tpu.ops import secp256k1_verify as _sk

                ok = _sk.verify_batch(pubs, digs, sigs, mesh=self._mesh)
        ok = np.asarray(ok, dtype=bool)
        self._warm.add("secp256k1")
        _record_dispatch(self.backend, "secp256k1", len(items), t0, ok,
                         first=first)
        return ok


_lock = threading.Lock()
_default = None


def get_batch_verifier(prefer_tpu: bool = True):
    """Process-wide default verifier. TPU backend if jax is importable.

    TM_BATCH_VERIFIER=host|xla|pallas overrides (deployment knob: small
    localnet validators with tiny commits want the host oracle — a tunneled
    device round-trip per 4-signature commit is pure loss)."""
    global _default
    with _lock:
        if _default is None:
            import os

            forced = os.environ.get("TM_BATCH_VERIFIER", "").lower()
            if forced == "host":
                _default = HostBatchVerifier()
            elif forced in ("xla", "pallas"):
                _default = TPUBatchVerifier(backend=forced)
            elif prefer_tpu:
                try:
                    v = TPUBatchVerifier()
                    # dead/absent chip degrades the verifier to XLA — but on
                    # a CPU-only host the XLA kernel is ~100x slower than the
                    # host C path, so the lazy default only keeps the device
                    # verifier when the fused pipeline is actually reachable
                    # (TM_BATCH_VERIFIER=xla forces the XLA backend instead)
                    if v.backend == "pallas":
                        _default = v
                    else:
                        _default = HostBatchVerifier()
                        get_verify_metrics().host_fallback.add(
                            1.0, ("no_tpu",)
                        )
                except Exception:
                    _default = HostBatchVerifier()
                    get_verify_metrics().host_fallback.add(
                        1.0, ("device_init_error",)
                    )
            else:
                _default = HostBatchVerifier()
        return _default


def set_batch_verifier(v) -> None:
    global _default
    with _lock:
        _default = v


def verify_items(items: Sequence[SigItem], verifier=None) -> np.ndarray:
    """Verify a heterogeneous batch. Ed25519 raw items go to the batch backend."""
    if verifier is None:
        verifier = get_batch_verifier()
    return verifier.verify_ed25519(items)


def verify_generic(
    pubkeys: Sequence[PubKey], msgs: Sequence[bytes], sigs: Sequence[bytes],
    verifier=None,
) -> np.ndarray:
    """Batch-verify over PubKey objects: ed25519 and secp256k1 keys batch to
    their backends; k-of-n threshold multisig aggregates FLATTEN into the
    ed25519 batch (every flagged signer's sub-signature rides the same
    device dispatch — ref threshold_pubkey.go:41-55 loops serially); only
    structurally odd items fall back to host verify_bytes."""
    from tendermint_tpu.crypto.keys import PubKeySecp256k1
    from tendermint_tpu.crypto.multisig import PubKeyMultisigThreshold

    if verifier is None:
        verifier = get_batch_verifier()
    n = len(pubkeys)
    # Homogeneous ed25519 batch — every fast-sync window and almost every
    # commit in practice.  Skip the per-item dispatch bookkeeping below
    # (isinstance + three index lists over |window|×|valset| items was a
    # measurable slice of the host ms/block ceiling).
    if all(type(pk) is PubKeyEd25519 for pk in pubkeys) and all(
        len(s) == 64 for s in sigs
    ):
        raw = getattr(verifier, "verify_ed25519_raw", None)
        if raw is not None:
            return np.asarray(
                raw([pk.bytes() for pk in pubkeys], msgs, sigs), dtype=bool
            )
        # verifiers without the column form (fakes in tests) get SigItems
        items = [
            SigItem(pk.bytes(), m, s) for pk, m, s in zip(pubkeys, msgs, sigs)
        ]
        return np.asarray(verifier.verify_ed25519(items), dtype=bool)
    out = np.zeros((n,), dtype=bool)
    ed_idx: List[Tuple[int, int]] = []  # (result index, position in ed_items)
    ed_items: List[SigItem] = []
    sk_idx: List[int] = []
    sk_items: List[SigItem] = []
    # multisig groups: (result index, start offset in ed_items, count)
    ms_groups: List[tuple] = []
    for i, pk in enumerate(pubkeys):
        if isinstance(pk, PubKeyEd25519) and len(sigs[i]) == 64:
            # (result index, position in ed_items) — multisig sub-items
            # interleave in ed_items, so positions must be explicit
            ed_idx.append((i, len(ed_items)))
            ed_items.append(SigItem(pk.bytes(), msgs[i], sigs[i]))
        elif isinstance(pk, PubKeySecp256k1):
            sk_idx.append(i)
            sk_items.append(SigItem(pk.bytes(), msgs[i], sigs[i]))
        elif isinstance(pk, PubKeyMultisigThreshold):
            flat = pk.flatten(msgs[i], sigs[i])
            if flat is None or len(flat) < pk.k:
                # structurally invalid / non-ed25519 sub-keys / too few
                # flagged signers — host path decides (usually False)
                try:
                    get_verify_metrics().host_fallback.add(
                        1.0, ("multisig_structural",)
                    )
                except Exception:
                    pass
                out[i] = pk.verify_bytes(msgs[i], sigs[i])
                continue
            ms_groups.append((i, len(ed_items), len(flat)))
            ed_items.extend(SigItem(p, m, s) for p, m, s in flat)
        else:
            try:
                get_verify_metrics().host_fallback.add(
                    1.0, ("unbatchable_key",)
                )
            except Exception:
                pass
            out[i] = pk.verify_bytes(msgs[i], sigs[i])
    if ed_items:
        res = verifier.verify_ed25519(ed_items)
        for i, pos in ed_idx:
            out[i] = res[pos]
        for i, start, cnt in ms_groups:
            out[i] = bool(np.all(res[start : start + cnt]))
    if sk_items:
        res = verifier.verify_secp256k1(sk_items)
        for j, i in enumerate(sk_idx):
            out[i] = res[j]
    return out
