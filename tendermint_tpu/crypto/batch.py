"""The BatchVerifier boundary — the seam where bulk signature verification leaves
the host control plane and lands on TPU.

The reference (v0.26.2) has NO batch interface; its one call-site shape is
``PubKey.VerifyBytes(msg, sig) bool`` (crypto/crypto.go:22-27), invoked serially
from types/validator_set.go:281-296 (commit verify), types/vote.go:102 (per-vote),
state/validation.go:102 and blockchain/reactor.go:306 (fast sync). This module
introduces the batch boundary those call sites feed (SURVEY.md north star):
callers collect (pubkey, msg, sig) tuples for a height — or a whole fast-sync
window of heights — and dispatch them in ONE call.

Backends:
  * HostBatchVerifier  — serial host loop (CPU oracle; always available).
  * TPUBatchVerifier   — device path. On a real TPU it dispatches the fused
    Pallas pipeline (ops/ed25519_pallas); on CPU or when a mesh is given it
    uses the portable XLA kernel (ops/ed25519_verify, shard_map-able).
    Non-ed25519 items (secp256k1, multisig) fall back to host.

Accept/reject is bit-exact across backends (tests/test_ops_ed25519.py).
"""

from __future__ import annotations

import threading
import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.crypto.keys import PubKey, PubKeyEd25519
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.metrics import get_verify_metrics


def _record_dispatch(backend: str, algo: str, n: int, t0: float, ok,
                     first: bool = False, fe_backend: str = "",
                     carry_mode: str = "", ed25519_path: str = "") -> None:
    """One VerifyMetrics record per batch dispatch (size, latency, rejects,
    and which limb-multiplier backend / carry schedule / verify strategy
    served the window).  Telemetry must never take down the verify path."""
    try:
        get_verify_metrics().record_dispatch(
            backend, algo, n, time.perf_counter() - t0,
            rejects=n - int(np.count_nonzero(ok)), first=first,
            fe_backend=fe_backend, carry_mode=carry_mode,
            ed25519_path=ed25519_path,
        )
    except Exception:
        pass


# limb-multiplier backends for the device kernels (ops/fe_common.FE_BACKENDS;
# duplicated here so pure-host users never import jax through this module)
_FE_BACKENDS = ("vpu", "mxu", "mxu16")
_default_fe_backend: Optional[str] = None


def set_default_fe_backend(value: Optional[str]) -> None:
    """Install the process-wide [verify] fe_backend choice (node composition
    root).  TM_FE_BACKEND still overrides per-process."""
    global _default_fe_backend
    _default_fe_backend = value or None


def _resolve_fe_backend(explicit: Optional[str]) -> str:
    import os

    v = explicit or os.environ.get("TM_FE_BACKEND", "") or \
        _default_fe_backend or "vpu"
    v = v.strip().lower()
    if v in ("", "auto"):
        return "vpu"
    if v not in _FE_BACKENDS:
        raise ValueError(
            f"fe_backend must be one of {_FE_BACKENDS}, got {v!r}"
        )
    return v


# device verify strategies (ops/ed25519_verify.verify_batch vs the
# one-MSM-per-window RLC path, ops/ed25519_msm)
_ED25519_PATHS = ("ladder", "msm")
_default_ed25519_path: Optional[str] = None


def set_default_ed25519_path(value: Optional[str]) -> None:
    """Install the process-wide [verify] ed25519_path choice (node
    composition root).  TM_ED25519_PATH still overrides per-process."""
    global _default_ed25519_path
    _default_ed25519_path = value or None


def _resolve_ed25519_path(explicit: Optional[str]) -> str:
    import os

    v = explicit or os.environ.get("TM_ED25519_PATH", "") or \
        _default_ed25519_path or "ladder"
    v = v.strip().lower()
    if v in ("", "auto"):
        return "ladder"
    if v not in _ED25519_PATHS:
        raise ValueError(
            f"ed25519_path must be one of {_ED25519_PATHS}, got {v!r}"
        )
    return v


class SigItem(NamedTuple):
    """One signature-verification work item. (NamedTuple, not dataclass:
    tens of thousands are created per fast-sync window and tuple
    construction is several times cheaper.)"""

    pubkey: bytes  # raw 32-byte ed25519 key (or PubKey for generic items)
    msg: bytes
    sig: bytes


class HostBatchVerifier:
    """Serial host verification — the oracle backend."""

    name = "host"

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host", algo="ed25519",
                        n=len(items)):
            ok = np.array(
                [_ed.verify(it.pubkey, it.msg, it.sig) for it in items],
                dtype=bool,
            )
        _record_dispatch("host", "ed25519", len(items), t0, ok)
        return ok

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        """Parallel-sequence form of verify_ed25519 — the hot callers
        (verify_generic's homogeneous fast path) already hold the three
        columns, and building |window|x|valset| SigItems was a measured
        slice of the fast-sync host ceiling."""
        t0 = time.perf_counter()
        verify = _ed.verify
        with trace.span("verify.dispatch", backend="host", algo="ed25519",
                        n=len(pubs)):
            ok = np.fromiter(
                (verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)),
                dtype=bool, count=len(pubs),
            )
        _record_dispatch("host", "ed25519", len(pubs), t0, ok)
        return ok

    def verify_secp256k1(self, items: Sequence[SigItem]) -> np.ndarray:
        """items carry (33B compressed pubkey, RAW msg, DER sig); the SHA-256
        premix (secp256k1.go:140) happens here."""
        from tendermint_tpu.crypto import secp256k1 as _secp
        from tendermint_tpu.crypto.hashing import sha256

        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host", algo="secp256k1",
                        n=len(items)):
            ok = np.array(
                [_secp.verify(it.pubkey, sha256(it.msg), it.sig) for it in items],
                dtype=bool,
            )
        _record_dispatch("host", "secp256k1", len(items), t0, ok)
        return ok


class RLCHostVerifier(HostBatchVerifier):
    """Host batch verification via the random-linear-combination check
    (ed25519.verify_batch): one Pippenger multi-scalar multiplication
    amortizes the per-signature double-scalar-mult, so a clean batch
    costs a fraction of the serial loop on hosts without the C fast
    path.  Accept/reject is bit-identical to ed25519.verify — failing
    batches are localized and re-checked per signature against the
    exact equation.  secp256k1 items still take the serial host loop."""

    name = "host_rlc"

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host_rlc",
                        algo="ed25519", n=len(items)):
            ok = np.array(
                _ed.verify_batch(
                    [(it.pubkey, it.msg, it.sig) for it in items]
                ),
                dtype=bool,
            ) if items else np.zeros((0,), dtype=bool)
        _record_dispatch("host_rlc", "ed25519", len(items), t0, ok)
        return ok

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        t0 = time.perf_counter()
        with trace.span("verify.dispatch", backend="host_rlc",
                        algo="ed25519", n=len(pubs)):
            ok = np.array(
                _ed.verify_batch(list(zip(pubs, msgs, sigs))), dtype=bool,
            ) if len(pubs) else np.zeros((0,), dtype=bool)
        _record_dispatch("host_rlc", "ed25519", len(pubs), t0, ok)
        return ok


def _find_tpu_device():
    """The real chip, if reachable (even when the default backend is CPU).

    Never performs jax device discovery in-process before a subprocess
    liveness probe has passed: on a wedged tunnel, discovery HANGS rather
    than erroring, which would freeze a validator at its first commit
    verify.  libs/tpu_probe holds the probe + cache; a dead verdict also
    pins this process to the CPU platform so the XLA fallback stays safe."""
    from tendermint_tpu.libs.tpu_probe import safe_tpu_device

    return safe_tpu_device()


class TPUBatchVerifier:
    """Batched device verification.

    backend: "pallas" (fused kernel, needs a real TPU), "xla" (portable,
    mesh-shardable), or None = pick pallas when a TPU is reachable and no
    mesh was requested.

    fe_backend: limb multiplier for the device kernels ("vpu" | "mxu" |
    "mxu16"; ops/fe_common).  None = TM_FE_BACKEND env, then the [verify]
    fe_backend config (set_default_fe_backend), then "vpu".  All backends
    are bit-exact — the PR 9 audit/breaker guard treats them identically.

    ed25519_path: "ladder" verifies one signature per lane with the
    double-scalar ladder kernel; "msm" folds the whole window into ONE
    Pippenger multi-scalar multiplication via a random linear combination
    (ops/ed25519_msm) and falls back to chunk RLCs + exact ladder rows on
    a window reject, so accept/reject stays bit-identical.  None =
    TM_ED25519_PATH env, then the [verify] ed25519_path config
    (set_default_ed25519_path), then "ladder".
    """

    name = "tpu"

    def __init__(self, mesh=None, backend: Optional[str] = None,
                 fe_backend: Optional[str] = None,
                 ed25519_path: Optional[str] = None):
        self.fe_backend = _resolve_fe_backend(fe_backend)
        self.ed25519_path = _resolve_ed25519_path(ed25519_path)
        # carry schedule the kernels will trace with — the kernels default
        # to lazy and degrade mxu16 to eager themselves
        # (fe_common.effective_carry_mode); mirrored here, without the jax
        # import, so telemetry labels match what actually ran
        self.carry_mode = "eager" if self.fe_backend == "mxu16" else "lazy"
        self._mesh = mesh
        self._tpu = None
        if backend is None:
            self._tpu = _find_tpu_device() if mesh is None else None
            backend = "pallas" if self._tpu is not None else "xla"
        elif backend == "pallas":
            self._tpu = _find_tpu_device()
            if self._tpu is None:
                raise RuntimeError("pallas backend requires a reachable TPU")
        elif backend == "xla" and mesh is None:
            # The XLA fallback touches jax at first dispatch; on a dead
            # tunnel that discovery would hang, so probe now (cached) and
            # pin the CPU platform when the chip is unreachable.  A caller
            # passing a mesh already performed discovery to build it.
            from tendermint_tpu.libs.tpu_probe import pin_cpu_platform, tpu_alive

            if not tpu_alive():
                pin_cpu_platform()
        self.backend = backend
        # deferred imports: keep jax out of pure-host users
        if backend == "pallas":
            from tendermint_tpu.ops import ed25519_pallas as kernel
        else:
            from tendermint_tpu.ops import ed25519_verify as kernel
        self._kernel = kernel
        # algos that have dispatched at least once on this verifier — the
        # first dispatch pays compile/upload and lands in compile_seconds
        self._warm: set = set()

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        if len(items) == 0:
            return np.zeros((0,), dtype=bool)
        return self.verify_ed25519_raw(
            [it.pubkey for it in items],
            [it.msg for it in items],
            [it.sig for it in items],
        )

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        """Column form of verify_ed25519 (see HostBatchVerifier's note)."""
        if len(pubs) == 0:
            return np.zeros((0,), dtype=bool)
        t0 = time.perf_counter()
        first = "ed25519" not in self._warm
        with trace.span("verify.dispatch", backend=self.backend,
                        algo="ed25519", n=len(pubs)):
            pubs_a = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(
                len(pubs), 32
            )
            sigs_a = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(
                len(sigs), 64
            )
            if self.backend == "pallas":
                import jax

                dev = None if jax.default_backend() == "tpu" else self._tpu
                if self.ed25519_path == "msm":
                    ok = self._kernel.rlc_verify_batch(
                        pubs_a, msgs, sigs_a, device=dev,
                        fe_backend=self.fe_backend,
                    )
                else:
                    ok = self._kernel.verify_batch(
                        pubs_a, msgs, sigs_a, device=dev,
                        fe_backend=self.fe_backend,
                    )
            elif self.ed25519_path == "msm":
                # the MSM folds the window into one point equation — there
                # is no lane axis to shard, so the mesh is not consulted
                ok = self._kernel.rlc_verify_batch(
                    pubs_a, msgs, sigs_a, fe_backend=self.fe_backend,
                )
            else:
                ok = self._kernel.verify_batch(
                    pubs_a, msgs, sigs_a, mesh=self._mesh,
                    fe_backend=self.fe_backend,
                )
        ok = np.asarray(ok, dtype=bool)
        self._warm.add("ed25519")
        _record_dispatch(self.backend, "ed25519", len(pubs), t0, ok,
                         first=first, fe_backend=self.fe_backend,
                         carry_mode=self.carry_mode,
                         ed25519_path=self.ed25519_path)
        return ok

    def verify_secp256k1(self, items: Sequence[SigItem]) -> np.ndarray:
        """Batched ECDSA on device. The pallas backend dispatches the fused
        windowed-Straus kernel (ops/secp256k1_pallas) on the real chip;
        otherwise the portable XLA kernel (mesh/shard_map-able) runs."""
        if len(items) == 0:
            return np.zeros((0,), dtype=bool)
        from tendermint_tpu.crypto.hashing import sha256

        t0 = time.perf_counter()
        first = "secp256k1" not in self._warm
        with trace.span("verify.dispatch", backend=self.backend,
                        algo="secp256k1", n=len(items)):
            pubs = [it.pubkey for it in items]
            digs = [sha256(it.msg) for it in items]
            sigs = [it.sig for it in items]
            if self.backend == "pallas":
                import jax

                from tendermint_tpu.ops import secp256k1_pallas as _skp

                dev = None if jax.default_backend() == "tpu" else self._tpu
                ok = _skp.verify_batch(pubs, digs, sigs, device=dev,
                                       fe_backend=self.fe_backend)
            else:
                from tendermint_tpu.ops import secp256k1_verify as _sk

                ok = _sk.verify_batch(pubs, digs, sigs, mesh=self._mesh,
                                      fe_backend=self.fe_backend)
        ok = np.asarray(ok, dtype=bool)
        self._warm.add("secp256k1")
        _record_dispatch(self.backend, "secp256k1", len(items), t0, ok,
                         first=first, fe_backend=self.fe_backend,
                         carry_mode=self.carry_mode)
        return ok


class GuardedBatchVerifier:
    """Fault-tolerant wrapper around a device BatchVerifier.

    Every dispatch runs the full guard (libs/breaker.py):

      1. breaker gate — open/quarantined diverts straight to the host
         oracle (bit-identical verdicts, just slower);
      2. supervised deadline — a hung device call becomes a fallback,
         not a stalled consensus routine;
      3. bounded retry — one transient failure is retried before the
         window completes on the host;
      4. seeded silent-corruption audit — k sampled lanes per device
         window are re-verified on the host oracle; any disagreement
         quarantines the breaker (operator reset required) and the
         window's verdict is recomputed entirely on the host, so a
         wrong device verdict never escapes this class.

    The wrapped device object only needs the BatchVerifier surface
    (verify_ed25519 / verify_ed25519_raw / verify_secp256k1), which is
    how the sim's FaultyDevice shim slots in.
    """

    name = "guarded"

    def __init__(self, device, host=None, breaker=None, deadline=None,
                 retries=None, audit_rate=None, audit_seed=None):
        from tendermint_tpu.libs import breaker as _brk

        cfg = _brk.guard_config()
        self.device = device
        self.host = host if host is not None else HostBatchVerifier()
        self.breaker = breaker if breaker is not None \
            else _brk.get_device_breaker()
        self.deadline = cfg.dispatch_deadline if deadline is None else deadline
        self.retries = cfg.retries if retries is None else int(retries)
        self.audit_rate = (
            cfg.audit_sample_rate if audit_rate is None else float(audit_rate)
        )
        self.audit_seed = cfg.audit_seed if audit_seed is None else int(audit_seed)
        self.backend = getattr(
            device, "backend", getattr(device, "name", "device")
        )
        self._mtx = threading.Lock()
        self._dispatches = 0
        self._audit_mismatches = 0

    # -- BatchVerifier surface -------------------------------------------------

    def verify_ed25519(self, items: Sequence[SigItem]) -> np.ndarray:
        return self._guard(
            "ed25519", len(items),
            lambda: self.device.verify_ed25519(items),
            lambda: self.host.verify_ed25519(items),
            lambda i: _ed.verify(items[i].pubkey, items[i].msg, items[i].sig),
        )

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        return self._guard(
            "ed25519", len(pubs),
            lambda: self.device.verify_ed25519_raw(pubs, msgs, sigs),
            lambda: self.host.verify_ed25519_raw(pubs, msgs, sigs),
            lambda i: _ed.verify(pubs[i], msgs[i], sigs[i]),
        )

    def verify_secp256k1(self, items: Sequence[SigItem]) -> np.ndarray:
        from tendermint_tpu.crypto import secp256k1 as _secp
        from tendermint_tpu.crypto.hashing import sha256

        return self._guard(
            "secp256k1", len(items),
            lambda: self.device.verify_secp256k1(items),
            lambda: self.host.verify_secp256k1(items),
            lambda i: _secp.verify(
                items[i].pubkey, sha256(items[i].msg), items[i].sig
            ),
        )

    # -- guard machinery -------------------------------------------------------

    def _guard(self, algo, n, dev_call, host_call, oracle) -> np.ndarray:
        if n == 0:
            return np.zeros((0,), dtype=bool)
        from tendermint_tpu.libs import breaker as _brk

        br = self.breaker
        if not br.allow():
            reason = (
                "quarantined" if br.state == _brk.QUARANTINED
                else "breaker_open"
            )
            self._note_fallback(reason, algo, n)
            return np.asarray(host_call(), dtype=bool)
        attempts = 0
        while True:
            try:
                ok = _brk.supervised_call(
                    dev_call, self.deadline, name=f"batch-{algo}"
                )
                ok = np.asarray(ok, dtype=bool)
            except Exception as e:
                timeout = isinstance(e, _brk.DispatchTimeout)
                reason = "timeout" if timeout else "error"
                br.record_failure(reason)
                attempts += 1
                if attempts <= self.retries and br.allow():
                    try:
                        get_verify_metrics().device_retries.add(1.0)
                    except Exception:
                        pass
                    continue
                self._note_fallback(reason, algo, n)
                return np.asarray(host_call(), dtype=bool)
            if self._audit(algo, n, ok, oracle):
                # the device disagrees with the host oracle: safety bug.
                # Quarantine (latched) and recompute the WHOLE window on
                # the host — the sampled lanes say nothing about the rest.
                br.quarantine(f"audit_mismatch:{algo}")
                self._note_fallback("audit_mismatch", algo, n)
                return np.asarray(host_call(), dtype=bool)
            br.record_success()
            return ok

    def _audit(self, algo, n, ok, oracle) -> bool:
        """Cross-check k seeded-sampled lanes against the host oracle.
        Returns True iff any lane disagrees."""
        rate = self.audit_rate
        if rate <= 0 or oracle is None:
            return False
        import math
        import random

        with self._mtx:
            seq = self._dispatches
            self._dispatches += 1
        k = min(n, max(1, int(math.ceil(n * rate))))
        rng = random.Random((self.audit_seed << 20) ^ seq)
        lanes = rng.sample(range(n), k)
        bad = [i for i in lanes if bool(ok[i]) != bool(oracle(i))]
        try:
            m = get_verify_metrics()
            if len(lanes) - len(bad):
                m.device_audit.add(float(len(lanes) - len(bad)), ("ok",))
            if bad:
                m.device_audit.add(float(len(bad)), ("mismatch",))
        except Exception:
            pass
        if bad:
            with self._mtx:
                self._audit_mismatches += len(bad)
            try:
                from tendermint_tpu.libs.profile import get_profiler

                get_profiler().record_event(
                    "audit_mismatch", algo=algo, backend=self.backend,
                    sampled=len(lanes), mismatches=len(bad),
                    lanes=bad[:8],
                )
            except Exception:
                pass
        return bool(bad)

    def _note_fallback(self, reason, algo, n) -> None:
        try:
            get_verify_metrics().device_fallback.add(1.0, (reason,))
        except Exception:
            pass
        try:
            from tendermint_tpu.libs.profile import get_profiler

            get_profiler().record_event(
                "device_fallback", reason=reason, algo=algo, n=n,
                backend=self.backend,
            )
        except Exception:
            pass

    def snapshot(self) -> dict:
        with self._mtx:
            return {
                "backend": self.backend,
                "deadline": self.deadline,
                "retries": self.retries,
                "audit_rate": self.audit_rate,
                "dispatches": self._dispatches,
                "audit_mismatches": self._audit_mismatches,
            }


_lock = threading.Lock()
_default = None
# why the lazy default latched the host path: None (device in use or host
# explicitly installed) | "no_tpu" | "device_init_error".  Only the init
# error is considered transient — the breaker's half-open probe re-drives
# device selection for it (the satellite-1 fix: no more permanent latch).
_latched_reason: Optional[str] = None


def _try_device_default():
    """One device-selection attempt: (verifier, latch_reason)."""
    v = TPUBatchVerifier()
    # dead/absent chip degrades the verifier to XLA — but on a CPU-only
    # host the XLA kernel is ~100x slower than the host C path, so the
    # lazy default only keeps the device verifier when the fused pipeline
    # is actually reachable (TM_BATCH_VERIFIER=xla forces XLA instead)
    if v.backend == "pallas":
        return GuardedBatchVerifier(v), None
    return HostBatchVerifier(), "no_tpu"


def get_batch_verifier(prefer_tpu: bool = True):
    """Process-wide default verifier. TPU backend if jax is importable.

    TM_BATCH_VERIFIER=host|xla|pallas overrides (deployment knob: small
    localnet validators with tiny commits want the host oracle — a tunneled
    device round-trip per 4-signature commit is pure loss).  Device-backed
    verifiers are wrapped in GuardedBatchVerifier, and a host latch caused
    by a device-init error is re-probed when the breaker grants its
    half-open probe."""
    global _default, _latched_reason
    from tendermint_tpu.libs.breaker import get_device_breaker

    with _lock:
        if _default is None:
            import os

            forced = os.environ.get("TM_BATCH_VERIFIER", "").lower()
            if forced == "host":
                _default = HostBatchVerifier()
            elif forced in ("xla", "pallas"):
                _default = GuardedBatchVerifier(TPUBatchVerifier(backend=forced))
            elif prefer_tpu:
                try:
                    _default, _latched_reason = _try_device_default()
                    if _latched_reason is not None:
                        get_verify_metrics().host_fallback.add(
                            1.0, (_latched_reason,)
                        )
                except Exception:
                    _default = HostBatchVerifier()
                    _latched_reason = "device_init_error"
                    get_verify_metrics().host_fallback.add(
                        1.0, ("device_init_error",)
                    )
                    # force the breaker open so re-probes are paced by its
                    # exponential backoff instead of hammering init on
                    # every commit verify
                    get_device_breaker().trip("device_init_error")
        elif _latched_reason == "device_init_error" and prefer_tpu:
            # re-probe seam: the half-open probe budget decides when a
            # recovered device is worth another (possibly slow) init
            br = get_device_breaker()
            if br.allow():
                try:
                    v, reason = _try_device_default()
                    if reason is None:
                        _default = v
                        _latched_reason = None
                        br.record_success()
                    else:
                        br.record_failure("no_tpu")
                except Exception:
                    br.record_failure("device_init_error")
        return _default


def set_batch_verifier(v) -> None:
    global _default, _latched_reason
    with _lock:
        _default = v
        _latched_reason = None


def reprobe(force: bool = False):
    """Drop the lazy default and re-run device selection.

    ``force=False`` only clears a host latch (a previous ``no_tpu`` /
    ``device_init_error`` verdict); an explicitly installed verifier is
    left alone.  ``force=True`` additionally forgets the tpu_probe
    liveness cache, so a tunnel that came back after a dead verdict is
    rediscovered — at the cost of a full probe timeout if it is still
    dead.  Returns the (possibly new) default verifier."""
    global _default, _latched_reason
    with _lock:
        if _latched_reason is None and not force:
            return _default
        _default = None
        _latched_reason = None
    if force:
        from tendermint_tpu.libs.tpu_probe import clear_cache

        clear_cache()
    return get_batch_verifier()


def verifier_info() -> dict:
    """Current default-verifier identity for dump_device_health."""
    with _lock:
        v = _default
        reason = _latched_reason
    info = {
        "installed": v is not None,
        "name": getattr(v, "name", None) if v is not None else None,
        "backend": getattr(v, "backend", None) if v is not None else None,
        "latched_reason": reason,
    }
    if isinstance(v, GuardedBatchVerifier):
        info["guard"] = v.snapshot()
    return info


def verify_items(items: Sequence[SigItem], verifier=None) -> np.ndarray:
    """Verify a heterogeneous batch. Ed25519 raw items go to the batch backend."""
    if verifier is None:
        verifier = get_batch_verifier()
    return verifier.verify_ed25519(items)


def verify_generic(
    pubkeys: Sequence[PubKey], msgs: Sequence[bytes], sigs: Sequence[bytes],
    verifier=None,
) -> np.ndarray:
    """Batch-verify over PubKey objects: ed25519 and secp256k1 keys batch to
    their backends; k-of-n threshold multisig aggregates FLATTEN into the
    ed25519 batch (every flagged signer's sub-signature rides the same
    device dispatch — ref threshold_pubkey.go:41-55 loops serially); only
    structurally odd items fall back to host verify_bytes."""
    from tendermint_tpu.crypto.keys import PubKeySecp256k1
    from tendermint_tpu.crypto.multisig import PubKeyMultisigThreshold

    if verifier is None:
        verifier = get_batch_verifier()
    n = len(pubkeys)
    # Homogeneous ed25519 batch — every fast-sync window and almost every
    # commit in practice.  Skip the per-item dispatch bookkeeping below
    # (isinstance + three index lists over |window|×|valset| items was a
    # measurable slice of the host ms/block ceiling).
    if all(type(pk) is PubKeyEd25519 for pk in pubkeys) and all(
        len(s) == 64 for s in sigs
    ):
        raw = getattr(verifier, "verify_ed25519_raw", None)
        if raw is not None:
            return np.asarray(
                raw([pk.bytes() for pk in pubkeys], msgs, sigs), dtype=bool
            )
        # verifiers without the column form (fakes in tests) get SigItems
        items = [
            SigItem(pk.bytes(), m, s) for pk, m, s in zip(pubkeys, msgs, sigs)
        ]
        return np.asarray(verifier.verify_ed25519(items), dtype=bool)
    out = np.zeros((n,), dtype=bool)
    ed_idx: List[Tuple[int, int]] = []  # (result index, position in ed_items)
    ed_items: List[SigItem] = []
    sk_idx: List[int] = []
    sk_items: List[SigItem] = []
    # multisig groups: (result index, start offset in ed_items, count)
    ms_groups: List[tuple] = []
    for i, pk in enumerate(pubkeys):
        if isinstance(pk, PubKeyEd25519) and len(sigs[i]) == 64:
            # (result index, position in ed_items) — multisig sub-items
            # interleave in ed_items, so positions must be explicit
            ed_idx.append((i, len(ed_items)))
            ed_items.append(SigItem(pk.bytes(), msgs[i], sigs[i]))
        elif isinstance(pk, PubKeySecp256k1):
            sk_idx.append(i)
            sk_items.append(SigItem(pk.bytes(), msgs[i], sigs[i]))
        elif isinstance(pk, PubKeyMultisigThreshold):
            flat = pk.flatten(msgs[i], sigs[i])
            if flat is None or len(flat) < pk.k:
                # structurally invalid / non-ed25519 sub-keys / too few
                # flagged signers — host path decides (usually False)
                try:
                    get_verify_metrics().host_fallback.add(
                        1.0, ("multisig_structural",)
                    )
                except Exception:
                    pass
                out[i] = pk.verify_bytes(msgs[i], sigs[i])
                continue
            ms_groups.append((i, len(ed_items), len(flat)))
            ed_items.extend(SigItem(p, m, s) for p, m, s in flat)
        else:
            try:
                get_verify_metrics().host_fallback.add(
                    1.0, ("unbatchable_key",)
                )
            except Exception:
                pass
            out[i] = pk.verify_bytes(msgs[i], sigs[i])
    if ed_items:
        res = verifier.verify_ed25519(ed_items)
        for i, pos in ed_idx:
            out[i] = res[pos]
        for i, start, cnt in ms_groups:
            out[i] = bool(np.all(res[start : start + cnt]))
    if sk_items:
        res = verifier.verify_secp256k1(sk_items)
        for j, i in enumerate(sk_idx):
            out[i] = res[j]
    return out
