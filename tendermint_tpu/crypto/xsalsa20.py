"""XSalsa20-Poly1305 secretbox + passphrase-style symmetric encryption
(ref: crypto/xsalsa20symmetric/symmetric.go — NaCl secretbox with a random
24-byte nonce prepended to the ciphertext).

Pure Python: Salsa20 core + HSalsa20 + Poly1305. This guards key files and
operator material, not the data plane — clarity over speed.  Layout matches
the reference: ciphertext = nonce(24) || secretbox(= tag(16) || body).
"""

from __future__ import annotations

import hmac as _hmac
import os
import struct

NONCE_LEN = 24
SECRET_LEN = 32
OVERHEAD = 16  # poly1305 tag

_MASK = 0xFFFFFFFF
_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _salsa20_rounds(state):
    """20 rounds (10 double rounds) over a 16-word state; returns the
    post-round words WITHOUT the feed-forward addition."""
    x = list(state)

    def qr(a, b, c, d):
        x[b] ^= _rotl((x[a] + x[d]) & _MASK, 7)
        x[c] ^= _rotl((x[b] + x[a]) & _MASK, 9)
        x[d] ^= _rotl((x[c] + x[b]) & _MASK, 13)
        x[a] ^= _rotl((x[d] + x[c]) & _MASK, 18)

    for _ in range(10):
        # column round
        qr(0, 4, 8, 12)
        qr(5, 9, 13, 1)
        qr(10, 14, 2, 6)
        qr(15, 3, 7, 11)
        # row round
        qr(0, 1, 2, 3)
        qr(5, 6, 7, 4)
        qr(10, 11, 8, 9)
        qr(15, 12, 13, 14)
    return x


def _salsa20_block(key: bytes, nonce8: bytes, counter: int) -> bytes:
    """One 64-byte Salsa20 keystream block."""
    k = struct.unpack("<8I", key)
    n = struct.unpack("<2I", nonce8)
    c = (counter & _MASK, (counter >> 32) & _MASK)
    state = (
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        c[0], c[1], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    )
    x = _salsa20_rounds(state)
    return struct.pack("<16I", *((a + b) & _MASK for a, b in zip(x, state)))


def hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """Subkey derivation: the diagonal+nonce words of the un-fed-forward
    Salsa20 state (NaCl core_hsalsa20)."""
    k = struct.unpack("<8I", key)
    n = struct.unpack("<4I", nonce16)
    state = (
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    )
    x = _salsa20_rounds(state)
    return struct.pack("<8I", *(x[i] for i in (0, 5, 10, 15, 6, 7, 8, 9)))


def _xsalsa20_stream(key: bytes, nonce24: bytes, length: int) -> bytes:
    """Keystream for XSalsa20: HSalsa20 subkey + 8-byte nonce tail."""
    subkey = hsalsa20(key, nonce24[:16])
    out = bytearray()
    counter = 0
    while len(out) < length:
        out += _salsa20_block(subkey, nonce24[16:], counter)
        counter += 1
    return bytes(out[:length])


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    """One-shot Poly1305 MAC (RFC 8439 §2.5)."""
    r = int.from_bytes(key32[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i : i + 16]
        n = int.from_bytes(blk + b"\x01", "little")
        acc = ((acc + n) * r) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def secretbox_seal(plaintext: bytes, nonce24: bytes, key: bytes) -> bytes:
    """NaCl crypto_secretbox: returns tag(16) || ciphertext. The first 32
    keystream bytes become the Poly1305 key; encryption starts at keystream
    offset 32 (i.e. the rest of block 0, then blocks 1..)."""
    stream = _xsalsa20_stream(key, nonce24, 32 + len(plaintext))
    poly_key, pad = stream[:32], stream[32:]
    ct = bytes(a ^ b for a, b in zip(plaintext, pad))
    return _poly1305(poly_key, ct) + ct


def secretbox_open(boxed: bytes, nonce24: bytes, key: bytes):
    """Returns plaintext or None on authentication failure."""
    if len(boxed) < OVERHEAD:
        return None
    tag, ct = boxed[:OVERHEAD], boxed[OVERHEAD:]
    stream = _xsalsa20_stream(key, nonce24, 32 + len(ct))
    poly_key, pad = stream[:32], stream[32:]
    if not _hmac.compare_digest(tag, _poly1305(poly_key, ct)):
        return None
    return bytes(a ^ b for a, b in zip(ct, pad))


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """symmetric.go:21 EncryptSymmetric: random nonce prepended; secret must
    be 32 bytes (e.g. sha256 of a KDF output)."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be {SECRET_LEN} bytes, got {len(secret)}")
    nonce = os.urandom(NONCE_LEN)
    return nonce + secretbox_seal(plaintext, nonce, secret)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """symmetric.go:38 DecryptSymmetric; raises ValueError on failure."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be {SECRET_LEN} bytes, got {len(secret)}")
    if len(ciphertext) <= OVERHEAD + NONCE_LEN:
        # NOTE: `<=` (not `<`) is deliberate reference parity — symmetric.go:44
        # also rejects the 40-byte ciphertext of an empty plaintext, so an
        # empty payload encrypts but never decrypts there either
        raise ValueError("ciphertext is too short")
    nonce, boxed = ciphertext[:NONCE_LEN], ciphertext[NONCE_LEN:]
    out = secretbox_open(boxed, nonce, secret)
    if out is None:
        raise ValueError("ciphertext decryption failed")
    return out
