"""CSPRNG helpers (ref: crypto/random.go).

The reference wraps Go's crypto/rand in a ChaCha20 stream reseeded by
MixEntropy because historical Go runtimes could block or weaken on some
platforms. Python's os.urandom IS the kernel CSPRNG (getrandom(2)), so
these are thin, honest shims keeping the reference's API shape:
MixEntropy is accepted (the kernel pool can always absorb more entropy via
os.urandom usage patterns, but user-supplied seeds cannot strengthen it
from userspace) and recorded for operator visibility only.
"""

from __future__ import annotations

import os


def mix_entropy(seed: bytes) -> None:
    """Accepted for API parity (random.go:36), and an explicit no-op:
    os.urandom draws from the kernel CSPRNG, which userspace seeds cannot
    meaningfully strengthen."""


def c_rand_bytes(n: int) -> bytes:
    """random.go:51 CRandBytes."""
    if n < 0:
        raise ValueError("negative byte count")
    return os.urandom(n)


def c_rand_hex(n_digits: int) -> str:
    """random.go:72 CRandHex — with one deliberate divergence: the reference
    hex-encodes n/2 bytes, so CRandHex(11) returns 10 chars; this returns
    exactly n digits (the extra nibble comes from one more CSPRNG byte)."""
    if n_digits < 0:
        raise ValueError("negative digit count")
    return os.urandom((n_digits + 1) // 2).hex()[:n_digits]
