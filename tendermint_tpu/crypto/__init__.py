"""Crypto layer: key interfaces, hashing, merkle, multisig, and the BatchVerifier
boundary that routes bulk signature batches to the TPU kernels in tendermint_tpu.ops.

Reference: crypto/ (SURVEY.md §2.1 "Crypto").
"""

from tendermint_tpu.crypto.hashing import (  # noqa: F401
    sha256,
    sha512,
    tmhash,
    tmhash_truncated,
    ripemd160,
    HASH_SIZE,
    TRUNCATED_SIZE,
)
from tendermint_tpu.crypto.keys import (  # noqa: F401
    ADDRESS_SIZE,
    PrivKey,
    PrivKeyEd25519,
    PrivKeySecp256k1,
    PubKey,
    PubKeyEd25519,
    PubKeySecp256k1,
    privkey_from_json_obj,
    pubkey_from_json_obj,
)
