"""ASCII armor for key material (ref: crypto/armor/armor.go — OpenPGP-style
armor blocks via x/crypto/openpgp/armor).

Format (RFC 4880 §6.2): BEGIN/END type lines, `Key: Value` headers, blank
line, base64 body wrapped at 64 columns, and a CRC-24 checksum line
(`=XXXX`, base64 of the 3-byte OpenPGP CRC).
"""

from __future__ import annotations

import base64
from typing import Dict, Tuple

_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB
_LINE = 64


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: Dict[str, str], data: bytes) -> str:
    """armor.go:11 EncodeArmor."""
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    body = base64.b64encode(data).decode()
    for i in range(0, len(body), _LINE):
        lines.append(body[i : i + _LINE])
    # an empty payload still gets its checksum line
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> Tuple[str, Dict[str, str], bytes]:
    """armor.go:28 DecodeArmor — returns (block_type, headers, data);
    raises ValueError on malformed input or checksum mismatch."""
    lines = [ln.rstrip("\r") for ln in armor_str.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN ") or not lines[0].endswith("-----"):
        raise ValueError("armor: missing BEGIN line")
    block_type = lines[0][len("-----BEGIN ") : -len("-----")]
    end = f"-----END {block_type}-----"
    if lines[-1] != end:
        raise ValueError("armor: missing or mismatched END line")
    headers: Dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i].strip():
        if ":" not in lines[i]:
            break  # body started without a blank separator
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i].strip():
        i += 1  # blank separator
    body_lines = lines[i:-1]
    crc_b64 = None
    if body_lines and body_lines[-1].startswith("="):
        crc_b64 = body_lines[-1][1:]
        body_lines = body_lines[:-1]
    try:
        data = base64.b64decode("".join(body_lines), validate=True)
    except Exception as e:
        raise ValueError(f"armor: bad base64 body: {e}")
    if crc_b64 is not None:
        want = base64.b64decode(crc_b64)
        if _crc24(data).to_bytes(3, "big") != want:
            raise ValueError("armor: CRC-24 checksum mismatch")
    return block_type, headers, data
