"""XChaCha20-Poly1305 AEAD — randomized 24-byte nonces for ChaCha20-Poly1305
(ref: crypto/xchacha20poly1305/xchachapoly.go).

Construction mirrors the reference exactly: the first 16 nonce bytes feed
HChaCha20 to derive a subkey; the remaining 8 become the tail of a 12-byte
IETF ChaCha20-Poly1305 nonce (prefixed with 4 zero bytes, xchachapoly.go:74-80).
HChaCha20 is pure Python (one 64-byte block per seal — not a hot path); the
bulk AEAD rides the `cryptography` C implementation when installed, else
the RFC-vector-validated pure-Python fallback (crypto/sts_fallback.py).
"""

from __future__ import annotations

import struct

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # pragma: no cover - environment-dependent
    from tendermint_tpu.crypto.sts_fallback import ChaCha20Poly1305, InvalidTag

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16
# single-call plaintext ceiling (xchachapoly.go:27-30)
MAX_PLAINTEXT_SIZE = (1 << 38) - 64
MAX_CIPHERTEXT_SIZE = (1 << 38) - 48

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_MASK = 0xFFFFFFFF


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """32 pseudo-random bytes from a 256-bit key and 128-bit nonce
    (xchachapoly.go:132-168)."""
    if len(key) != KEY_SIZE:
        raise ValueError("hchacha20: key must be 32 bytes")
    if len(nonce16) != 16:
        raise ValueError("hchacha20: nonce must be 16 bytes")
    v = list(_SIGMA) + list(struct.unpack("<8I", key)) + list(
        struct.unpack("<4I", nonce16)
    )

    def qr(a, b, c, d):
        v[a] = (v[a] + v[b]) & _MASK
        v[d] = _rotl(v[d] ^ v[a], 16)
        v[c] = (v[c] + v[d]) & _MASK
        v[b] = _rotl(v[b] ^ v[c], 12)
        v[a] = (v[a] + v[b]) & _MASK
        v[d] = _rotl(v[d] ^ v[a], 8)
        v[c] = (v[c] + v[d]) & _MASK
        v[b] = _rotl(v[b] ^ v[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    return struct.pack("<8I", *(v[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


def _subparts(key: bytes, nonce: bytes):
    if len(key) != KEY_SIZE:
        raise ValueError("xchacha20poly1305: bad key length")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("xchacha20poly1305: bad nonce length")
    subkey = hchacha20(key, nonce[:16])
    return subkey, b"\x00" * 4 + nonce[16:]


def seal(key: bytes, nonce: bytes, plaintext: bytes, ad: bytes = b"") -> bytes:
    """Encrypt + authenticate; output = ciphertext || 16-byte tag."""
    if len(plaintext) > MAX_PLAINTEXT_SIZE:
        raise ValueError("xchacha20poly1305: plaintext too large")
    subkey, n12 = _subparts(key, nonce)
    return ChaCha20Poly1305(subkey).encrypt(n12, plaintext, ad or None)


def open_(key: bytes, nonce: bytes, ciphertext: bytes, ad: bytes = b"") -> bytes:
    """Authenticate + decrypt; raises ValueError on forgery."""
    if len(ciphertext) < TAG_SIZE:
        raise ValueError("xchacha20poly1305: ciphertext too short")
    if len(ciphertext) > MAX_CIPHERTEXT_SIZE:
        raise ValueError("xchacha20poly1305: ciphertext too large")
    subkey, n12 = _subparts(key, nonce)
    try:
        return ChaCha20Poly1305(subkey).decrypt(n12, ciphertext, ad or None)
    except InvalidTag:
        raise ValueError("xchacha20poly1305: message authentication failed")
