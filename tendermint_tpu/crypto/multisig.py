"""k-of-n threshold multisig pubkey + compact bit array.

Mirrors reference crypto/multisig/threshold_pubkey.go:34 (VerifyBytes walks the
sub-signatures in pubkey order, guided by a compact bit array) and
crypto/multisig/bitarray/compact_bit_array.go.

TPU note: a multisig verify over a batch of validators decomposes into the same
flat (pubkey, msg, sig) tensor the ed25519 batch kernel consumes; Multisignature
provides `flatten()` for that path (BASELINE.json configs[4]).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from tendermint_tpu.crypto.hashing import tmhash_truncated
from tendermint_tpu.crypto.keys import PubKey


class CompactBitArray:
    """Bit array with minimal byte storage (cf. compact_bit_array.go)."""

    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative size")
        self.bits = bits
        self.elems = bytearray((bits + 7) // 8)

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        return bool(self.elems[i >> 3] & (1 << (7 - (i % 8))))

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        if v:
            self.elems[i >> 3] |= 1 << (7 - (i % 8))
        else:
            self.elems[i >> 3] &= ~(1 << (7 - (i % 8))) & 0xFF
        return True

    def num_true_bits_before(self, index: int) -> int:
        return sum(1 for i in range(index) if self.get_index(i))

    def count(self) -> int:
        return self.num_true_bits_before(self.bits)

    def __eq__(self, other):
        return (
            isinstance(other, CompactBitArray)
            and self.bits == other.bits
            and self.elems == other.elems
        )

    def to_bytes(self) -> bytes:
        return self.bits.to_bytes(4, "big") + bytes(self.elems)

    @staticmethod
    def from_bytes(data: bytes) -> "CompactBitArray":
        bits = int.from_bytes(data[:4], "big")
        ba = CompactBitArray(bits)
        ba.elems = bytearray(data[4 : 4 + (bits + 7) // 8])
        return ba


@dataclass
class Multisignature:
    """Ordered sub-signatures + participation bitmap (cf. multisignature.go)."""

    bitarray: CompactBitArray
    sigs: List[bytes] = field(default_factory=list)

    @staticmethod
    def new(n: int) -> "Multisignature":
        return Multisignature(CompactBitArray(n))

    def add_signature_from_pubkey(
        self, sig: bytes, pubkey: PubKey, keys: Sequence[PubKey]
    ) -> None:
        index = next((i for i, k in enumerate(keys) if k.equals(pubkey)), -1)
        if index < 0:
            raise ValueError("pubkey not in multisig key set")
        new_sig_index = self.bitarray.num_true_bits_before(index)
        if self.bitarray.get_index(index):
            self.sigs[new_sig_index] = sig  # replace
            return
        self.bitarray.set_index(index, True)
        self.sigs.insert(new_sig_index, sig)

    def marshal(self) -> bytes:
        out = self.bitarray.to_bytes()
        out += len(self.sigs).to_bytes(2, "big")
        for s in self.sigs:
            out += len(s).to_bytes(2, "big") + s
        return out

    @staticmethod
    def unmarshal(data: bytes) -> "Multisignature":
        ba = CompactBitArray.from_bytes(data)
        off = 4 + (ba.bits + 7) // 8
        nsigs = int.from_bytes(data[off : off + 2], "big")
        off += 2
        sigs = []
        for _ in range(nsigs):
            ln = int.from_bytes(data[off : off + 2], "big")
            off += 2
            sigs.append(data[off : off + ln])
            off += ln
        return Multisignature(ba, sigs)


@dataclass(frozen=True)
class PubKeyMultisigThreshold(PubKey):
    """k-of-n threshold key (cf. threshold_pubkey.go:11)."""

    k: int
    pubkeys: Tuple[PubKey, ...]
    type_name = "tendermint/PubKeyMultisigThreshold"

    def __post_init__(self):
        if self.k <= 0:
            raise ValueError("threshold k must be positive")
        if len(self.pubkeys) < self.k:
            raise ValueError("threshold k cannot exceed number of keys")

    def address(self) -> bytes:
        return tmhash_truncated(self.bytes())

    def bytes(self) -> bytes:
        out = self.k.to_bytes(4, "big") + len(self.pubkeys).to_bytes(4, "big")
        for pk in self.pubkeys:
            tb = pk.type_name.encode()
            out += len(tb).to_bytes(1, "big") + tb
            kb = pk.bytes()
            out += len(kb).to_bytes(2, "big") + kb
        return out

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        try:
            multisig = Multisignature.unmarshal(sig)
        except Exception:
            return False
        size = multisig.bitarray.bits
        if len(self.pubkeys) != size:
            return False
        if len(multisig.sigs) < self.k:
            return False
        # adversarial bytes can flag more signers than signatures supplied —
        # reject instead of indexing out of range (the reference would panic).
        # count < len(sigs) (unused trailing sigs) stays ACCEPTED: the
        # reference only indexes flagged entries and never looks at the rest
        if multisig.bitarray.count() > len(multisig.sigs):
            return False
        # each flagged signer must verify (threshold_pubkey.go:41-55)
        sig_index = 0
        for i in range(size):
            if multisig.bitarray.get_index(i):
                if not self.pubkeys[i].verify_bytes(msg, multisig.sigs[sig_index]):
                    return False
                sig_index += 1
        return sig_index >= self.k

    def flatten(
        self, msg: bytes, sig: bytes
    ) -> Optional[List[Tuple[bytes, bytes, bytes]]]:
        """Decompose into (pubkey32, msg, sig64) tuples for the TPU batch path.
        Returns None if structurally invalid or any sub-key is not ed25519."""
        try:
            multisig = Multisignature.unmarshal(sig)
        except Exception:
            return None
        if multisig.bitarray.bits != len(self.pubkeys):
            return None
        if len(multisig.sigs) < self.k:
            return None
        if multisig.bitarray.count() > len(multisig.sigs):
            return None  # mirrors verify_bytes' out-of-range rejection
        out = []
        sig_index = 0
        for i in range(len(self.pubkeys)):
            if multisig.bitarray.get_index(i):
                pk = self.pubkeys[i]
                if pk.type_name != "tendermint/PubKeyEd25519":
                    return None
                if sig_index >= len(multisig.sigs):
                    return None
                sub = multisig.sigs[sig_index]
                if len(sub) != 64:
                    # unmarshal accepts any sub-sig length; a short one would
                    # crash the whole batched dispatch downstream (frombuffer
                    # reshape) — bail to the host path, which returns False
                    return None
                out.append((pk.bytes(), msg, sub))
                sig_index += 1
        return out

    def __hash__(self):
        return hash((self.k, self.pubkeys))
