/* Native SHA-256 + merkle tree ops — the host-side hashing hot path of
 * block application (part-set construction, commit/header/validator-set
 * merkle roots). Mirrors crypto/merkle.py's RFC-6962-style tree exactly
 * (0x00 leaf prefix, 0x01 inner prefix, split at the largest power of two
 * strictly less than n, empty tree = SHA256("")).
 *
 * Replaces the reference's serial Go hashing at types/part_set.go:99 and
 * crypto/merkle/simple_tree.go:23 on the fast-sync apply path
 * (blockchain/reactor.go:299 MakePartSet rehash — SURVEY §3.4's CPU hot
 * spot). Uses x86 SHA-NI when the CPU has it (runtime-detected), with a
 * portable C fallback; both produce identical FIPS-180-4 digests.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define HAVE_X86 1
#endif

/* ------------------------------------------------------------------ */
/* portable SHA-256                                                   */
/* ------------------------------------------------------------------ */

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_block_portable(uint32_t st[8], const uint8_t *p)
{
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
               ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K256[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* ------------------------------------------------------------------ */
/* SHA-NI block function (x86)                                        */
/* ------------------------------------------------------------------ */

#ifdef HAVE_X86
__attribute__((target("sha,sse4.1")))
static void sha256_block_shani(uint32_t st[8], const uint8_t *p)
{
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    /* load state: st = {a,b,c,d,e,f,g,h}; SHA-NI wants {abef, cdgh} */
    __m128i tmp = _mm_loadu_si128((const __m128i *)&st[0]); /* a b c d */
    __m128i s1 = _mm_loadu_si128((const __m128i *)&st[4]);  /* e f g h */
    tmp = _mm_shuffle_epi32(tmp, 0xB1);  /* b a d c */
    s1 = _mm_shuffle_epi32(s1, 0x1B);    /* h g f e */
    __m128i state0 = _mm_alignr_epi8(tmp, s1, 8);   /* abef */
    __m128i state1 = _mm_blend_epi16(s1, tmp, 0xF0); /* cdgh */

    __m128i abef_save = state0, cdgh_save = state1;
    __m128i msg, msg0, msg1, msg2, msg3;

#define QROUND(m, k0, k1)                                                 \
    msg = _mm_add_epi32(m, _mm_set_epi64x(k1, k0));                       \
    state1 = _mm_sha256rnds2_epu32(state1, state0, msg);                  \
    msg = _mm_shuffle_epi32(msg, 0x0E);                                   \
    state0 = _mm_sha256rnds2_epu32(state0, state1, msg);

    msg0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 0)), MASK);
    QROUND(msg0, 0x71374491428A2F98ULL, 0xE9B5DBA5B5C0FBCFULL);
    msg1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 16)), MASK);
    QROUND(msg1, 0x59F111F13956C25BULL, 0xAB1C5ED5923F82A4ULL);
    msg0 = _mm_sha256msg1_epu32(msg0, msg1);
    msg2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 32)), MASK);
    QROUND(msg2, 0x12835B01D807AA98ULL, 0x550C7DC3243185BEULL);
    msg1 = _mm_sha256msg1_epu32(msg1, msg2);
    msg3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(p + 48)), MASK);
    QROUND(msg3, 0x80DEB1FE72BE5D74ULL, 0xC19BF1749BDC06A7ULL);

    /* Schedule step for the group rebuilding m0 as w[i..i+3]: m3 holds the
     * previous w-block (w[i-4..i-1]) and m2 the one before it — m2 must
     * still be RAW for the alignr (it supplies w[i-7..i-5]); only after
     * that may m2 take its msg1 step (whose input is its successor m3). */
#define SCHED(m0, m1, m2, m3, k0, k1)                                     \
    m0 = _mm_add_epi32(m0, _mm_alignr_epi8(m3, m2, 4));                   \
    m0 = _mm_sha256msg2_epu32(m0, m3);                                    \
    m2 = _mm_sha256msg1_epu32(m2, m3);                                    \
    QROUND(m0, k0, k1);

    SCHED(msg0, msg1, msg2, msg3, 0xEFBE4786E49B69C1ULL, 0x240CA1CC0FC19DC6ULL);
    SCHED(msg1, msg2, msg3, msg0, 0x4A7484AA2DE92C6FULL, 0x76F988DA5CB0A9DCULL);
    SCHED(msg2, msg3, msg0, msg1, 0xA831C66D983E5152ULL, 0xBF597FC7B00327C8ULL);
    SCHED(msg3, msg0, msg1, msg2, 0xD5A79147C6E00BF3ULL, 0x1429296706CA6351ULL);
    SCHED(msg0, msg1, msg2, msg3, 0x2E1B213827B70A85ULL, 0x53380D134D2C6DFCULL);
    SCHED(msg1, msg2, msg3, msg0, 0x766A0ABB650A7354ULL, 0x92722C8581C2C92EULL);
    SCHED(msg2, msg3, msg0, msg1, 0xA81A664BA2BFE8A1ULL, 0xC76C51A3C24B8B70ULL);
    SCHED(msg3, msg0, msg1, msg2, 0xD6990624D192E819ULL, 0x106AA070F40E3585ULL);
    SCHED(msg0, msg1, msg2, msg3, 0x1E376C0819A4C116ULL, 0x34B0BCB52748774CULL);
    SCHED(msg1, msg2, msg3, msg0, 0x4ED8AA4A391C0CB3ULL, 0x682E6FF35B9CCA4FULL);

    /* rounds 48-63: no more msg1 scheduling needed */
    msg2 = _mm_add_epi32(msg2, _mm_alignr_epi8(msg1, msg0, 4));
    msg2 = _mm_sha256msg2_epu32(msg2, msg1);
    QROUND(msg2, 0x78A5636F748F82EEULL, 0x8CC7020884C87814ULL);
    msg3 = _mm_add_epi32(msg3, _mm_alignr_epi8(msg2, msg1, 4));
    msg3 = _mm_sha256msg2_epu32(msg3, msg2);
    QROUND(msg3, 0xA4506CEB90BEFFFAULL, 0xC67178F2BEF9A3F7ULL);

#undef SCHED
#undef QROUND

    state0 = _mm_add_epi32(state0, abef_save);
    state1 = _mm_add_epi32(state1, cdgh_save);

    /* unpack {abef, cdgh} back to {a..h} */
    tmp = _mm_shuffle_epi32(state0, 0x1B); /* feba */
    s1 = _mm_shuffle_epi32(state1, 0xB1);  /* dchg */
    __m128i abcd = _mm_blend_epi16(tmp, s1, 0xF0);
    __m128i efgh = _mm_alignr_epi8(s1, tmp, 8);
    _mm_storeu_si128((__m128i *)&st[0], abcd);
    _mm_storeu_si128((__m128i *)&st[4], efgh);
}

static int g_have_shani = -1;
#endif

static void (*sha256_block)(uint32_t st[8], const uint8_t *p) =
    sha256_block_portable;

/* incremental context */
typedef struct {
    uint32_t st[8];
    uint8_t buf[64];
    size_t buflen;
    uint64_t total;
} sha256_ctx;

static void sha256_init(sha256_ctx *c)
{
    static const uint32_t IV[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                   0xa54ff53a, 0x510e527f, 0x9b05688c,
                                   0x1f83d9ab, 0x5be0cd19};
    memcpy(c->st, IV, sizeof(IV));
    c->buflen = 0;
    c->total = 0;
}

static void sha256_update(sha256_ctx *c, const uint8_t *p, size_t n)
{
    c->total += n;
    if (c->buflen) {
        size_t take = 64 - c->buflen;
        if (take > n)
            take = n;
        memcpy(c->buf + c->buflen, p, take);
        c->buflen += take;
        p += take;
        n -= take;
        if (c->buflen == 64) {
            sha256_block(c->st, c->buf);
            c->buflen = 0;
        }
    }
    while (n >= 64) {
        sha256_block(c->st, p);
        p += 64;
        n -= 64;
    }
    if (n) {
        memcpy(c->buf, p, n);
        c->buflen = n;
    }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32])
{
    uint64_t bits = c->total * 8;
    uint8_t pad = 0x80;
    sha256_update(c, &pad, 1);
    uint8_t zero[64] = {0};
    size_t padlen = (c->buflen <= 56) ? 56 - c->buflen : 120 - c->buflen;
    sha256_update(c, zero, padlen);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++)
        lenb[i] = (uint8_t)(bits >> (8 * (7 - i)));
    sha256_update(c, lenb, 8);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(c->st[i] >> 24);
        out[4 * i + 1] = (uint8_t)(c->st[i] >> 16);
        out[4 * i + 2] = (uint8_t)(c->st[i] >> 8);
        out[4 * i + 3] = (uint8_t)(c->st[i]);
    }
}

static void sha256_oneshot(const uint8_t *p, size_t n, uint8_t out[32])
{
    sha256_ctx c;
    sha256_init(&c);
    sha256_update(&c, p, n);
    sha256_final(&c, out);
}

/* prefix-domain digest: SHA256(prefix || data) */
static void sha256_prefixed(uint8_t prefix, const uint8_t *p, size_t n,
                            uint8_t out[32])
{
    sha256_ctx c;
    sha256_init(&c);
    sha256_update(&c, &prefix, 1);
    sha256_update(&c, p, n);
    sha256_final(&c, out);
}

/* inner node: SHA256(0x01 || left32 || right32) */
static void merkle_inner(const uint8_t *l, const uint8_t *r, uint8_t out[32])
{
    uint8_t buf[65];
    buf[0] = 0x01;
    memcpy(buf + 1, l, 32);
    memcpy(buf + 33, r, 32);
    sha256_oneshot(buf, 65, out);
}

static size_t split_point(size_t n)
{
    size_t k = 1;
    while (k * 2 < n)
        k *= 2;
    return k;
}

/* root over contiguous leaf-hash array [lo, hi) */
static void merkle_root_of_hashes(const uint8_t *lh, size_t lo, size_t hi,
                                  uint8_t out[32])
{
    size_t cnt = hi - lo;
    if (cnt == 1) {
        memcpy(out, lh + 32 * lo, 32);
        return;
    }
    size_t k = split_point(cnt);
    uint8_t left[32], right[32];
    merkle_root_of_hashes(lh, lo, lo + k, left);
    merkle_root_of_hashes(lh, lo + k, hi, right);
    merkle_inner(left, right, out);
}

/* ------------------------------------------------------------------ */
/* Python bindings                                                    */
/* ------------------------------------------------------------------ */

static PyObject *py_sha256(PyObject *mod, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    uint8_t out[32];
    sha256_oneshot((const uint8_t *)view.buf, (size_t)view.len, out);
    PyBuffer_Release(&view);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyObject *py_leaf_hash(PyObject *mod, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    uint8_t out[32];
    sha256_prefixed(0x00, (const uint8_t *)view.buf, (size_t)view.len, out);
    PyBuffer_Release(&view);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyObject *py_inner_hash(PyObject *mod, PyObject *args)
{
    Py_buffer l, r;
    if (!PyArg_ParseTuple(args, "y*y*", &l, &r))
        return NULL;
    if (l.len != 32 || r.len != 32) {
        PyBuffer_Release(&l);
        PyBuffer_Release(&r);
        PyErr_SetString(PyExc_ValueError, "inner_hash wants two 32-byte digests");
        return NULL;
    }
    uint8_t out[32];
    merkle_inner((const uint8_t *)l.buf, (const uint8_t *)r.buf, out);
    PyBuffer_Release(&l);
    PyBuffer_Release(&r);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

/* merkle_root(items: sequence[bytes]) -> bytes32 */
static PyObject *py_merkle_root(PyObject *mod, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "merkle_root wants a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    uint8_t out[32];
    if (n == 0) {
        sha256_oneshot((const uint8_t *)"", 0, out);
        Py_DECREF(seq);
        return PyBytes_FromStringAndSize((const char *)out, 32);
    }
    uint8_t *lh = PyMem_Malloc((size_t)n * 32);
    if (!lh) {
        Py_DECREF(seq);
        return PyErr_NoMemory();
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        Py_buffer view;
        if (PyObject_GetBuffer(it, &view, PyBUF_SIMPLE) < 0) {
            PyMem_Free(lh);
            Py_DECREF(seq);
            return NULL;
        }
        sha256_prefixed(0x00, (const uint8_t *)view.buf, (size_t)view.len,
                        lh + 32 * i);
        PyBuffer_Release(&view);
    }
    merkle_root_of_hashes(lh, 0, (size_t)n, out);
    PyMem_Free(lh);
    Py_DECREF(seq);
    return PyBytes_FromStringAndSize((const char *)out, 32);
}

/* leaf_hashes(items) -> list[bytes32] (for proof builders) */
static PyObject *py_leaf_hashes(PyObject *mod, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "leaf_hashes wants a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        Py_buffer view;
        if (PyObject_GetBuffer(it, &view, PyBUF_SIMPLE) < 0) {
            Py_DECREF(out);
            Py_DECREF(seq);
            return NULL;
        }
        uint8_t h[32];
        sha256_prefixed(0x00, (const uint8_t *)view.buf, (size_t)view.len, h);
        PyBuffer_Release(&view);
        PyObject *b = PyBytes_FromStringAndSize((const char *)h, 32);
        if (!b) {
            Py_DECREF(out);
            Py_DECREF(seq);
            return NULL;
        }
        PyList_SET_ITEM(out, i, b);
    }
    Py_DECREF(seq);
    return out;
}

/* part_leaf_hashes(data: bytes, part_size: int) -> list[bytes32]
 * leaf hashes of the 64kB chunks of a block's marshaled bytes — the
 * part-set construction hot loop in one native call. */
static PyObject *py_part_leaf_hashes(PyObject *mod, PyObject *args)
{
    Py_buffer view;
    Py_ssize_t part_size;
    if (!PyArg_ParseTuple(args, "y*n", &view, &part_size))
        return NULL;
    if (part_size <= 0) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "part_size must be positive");
        return NULL;
    }
    Py_ssize_t total = (view.len + part_size - 1) / part_size;
    if (total == 0)
        total = 1;
    PyObject *out = PyList_New(total);
    if (!out) {
        PyBuffer_Release(&view);
        return NULL;
    }
    const uint8_t *p = (const uint8_t *)view.buf;
    for (Py_ssize_t i = 0; i < total; i++) {
        Py_ssize_t off = i * part_size;
        Py_ssize_t len = view.len - off;
        if (len > part_size)
            len = part_size;
        if (len < 0)
            len = 0;
        uint8_t h[32];
        sha256_prefixed(0x00, p + off, (size_t)len, h);
        PyObject *b = PyBytes_FromStringAndSize((const char *)h, 32);
        if (!b) {
            Py_DECREF(out);
            PyBuffer_Release(&view);
            return NULL;
        }
        PyList_SET_ITEM(out, i, b);
    }
    PyBuffer_Release(&view);
    return out;
}

static PyObject *py_have_shani(PyObject *mod, PyObject *noarg)
{
#ifdef HAVE_X86
    return PyBool_FromLong(g_have_shani == 1);
#else
    Py_RETURN_FALSE;
#endif
}

static PyMethodDef hash_methods[] = {
    {"sha256", (PyCFunction)py_sha256, METH_O, NULL},
    {"leaf_hash", (PyCFunction)py_leaf_hash, METH_O, NULL},
    {"inner_hash", (PyCFunction)py_inner_hash, METH_VARARGS, NULL},
    {"merkle_root", (PyCFunction)py_merkle_root, METH_O, NULL},
    {"leaf_hashes", (PyCFunction)py_leaf_hashes, METH_O, NULL},
    {"part_leaf_hashes", (PyCFunction)py_part_leaf_hashes, METH_VARARGS, NULL},
    {"have_shani", (PyCFunction)py_have_shani, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef hash_module = {
    PyModuleDef_HEAD_INIT,
    "_hash_native",
    "Native SHA-256 + merkle (see crypto/merkle.py for the tree spec).",
    -1,
    hash_methods,
};

PyMODINIT_FUNC PyInit__hash_native(void)
{
#ifdef HAVE_X86
    /* TM_NO_SHANI forces the portable block fn (tests cover both paths) */
    if (!getenv("TM_NO_SHANI") && __builtin_cpu_supports("sha") &&
        __builtin_cpu_supports("sse4.1")) {
        g_have_shani = 1;
        sha256_block = sha256_block_shani;
    } else {
        g_have_shani = 0;
    }
#endif
    return PyModule_Create(&hash_module);
}
