"""Mempool — pending txs validated by the app's CheckTx
(ref: mempool/mempool.go, 980 LoC).

Structure mirrors the reference: a concurrent list of good txs feeding both
block proposals (reap_max_bytes_max_gas) and peer gossip (clist iteration with
wait-for-next), an LRU-ish cache of seen txs, recheck of survivors after every
commit, and an optional WAL of accepted txs.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto.hashing import tmhash
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.clist import CElement, CList
from tendermint_tpu.state.services import Mempool as MempoolIface


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


class MempoolFullError(MempoolError):
    def __init__(self, size: int, max_size: int):
        super().__init__(f"mempool is full: {size} >= {max_size}")


@dataclass
class MempoolTx:
    height: int  # height when tx was validated
    gas_wanted: int
    tx: bytes


class TxCache:
    """Bounded FIFO set of seen tx hashes (ref mempool.go txCache)."""

    def __init__(self, size: int):
        self._size = size
        self._map: Dict[bytes, None] = {}
        self._queue: collections.deque = collections.deque()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        h = tmhash(tx)
        with self._mtx:
            if h in self._map:
                return False
            if len(self._queue) >= self._size:
                old = self._queue.popleft()
                self._map.pop(old, None)
            self._queue.append(h)
            self._map[h] = None
            return True

    def remove(self, tx: bytes) -> None:
        h = tmhash(tx)
        with self._mtx:
            if h in self._map:
                del self._map[h]
                try:
                    self._queue.remove(h)
                except ValueError:
                    pass

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()
            self._queue.clear()


class Mempool(MempoolIface):
    def __init__(
        self,
        proxy_app,  # AppConnMempool
        height: int = 0,
        size: int = 5000,
        cache_size: int = 10000,
        max_tx_bytes: int = 1024 * 1024,
        recheck: bool = True,
        wal_group=None,
        metrics=None,
        logger=None,
    ):
        self._proxy = proxy_app
        self._txs = CList()
        self._tx_map: Dict[bytes, CElement] = {}  # tx hash -> element
        self._height = height
        self._rechecking = False
        self._recheck_cursor: Optional[CElement] = None
        self._recheck_end: Optional[CElement] = None
        self._notified_txs_available = False
        self._txs_available: Optional[threading.Event] = None
        self._max_size = size
        self._max_tx_bytes = max_tx_bytes
        self._recheck_enabled = recheck
        self.cache = TxCache(cache_size)
        self._mtx = threading.RLock()  # the consensus Lock/Unlock boundary
        self._wal = wal_group
        self.metrics = metrics
        import logging

        self.logger = logger or logging.getLogger("tm.mempool")
        self._proxy.set_response_callback(self._res_cb)

    # locking (held by BlockExecutor.commit) -------------------------------
    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    # info -----------------------------------------------------------------
    def size(self) -> int:
        return len(self._txs)

    def flush_app_conn(self) -> None:
        self._proxy.flush_sync()

    def flush(self) -> None:
        """Drop all txs + cache (unsafe_flush_mempool RPC)."""
        with self._mtx:
            self.cache.reset()
            el = self._txs.front()
            while el is not None:
                nxt = el.next()
                self._txs.remove(el)
                el = nxt
            self._tx_map.clear()

    def txs_front(self) -> Optional[CElement]:
        return self._txs.front()

    def txs_wait_chan(self):
        return self._txs

    # txs available notification -------------------------------------------
    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> Optional[threading.Event]:
        return self._txs_available

    def _notify_txs_available(self) -> None:
        if self.size() == 0:
            return
        if self._txs_available is not None and not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()

    # CheckTx ---------------------------------------------------------------
    def check_tx(self, tx: bytes, callback: Optional[Callable] = None) -> None:
        """Queue tx for app validation; good txs enter the list
        (mempool.go:301)."""
        with self._mtx:
            if self.size() >= self._max_size:
                raise MempoolFullError(self.size(), self._max_size)
            if len(tx) > self._max_tx_bytes:
                raise MempoolError(f"tx too large ({len(tx)} bytes)")
            if not self.cache.push(tx):
                raise TxInCacheError()
            if self._wal is not None:
                self._wal.write(tx + b"\n")
                self._wal.flush()
            rr = self._proxy.check_tx_async(tx)
            if callback is not None:
                rr.set_callback(lambda req, res: callback(res))
        self._proxy.flush_async()

    def _res_cb(self, req, res) -> None:
        if isinstance(res, abci.ResponseCheckTx):
            if self._recheck_cursor is None:
                self._res_cb_normal(req, res)
            else:
                self._res_cb_recheck(req, res)
            if self.metrics is not None:
                self.metrics.mempool_size.set(self.size())

    def _res_cb_normal(self, req: abci.RequestCheckTx, res: abci.ResponseCheckTx) -> None:
        tx = req.tx
        if res.code == abci.CODE_TYPE_OK:
            memtx = MempoolTx(height=self._height, gas_wanted=res.gas_wanted, tx=tx)
            el = self._txs.push_back(memtx)
            self._tx_map[tmhash(tx)] = el
            if self.metrics is not None:
                self.metrics.mempool_tx_size_bytes.observe(len(tx))
            self.logger.debug("added good tx size=%d", self.size())
            self._notify_txs_available()
        else:
            self.logger.debug("rejected bad tx code=%d log=%s", res.code, res.log)
            if self.metrics is not None:
                self.metrics.mempool_failed_txs.add(1)
            self.cache.remove(tx)

    def _res_cb_recheck(self, req: abci.RequestCheckTx, res: abci.ResponseCheckTx) -> None:
        if self.metrics is not None:
            self.metrics.mempool_recheck_times.add(1)
        cursor = self._recheck_cursor
        memtx = cursor.value
        if memtx.tx != req.tx:
            self.logger.error("recheck transaction mismatch")
        if res.code != abci.CODE_TYPE_OK:
            # committed-state invalidated this tx
            self._txs.remove(cursor)
            self._tx_map.pop(tmhash(memtx.tx), None)
            self.cache.remove(memtx.tx)
        if cursor is self._recheck_end:
            self._recheck_cursor = None
            self._rechecking = False
        else:
            self._recheck_cursor = cursor.next()

    # Reap ------------------------------------------------------------------
    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """Collect txs for a proposal under byte/gas budgets (mempool.go:471)."""
        with self._mtx:
            total_bytes = 0
            total_gas = 0
            out: List[bytes] = []
            for memtx in self._txs:
                sz = len(memtx.tx) + 8  # frame overhead allowance
                if max_bytes > -1 and total_bytes + sz > max_bytes:
                    break
                if max_gas > -1 and total_gas + memtx.gas_wanted > max_gas:
                    break
                total_bytes += sz
                total_gas += memtx.gas_wanted
                out.append(memtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._mtx:
            out = []
            for memtx in self._txs:
                if len(out) >= n >= 0:
                    break
                out.append(memtx.tx)
            return out

    # Update (after commit; mempool locked by the executor) -----------------
    def update(self, height: int, txs, pre_check=None, post_check=None) -> None:
        """Remove committed txs, recheck the rest (mempool.go:531)."""
        self._height = height
        self._notified_txs_available = False
        if self._txs_available is not None:
            self._txs_available.clear()
        for tx in txs:
            tx = bytes(tx)
            self.cache.push(tx)  # committed: keep in cache so re-adds fail
            el = self._tx_map.pop(tmhash(tx), None)
            if el is not None and not el.removed:
                self._txs.remove(el)
        if self._recheck_enabled and self.size() > 0:
            self._recheck_txs()
        else:
            self._notify_txs_available()

    def _recheck_txs(self) -> None:
        with trace.span("mempool.recheck", n=self.size()):
            self._recheck_cursor = self._txs.front()
            self._recheck_end = self._txs.back()
            self._rechecking = True
            for memtx in self._txs:
                self._proxy.check_tx_async(memtx.tx)
            self._proxy.flush_async()
        self._notify_txs_available()
