"""Mempool — pending txs validated by the app's CheckTx
(ref: mempool/mempool.go, 980 LoC).

Structure mirrors the reference: a concurrent list of good txs feeding both
block proposals (reap_max_bytes_max_gas) and peer gossip (clist iteration with
wait-for-next), an LRU-ish cache of seen txs, recheck of survivors after every
commit, and an optional WAL of accepted txs.

On top of the reference shape this mempool adds the ingestion hardening from
CometBFT's priority mempool era:

* **priority lanes** — ``ResponseCheckTx.priority`` (falling back to
  ``gas_wanted`` as a gas-price proxy) assigns each tx a lane via the
  configured ``lane_bounds`` thresholds.  Reap serves higher lanes first
  (FIFO within a lane); when the pool is full, a new tx evicts the oldest
  tx from the lowest strictly-lower lane instead of being rejected.  With
  no lanes configured (the default) behavior is exactly the reference:
  full pool ⇒ synchronous ``MempoolFullError``.
* **micro-batched CheckTx / batched recheck** — with ``checktx_batch > 1``
  incoming submissions coalesce into one app-conn flush window;
  ``recheck_batch > 0`` chunks the post-commit recheck the same way.  Pack
  and flush timings land in the `libs/profile.py` dispatch ledger
  (``mempool.checktx_batch`` / ``mempool.recheck_batch`` entries), and
  ``batch_check_hook`` is the seam where planner-based batched signature
  verification plugs in: observational by default,
  ``set_batch_check_hook(hook, verdicts=True)`` upgrades it to the
  verdict-bearing seam (mempool/tx_verify.BatchTxVerifier +
  parallel/planner.TxFeed) — each window's app sends wait for the hook's
  per-tx signature verdicts, which ride ``RequestCheckTx.sig_verified``
  so the app never pays a serial verify the planner already paid.
* **recheck cursor resync** — a tx removed mid-recheck (committed while
  responses were in flight) desynchronizes the cursor; the hash index is
  used to resynchronize instead of silently corrupting the walk.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto.hashing import tmhash
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.clist import CElement, CList
from tendermint_tpu.libs.profile import get_profiler
from tendermint_tpu.state.services import Mempool as MempoolIface


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


class MempoolFullError(MempoolError):
    def __init__(self, size: int, max_size: int):
        super().__init__(f"mempool is full: {size} >= {max_size}")


# nonzero ResponseCheckTx.code stamped on a tx rejected because the pool is
# full and no lower-lane tx can be evicted for it (multi-lane configs defer
# the full decision to the response callback, where the lane is known)
CODE_MEMPOOL_FULL = 0xF001


@dataclass
class MempoolTx:
    height: int  # height when tx was validated
    gas_wanted: int
    tx: bytes
    priority: int = 0
    lane: int = 0


class TxCache:
    """Bounded FIFO set of seen tx hashes (ref mempool.go txCache)."""

    def __init__(self, size: int):
        self._size = size
        self._map: Dict[bytes, None] = {}
        self._queue: collections.deque = collections.deque()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        h = tmhash(tx)
        with self._mtx:
            if h in self._map:
                return False
            if len(self._queue) >= self._size:
                old = self._queue.popleft()
                self._map.pop(old, None)
            self._queue.append(h)
            self._map[h] = None
            return True

    def remove(self, tx: bytes) -> None:
        h = tmhash(tx)
        with self._mtx:
            if h in self._map:
                del self._map[h]
                try:
                    self._queue.remove(h)
                except ValueError:
                    pass

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()
            self._queue.clear()


class Mempool(MempoolIface):
    def __init__(
        self,
        proxy_app,  # AppConnMempool
        height: int = 0,
        size: int = 5000,
        cache_size: int = 10000,
        max_tx_bytes: int = 1024 * 1024,
        recheck: bool = True,
        wal_group=None,
        metrics=None,
        logger=None,
        lane_bounds: Sequence[int] = (),
        checktx_batch: int = 1,
        checktx_batch_wait: float = 0.005,
        recheck_batch: int = 0,
    ):
        self._proxy = proxy_app
        self._txs = CList()
        self._tx_map: Dict[bytes, CElement] = {}  # tx hash -> element
        self._height = height
        self._rechecking = False
        self._recheck_cursor: Optional[CElement] = None
        self._recheck_end: Optional[CElement] = None
        self._recheck_pending = 0
        self._stale_recheck = 0
        self._notified_txs_available = False
        self._txs_available: Optional[threading.Event] = None
        self._max_size = size
        self._max_tx_bytes = max_tx_bytes
        self._recheck_enabled = recheck
        self.cache = TxCache(cache_size)
        self._mtx = threading.RLock()  # the consensus Lock/Unlock boundary
        self._wal = wal_group
        self.metrics = metrics
        # priority lanes: ascending thresholds; priority >= bounds[i] rides
        # lane i+1. Lane dicts hold CElement -> None in insertion (FIFO)
        # order beside the gossip CList.
        self._lane_bounds = tuple(sorted(lane_bounds))
        self._lanes: List[Dict[CElement, None]] = [
            {} for _ in range(len(self._lane_bounds) + 1)
        ]
        # micro-batching (1 = flush per submission, reference behavior)
        self._checktx_batch = max(1, int(checktx_batch))
        self._checktx_batch_wait = checktx_batch_wait
        self._recheck_batch = max(0, int(recheck_batch))
        self._pending_flush = 0
        self._pending_since = 0.0
        self._flush_timer: Optional[threading.Timer] = None
        # seam for planner-based batched signature verification: when set,
        # called with the list of raw txs in each CheckTx/recheck window
        # before the flush that dispatches them.  Observational by default
        # (the PR-8 contract); set_batch_check_hook(hook, verdicts=True)
        # upgrades it to the verdict-bearing seam: the window's app sends
        # are deferred until the hook returns its per-tx signature
        # verdicts, which ride RequestCheckTx.sig_verified so the app
        # skips its own serial verify.
        self.batch_check_hook: Optional[Callable[[List[bytes]], None]] = None
        self._hook_verdicts = False
        self._batch_txs: List[bytes] = []
        self._batch_cbs: List[Optional[Callable]] = []
        self._proxy_takes_verdict: Optional[bool] = None
        import logging

        self.logger = logger or logging.getLogger("tm.mempool")
        self._proxy.set_response_callback(self._res_cb)

    # locking (held by BlockExecutor.commit) -------------------------------
    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def set_batch_check_hook(
        self, hook: Optional[Callable], *, verdicts: bool = False
    ) -> None:
        """Install the CheckTx-window hook.

        ``verdicts=False`` keeps the observational contract: the hook is
        called with each window's raw txs, fire-and-forget, after the app
        requests were already queued.  ``verdicts=True`` makes it the
        verdict-bearing seam (mempool/tx_verify.BatchTxVerifier): the
        window's ``check_tx_async`` sends are DEFERRED until the hook
        returns a per-tx verdict list (True = signature verified good,
        False = verified bad, None = unknown), and each verdict rides its
        request's ``sig_verified`` field so the app skips its own serial
        signature check.  Verdicts are advisory exactly as far as the
        planner's bit-identical accept/reject contract reaches — the app
        still owns the response (nonce/state checks, reject codes)."""
        self.batch_check_hook = hook
        self._hook_verdicts = bool(hook is not None and verdicts)

    def _send_checktx(self, tx: bytes, sig_verified=None):
        """One app-conn CheckTx send carrying the batched-verify verdict;
        conns predating the hint (test fakes) get the bare call.  The
        probe is by signature, not try/except: a local conn runs the app
        inline, so a TypeError out of the app must not trigger a resend."""
        if self._proxy_takes_verdict is None:
            import inspect

            try:
                params = inspect.signature(
                    self._proxy.check_tx_async
                ).parameters
                self._proxy_takes_verdict = "sig_verified" in params
            except (TypeError, ValueError):
                self._proxy_takes_verdict = False
        if self._proxy_takes_verdict:
            return self._proxy.check_tx_async(tx, sig_verified=sig_verified)
        return self._proxy.check_tx_async(tx)

    # info -----------------------------------------------------------------
    def size(self) -> int:
        return len(self._txs)

    def height(self) -> int:
        """Height the pool last validated against (feeds the tx feed's
        critpath height annotation)."""
        return self._height

    def n_lanes(self) -> int:
        return len(self._lanes)

    def lane_of(self, priority: int) -> int:
        lane = 0
        for bound in self._lane_bounds:
            if priority >= bound:
                lane += 1
            else:
                break
        return lane

    def lane_sizes(self) -> List[int]:
        with self._mtx:
            return [len(lane) for lane in self._lanes]

    def flush_app_conn(self) -> None:
        self._proxy.flush_sync()

    def flush(self) -> None:
        """Drop all txs + cache (unsafe_flush_mempool RPC)."""
        with self._mtx:
            self.cache.reset()
            el = self._txs.front()
            while el is not None:
                nxt = el.next()
                self._txs.remove(el)
                el = nxt
            self._tx_map.clear()
            for lane in self._lanes:
                lane.clear()
            self._update_lane_metrics()

    def txs_front(self) -> Optional[CElement]:
        return self._txs.front()

    def txs_wait_chan(self):
        return self._txs

    # txs available notification -------------------------------------------
    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> Optional[threading.Event]:
        return self._txs_available

    def _notify_txs_available(self) -> None:
        if self.size() == 0:
            return
        if self._txs_available is not None and not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()

    # element bookkeeping ---------------------------------------------------
    def _add_tx(self, memtx: MempoolTx) -> CElement:
        el = self._txs.push_back(memtx)
        self._tx_map[tmhash(memtx.tx)] = el
        self._lanes[memtx.lane][el] = None
        return el

    def _remove_el(self, el: CElement, *, from_cache: bool) -> None:
        if el.removed:
            return
        self._txs.remove(el)
        memtx = el.value
        self._tx_map.pop(tmhash(memtx.tx), None)
        self._lanes[memtx.lane].pop(el, None)
        if from_cache:
            self.cache.remove(memtx.tx)

    def _update_lane_metrics(self) -> None:
        if self.metrics is None or len(self._lanes) <= 1:
            return
        for i, lane in enumerate(self._lanes):
            self.metrics.mempool_lane_txs.set(len(lane), (str(i),))

    def _evict_for_lane(self, lane: int) -> bool:
        """Make room for an incoming lane-`lane` tx: drop the oldest tx from
        the lowest occupied lane strictly below it.  False = nothing
        evictable (the newcomer is rejected instead)."""
        for low in range(lane):
            if self._lanes[low]:
                victim = next(iter(self._lanes[low]))
                self._remove_el(victim, from_cache=True)
                self.logger.debug(
                    "evicted lane-%d tx for lane-%d arrival", low, lane
                )
                if self.metrics is not None:
                    self.metrics.mempool_qos_evicted_total.add(1.0, (str(low),))
                return True
        return False

    # CheckTx ---------------------------------------------------------------
    def check_tx(self, tx: bytes, callback: Optional[Callable] = None) -> None:
        """Queue tx for app validation; good txs enter the list
        (mempool.go:301).

        Single-lane configs keep the reference contract: a full pool raises
        ``MempoolFullError`` synchronously.  With lanes configured the full
        decision needs the tx's priority, so it is deferred to the response
        callback — the tx either evicts a lower-lane victim or comes back
        with ``code=CODE_MEMPOOL_FULL``.
        """
        flush = False
        with self._mtx:
            if self.size() >= self._max_size and len(self._lanes) == 1:
                raise MempoolFullError(self.size(), self._max_size)
            if len(tx) > self._max_tx_bytes:
                raise MempoolError(f"tx too large ({len(tx)} bytes)")
            if not self.cache.push(tx):
                raise TxInCacheError()
            if self._wal is not None:
                self._wal.write(tx + b"\n")
                self._wal.flush()
            if self._hook_verdicts:
                # verdict-bearing seam: the app send waits for the flush,
                # where the batched signature verdict rides the request
                self._batch_cbs.append(callback)
            else:
                rr = self._proxy.check_tx_async(tx)
                if callback is not None:
                    rr.set_callback(lambda req, res: callback(res))
            if self._pending_flush == 0:
                self._pending_since = time.perf_counter()
            self._pending_flush += 1
            self._batch_txs.append(tx)
            if (self._checktx_batch <= 1
                    or self._pending_flush >= self._checktx_batch):
                flush = True
            elif self._flush_timer is None:
                t = threading.Timer(
                    self._checktx_batch_wait, self._flush_deadline
                )
                t.daemon = True
                self._flush_timer = t
                t.start()
        if flush:
            self._flush_checktx_batch()

    def _flush_deadline(self) -> None:
        # batch-wait timer: flush whatever has accumulated
        with self._mtx:
            self._flush_timer = None
        self._flush_checktx_batch()

    def _flush_checktx_batch(self) -> None:
        """Close the current micro-batch: one app-conn flush window for
        every CheckTx accumulated since the last one."""
        with self._mtx:
            n = self._pending_flush
            if n == 0:
                return
            self._pending_flush = 0
            batch_txs, self._batch_txs = self._batch_txs, []
            batch_cbs, self._batch_cbs = self._batch_cbs, []
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None
            pack_s = time.perf_counter() - self._pending_since
            hook = self.batch_check_hook
            verdict_mode = self._hook_verdicts and hook is not None
            if hook is not None and not verdict_mode:
                hook(batch_txs)
        if verdict_mode:
            # hook OUTSIDE the lock: it blocks on the tx feed's flush
            # window and admission must not hold the consensus Lock/Unlock
            # boundary hostage for it
            verdicts = None
            try:
                verdicts = hook(batch_txs)
            except Exception:
                self.logger.exception(
                    "batch check hook failed; falling back to serial verify"
                )
            if verdicts is not None and len(verdicts) != len(batch_txs):
                self.logger.error(
                    "batch check hook returned %d verdicts for %d txs; "
                    "ignored", len(verdicts), len(batch_txs),
                )
                verdicts = None
            with self._mtx:
                for i, tx in enumerate(batch_txs):
                    rr = self._send_checktx(
                        tx, None if verdicts is None else verdicts[i]
                    )
                    cb = batch_cbs[i] if i < len(batch_cbs) else None
                    if cb is not None:
                        rr.set_callback(lambda req, res, _cb=cb: _cb(res))
        t0 = time.perf_counter()
        self._proxy.flush_async()
        run_s = time.perf_counter() - t0
        if self._checktx_batch > 1:
            get_profiler().record(
                "mempool.checktx_batch",
                bucket=(n,),
                lanes_present=n,
                pack_seconds=pack_s,
                run_seconds=run_s,
            )
        if self.metrics is not None:
            self.metrics.mempool_checktx_batch_size.observe(n)

    def _res_cb(self, req, res) -> None:
        if isinstance(res, abci.ResponseCheckTx):
            with self._mtx:
                if self._stale_recheck > 0:
                    # a commit aborted the recheck round these belong to;
                    # responses arrive in send order, so the next N CheckTx
                    # responses are exactly the aborted round's leftovers
                    self._stale_recheck -= 1
                    return
                if self._rechecking:
                    self._res_cb_recheck(req, res)
                else:
                    self._res_cb_normal(req, res)
                self._update_lane_metrics()
            if self.metrics is not None:
                self.metrics.mempool_size.set(self.size())

    def _res_cb_normal(self, req: abci.RequestCheckTx, res: abci.ResponseCheckTx) -> None:
        tx = req.tx
        if res.code == abci.CODE_TYPE_OK:
            priority = res.priority if res.priority else res.gas_wanted
            lane = self.lane_of(priority)
            if self.size() >= self._max_size:
                # full: admit by evicting below, else reject this tx —
                # the rejection is stamped on the response so RPC callbacks
                # (broadcast_tx_sync/commit) surface it to the submitter
                if not self._evict_for_lane(lane):
                    self.logger.debug(
                        "full mempool rejected lane-%d tx", lane
                    )
                    if self.metrics is not None:
                        self.metrics.mempool_failed_txs.add(1)
                    self.cache.remove(tx)
                    res.code = CODE_MEMPOOL_FULL
                    res.log = (
                        f"mempool is full: {self.size()} >= {self._max_size}"
                    )
                    return
            memtx = MempoolTx(
                height=self._height, gas_wanted=res.gas_wanted, tx=tx,
                priority=priority, lane=lane,
            )
            self._add_tx(memtx)
            if self.metrics is not None:
                self.metrics.mempool_tx_size_bytes.observe(len(tx))
            self.logger.debug("added good tx size=%d", self.size())
            self._notify_txs_available()
        else:
            self.logger.debug("rejected bad tx code=%d log=%s", res.code, res.log)
            if self.metrics is not None:
                self.metrics.mempool_failed_txs.add(1)
            self.cache.remove(tx)

    def _res_cb_recheck(self, req: abci.RequestCheckTx, res: abci.ResponseCheckTx) -> None:
        if self.metrics is not None:
            self.metrics.mempool_recheck_times.add(1)
        self._recheck_pending -= 1
        cursor = self._recheck_cursor
        el: Optional[CElement] = None
        if (cursor is not None and not cursor.removed
                and cursor.value.tx == req.tx):
            el = cursor
        else:
            # desync: the cursor's tx was removed mid-recheck (committed
            # while responses were in flight). Resynchronize on the live
            # element for THIS response via the hash index; a response for
            # a tx no longer in the pool is simply dropped.
            el = self._tx_map.get(tmhash(req.tx))
            if el is not None and el.removed:
                el = None
            if el is not None:
                self.logger.warning(
                    "recheck transaction mismatch; cursor resynchronized"
                )
            else:
                self.logger.debug(
                    "recheck response for tx no longer in pool; dropped"
                )
        if el is not None:
            if res.code != abci.CODE_TYPE_OK:
                # committed-state invalidated this tx
                self._remove_el(el, from_cache=True)
            # removed elements keep their next pointer, so this advances
            # correctly even when the walk crossed removed territory
            self._recheck_cursor = el.next()
        if self._recheck_pending <= 0:
            self._recheck_cursor = None
            self._recheck_end = None
            self._rechecking = False

    # Reap ------------------------------------------------------------------
    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """Collect txs for a proposal under byte/gas budgets (mempool.go:471).

        Lanes serve high to low, FIFO within a lane; single-lane configs
        degrade to pure insertion order (the reference behavior)."""
        with self._mtx:
            total_bytes = 0
            total_gas = 0
            out: List[bytes] = []
            for lane in reversed(self._lanes):
                for el in lane:
                    memtx = el.value
                    sz = len(memtx.tx) + 8  # frame overhead allowance
                    if max_bytes > -1 and total_bytes + sz > max_bytes:
                        return out
                    if max_gas > -1 and total_gas + memtx.gas_wanted > max_gas:
                        return out
                    total_bytes += sz
                    total_gas += memtx.gas_wanted
                    out.append(memtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._mtx:
            out: List[bytes] = []
            for lane in reversed(self._lanes):
                for el in lane:
                    if len(out) >= n >= 0:
                        return out
                    out.append(el.value.tx)
            return out

    # Update (after commit; mempool locked by the executor) -----------------
    def update(self, height: int, txs, pre_check=None, post_check=None) -> None:
        """Remove committed txs, recheck the rest (mempool.go:531)."""
        self._height = height
        if self._rechecking:
            # the previous round never finished (async app conn): its
            # in-flight responses describe pre-commit state, so mark them
            # stale rather than letting them race the new round's cursor
            self._stale_recheck += self._recheck_pending
            self._recheck_pending = 0
            self._recheck_cursor = None
            self._recheck_end = None
            self._rechecking = False
        self._notified_txs_available = False
        if self._txs_available is not None:
            self._txs_available.clear()
        for tx in txs:
            tx = bytes(tx)
            self.cache.push(tx)  # committed: keep in cache so re-adds fail
            el = self._tx_map.get(tmhash(tx))
            if el is not None:
                self._remove_el(el, from_cache=False)
        self._update_lane_metrics()
        if self._recheck_enabled and self.size() > 0:
            self._recheck_txs()
        else:
            self._notify_txs_available()

    def _recheck_txs(self) -> None:
        with trace.span("mempool.recheck", n=self.size()):
            self._recheck_cursor = self._txs.front()
            self._recheck_end = self._txs.back()
            self._recheck_pending = self.size()
            self._rechecking = True
            batch = self._recheck_batch or self.size()
            sent: List[bytes] = []
            t_pack = time.perf_counter()
            # snapshot first: with a local app conn, responses arrive inline
            # and mutate the list while we would still be walking it
            survivors = [memtx.tx for memtx in self._txs]
            for tx in survivors:
                if not self._hook_verdicts:
                    self._proxy.check_tx_async(tx)
                # verdict mode defers the send to the window flush, where
                # the (cached) signature verdict rides along
                sent.append(tx)
                if len(sent) >= batch:
                    self._flush_recheck_batch(sent, t_pack)
                    sent = []
                    t_pack = time.perf_counter()
            if sent:
                self._flush_recheck_batch(sent, t_pack)
        self._notify_txs_available()

    def _flush_recheck_batch(self, batch_txs: List[bytes], t_pack: float) -> None:
        hook = self.batch_check_hook
        if hook is not None and not self._hook_verdicts:
            hook(batch_txs)
        elif hook is not None:
            # verdict-bearing recheck: survivors already passed admission,
            # so the hook answers from its tx-hash verdict cache — rechecks
            # re-run app state checks only, not signatures.  Sends stay in
            # walk order so the recheck cursor's FIFO contract holds.
            verdicts = None
            try:
                verdicts = hook(batch_txs)
            except Exception:
                self.logger.exception(
                    "batch check hook failed on recheck; serial verify"
                )
            if verdicts is not None and len(verdicts) != len(batch_txs):
                verdicts = None
            for i, tx in enumerate(batch_txs):
                self._send_checktx(
                    tx, None if verdicts is None else verdicts[i]
                )
        pack_s = time.perf_counter() - t_pack
        t0 = time.perf_counter()
        self._proxy.flush_async()
        run_s = time.perf_counter() - t0
        if self._recheck_batch > 0:
            get_profiler().record(
                "mempool.recheck_batch",
                bucket=(len(batch_txs),),
                lanes_present=len(batch_txs),
                pack_seconds=pack_s,
                run_seconds=run_s,
            )
        if self.metrics is not None:
            self.metrics.mempool_checktx_batch_size.observe(len(batch_txs))
