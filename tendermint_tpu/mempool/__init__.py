from tendermint_tpu.mempool.mempool import (
    CODE_MEMPOOL_FULL,
    Mempool,
    MempoolError,
    MempoolFullError,
    MempoolTx,
    TxCache,
    TxInCacheError,
)
from tendermint_tpu.mempool.qos import MempoolQoS, TokenBucket
from tendermint_tpu.mempool.reactor import MempoolReactor

__all__ = [
    "CODE_MEMPOOL_FULL",
    "Mempool",
    "MempoolError",
    "MempoolFullError",
    "MempoolQoS",
    "MempoolReactor",
    "MempoolTx",
    "TokenBucket",
    "TxCache",
    "TxInCacheError",
]
