"""Mempool admission control: per-peer token buckets, deterministic
fairness under contention, and repeat-offender muting.

The reference mempool admits any peer's txs as fast as the wire delivers
them; one spamming peer can starve honest traffic long before consensus
notices.  Later Tendermint/CometBFT releases grew per-peer flow control
around the priority mempool — this module is that layer for the gossip
reactor (and anything else with a per-source identity):

* **per-peer token buckets** — txs/s and bytes/s, refilled continuously
  from an injectable ``now_ns`` clock (``sim/clock.SimClock`` plugs in
  directly, so refill math is unit-testable to the token);
* **deterministic fairness** — an optional aggregate bucket caps total
  admission; when it contends, peers at or below their fair share of the
  recent grant window may overdraft a bounded reserve while over-share
  peers are shed first.  Every decision is a pure function of the call
  sequence and the injected clock — no randomness;
* **repeat-offender muting** — sustained violations demote the peer:
  drops escalate into a temporary mute whose duration doubles per offense
  (capped), and a clean quiet period forgives the offense count.

Decisions are never silent: each one lands in the
``tendermint_mempool_qos_*`` counters (when metrics are wired) and in the
per-peer ledger served by the unsafe ``dump_mempool_qos`` RPC.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

# admission decision reasons (the `reason` label on
# tendermint_mempool_qos_dropped_total)
ADMIT = "ok"
DROP_TX_RATE = "tx_rate"
DROP_BYTE_RATE = "byte_rate"
DROP_MUTED = "muted"
DROP_FAIR = "fair"


class TokenBucket:
    """Continuous-refill token bucket over an injectable ns clock.

    ``rate <= 0`` disables the bucket (every consume succeeds).  Refill is
    exact float math on the clock delta, so with a frozen/stepped clock the
    token level is fully deterministic.
    """

    def __init__(self, rate: float, burst: float,
                 now_ns: Callable[[], int] = time.monotonic_ns):
        self.rate = float(rate)
        self.burst = float(burst)
        self._now_ns = now_ns
        self._tokens = self.burst
        self._last_ns = now_ns()

    def _refill(self, t_ns: int) -> None:
        dt_ns = t_ns - self._last_ns
        if dt_ns > 0:
            self._tokens = min(
                self.burst, self._tokens + (dt_ns / 1e9) * self.rate
            )
            self._last_ns = t_ns

    def try_consume(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        self._refill(self._now_ns())
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def consume_with_overdraft(self, n: float = 1.0,
                               floor: float = 0.0) -> bool:
        """Consume even past empty, down to ``-floor`` — the bounded
        reserve an under-share peer may draw on when the bucket contends."""
        if self.rate <= 0:
            return True
        self._refill(self._now_ns())
        if self._tokens - n >= -floor:
            self._tokens -= n
            return True
        return False

    def level(self) -> float:
        if self.rate <= 0:
            return self.burst
        self._refill(self._now_ns())
        return self._tokens


class PeerState:
    """Per-peer admission ledger (buckets + offender bookkeeping)."""

    def __init__(self, tx_bucket: TokenBucket, byte_bucket: TokenBucket):
        self.tx_bucket = tx_bucket
        self.byte_bucket = byte_bucket
        self.admitted = 0        # lifetime admitted txs
        self.dropped = 0         # lifetime dropped txs
        self.window_admitted = 0.0  # decayed fair-share counter
        self.violations = 0      # consecutive-ish drops since last clean run
        self.offenses = 0        # mutes served (exponential penalty index)
        self.muted_until_ns = 0
        self.last_drop_reason = ""

    def snapshot(self) -> dict:
        return {
            "admitted": self.admitted,
            "dropped": self.dropped,
            "window_admitted": round(self.window_admitted, 2),
            "violations": self.violations,
            "offenses": self.offenses,
            "muted": self.muted_until_ns > 0,
            "muted_until_ns": self.muted_until_ns,
            "last_drop_reason": self.last_drop_reason,
            "tx_tokens": round(self.tx_bucket.level(), 3),
            "byte_tokens": round(self.byte_bucket.level(), 1),
        }


class MempoolQoS:
    """Admission controller for per-source mempool traffic.

    One instance per reactor; ``admit(peer_id, n_bytes)`` is the single
    decision point.  All state is guarded by one lock — admission is a few
    float ops, far off the hot path's critical constant.
    """

    def __init__(self, config, metrics=None,
                 now_ns: Callable[[], int] = time.monotonic_ns):
        """``config`` is a ``MempoolConfig`` (only the ``qos_*`` fields are
        read); ``metrics`` is a ``NodeMetrics`` (or None)."""
        self._cfg = config
        self.metrics = metrics
        self._now_ns = now_ns
        self._mtx = threading.Lock()
        self._peers: Dict[str, PeerState] = {}
        self._global: Optional[TokenBucket] = None
        if getattr(config, "qos_global_tx_rate", 0) > 0:
            burst = getattr(config, "qos_global_tx_burst", 0) or (
                2.0 * config.qos_global_tx_rate
            )
            self._global = TokenBucket(
                config.qos_global_tx_rate, burst, now_ns
            )
        # fair-share window: decays lazily every window_ns of injected time
        self._window_ns = int(
            getattr(config, "qos_fair_window_s", 1.0) * 1e9
        )
        self._window_start_ns = now_ns()
        self._window_grants = 0.0
        self._mutes_total = 0

    # -- internals -----------------------------------------------------------

    def _peer(self, peer_id: str) -> PeerState:
        st = self._peers.get(peer_id)
        if st is None:
            c = self._cfg
            st = PeerState(
                TokenBucket(
                    getattr(c, "qos_peer_tx_rate", 0),
                    getattr(c, "qos_peer_tx_burst", 0)
                    or 2.0 * getattr(c, "qos_peer_tx_rate", 0),
                    self._now_ns,
                ),
                TokenBucket(
                    getattr(c, "qos_peer_byte_rate", 0),
                    getattr(c, "qos_peer_byte_burst", 0)
                    or 2.0 * getattr(c, "qos_peer_byte_rate", 0),
                    self._now_ns,
                ),
            )
            self._peers[peer_id] = st
        return st

    def _decay_window(self, t_ns: int) -> None:
        """Halve the fair-share counters once per elapsed window — cheap,
        lazy, and a pure function of the injected clock."""
        while t_ns - self._window_start_ns >= self._window_ns:
            self._window_start_ns += self._window_ns
            self._window_grants /= 2.0
            for st in self._peers.values():
                st.window_admitted /= 2.0

    def _fair_share(self) -> float:
        """A peer's tolerated slice of the recent grant window."""
        n = max(1, len(self._peers))
        slack = getattr(self._cfg, "qos_fair_slack", 1.5)
        # +1 keeps the very first grants of a window from tripping fairness
        return slack * (self._window_grants / n) + 1.0

    def _violate(self, st: PeerState, reason: str, t_ns: int) -> Tuple[bool, str]:
        st.dropped += 1
        st.violations += 1
        st.last_drop_reason = reason
        mute_after = getattr(self._cfg, "qos_mute_after", 0)
        if mute_after > 0 and st.violations >= mute_after:
            base = getattr(self._cfg, "qos_mute_base_s", 1.0)
            cap = getattr(self._cfg, "qos_mute_max_s", 60.0)
            dur_s = min(cap, base * (2.0 ** st.offenses))
            st.offenses += 1
            st.violations = 0
            st.muted_until_ns = t_ns + int(dur_s * 1e9)
            self._mutes_total += 1
            if self.metrics is not None:
                self.metrics.mempool_qos_mutes_total.add(1)
                self.metrics.mempool_qos_muted_peers.set(
                    sum(1 for p in self._peers.values()
                        if p.muted_until_ns > t_ns)
                )
        if self.metrics is not None:
            self.metrics.mempool_qos_dropped_total.add(1.0, (reason,))
        return False, reason

    # -- the decision point --------------------------------------------------

    def admit(self, peer_id: str, n_bytes: int) -> Tuple[bool, str]:
        """Admission decision for one tx from ``peer_id``.

        Returns ``(admitted, reason)``; reason is ``"ok"`` on admission or
        one of {tx_rate, byte_rate, muted, fair} on a drop.
        """
        t_ns = self._now_ns()
        with self._mtx:
            st = self._peer(peer_id)
            self._decay_window(t_ns)
            if st.muted_until_ns:
                if st.muted_until_ns > t_ns:
                    st.dropped += 1
                    st.last_drop_reason = DROP_MUTED
                    if self.metrics is not None:
                        self.metrics.mempool_qos_dropped_total.add(
                            1.0, (DROP_MUTED,)
                        )
                    return False, DROP_MUTED
                # mute expired; a long-enough clean stretch forgives the
                # exponential-penalty index entirely
                forgive_ns = int(
                    getattr(self._cfg, "qos_forgive_s", 30.0) * 1e9
                )
                if t_ns - st.muted_until_ns > forgive_ns:
                    st.offenses = 0
                st.muted_until_ns = 0
                if self.metrics is not None:
                    self.metrics.mempool_qos_muted_peers.set(
                        sum(1 for p in self._peers.values()
                            if p.muted_until_ns > t_ns)
                    )
            if not st.tx_bucket.try_consume(1.0):
                return self._violate(st, DROP_TX_RATE, t_ns)
            if not st.byte_bucket.try_consume(float(n_bytes)):
                return self._violate(st, DROP_BYTE_RATE, t_ns)
            if self._global is not None and not self._global.try_consume(1.0):
                # aggregate budget contends: shed over-share peers first;
                # an under-share peer may overdraft a bounded reserve so a
                # spammer cannot starve honest, slower sources
                reserve = getattr(self._cfg, "qos_fair_reserve", 0) or (
                    self._global.burst
                )
                if (st.window_admitted > self._fair_share()
                        or not self._global.consume_with_overdraft(
                            1.0, floor=reserve)):
                    return self._violate(st, DROP_FAIR, t_ns)
            st.admitted += 1
            st.window_admitted += 1.0
            self._window_grants += 1.0
            st.violations = max(0, st.violations - 1)
            if self.metrics is not None:
                self.metrics.mempool_qos_admitted_total.add(1)
            return True, ADMIT

    # -- lifecycle / introspection -------------------------------------------

    def forget_peer(self, peer_id: str) -> None:
        """Drop a disconnected peer's ledger (cardinality hygiene — a
        reconnecting offender starts from a fresh, full bucket, exactly as
        a restarted reference node would see it)."""
        with self._mtx:
            self._peers.pop(peer_id, None)

    def peer_state(self, peer_id: str) -> Optional[dict]:
        with self._mtx:
            st = self._peers.get(peer_id)
            return st.snapshot() if st is not None else None

    def snapshot(self) -> dict:
        """The dump_mempool_qos view: per-peer ledgers + controller totals."""
        t_ns = self._now_ns()
        with self._mtx:
            return {
                "enabled": True,
                "peers": {pid: st.snapshot() for pid, st in self._peers.items()},
                "muted_peers": sum(
                    1 for st in self._peers.values()
                    if st.muted_until_ns > t_ns
                ),
                "mutes_total": self._mutes_total,
                "window_grants": round(self._window_grants, 2),
                "global_tokens": (
                    round(self._global.level(), 3)
                    if self._global is not None else None
                ),
            }
