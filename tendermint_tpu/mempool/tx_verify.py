"""The mempool side of batched transaction-ingest verification.

`BatchTxVerifier` is the verdict-bearing ``batch_check_hook``
(Mempool.set_batch_check_hook(..., verdicts=True)): for each CheckTx or
recheck window it extracts every tx's ``(pubkey, sign_bytes, sig)`` via an
app-supplied extractor (e.g. abci/examples/kvstore.extract_signed_tx_sig),
submits the rows to a `parallel/planner.TxFeed` keyed by the window, and
blocks on the verdict tickets — one deadline-bounded `plan_windows`
superdispatch per flush, riding the PR-9 breaker/deadline/audit/host-
fallback guard, with `RLCHostVerifier` as the chipless backend.

Verdicts are cached by tx hash, which is what makes the post-commit
recheck cheap: survivors already passed admission, so their recheck window
answers entirely from the cache and only re-runs the app's state checks —
never a second signature verification (the mempool.py recheck-flush parity
fix).  Cache entries are deterministic facts (a signature either verifies
over its sign-bytes or it doesn't), so serving a hit is bit-identical to
re-dispatching.

Every degradation is graceful and bit-identical: an unsigned/odd tx, a
closed feed, a flush error or a ticket timeout all yield a ``None``
verdict, which the app answers with its own serial verify — the exact
check the planner would have run.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, List, Optional

from tendermint_tpu.crypto.hashing import tmhash


class BatchTxVerifier:
    """Verdict-bearing CheckTx-window hook: extract → feed → tickets →
    per-tx verdicts, with a bounded tx-hash verdict cache for rechecks.

    extractor: ``tx -> (pub, sign_bytes, sig) | None`` (None = the app
    decides the whole verdict serially).
    height_fn: ``() -> int`` supplying the mempool's current height for
    the feed's group keys — the critpath analyzer joins each flush's cost
    into the ``verify_dispatch`` overlay of that height.
    """

    def __init__(self, feed, extractor: Callable, *,
                 timeout_s: float = 5.0, cache_size: int = 10000,
                 height_fn: Optional[Callable[[], int]] = None):
        self.feed = feed
        self.extractor = extractor
        self.timeout_s = float(timeout_s)
        self.height_fn = height_fn
        self._cache_size = max(1, int(cache_size))
        self._cache: "collections.OrderedDict[bytes, bool]" = (
            collections.OrderedDict()
        )
        self._mtx = threading.Lock()
        self._seq = 0
        # observability (asserted by tests, surfaced by benches)
        self.windows = 0  # hook invocations (CheckTx + recheck flushes)
        self.submitted = 0  # txs dispatched to the feed
        self.cache_hits = 0  # verdicts served from the tx-hash cache
        self.unsigned = 0  # txs the extractor declined (app decides)
        self.feed_errors = 0  # submit/flush/timeout failures (app decides)

    def __call__(self, batch_txs: List[bytes]) -> List[Optional[bool]]:
        n = len(batch_txs)
        verdicts: List[Optional[bool]] = [None] * n
        with self._mtx:
            self.windows += 1
            self._seq += 1
            seq = self._seq
        height = 0
        if self.height_fn is not None:
            try:
                height = int(self.height_fn())
            except Exception:
                height = 0
        group_key = (height, seq)
        tickets = []  # (batch index, tx hash, ticket)
        for i, tx in enumerate(batch_txs):
            h = tmhash(tx)
            with self._mtx:
                cached = self._cache.get(h)
            if cached is not None:
                verdicts[i] = cached
                self.cache_hits += 1
                continue
            try:
                item = self.extractor(tx)
            except Exception:
                item = None
            if item is None:
                self.unsigned += 1
                continue
            pub, msg, sig = item
            try:
                tickets.append((i, h, self.feed.submit(group_key, pub, msg, sig)))
            except Exception:
                self.feed_errors += 1
                continue
            self.submitted += 1
        if tickets:
            # the window IS a complete mempool batch — collapse the feed's
            # deadline so admission never waits it out; the window only
            # pays off when several callers (recheck + admission, other
            # reactors) land inside it anyway
            self.feed.flush_now()
            for i, h, ticket in tickets:
                try:
                    ok = bool(ticket.result(timeout=self.timeout_s).ok)
                except BaseException:
                    self.feed_errors += 1
                    continue
                verdicts[i] = ok
                with self._mtx:
                    self._cache[h] = ok
                    while len(self._cache) > self._cache_size:
                        self._cache.popitem(last=False)
        return verdicts
