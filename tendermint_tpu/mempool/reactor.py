"""Mempool gossip reactor, channel 0x30 (ref: mempool/reactor.go).

One broadcast thread per peer walks the mempool's concurrent list with
wait-for-next semantics (reactor.go broadcastTxRoutine:118-166): every good
tx reaches every peer exactly once per connection, new txs wake the walkers.
A tx is held back while the peer lags more than one height behind the height
the tx was validated at (reactor.go:150 peerState height check). Received
txs go through CheckTx like any RPC submission — the app is the filter.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.libs.gossip import walk_and_send
from tendermint_tpu.mempool.mempool import Mempool, MempoolError
from tendermint_tpu.mempool.qos import MempoolQoS
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor

MEMPOOL_CHANNEL = 0x30
MAX_MSG_SIZE = 1024 * 1024  # reactor.go maxMsgSize
PEER_CATCHUP_SLEEP = 0.1  # reactor.go peerCatchupSleepIntervalMS


def encode_tx_msg(tx: bytes) -> bytes:
    w = Writer()
    w.uvarint(1)  # TxMessage tag
    w.bytes(tx)
    return w.build()


def decode_tx_msg(data: bytes) -> bytes:
    r = Reader(data)
    if r.uvarint() != 1:
        raise ValueError("unknown mempool message tag")
    return r.bytes()


class MempoolReactor(Reactor):
    def __init__(self, mempool: Mempool, peer_height_lookup=None, config=None,
                 metrics=None, now_ns=None):
        """peer_height_lookup(peer_id) -> Optional[int]: the peer's consensus
        height, normally ConsensusReactor.peer_height (wired by the node /
        harness); None = assume caught up.

        When ``config.qos_enabled`` (a MempoolConfig) the per-peer admission
        controller gates every received tx before CheckTx; ``now_ns`` is the
        QoS clock (a SimClock in the simulator)."""
        super().__init__(name="MempoolReactor")
        self.mempool = mempool
        self.config = config
        self._peer_height_lookup = peer_height_lookup
        self.qos: Optional[MempoolQoS] = None
        if config is not None and getattr(config, "qos_enabled", False):
            kwargs = {"metrics": metrics}
            if now_ns is not None:
                kwargs["now_ns"] = now_ns
            self.qos = MempoolQoS(config, **kwargs)

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=MEMPOOL_CHANNEL, priority=5, send_queue_capacity=100,
                recv_message_capacity=MAX_MSG_SIZE,
            )
        ]

    def _peer_height(self, peer_id: str) -> Optional[int]:
        if self._peer_height_lookup is None:
            return None
        try:
            return self._peer_height_lookup(peer_id)
        except Exception:
            return None

    def add_peer(self, peer) -> None:
        if self.config is not None and not self.config.broadcast:
            return  # tx gossip disabled (reactor.go gates on config.Broadcast)
        threading.Thread(
            target=self._broadcast_tx_routine,
            args=(peer,),
            name=f"mempool-gossip-{peer.id[:8]}",
            daemon=True,
        ).start()
    def remove_peer(self, peer, reason=None) -> None:
        # the broadcast thread exits on peer.is_running; only the QoS
        # ledger needs explicit cleanup (label-cardinality hygiene)
        if self.qos is not None:
            self.qos.forget_peer(peer.id)

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        if len(msg_bytes) > MAX_MSG_SIZE:
            raise ValueError("oversized mempool message")
        tx = decode_tx_msg(msg_bytes)
        if self.qos is not None:
            ok, _reason = self.qos.admit(peer.id, len(tx))
            if not ok:
                return  # counted (qos_dropped_total{reason}) — not silent
        try:
            self.mempool.check_tx(tx)
        except MempoolError:
            pass  # dup/full/bad txs are unremarkable from gossip

    def qos_snapshot(self):
        """Per-peer admission ledger for the dump_mempool_qos RPC."""
        if self.qos is None:
            return {"enabled": False, "peers": {}}
        return self.qos.snapshot()

    # -- per-peer walker ---------------------------------------------------------
    def _broadcast_tx_routine(self, peer) -> None:
        def hold_back(memtx) -> bool:
            # hold while the peer's consensus height lags the tx's height
            h = self._peer_height(peer.id)
            return h is not None and h < memtx.height - 1

        walk_and_send(
            alive=lambda: self.is_running and peer.is_running,
            front=self.mempool.txs_front,
            send=lambda memtx: peer.send(MEMPOOL_CHANNEL, encode_tx_msg(memtx.tx)),
            hold_back=hold_back,
        )
