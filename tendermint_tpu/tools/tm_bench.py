"""tm-bench — tx load generator + throughput stats
(ref: tools/tm-bench/main.go:21, statistics.go:132-141).

Spams broadcast_tx_async at target rate over N connections for T seconds,
watches NewBlock events over the websocket, and reports Txs/sec and
Blocks/sec (avg/stddev/max) exactly like the reference's summary table.

Usage:
    python -m tendermint_tpu.tools.tm_bench [-T 10] [-r 1000] [-c 1] \
        [--output-format plain|json] tcp://127.0.0.1:26657
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
from collections import defaultdict
from typing import Dict, List

from tendermint_tpu.rpc.client import HTTPClient, WSEventClient


def _spammer(addr: str, rate: int, duration: float, stop, sent_counter: List[int], idx: int):
    client = HTTPClient(addr)
    interval = 1.0 / max(1, rate)
    deadline = time.monotonic() + duration
    i = 0
    while time.monotonic() < deadline and not stop.is_set():
        tx = f"bench-{idx}-{i}-{os.getpid()}=x{time.monotonic_ns()}".encode()
        try:
            client.broadcast_tx_async(tx)
            sent_counter[idx] += 1
        except Exception:
            time.sleep(0.05)
            continue
        i += 1
        # pace toward the target rate (busy loops melt small nodes)
        next_at = deadline - duration + i * interval
        delay = next_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)


def run_bench(
    addr: str, duration: float = 10.0, rate: int = 1000, connections: int = 1
) -> Dict:
    stop = threading.Event()
    sent = [0] * connections

    # watch blocks over WS while spamming
    ws = WSEventClient(addr)
    ws.subscribe("tm.event = 'NewBlock'")
    blocks: List[dict] = []

    def _watch():
        while not stop.is_set():
            try:
                ev = ws.next_event(timeout=0.5)
            except Exception:
                continue
            header = ev["data"]["value"]["block"]["header"]
            blocks.append(
                {"height": header["height"], "num_txs": header["num_txs"],
                 "at": time.monotonic()}
            )

    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()

    threads = [
        threading.Thread(
            target=_spammer, args=(addr, rate // connections, duration, stop, sent, i),
            daemon=True,
        )
        for i in range(connections)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    time.sleep(1.0)  # drain the last block(s)
    stop.set()
    elapsed = time.monotonic() - t0
    ws.close()

    # per-second tallies (statistics.go groups per second)
    per_sec_txs: Dict[int, int] = defaultdict(int)
    per_sec_blocks: Dict[int, int] = defaultdict(int)
    for b in blocks:
        sec = int(b["at"] - t0)
        per_sec_txs[sec] += b["num_txs"]
        per_sec_blocks[sec] += 1
    secs = range(int(elapsed) + 1)
    tx_rates = [per_sec_txs.get(s, 0) for s in secs]
    block_rates = [per_sec_blocks.get(s, 0) for s in secs]

    def _stats(xs):
        if not xs:
            return {"avg": 0, "stddev": 0, "max": 0}
        avg = sum(xs) / len(xs)
        var = sum((x - avg) ** 2 for x in xs) / len(xs)
        return {"avg": round(avg, 3), "stddev": round(math.sqrt(var), 3), "max": max(xs)}

    return {
        "duration_s": round(elapsed, 2),
        "txs_sent": sum(sent),
        "txs_committed": sum(b["num_txs"] for b in blocks),
        "blocks_seen": len(blocks),
        "txs_per_sec": _stats(tx_rates),
        "blocks_per_sec": _stats(block_rates),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("endpoint", nargs="?", default="tcp://127.0.0.1:26657")
    p.add_argument("-T", "--duration", type=float, default=10.0)
    p.add_argument("-r", "--rate", type=int, default=1000)
    p.add_argument("-c", "--connections", type=int, default=1)
    p.add_argument("--output-format", choices=["plain", "json"], default="plain")
    args = p.parse_args(argv)

    stats = run_bench(args.endpoint, args.duration, args.rate, args.connections)
    if args.output_format == "json":
        print(json.dumps(stats))
    else:
        print("===")
        print(
            f"Txs/sec    avg {stats['txs_per_sec']['avg']}  "
            f"stddev {stats['txs_per_sec']['stddev']}  max {stats['txs_per_sec']['max']}"
        )
        print(
            f"Blocks/sec avg {stats['blocks_per_sec']['avg']}  "
            f"stddev {stats['blocks_per_sec']['stddev']}  max {stats['blocks_per_sec']['max']}"
        )
        print(
            f"(sent {stats['txs_sent']} txs, committed {stats['txs_committed']}, "
            f"{stats['blocks_seen']} blocks in {stats['duration_s']}s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
