"""Operational tools: tm-bench (tx load generator) and tm-monitor (multi-node
health dashboard) equivalents (ref: /root/reference/tools/)."""
