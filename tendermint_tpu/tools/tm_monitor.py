"""tm-monitor — multi-node health/uptime dashboard
(ref: tools/tm-monitor/monitor/monitor.go:21, node.go, network.go).

Tracks N nodes over RPC + websocket NewBlock events: per-node height,
latency, uptime %, and network-wide health (all nodes online + heights in
agreement). A /metrics scrape per poll feeds verify-dispatch latency and
p2p traffic columns. Renders a refreshing table, or JSON snapshots with
--json; offline nodes carry the last error and downtime duration instead
of silently flipping `online`.

Usage:
    python -m tendermint_tpu.tools.tm_monitor tcp://127.0.0.1:26657,tcp://...
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import threading
import time
from typing import Dict, List, Optional
from urllib.parse import urlparse

from tendermint_tpu.rpc.client import HTTPClient, WSEventClient


def _scrape_metrics(addr: str, timeout: float = 3.0) -> Dict[str, float]:
    """Raw GET of /metrics (the JSON-RPC client can't — exposition is plain
    text).  Returns {metric_key: value} where labeled series key as
    `name{labels}`; histograms contribute their _sum/_count series."""
    u = urlparse(addr if "//" in addr else f"tcp://{addr}")
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        if resp.status != 200:
            return {}
        text = resp.read().decode("utf-8", "replace")
    finally:
        conn.close()
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(None, 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out


def _sum_family(metrics: Dict[str, float], name: str) -> float:
    """Total across every series of a (possibly labeled) family."""
    total = 0.0
    for k, v in metrics.items():
        if k == name or k.startswith(name + "{"):
            total += v
    return total


# commit-path phase -> CRIT column abbreviation (critpath.PHASES order)
_CRIT_ABBREV = {
    "propose_wait": "prop",
    "block_parts": "parts",
    "prevote_quorum": "prevote",
    "precommit_quorum": "precommit",
    "wal_append": "wal",
    "wal_fsync": "fsync",
    "abci_exec": "exec",
    "commit_persist": "persist",
}


def _phase_label(key: str, family: str) -> Optional[str]:
    """Extract phase="..." from `family{...}` series keys."""
    if not key.startswith(family + "{"):
        return None
    i = key.find('phase="')
    if i < 0:
        return None
    j = key.find('"', i + 7)
    return key[i + 7 : j] if j > i else None


def _verify_path(metrics: Dict[str, float]) -> str:
    """Which ed25519 verify strategy served device windows, from the
    `ed25519_path` label of verify_fe_backend_total: "ladder", "msm",
    "mixed" when both appear, "-" when no device dispatch recorded."""
    fam = "tendermint_verify_fe_backend_total{"
    seen = set()
    for k, v in metrics.items():
        if not k.startswith(fam) or v <= 0:
            continue
        i = k.find('ed25519_path="')
        if i < 0:
            continue
        j = k.find('"', i + 14)
        if j > i:
            seen.add(k[i + 14 : j])
    if not seen:
        return "-"
    if len(seen) > 1:
        return "mixed"
    return seen.pop()


def _quorum_column(metrics: Dict[str, float]) -> str:
    """Mean time-to-strict-2/3 quorum across vote kinds, from the
    quorum_time_to_two_thirds_seconds family's _sum/_count; "-" when the
    quorum observatory has no samples (flight recorder off)."""
    fam = "tendermint_consensus_quorum_time_to_two_thirds_seconds"
    total = _sum_family(metrics, fam + "_sum")
    count = _sum_family(metrics, fam + "_count")
    if count <= 0:
        return "-"
    return f"{1e3 * total / count:.0f}ms"


def _spool_column(metrics: Dict[str, float]) -> str:
    """Telemetry-spool health from the tendermint_telemetry_* families:
    `N@SIZE` (snapshots written @ on-disk bytes), suffixed `!E` when any
    write/drop errors accumulated; "-" when the spool is not running."""
    snaps = _sum_family(metrics, "tendermint_telemetry_snapshots_total")
    size = _sum_family(metrics, "tendermint_telemetry_spool_bytes")
    if snaps <= 0 and size <= 0:
        return "-"
    errs = _sum_family(
        metrics, "tendermint_telemetry_write_errors_total"
    ) + _sum_family(metrics, "tendermint_telemetry_dropped_snapshots_total")
    out = f"{snaps:.0f}@{_fmt_bytes(size)}"
    return f"{out}!{errs:.0f}" if errs > 0 else out


def _crit_column(metrics: Dict[str, float]) -> str:
    """Dominant commit-path phase from the height_phase_seconds family:
    `phase avg_ms` where avg is the per-height mean of the phase with the
    largest accumulated seconds; "-" when the family has no samples."""
    fam = "tendermint_consensus_height_phase_seconds"
    sums: Dict[str, float] = {}
    counts: Dict[str, float] = {}
    for k, v in metrics.items():
        phase = _phase_label(k, fam + "_sum")
        if phase is not None:
            sums[phase] = sums.get(phase, 0.0) + v
            continue
        phase = _phase_label(k, fam + "_count")
        if phase is not None:
            counts[phase] = counts.get(phase, 0.0) + v
    live = {p: s for p, s in sums.items() if counts.get(p, 0) > 0}
    if not live:
        return "-"
    top = max(live, key=live.get)
    avg_ms = 1e3 * live[top] / counts[top]
    return f"{_CRIT_ABBREV.get(top, top)} {avg_ms:.0f}ms"


class NodeMonitor:
    """One node's live stats (monitor/node.go)."""

    def __init__(self, addr: str):
        self.addr = addr
        self.online = False
        self.moniker = "?"
        self.network = "?"
        self.height = 0
        self.block_latency_ms = 0.0
        # offline diagnostics: why and since when (monotonic)
        self.last_error: Optional[str] = None
        self.offline_since: Optional[float] = None
        # hot-path columns from /metrics
        self.verify_ms = 0.0  # avg verify-dispatch latency
        self.verify_path = "-"  # ed25519 strategy (ladder | msm | mixed)
        self.traffic_bytes = 0.0  # total per-peer send+recv wire bytes
        # liveness-watchdog columns (tendermint_consensus_stall*)
        self.stalls_total = 0
        self.stall_seconds = 0.0
        # device-guard columns (tendermint_verify_device_*)
        self.device_state = -1  # -1 unknown, else breaker gauge code
        self.device_fallbacks = 0
        # critical-path column (tendermint_consensus_height_phase_seconds):
        # dominant commit-path phase + its mean per-height cost, or "-"
        # when the critpath analyzer has no samples (flight recorder off)
        self.crit = "-"
        # quorum column (tendermint_consensus_quorum_time_to_two_thirds_
        # seconds): mean time-to-strict-2/3 across vote kinds, or "-"
        self.quorum = "-"
        # telemetry-spool column (tendermint_telemetry_*): snapshots
        # written @ spool bytes, error-suffixed; "-" when spooling is off
        self.spool = "-"
        self._last_block_at: Optional[float] = None
        self._started = time.monotonic()
        self._online_time = 0.0
        self._last_poll = self._started
        self._stop = threading.Event()
        self._ws: Optional[WSEventClient] = None
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self) -> None:
        client = HTTPClient(self.addr, timeout=3.0)
        while not self._stop.is_set():
            now = time.monotonic()
            try:
                st = client.status()
                self.moniker = st["node_info"]["moniker"]
                self.network = st["node_info"]["network"]
                self.height = int(st["sync_info"]["latest_block_height"])
                if self.online:
                    self._online_time += now - self._last_poll
                self.online = True
                self.last_error = None
                self.offline_since = None
                self._scrape()
                if self._ws is None:
                    self._connect_ws()
            except Exception as e:
                if self.online or self.offline_since is None:
                    self.offline_since = now
                self.online = False
                self.last_error = f"{type(e).__name__}: {e}"
                if self._ws is not None:
                    self._ws.close()  # else the socket + watcher thread leak
                    self._ws = None
            self._last_poll = now
            self._stop.wait(1.0)

    def _scrape(self) -> None:
        """Best-effort /metrics poll for the latency/traffic columns —
        a node with prometheus disabled just shows zeros."""
        try:
            m = _scrape_metrics(self.addr)
        except Exception:
            return
        s = _sum_family(m, "tendermint_verify_dispatch_seconds_sum")
        c = _sum_family(m, "tendermint_verify_dispatch_seconds_count")
        if c > 0:
            self.verify_ms = round(1e3 * s / c, 1)
        self.verify_path = _verify_path(m)
        self.traffic_bytes = _sum_family(
            m, "tendermint_p2p_peer_send_bytes_total"
        ) + _sum_family(m, "tendermint_p2p_peer_receive_bytes_total")
        self.stalls_total = int(
            _sum_family(m, "tendermint_consensus_stalls_total")
        )
        self.stall_seconds = _sum_family(
            m, "tendermint_consensus_stall_seconds"
        )
        if "tendermint_verify_device_breaker_state" in m:
            self.device_state = int(
                m["tendermint_verify_device_breaker_state"]
            )
        self.device_fallbacks = int(
            _sum_family(m, "tendermint_verify_device_fallback_total")
        )
        self.crit = _crit_column(m)
        self.quorum = _quorum_column(m)
        self.spool = _spool_column(m)

    def _connect_ws(self) -> None:
        try:
            ws = WSEventClient(self.addr, timeout=3.0)
            ws.subscribe("tm.event = 'NewBlock'")
            self._ws = ws
            threading.Thread(target=self._watch_blocks, daemon=True).start()
        except Exception:
            self._ws = None

    def _watch_blocks(self) -> None:
        ws = self._ws
        while not self._stop.is_set() and ws is not None:
            try:
                ev = ws.next_event(timeout=1.0)
            except Exception:
                if self._ws is not ws:
                    return
                continue
            now = time.monotonic()
            header = ev["data"]["value"]["block"]["header"]
            self.height = max(self.height, int(header["height"]))
            if self._last_block_at is not None:
                self.block_latency_ms = round((now - self._last_block_at) * 1e3, 1)
            self._last_block_at = now

    @property
    def uptime_pct(self) -> float:
        total = time.monotonic() - self._started
        return round(100.0 * self._online_time / total, 1) if total > 0 else 0.0

    @property
    def downtime_s(self) -> Optional[float]:
        if self.offline_since is None:
            return None
        return round(time.monotonic() - self.offline_since, 1)

    def snapshot(self) -> dict:
        return {
            "addr": self.addr,
            "moniker": self.moniker,
            "network": self.network,
            "online": self.online,
            "last_error": self.last_error,
            "downtime_s": self.downtime_s,
            "height": self.height,
            "block_interval_ms": self.block_latency_ms,
            "verify_ms": self.verify_ms,
            "verify_path": self.verify_path,
            "traffic_bytes": self.traffic_bytes,
            "stalls_total": self.stalls_total,
            "stall_seconds": self.stall_seconds,
            "device_state": self.device_state,
            "device_fallbacks": self.device_fallbacks,
            "crit": self.crit,
            "quorum": self.quorum,
            "spool": self.spool,
            "uptime_pct": self.uptime_pct,
        }

    def stop(self) -> None:
        self._stop.set()
        if self._ws is not None:
            self._ws.close()


class NetworkMonitor:
    """Aggregates node monitors into network health (monitor/network.go)."""

    def __init__(self, addrs: List[str]):
        self.nodes = [NodeMonitor(a) for a in addrs]

    def health(self) -> str:
        ups = [n for n in self.nodes if n.online]
        if not ups:
            return "dead"
        if len(ups) < len(self.nodes):
            return "moderate"
        heights = [n.height for n in ups]
        if max(heights) - min(heights) > 5:
            return "moderate"  # someone lags
        return "full"

    def snapshot(self) -> dict:
        return {
            "health": self.health(),
            "num_nodes": len(self.nodes),
            "num_online": sum(1 for n in self.nodes if n.online),
            "max_height": max((n.height for n in self.nodes), default=0),
            "nodes": [n.snapshot() for n in self.nodes],
        }

    def stop(self) -> None:
        for n in self.nodes:
            n.stop()


# breaker gauge code -> DEVICE column label (libs/breaker.STATE_GAUGE)
_DEVICE_LABEL = {0: "ok", 1: "OPEN", 2: "PROBE", 3: "QUAR"}


def _fmt_verify(ms: float, path: str) -> str:
    """VERIFY column: mean dispatch latency, annotated with the ed25519
    strategy once a device window has dispatched (ladder | msm | mixed)."""
    base = f"{ms}ms"
    return base if path in ("-", "") else f"{base}/{path}"


def _fmt_device(state: int, fallbacks: int) -> str:
    if state < 0:
        return "-"
    label = _DEVICE_LABEL.get(state, f"?{state}")
    return f"{label}+fb{fallbacks}" if fallbacks else label


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GB"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("endpoints", help="comma-separated tcp://host:port list")
    p.add_argument("--json", action="store_true", help="emit JSON snapshots")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--iterations", type=int, default=0, help="0 = forever")
    args = p.parse_args(argv)

    net = NetworkMonitor([a.strip() for a in args.endpoints.split(",") if a.strip()])
    i = 0
    try:
        while True:
            time.sleep(args.interval)
            snap = net.snapshot()
            if args.json:
                print(json.dumps(snap), flush=True)
            else:
                print(f"\nnetwork: {snap['health']}  "
                      f"({snap['num_online']}/{snap['num_nodes']} online, "
                      f"height {snap['max_height']})")
                print(f"{'MONIKER':<16}{'HEIGHT':>8}{'INTERVAL':>10}"
                      f"{'VERIFY':>14}{'DEVICE':>10}{'CRIT':>15}"
                      f"{'QUORUM':>8}{'SPOOL':>12}"
                      f"{'TRAFFIC':>10}{'STALL':>9}{'UPTIME':>8}  ADDR")
                for n in snap["nodes"]:
                    if n["online"]:
                        suffix = ""
                    else:
                        why = n["last_error"] or "unreachable"
                        down = n["downtime_s"]
                        dur = f" {down:.0f}s" if down is not None else ""
                        suffix = f"  (OFFLINE{dur}: {why})"
                    # actively stalled -> live stall age; past stalls -> count
                    if n["stall_seconds"] > 0:
                        stall = f"!{n['stall_seconds']:.0f}s"
                    elif n["stalls_total"] > 0:
                        stall = f"x{n['stalls_total']}"
                    else:
                        stall = "-"
                    print(
                        f"{n['moniker']:<16}{n['height']:>8}"
                        f"{n['block_interval_ms']:>9}ms"
                        f"{_fmt_verify(n['verify_ms'], n.get('verify_path', '-')):>14}"
                        f"{_fmt_device(n['device_state'], n['device_fallbacks']):>10}"
                        f"{n['crit']:>15}"
                        f"{n.get('quorum', '-'):>8}"
                        f"{n.get('spool', '-'):>12}"
                        f"{_fmt_bytes(n['traffic_bytes']):>10}"
                        f"{stall:>9}"
                        f"{n['uptime_pct']:>7}%  "
                        f"{n['addr']}{suffix}"
                    )
            i += 1
            if args.iterations and i >= args.iterations:
                return 0
    except KeyboardInterrupt:
        return 0
    finally:
        net.stop()


if __name__ == "__main__":
    sys.exit(main())
