"""tm-monitor — multi-node health/uptime dashboard
(ref: tools/tm-monitor/monitor/monitor.go:21, node.go, network.go).

Tracks N nodes over RPC + websocket NewBlock events: per-node height,
latency, uptime %, and network-wide health (all nodes online + heights in
agreement). Renders a refreshing table, or JSON snapshots with --json.

Usage:
    python -m tendermint_tpu.tools.tm_monitor tcp://127.0.0.1:26657,tcp://...
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

from tendermint_tpu.rpc.client import HTTPClient, WSEventClient


class NodeMonitor:
    """One node's live stats (monitor/node.go)."""

    def __init__(self, addr: str):
        self.addr = addr
        self.online = False
        self.moniker = "?"
        self.network = "?"
        self.height = 0
        self.block_latency_ms = 0.0
        self._last_block_at: Optional[float] = None
        self._started = time.monotonic()
        self._online_time = 0.0
        self._last_poll = self._started
        self._stop = threading.Event()
        self._ws: Optional[WSEventClient] = None
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self) -> None:
        client = HTTPClient(self.addr, timeout=3.0)
        while not self._stop.is_set():
            now = time.monotonic()
            try:
                st = client.status()
                self.moniker = st["node_info"]["moniker"]
                self.network = st["node_info"]["network"]
                self.height = int(st["sync_info"]["latest_block_height"])
                if self.online:
                    self._online_time += now - self._last_poll
                self.online = True
                if self._ws is None:
                    self._connect_ws()
            except Exception:
                self.online = False
                if self._ws is not None:
                    self._ws.close()  # else the socket + watcher thread leak
                    self._ws = None
            self._last_poll = now
            self._stop.wait(1.0)

    def _connect_ws(self) -> None:
        try:
            ws = WSEventClient(self.addr, timeout=3.0)
            ws.subscribe("tm.event = 'NewBlock'")
            self._ws = ws
            threading.Thread(target=self._watch_blocks, daemon=True).start()
        except Exception:
            self._ws = None

    def _watch_blocks(self) -> None:
        ws = self._ws
        while not self._stop.is_set() and ws is not None:
            try:
                ev = ws.next_event(timeout=1.0)
            except Exception:
                if self._ws is not ws:
                    return
                continue
            now = time.monotonic()
            header = ev["data"]["value"]["block"]["header"]
            self.height = max(self.height, int(header["height"]))
            if self._last_block_at is not None:
                self.block_latency_ms = round((now - self._last_block_at) * 1e3, 1)
            self._last_block_at = now

    @property
    def uptime_pct(self) -> float:
        total = time.monotonic() - self._started
        return round(100.0 * self._online_time / total, 1) if total > 0 else 0.0

    def snapshot(self) -> dict:
        return {
            "addr": self.addr,
            "moniker": self.moniker,
            "network": self.network,
            "online": self.online,
            "height": self.height,
            "block_interval_ms": self.block_latency_ms,
            "uptime_pct": self.uptime_pct,
        }

    def stop(self) -> None:
        self._stop.set()
        if self._ws is not None:
            self._ws.close()


class NetworkMonitor:
    """Aggregates node monitors into network health (monitor/network.go)."""

    def __init__(self, addrs: List[str]):
        self.nodes = [NodeMonitor(a) for a in addrs]

    def health(self) -> str:
        ups = [n for n in self.nodes if n.online]
        if not ups:
            return "dead"
        if len(ups) < len(self.nodes):
            return "moderate"
        heights = [n.height for n in ups]
        if max(heights) - min(heights) > 5:
            return "moderate"  # someone lags
        return "full"

    def snapshot(self) -> dict:
        return {
            "health": self.health(),
            "num_nodes": len(self.nodes),
            "num_online": sum(1 for n in self.nodes if n.online),
            "max_height": max((n.height for n in self.nodes), default=0),
            "nodes": [n.snapshot() for n in self.nodes],
        }

    def stop(self) -> None:
        for n in self.nodes:
            n.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("endpoints", help="comma-separated tcp://host:port list")
    p.add_argument("--json", action="store_true", help="emit JSON snapshots")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--iterations", type=int, default=0, help="0 = forever")
    args = p.parse_args(argv)

    net = NetworkMonitor([a.strip() for a in args.endpoints.split(",") if a.strip()])
    i = 0
    try:
        while True:
            time.sleep(args.interval)
            snap = net.snapshot()
            if args.json:
                print(json.dumps(snap), flush=True)
            else:
                print(f"\nnetwork: {snap['health']}  "
                      f"({snap['num_online']}/{snap['num_nodes']} online, "
                      f"height {snap['max_height']})")
                print(f"{'MONIKER':<16}{'HEIGHT':>8}{'INTERVAL':>10}{'UPTIME':>8}  ADDR")
                for n in snap["nodes"]:
                    print(
                        f"{n['moniker']:<16}{n['height']:>8}"
                        f"{n['block_interval_ms']:>9}ms{n['uptime_pct']:>7}%  "
                        f"{n['addr']}{'' if n['online'] else '  (OFFLINE)'}"
                    )
            i += 1
            if args.iterations and i >= args.iterations:
                return 0
    except KeyboardInterrupt:
        return 0
    finally:
        net.stop()


if __name__ == "__main__":
    sys.exit(main())
