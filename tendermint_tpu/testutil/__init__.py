from tendermint_tpu.testutil.chain import ChainFixture, build_chain

__all__ = ["ChainFixture", "build_chain"]
