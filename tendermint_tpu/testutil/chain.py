"""Synthetic signed-chain builder — N validators, K heights, real commits,
real state execution (the reference grows such fixtures ad hoc in
types/test_util.go MakeCommit + consensus/wal_generator.go:31).

Used by the fast-sync tests, the light-client tests, and the fast-sync
benchmark (50k-block replay config, BASELINE.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from tendermint_tpu.abci.examples.kvstore import KVStoreApp
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.crypto.keys import PrivKeyEd25519
from tendermint_tpu.libs.db.kv import DB, MemDB
from tendermint_tpu.proxy.app_conn import LocalClientCreator, MultiAppConn
from tendermint_tpu.state import store as sm_store
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state_types import State, state_from_genesis
from tendermint_tpu.types import (
    BlockID,
    Commit,
    GenesisDoc,
    GenesisValidator,
    MockPV,
    SignedMsgType,
    Vote,
    VoteSet,
)


@dataclass
class ChainFixture:
    chain_id: str
    genesis: GenesisDoc
    pvs: List[MockPV]  # sorted-set order
    state: State  # state after the last applied block
    state_db: DB
    block_store: BlockStore
    height: int


def build_chain(
    n_vals: int = 4,
    n_heights: int = 10,
    chain_id: str = "chain-fixture",
    txs_per_block: int = 0,
    block_store_db: Optional[DB] = None,
    state_db: Optional[DB] = None,
    app_factory: Optional[Callable[[], object]] = None,
    genesis: Optional[GenesisDoc] = None,
    pvs: Optional[List[MockPV]] = None,
    on_height: Optional[Callable[[int, State], List[bytes]]] = None,
    extra_pvs: Optional[List[MockPV]] = None,
) -> ChainFixture:
    """Builds and EXECUTES a chain: every block's commit is signed by all
    validators and applied through a real BlockExecutor + app, so headers
    (app_hash, results, valset hashes) are exactly what a live node produces.

    on_height(h, state) -> txs lets callers inject txs (e.g. valset changes
    via PersistentKVStoreApp val-txs)."""
    if genesis is None:
        # 4-byte counter repeated: unique for any n_vals (a single repeated
        # byte capped fixtures at 255 validators)
        seeds = [(i + 1).to_bytes(4, "big") * 8 for i in range(n_vals)]
        pv_list = [MockPV(PrivKeyEd25519.generate(s)) for s in seeds]
        genesis = GenesisDoc(
            chain_id=chain_id,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[GenesisValidator(pv.get_pub_key(), 10) for pv in pv_list],
        )
        genesis.validate_and_complete()
    else:
        pv_list = list(pvs or [])
        chain_id = genesis.chain_id

    st = state_from_genesis(genesis)
    # order pvs by sorted validator-set position; extra_pvs = keys for
    # validators that JOIN mid-chain (via app val-txs) and must sign commits
    by_addr = {pv.get_pub_key().address(): pv for pv in pv_list}
    for pv in extra_pvs or []:
        by_addr[pv.get_pub_key().address()] = pv
    sorted_pvs = [by_addr[v.address] for v in st.validators.validators]

    state_db = state_db if state_db is not None else MemDB()
    sm_store.save_state(state_db, st)
    conn = MultiAppConn(
        LocalClientCreator(app_factory() if app_factory else KVStoreApp())
    )
    conn.start()
    block_exec = BlockExecutor(state_db, conn.consensus)
    block_store = BlockStore(block_store_db if block_store_db is not None else MemDB())

    last_commit = Commit()
    base_time = genesis.genesis_time_ns
    for h in range(1, n_heights + 1):
        if on_height is not None:
            txs = on_height(h, st)
        else:
            txs = [
                f"k{h}-{j}=v{h}".encode() for j in range(txs_per_block)
            ]
        proposer = st.validators.get_proposer()
        block = st.make_block(h, txs, last_commit, [], proposer.address)
        parts = block.make_part_set()
        block_id = BlockID(hash=block.hash(), parts_header=parts.header())

        # all validators precommit (timestamps strictly after block time so
        # the NEXT block's median passes the monotonic-time check)
        vote_set = VoteSet(chain_id, h, 0, SignedMsgType.PRECOMMIT, st.validators)
        for idx, val in enumerate(st.validators.validators):
            pv = by_addr[val.address]
            vote = Vote(
                vote_type=SignedMsgType.PRECOMMIT,
                height=h,
                round=0,
                timestamp_ns=base_time + (h + 1) * 1_000_000_000,
                block_id=block_id,
                validator_address=val.address,
                validator_index=idx,
            )
            vote_set.add_vote(pv.sign_vote(chain_id, vote))
        seen_commit = vote_set.make_commit()

        block_store.save_block(block, parts, seen_commit)
        st = block_exec.apply_block(st, block_id, block)
        last_commit = seen_commit

    return ChainFixture(
        chain_id=chain_id,
        genesis=genesis,
        pvs=sorted_pvs,
        state=st,
        state_db=state_db,
        block_store=block_store,
        height=n_heights,
    )
