"""Scenario — the timed fault-schedule DSL and its runner.

A `Scenario` is data: node count, one PRNG seed, a timed list of
`FaultOp`s (policy changes, partitions, silences, clock skews, tx
injections, height marks), plus optional `setup`/`drive`/`check` hooks for
phase-dependent logic that a fixed timeline can't express (e.g. "wait for
the stall report, then heal").

`run_scenario` builds the net, replays the ops on their timeline, waits
for the completion condition, and then ALWAYS asserts the two invariants
every scenario shares:

* **safety** — no two nodes committed different blocks at any height
  (cross-checked from every node's block store);
* **replayability** — every seeded fault decision the fabric logged
  re-derives bit-identically from (seed, link, seq).

Everything observed lands in the returned `ScenarioResult`: per-node
commit hashes, flight-recorder dumps (for `trace_merge`), fault counters,
stall reports, failures.  `seed` is printed on every failure so the run
can be replayed exactly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tendermint_tpu.sim.simnet import LinkPolicy


@dataclass
class FaultOp:
    """One timed operation.  `at_s` is seconds after net start."""

    at_s: float
    # policy|clear_policies|partition|heal|silence|unsilence|skew|tx|mark|
    # crash_restart
    op: str
    kwargs: dict = field(default_factory=dict)


@dataclass
class Scenario:
    name: str
    description: str
    n_vals: int = 4
    seed: int = 0
    target_height: int = 5
    timeout_s: float = 60.0
    ops: List[FaultOp] = field(default_factory=list)
    config_factory: Optional[Callable[[], object]] = None
    app_factory: Optional[Callable[[int], object]] = None
    clock_factory: Optional[Callable[[int], object]] = None
    byzantine: Optional[Dict[int, Callable]] = None
    setup: Optional[Callable[["ScenarioRun"], None]] = None
    # phase-dependent middle part; returns failure strings.  Default waits
    # for every node to pass target_height.
    drive: Optional[Callable[["ScenarioRun"], List[str]]] = None
    check: Optional[Callable[["ScenarioRun"], List[str]]] = None


@dataclass
class ScenarioResult:
    name: str
    seed: int
    ok: bool
    failures: List[str]
    elapsed_s: float
    heights: List[int]
    commit_hashes: List[Dict[int, str]]  # per node: height -> hash hex
    commit_rounds: List[Dict[int, int]]  # per node: height -> commit round
    flight_dumps: List[dict]
    critpath_dumps: List[dict]  # per node: cs.critpath.snapshot()
    quorum_dumps: List[dict]  # per node: cs.quorumtrace.snapshot()
    fault_summary: dict
    stall_reports: List[dict]
    marks: Dict[str, dict]


def round0_clean_top(result: "ScenarioResult") -> int:
    """Highest height H such that every node committed heights 1..H with
    every commit forming at round 0.  Same-seed determinism is only
    guaranteed up to this height: a round > 0 commit means a real-time
    timeout fired (host under load), after which proposer rotation may
    legitimately diverge between otherwise identical runs."""
    tops = []
    for hashes, rounds in zip(result.commit_hashes, result.commit_rounds):
        top = 0
        h = 1
        while h in hashes and rounds.get(h, 0) == 0:
            top = h
            h += 1
        tops.append(top)
    return min(tops) if tops else 0


class ScenarioRun:
    """Live state handed to setup/drive/check hooks."""

    def __init__(self, scenario: Scenario, fabric, nodes):
        self.scenario = scenario
        self.fabric = fabric
        self.nodes = nodes
        self.marks: Dict[str, dict] = {}
        self.failures: List[str] = []
        self.t0 = 0.0
        self._defers: List[Callable[[], None]] = []

    def defer(self, fn: Callable[[], None]) -> None:
        """Register a cleanup to run after every node has stopped (LIFO) —
        scenarios use it for tmpdirs and process-global verifier swaps."""
        self._defers.append(fn)

    def heights(self) -> List[int]:
        return [n.height for n in self.nodes]

    def mark(self, label: str) -> dict:
        m = {"t_s": round(time.monotonic() - self.t0, 3),
             "heights": self.heights()}
        self.marks[label] = m
        return m

    def wait_for(self, predicate, timeout: float, interval: float = 0.02) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(interval)
        return False

    def wait_height(self, height: int, timeout: float,
                    nodes: Optional[List[int]] = None) -> bool:
        idx = nodes if nodes is not None else range(len(self.nodes))
        return self.wait_for(
            lambda: all(self.nodes[i].height > height for i in idx), timeout
        )

    # -- op application ------------------------------------------------------
    def apply_op(self, op: FaultOp) -> None:
        kw = op.kwargs
        if op.op == "policy":
            self.fabric.set_policy(
                kw.get("src"), kw.get("dst"), LinkPolicy(**kw["policy"])
            )
        elif op.op == "clear_policies":
            self.fabric.clear_policies()
        elif op.op == "partition":
            groups = [
                {self.nodes[i].node_id for i in group}
                for group in kw["groups"]
            ]
            self.fabric.set_partition(groups)
        elif op.op == "heal":
            self.fabric.heal_partition()
        elif op.op == "silence":
            self.fabric.silence({self.nodes[i].node_id for i in kw["nodes"]})
        elif op.op == "unsilence":
            self.fabric.unsilence(
                None if "nodes" not in kw
                else {self.nodes[i].node_id for i in kw["nodes"]}
            )
        elif op.op == "skew":
            self.nodes[kw["node"]].clock.set_skew(kw["skew_ns"])
        elif op.op == "tx":
            for i in kw.get("nodes", range(len(self.nodes))):
                try:
                    self.nodes[i].mempool.check_tx(kw["tx"])
                except Exception:
                    pass  # duplicate/rejected on some nodes is fine
        elif op.op == "crash_restart":
            self.crash_restart(kw["node"], fresh_app=kw.get("fresh_app", True))
        elif op.op == "mark":
            self.mark(kw["label"])
        else:
            raise ValueError(f"unknown fault op {op.op!r}")

    def crash_restart(self, i: int, fresh_app: bool = True):
        """Kill node `i` and rebuild it from its surviving stores.  The
        replacement loads state from the old state_db, replays the WAL
        into the round state, runs the ABCI handshake (re-applying every
        committed block into a fresh app when `fresh_app`), and rejoins
        the fabric under the same node id.  Replaces ``self.nodes[i]`` in
        place — run_scenario's final stop loop sees the new node."""
        from tendermint_tpu.sim.node import SimNode

        old = self.nodes[i]
        old.crash()
        node = SimNode(
            index=old.index, node_id=old.node_id, doc=old.doc, pv=old.pv,
            fabric=self.fabric, config=old.config,
            app=None if fresh_app else old.app, clock=old.clock,
            state_db=old.state_db, block_store=old.block_store,
            wal_path=old.wal_path, handshake=True,
        )
        # Re-wire the mesh from the new switch's side; the other nodes'
        # existing InProcPeer handles stay valid (the fabric routes by
        # node id, and register() re-points the id at the new switch).
        for other in self.nodes:
            if other is not old:
                node.switch.connect(other.node_id)
                other.switch.connect(node.node_id)  # idempotent
        node.start()
        self.nodes[i] = node
        self.mark(f"crash_restart:{old.node_id}")
        return node


def _safety_failures(run: ScenarioRun) -> List[str]:
    """No two nodes may commit different blocks at the same height."""
    failures = []
    by_height: Dict[int, Dict[str, List[str]]] = {}
    for node in run.nodes:
        for h, hh in node.committed_hashes().items():
            by_height.setdefault(h, {}).setdefault(hh, []).append(node.node_id)
    for h in sorted(by_height):
        if len(by_height[h]) > 1:
            failures.append(
                f"SAFETY VIOLATION at height {h}: conflicting commits "
                f"{by_height[h]}"
            )
    return failures


def run_scenario(scenario: Scenario, seed: Optional[int] = None) -> ScenarioResult:
    """Build, run, fault-inject, and invariant-check one scenario."""
    from tendermint_tpu.sim.node import build_sim_net

    seed = scenario.seed if seed is None else seed
    config = (scenario.config_factory() if scenario.config_factory
              else None)
    fabric, nodes = build_sim_net(
        scenario.n_vals,
        seed=seed,
        config=config,
        app_factory=scenario.app_factory,
        clock_factory=scenario.clock_factory,
        byzantine=scenario.byzantine,
    )
    run = ScenarioRun(scenario, fabric, nodes)
    failures: List[str] = []
    heights: List[int] = []
    commit_hashes: List[Dict[int, str]] = []
    commit_rounds: List[Dict[int, int]] = []
    flight_dumps: List[dict] = []
    critpath_dumps: List[dict] = []
    quorum_dumps: List[dict] = []
    stall_reports: List[dict] = []
    summary: dict = {}
    started = time.monotonic()
    try:
        if scenario.setup is not None:
            scenario.setup(run)
        fabric.start()
        for node in nodes:
            node.start()
        run.t0 = time.monotonic()

        # the timed fault schedule, interleaved with the drive below
        import threading

        def ops_timeline():
            for op in sorted(scenario.ops, key=lambda o: o.at_s):
                delay = run.t0 + op.at_s - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                try:
                    run.apply_op(op)
                except Exception as e:
                    run.failures.append(f"op {op.op}@{op.at_s}s failed: {e}")

        ops_thread = threading.Thread(
            target=ops_timeline, name="scenario-ops", daemon=True
        )
        ops_thread.start()

        if scenario.drive is not None:
            failures.extend(scenario.drive(run) or [])
        else:
            if not run.wait_height(scenario.target_height, scenario.timeout_s):
                failures.append(
                    f"liveness: heights={run.heights()} never passed "
                    f"{scenario.target_height} within {scenario.timeout_s}s"
                )
        ops_thread.join(timeout=5.0)
        failures.extend(run.failures)

        if scenario.check is not None:
            failures.extend(scenario.check(run) or [])
        failures.extend(_safety_failures(run))
        bad = fabric.replay_schedule()
        if bad:
            failures.append(
                f"replay: {len(bad)} seeded fault decisions did not "
                f"re-derive from seed {seed}"
            )

        heights = run.heights()
        commit_hashes = [n.committed_hashes() for n in nodes]
        commit_rounds = [n.commit_rounds() for n in nodes]
        flight_dumps = [n.cs.flight.snapshot() for n in nodes]
        critpath_dumps = [n.cs.critpath.snapshot() for n in nodes]
        quorum_dumps = [n.cs.quorumtrace.snapshot() for n in nodes]
        stall_reports = [
            n.watchdog.report() for n in nodes
            if n.watchdog is not None and n.watchdog.report() is not None
        ]
        summary = fabric.fault_summary()
    except Exception as e:  # a crashed scenario is a failed scenario
        failures.append(f"scenario crashed: {e!r}")
    finally:
        for node in nodes:
            node.stop()
        fabric.stop()
        for fn in reversed(run._defers):
            try:
                fn()
            except Exception:
                pass

    return ScenarioResult(
        name=scenario.name,
        seed=seed,
        ok=not failures,
        failures=failures,
        elapsed_s=round(time.monotonic() - started, 3),
        heights=heights,
        commit_hashes=commit_hashes,
        commit_rounds=commit_rounds,
        flight_dumps=flight_dumps,
        critpath_dumps=critpath_dumps,
        quorum_dumps=quorum_dumps,
        fault_summary=summary,
        stall_reports=stall_reports,
        marks=run.marks,
    )
