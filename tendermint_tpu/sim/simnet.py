"""SimNet — the seeded, fault-injecting message fabric.

Every `InProcSwitch.send` lands here.  For each ordered link (src, dst) the
fabric keeps a policy (delay/jitter/drop/duplicate/reorder) and a
monotonically increasing per-link sequence number; every fault decision is
drawn from ``random.Random(sha256(seed | src | dst | seq))`` in a FIXED
draw order, so the entire fault schedule is a pure function of
``(seed, traffic shape)`` — same seed + same message sequence ⇒ same drops,
same delays, same duplicates.  ``replay_schedule()`` re-derives every
logged decision from the seed and verifies the log matches, which is what
`chaos_smoke` asserts when it claims a run is replayable.

Structural faults are separate from the seeded ones:

* **partition** — a group assignment; cross-group messages are dropped
  (counted, not logged as seeded decisions) until ``heal()``;
* **silence** — outbound blackhole per node (the >1/3-silence scenario);
* per-link FIFO: equal-delay messages arrive in send order (heap ties
  broken by a global sequence), so "reorder" means *extra delay drawn for
  one message*, exactly like a real queueing network.
"""

from __future__ import annotations

import hashlib
import heapq
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


@dataclass
class LinkPolicy:
    """Fault parameters for one ordered link (or the default for all)."""

    delay_s: float = 0.0       # base one-way latency
    jitter_s: float = 0.0      # + uniform extra in [0, jitter_s)
    drop: float = 0.0          # P(message vanishes)
    duplicate: float = 0.0     # P(a second copy is scheduled)
    reorder: float = 0.0       # P(message gets reorder_extra_s added)
    reorder_extra_s: float = 0.05

    def is_faulty(self) -> bool:
        return any((self.delay_s, self.jitter_s, self.drop,
                    self.duplicate, self.reorder))


@dataclass
class _Decision:
    """One seeded fault decision, as logged and as re-derived on replay."""

    src: str
    dst: str
    seq: int
    chan_id: int
    size: int
    dropped: bool
    duplicated: bool
    delay_s: float
    dup_delay_s: float = 0.0
    # the policy in force when the decision was drawn — policies change
    # mid-run (fault ops), so replay must re-derive under the same one
    policy: LinkPolicy = field(default_factory=LinkPolicy, compare=False)


def _link_rng(seed: int, src: str, dst: str, seq: int) -> random.Random:
    h = hashlib.sha256(f"{seed}|{src}|{dst}|{seq}".encode()).digest()
    return random.Random(int.from_bytes(h[:8], "big"))


def _decide(policy: LinkPolicy, seed: int, src: str, dst: str, seq: int,
            chan_id: int, size: int) -> _Decision:
    """The pure function at the heart of replayability.  Draw order is part
    of the contract: drop, duplicate, jitter, reorder, dup-jitter."""
    rng = _link_rng(seed, src, dst, seq)
    dropped = rng.random() < policy.drop
    duplicated = rng.random() < policy.duplicate
    delay = policy.delay_s + rng.random() * policy.jitter_s
    if rng.random() < policy.reorder:
        delay += policy.reorder_extra_s
    dup_delay = policy.delay_s + rng.random() * (
        policy.jitter_s + policy.reorder_extra_s
    )
    return _Decision(src=src, dst=dst, seq=seq, chan_id=chan_id, size=size,
                     dropped=dropped, duplicated=duplicated,
                     delay_s=delay, dup_delay_s=dup_delay, policy=policy)


class SimNet:
    """The fabric `InProcSwitch` sends through.  Register switches, wire a
    topology with ``connect_full_mesh``, then `start()`."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.default_policy = LinkPolicy()
        self._switches: Dict[str, object] = {}
        self._policies: Dict[Tuple[str, str], LinkPolicy] = {}
        self._link_seq: Dict[Tuple[str, str], int] = {}
        self._partition: Optional[Dict[str, int]] = None  # node -> group
        self._silenced: Set[str] = set()
        self.schedule_log: List[_Decision] = []
        self.stats = {"sent": 0, "delivered": 0, "dropped": 0,
                      "duplicated": 0, "partition_dropped": 0,
                      "silence_dropped": 0}
        self._mtx = threading.Lock()
        self._cv = threading.Condition(self._mtx)
        self._heap: List[tuple] = []  # (due_monotonic, tiebreak, dst, chan, src, msg)
        self._tiebreak = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # -- topology ------------------------------------------------------------
    def register(self, switch) -> None:
        self._switches[switch.node_id] = switch

    def connect_full_mesh(self) -> None:
        ids = sorted(self._switches)
        for a in ids:
            for b in ids:
                if a != b:
                    self._switches[a].connect(b)

    def node_ids(self) -> List[str]:
        return sorted(self._switches)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._mtx:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._scheduler, name="simnet-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # -- fault controls --------------------------------------------------------
    def set_policy(self, src: Optional[str], dst: Optional[str],
                   policy: LinkPolicy) -> None:
        """Policy for one ordered link, or the all-links default when both
        src and dst are None."""
        with self._mtx:
            if src is None and dst is None:
                self.default_policy = policy
            else:
                self._policies[(src, dst)] = policy

    def clear_policies(self) -> None:
        with self._mtx:
            self._policies.clear()
            self.default_policy = LinkPolicy()

    def set_partition(self, groups: List[Set[str]]) -> None:
        """Messages only flow within a group.  Nodes in no group are
        isolated entirely.

        A partition also DISCONNECTS cross-group peers, like the TCP
        connection breakage a real partition causes.  Dropping silently
        while the peer object stays up would poison the consensus reactor's
        PeerState: votes sent into the blackhole get marked as delivered,
        so after the heal nothing is ever resent and a 2-2 split deadlocks
        forever — real nodes recover precisely because reconnection resets
        the peer's vote bitmaps."""
        assign: Dict[str, int] = {}
        for gi, group in enumerate(groups):
            for node in group:
                assign[node] = gi
        with self._mtx:
            self._partition = assign
            switches = dict(self._switches)
        for a, sw in switches.items():
            for b in switches:
                if a != b and assign.get(a, -1) != assign.get(b, -2):
                    sw.disconnect(b, reason="partitioned")

    def heal_partition(self) -> None:
        with self._mtx:
            self._partition = None
            switches = dict(self._switches)
        for a, sw in switches.items():
            for b in switches:
                if a != b:
                    sw.connect(b)  # idempotent: fresh peers only where cut

    def silence(self, node_ids) -> None:
        with self._mtx:
            self._silenced.update(node_ids)

    def unsilence(self, node_ids=None) -> None:
        """Lift the blackhole AND bounce the node's connections.  While
        silenced, the node kept 'sending' into the void, so its PeerStates
        have marked votes as delivered that never were; without a
        connection reset nothing is ever resent and the voting-power it
        carries never rejoins — a real node coming back from a freeze gets
        its TCP sessions torn down and redialed, which is what resets the
        reactors' per-peer state."""
        with self._mtx:
            affected = (set(self._silenced) if node_ids is None
                        else set(node_ids) & self._silenced)
            self._silenced.difference_update(affected)
            switches = dict(self._switches)
        for a in affected:
            sw = switches.get(a)
            if sw is None:
                continue
            for b in switches:
                if b != a:
                    sw.disconnect(b, reason="unsilenced: session reset")
                    switches[b].disconnect(a, reason="peer unsilenced")
        for a in affected:
            sw = switches.get(a)
            if sw is None:
                continue
            for b in switches:
                if b != a:
                    sw.connect(b)
                    switches[b].connect(a)

    # -- the data path ---------------------------------------------------------
    def send(self, src: str, dst: str, chan_id: int, msg: bytes) -> bool:
        with self._cv:
            if not self._running or dst not in self._switches:
                return False
            self.stats["sent"] += 1
            if src in self._silenced:
                self.stats["silence_dropped"] += 1
                return True  # the sender can't tell a blackhole from slow
            part = self._partition
            if part is not None and part.get(src, -1) != part.get(dst, -2):
                self.stats["partition_dropped"] += 1
                return True
            policy = self._policies.get((src, dst), self.default_policy)
            if not policy.is_faulty():
                # clean link: skip the rng + log entirely so pristine runs
                # don't grow an unbounded decision log
                self._push(0.0, dst, chan_id, src, msg)
                self.stats["delivered"] += 1
                return True
            key = (src, dst)
            seq = self._link_seq.get(key, 0)
            self._link_seq[key] = seq + 1
            d = _decide(policy, self.seed, src, dst, seq, chan_id, len(msg))
            self.schedule_log.append(d)
            if d.dropped:
                self.stats["dropped"] += 1
                return True
            self._push(d.delay_s, dst, chan_id, src, msg)
            self.stats["delivered"] += 1
            if d.duplicated:
                self.stats["duplicated"] += 1
                self._push(d.dup_delay_s, dst, chan_id, src, msg)
            return True

    def _push(self, delay_s: float, dst: str, chan_id: int, src: str,
              msg: bytes) -> None:
        """Caller holds self._cv."""
        due = time.monotonic() + max(0.0, delay_s)
        self._tiebreak += 1
        heapq.heappush(self._heap, (due, self._tiebreak, dst, chan_id, src, msg))
        self._cv.notify()

    def _scheduler(self) -> None:
        while True:
            with self._cv:
                while self._running and (
                    not self._heap or self._heap[0][0] > time.monotonic()
                ):
                    timeout = (
                        None if not self._heap
                        else max(0.0, self._heap[0][0] - time.monotonic())
                    )
                    self._cv.wait(timeout)
                if not self._running:
                    return
                _, _, dst, chan_id, src, msg = heapq.heappop(self._heap)
                part = self._partition
                if part is not None and part.get(src, -1) != part.get(dst, -2):
                    # in-flight messages die with the link when a partition
                    # lands between send and delivery
                    self.stats["partition_dropped"] += 1
                    continue
                sw = self._switches.get(dst)
            if sw is not None:
                try:
                    sw.deliver(chan_id, src, msg)
                except Exception:
                    pass

    # -- replay verification ---------------------------------------------------
    def replay_schedule(self) -> List[int]:
        """Re-derive every logged seeded decision from (seed, link, seq) and
        return the indices that DON'T match — non-empty means the run was
        not replayable (must never happen)."""
        bad = []
        with self._mtx:
            log = list(self.schedule_log)
        for i, d in enumerate(log):
            rd = _decide(d.policy, self.seed, d.src, d.dst, d.seq,
                         d.chan_id, d.size)
            if rd != d:
                bad.append(i)
        return bad

    def fault_summary(self) -> dict:
        with self._mtx:
            out = dict(self.stats)
            out["seeded_decisions"] = len(self.schedule_log)
        return out
