"""Byzantine signer wrappers.

``EquivocatingPV`` wraps any PrivValidator and, for every prevote/precommit
it signs from ``start_height`` on, ALSO signs a conflicting vote for a
fabricated block hash — the classic double-sign.  The wrapper itself only
collects the conflicting signatures; the sim node's equivocation pump
(`SimNode.start_equivocation_pump`) broadcasts them on the consensus VOTE
channel so honest peers see both votes, hit ``ErrVoteConflictingVotes`` in
their vote sets, mint ``DuplicateVoteEvidence``, and push it into their
evidence pools — the entry point of the whole evidence pipeline.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import replace
from typing import List

from tendermint_tpu.types import BlockID, PartSetHeader, SignedMsgType, Vote
from tendermint_tpu.types.priv_validator import PrivValidator


def _fabricated_block_id(height: int, round: int, vote_type: int) -> BlockID:
    """A syntactically valid, never-proposed BlockID, deterministic in
    (height, round, type) so reruns equivocate identically."""
    h = hashlib.sha256(b"equivocation|%d|%d|%d" % (height, round, vote_type))
    block_hash = h.digest()
    parts_hash = hashlib.sha256(block_hash).digest()
    return BlockID(hash=block_hash,
                   parts_header=PartSetHeader(total=1, hash=parts_hash))


class EquivocatingPV(PrivValidator):
    """Signs the honest vote AND a conflicting double for the same
    (height, round, type).  Proposals pass through untouched."""

    def __init__(self, inner: PrivValidator, start_height: int = 2,
                 max_equivocations: int = 8):
        self.inner = inner
        self.start_height = start_height
        self.max_equivocations = max_equivocations
        self._mtx = threading.Lock()
        self._conflicting: List[Vote] = []
        self.equivocations = 0

    def get_pub_key(self):
        return self.inner.get_pub_key()

    def sign_proposal(self, chain_id: str, proposal):
        return self.inner.sign_proposal(chain_id, proposal)

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        signed = self.inner.sign_vote(chain_id, vote)
        if (
            vote.height >= self.start_height
            and vote.vote_type in (SignedMsgType.PREVOTE,
                                   SignedMsgType.PRECOMMIT)
            and self.equivocations < self.max_equivocations
        ):
            alt_id = _fabricated_block_id(
                vote.height, vote.round, int(vote.vote_type)
            )
            if alt_id.hash != signed.block_id.hash:
                alt = replace(signed, block_id=alt_id, signature=b"")
                alt_signed = self.inner.sign_vote(chain_id, alt)
                with self._mtx:
                    self._conflicting.append(alt_signed)
                    self.equivocations += 1
        return signed

    def drain_conflicting(self) -> List[Vote]:
        """The double-signed votes accumulated since the last drain; the
        node's equivocation pump broadcasts these to peers."""
        with self._mtx:
            out, self._conflicting = self._conflicting, []
            return out
