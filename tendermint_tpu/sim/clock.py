"""Per-node wall-clock injection.

Every timestamp a node emits (proposal/vote times, flight-recorder stamps,
watchdog report wall time) flows through a pluggable ``now_ns`` callable
(`ConsensusState.now_ns`, `FlightRecorder.now_ns`, `LivenessWatchdog.now_ns`).
A ``SimClock`` bound there gives the harness two capabilities:

* **skew** — shift one node's wall clock by a known offset and verify the
  observability stack (trace_merge's commit-anchor skew recovery) measures
  it back out;
* **freeze** — pin the clock to one constant, which (together with
  ``blocktime_iota``) makes vote/block times pure functions of the chain —
  the determinism scenarios compare commit hashes across runs.
"""

from __future__ import annotations

import time


class SimClock:
    """Wall-clock source for one simulated node."""

    def __init__(self, skew_ns: int = 0, frozen_at_ns: int = 0):
        self.skew_ns = int(skew_ns)
        self.frozen_at_ns = int(frozen_at_ns)  # 0 = not frozen

    def now_ns(self) -> int:
        if self.frozen_at_ns:
            return self.frozen_at_ns + self.skew_ns
        return time.time_ns() + self.skew_ns

    def set_skew(self, skew_ns: int) -> None:
        self.skew_ns = int(skew_ns)

    def freeze(self, at_ns: int) -> None:
        self.frozen_at_ns = int(at_ns)

    def __call__(self) -> int:  # usable directly as a now_ns callable
        return self.now_ns()
