"""SimNode — one full validator node assembled for the simulator.

Everything is REAL: ConsensusState + ConsensusReactor, Mempool +
MempoolReactor, EvidencePool + EvidenceReactor, BlockExecutor over a
kvstore ABCI app, per-node in-memory stores.  Only the transport is
simulated (`p2p/inproc.py` over a `SimNet` fabric) and the wall clock is
injectable (`sim/clock.py`).

This intentionally mirrors `tests/consensus_harness.py`'s builders — the
sim package is importable from production code and scripts, so it cannot
reach into `tests/`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from tendermint_tpu.abci.examples.kvstore import KVStoreApp
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config.config import test_config
from tendermint_tpu.consensus.messages import VoteMessage, encode_msg
from tendermint_tpu.consensus.reactor import VOTE_CHANNEL, ConsensusReactor
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.crypto.keys import PrivKeyEd25519
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.evidence.reactor import EvidenceReactor
from tendermint_tpu.libs.db.kv import MemDB
from tendermint_tpu.libs.metrics import NodeMetrics
from tendermint_tpu.libs.watchdog import LivenessWatchdog
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.inproc import InProcSwitch
from tendermint_tpu.proxy.app_conn import LocalClientCreator, MultiAppConn
from tendermint_tpu.sim.byzantine import EquivocatingPV
from tendermint_tpu.sim.clock import SimClock
from tendermint_tpu.sim.simnet import SimNet
from tendermint_tpu.state import store as sm_store
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state_types import state_from_genesis
from tendermint_tpu.types import GenesisDoc, GenesisValidator, MockPV
from tendermint_tpu.types.events import EventBus

SIM_CHAIN_ID = "sim-chain"
SIM_GENESIS_TIME_NS = 1_700_000_000_000_000_000


def make_sim_genesis(n_vals: int, power: int = 10):
    """Deterministic genesis: seeded keys, fixed genesis time — identical
    across runs so commit hashes are comparable run-to-run."""
    pvs = [MockPV(PrivKeyEd25519.generate(bytes([i + 1]) * 32))
           for i in range(n_vals)]
    doc = GenesisDoc(
        chain_id=SIM_CHAIN_ID,
        genesis_time_ns=SIM_GENESIS_TIME_NS,
        validators=[GenesisValidator(pv.get_pub_key(), power) for pv in pvs],
    )
    doc.validate_and_complete()
    return doc, pvs


class SimNode:
    """One simulated validator: real consensus stack over the fabric."""

    def __init__(self, index: int, node_id: str, doc: GenesisDoc, pv,
                 fabric: SimNet, config=None, app=None,
                 clock: Optional[SimClock] = None,
                 state_db=None, block_store=None,
                 wal_path: Optional[str] = None, handshake: bool = False):
        self.index = index
        self.node_id = node_id
        self.doc = doc
        self.pv = pv
        self.fabric = fabric
        self.clock = clock or SimClock()
        self.config = cfg = config or test_config()
        self.wal_path = wal_path

        # crash_restart hands back the dead node's stores: rebuild state
        # from the DB instead of genesis, and let the ABCI handshake
        # re-apply any blocks the (fresh) app is missing.
        if state_db is not None:
            self.state_db = state_db
            st = sm_store.load_state_from_db_or_genesis(self.state_db, doc)
        else:
            st = state_from_genesis(doc)
            self.state_db = MemDB()
            sm_store.save_state(self.state_db, st)
        self.block_store = (block_store if block_store is not None
                            else BlockStore(MemDB()))

        self.app = app or KVStoreApp()
        self.conn = MultiAppConn(LocalClientCreator(self.app))
        self.conn.start()
        self.handshake_blocks = 0
        if handshake:
            from tendermint_tpu.consensus.replay import Handshaker

            hs = Handshaker(self.state_db, st, self.block_store, doc)
            st = hs.handshake(self.conn)
            sm_store.save_state(self.state_db, st)
            self.handshake_blocks = hs.n_blocks
        # per-node registry so scenarios can assert on QoS/lane counters
        self.metrics = NodeMetrics()
        self.mempool = Mempool(
            self.conn.mempool,
            size=cfg.mempool.size,
            cache_size=cfg.mempool.cache_size,
            recheck=cfg.mempool.recheck,
            metrics=self.metrics,
            lane_bounds=cfg.mempool.lane_bounds,
            checktx_batch=cfg.mempool.checktx_batch,
            recheck_batch=cfg.mempool.recheck_batch,
        )
        self.evpool = EvidencePool(self.state_db, MemDB(), st.copy())

        self.bus = EventBus()
        self.bus.start()
        block_exec = BlockExecutor(
            self.state_db, self.conn.consensus, self.mempool, self.evpool,
            self.bus,
        )
        wal = None
        if wal_path:
            from tendermint_tpu.consensus.wal import WAL

            wal = WAL(wal_path, metrics=self.metrics)
        self.cs = ConsensusState(
            cfg.consensus, st.copy(), block_exec, self.block_store,
            self.mempool, self.evpool, wal=wal, metrics=self.metrics,
        )
        # [verify] vote_batch_window_ms > 0: batched live-vote verification
        # (same wiring as node/node.py; exposed so scenarios can assert the
        # feed actually engaged)
        self.vote_feed = None
        if getattr(cfg.verify, "vote_batch_window_ms", 0.0) > 0:
            from tendermint_tpu.parallel.planner import VoteFeed

            self.vote_feed = VoteFeed(
                window_s=cfg.verify.vote_batch_window_ms / 1000.0,
                max_rows=cfg.verify.vote_batch_rows,
                # ticket stamps share the node's (possibly skewed) clock so
                # flush spans fuse onto the node's flight-record timeline
                now_ns=self.clock,
            )
            self.cs.set_vote_feed(self.vote_feed)
        # [mempool] tx_batch_window_ms > 0: batched CheckTx signature
        # ingest when the app publishes a tx_sig_extractor (same wiring as
        # node/node.py; exposed so scenarios can assert dispatch counts)
        self.tx_feed = None
        self.tx_verifier = None
        _extractor = getattr(self.app, "tx_sig_extractor", None)
        if getattr(cfg.mempool, "tx_batch_window_ms", 0.0) > 0 and _extractor:
            from tendermint_tpu.mempool.tx_verify import BatchTxVerifier
            from tendermint_tpu.parallel.planner import TxFeed

            self.tx_feed = TxFeed(
                window_s=cfg.mempool.tx_batch_window_ms / 1000.0,
                max_rows=cfg.mempool.tx_batch_rows,
            )
            self.tx_verifier = BatchTxVerifier(
                self.tx_feed, _extractor, height_fn=self.mempool.height
            )
            self.mempool.set_batch_check_hook(self.tx_verifier, verdicts=True)
        self.cs.set_event_bus(self.bus)
        self.cs.set_priv_validator(pv)
        self.cs.now_ns = self.clock
        self.cs.flight.now_ns = self.clock
        self.cs.flight.node_id = node_id
        self.cs.flight.enable()

        self.reactor = ConsensusReactor(self.cs)
        self.mempool_reactor = MempoolReactor(
            self.mempool, peer_height_lookup=self.reactor.peer_height,
            config=cfg.mempool, metrics=self.metrics, now_ns=self.clock,
        )
        self.evidence_reactor = EvidenceReactor(
            self.evpool, peer_height_lookup=self.reactor.peer_height
        )
        self.switch = InProcSwitch(node_id, fabric)
        self.switch.add_reactor("consensus", self.reactor)
        self.switch.add_reactor("mempool", self.mempool_reactor)
        self.switch.add_reactor("evidence", self.evidence_reactor)
        fabric.register(self.switch)

        self.watchdog: Optional[LivenessWatchdog] = None
        self._equiv_thread: Optional[threading.Thread] = None
        self._equiv_stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self.switch.start()

    def stop(self) -> None:
        self._equiv_stop.set()
        if self.watchdog is not None:
            self.watchdog.stop()
        try:
            if self.switch.is_running:
                self.switch.stop()  # stops reactors, which stop the cs
        except Exception:
            pass
        try:
            self.bus.stop()
        except Exception:
            pass
        if self.vote_feed is not None:
            try:
                self.vote_feed.close()
            except Exception:
                pass
        if self.tx_feed is not None:
            try:
                self.tx_feed.close()
            except Exception:
                pass

    def crash(self) -> None:
        """Kill the node mid-flight, keeping its durable state (state_db,
        block_store, WAL file) for a replacement SimNode to rebuild from.
        Every WAL write already flushed (see WAL.write), so the file on
        disk is exactly what a kill -9 would leave behind."""
        self.stop()
        try:
            self.conn.stop()
        except Exception:
            pass

    def start_watchdog(self, **kwargs) -> LivenessWatchdog:
        self.watchdog = LivenessWatchdog(
            self.cs, switch=self.switch, now_ns=self.clock, **kwargs
        )
        self.watchdog.start()
        return self.watchdog

    def start_equivocation_pump(self, interval: float = 0.02) -> None:
        """Broadcast the EquivocatingPV's double-signed votes to all peers
        on the consensus VOTE channel — honest nodes mint the evidence."""
        if not isinstance(self.pv, EquivocatingPV):
            raise TypeError("node's priv validator is not an EquivocatingPV")

        def pump():
            while not self._equiv_stop.is_set():
                for vote in self.pv.drain_conflicting():
                    self.switch.broadcast(
                        VOTE_CHANNEL, encode_msg(VoteMessage(vote))
                    )
                time.sleep(interval)

        self._equiv_thread = threading.Thread(
            target=pump, name=f"equiv-pump-{self.node_id}", daemon=True
        )
        self._equiv_thread.start()

    # -- inspection ----------------------------------------------------------
    @property
    def height(self) -> int:
        return self.cs.rs.height

    def committed_hashes(self) -> Dict[int, str]:
        """height -> block hash hex for every block in our store."""
        out = {}
        base = max(1, self.block_store.base())
        for h in range(base, self.block_store.height() + 1):
            meta = self.block_store.load_block_meta(h)
            if meta is not None:
                out[h] = meta.block_id.hash.hex().upper()
        return out

    def commit_rounds(self) -> Dict[int, int]:
        """height -> round the commit formed at.  Any round > 0 means a
        real-time timeout fired (host load), which is exactly the case
        where same-seed runs may legitimately diverge."""
        out = {}
        base = max(1, self.block_store.base())
        for h in range(base, self.block_store.height() + 1):
            commit = self.block_store.load_seen_commit(h)
            if commit is not None:
                out[h] = commit.round()
        return out

    def committed_evidence_heights(self) -> List[int]:
        """Heights of blocks in our store that carry committed evidence."""
        out = []
        base = max(1, self.block_store.base())
        for h in range(base, self.block_store.height() + 1):
            block = self.block_store.load_block(h)
            if block is not None and block.evidence.evidence:
                out.append(h)
        return out


def build_sim_net(
    n_vals: int,
    seed: int = 0,
    config=None,
    app_factory: Optional[Callable[[int], object]] = None,
    clock_factory: Optional[Callable[[int], SimClock]] = None,
    byzantine: Optional[Dict[int, Callable]] = None,
):
    """N-node full-mesh simulated net.  `byzantine` maps a validator index
    (in sorted valset order) to a PrivValidator wrapper, e.g.
    ``{3: lambda pv: EquivocatingPV(pv)}``.  Returns (fabric, nodes);
    neither is started."""
    # Pin the commit verifier to the host backend before the first commit
    # verify: the lazy default runs a TPU subprocess liveness probe under the
    # process-wide verifier lock (tens of seconds on a CPU host), which
    # blocks every node's receive routine mid-consensus and forces
    # timeout-driven round bumps that destroy run-to-run hash determinism.
    # An explicit TM_BATCH_VERIFIER or an already-installed verifier wins.
    import os

    from tendermint_tpu.crypto import batch as _batch

    if _batch._default is None and not os.environ.get("TM_BATCH_VERIFIER"):
        _batch.set_batch_verifier(_batch.HostBatchVerifier())

    fabric = SimNet(seed=seed)
    doc, pvs = make_sim_genesis(n_vals)
    st = state_from_genesis(doc)
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    sorted_pvs = [by_addr[v.address] for v in st.validators.validators]

    nodes = []
    for i in range(n_vals):
        pv = sorted_pvs[i]
        if byzantine and i in byzantine:
            pv = byzantine[i](pv)
        nodes.append(
            SimNode(
                index=i,
                node_id=f"sim{i}",
                doc=doc,
                pv=pv,
                fabric=fabric,
                config=config,
                app=app_factory(i) if app_factory is not None else None,
                clock=clock_factory(i) if clock_factory is not None else None,
            )
        )
    fabric.connect_full_mesh()
    return fabric, nodes
