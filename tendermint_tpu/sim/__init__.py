"""Deterministic chaos/Byzantine simulation harness.

Runs N REAL ConsensusStates (mempool, evidence pool, evidence reactor
included) over an in-proc simulated transport (`p2p/inproc.py` +
`SimNet`), with seeded per-link fault injection, partitions, clock skew,
validator churn, and Byzantine signer wrappers.  `scenario.py` is the
timed fault-schedule DSL; `scenarios.py` the named scenario matrix that
`scripts/chaos_smoke.py` / `make chaos-smoke` executes.
"""

from tendermint_tpu.sim.byzantine import EquivocatingPV
from tendermint_tpu.sim.clock import SimClock
from tendermint_tpu.sim.faults import FaultyDevice
from tendermint_tpu.sim.node import SimNode, build_sim_net
from tendermint_tpu.sim.scenario import (
    FaultOp,
    Scenario,
    ScenarioResult,
    round0_clean_top,
    run_scenario,
)
from tendermint_tpu.sim.scenarios import SCENARIOS
from tendermint_tpu.sim.simnet import LinkPolicy, SimNet

__all__ = [
    "EquivocatingPV",
    "FaultOp",
    "FaultyDevice",
    "LinkPolicy",
    "SCENARIOS",
    "Scenario",
    "ScenarioResult",
    "SimClock",
    "SimNet",
    "SimNode",
    "build_sim_net",
    "round0_clean_top",
    "run_scenario",
]
