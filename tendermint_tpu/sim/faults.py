"""FaultyDevice — a chaos shim for the batch-verification device path.

Wraps a real BatchVerifier (usually ``HostBatchVerifier``) and injects
deterministic, seeded faults into every ``verify_*`` call:

* **fail** — raise ``InjectedDeviceError`` (models a crashed dispatch);
* **hang** — sleep ``hang_s`` before answering (models a wedged device;
  pair with a small ``dispatch_deadline`` so ``supervised_call`` times
  out);
* **corrupt** — return the inner verdict with one lane's bit flipped
  (models silent corruption; the guard's audit must catch it).

Faults come from an explicit per-call ``schedule`` list (consumed in call
order: ``"ok" | "fail" | "hang" | "corrupt"``) and, once exhausted, from
seeded per-call rates.  Same seed + same call order → same fault
sequence, so sim scenarios using it stay replayable.

The shim exposes the BatchVerifier surface plus a ``backend`` attr so
``GuardedBatchVerifier`` treats it as a device backend.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Sequence

import numpy as np


class InjectedDeviceError(RuntimeError):
    """A scheduled/seeded device failure from FaultyDevice."""


class FaultyDevice:
    name = "faulty"

    def __init__(
        self,
        inner,
        seed: int = 0,
        fail_rate: float = 0.0,
        hang_rate: float = 0.0,
        corrupt_rate: float = 0.0,
        hang_s: float = 0.05,
        schedule: Optional[List[str]] = None,
    ):
        self.inner = inner
        self.backend = getattr(inner, "backend", getattr(inner, "name", "host"))
        self.hang_s = hang_s
        self.fail_rate = fail_rate
        self.hang_rate = hang_rate
        self.corrupt_rate = corrupt_rate
        self._rng = random.Random(seed)
        self._schedule = list(schedule or [])
        self._mtx = threading.Lock()
        self.calls = 0
        self.failures = 0
        self.hangs = 0
        self.corruptions = 0

    # -- fault decision ------------------------------------------------------
    def _next_fault(self) -> str:
        with self._mtx:
            self.calls += 1
            if self._schedule:
                return self._schedule.pop(0)
            r = self._rng.random()
            if r < self.fail_rate:
                return "fail"
            if r < self.fail_rate + self.hang_rate:
                return "hang"
            if r < self.fail_rate + self.hang_rate + self.corrupt_rate:
                return "corrupt"
            return "ok"

    def _apply(self, call) -> np.ndarray:
        fault = self._next_fault()
        if fault == "fail":
            with self._mtx:
                self.failures += 1
            raise InjectedDeviceError("injected device failure")
        if fault == "hang":
            with self._mtx:
                self.hangs += 1
            time.sleep(self.hang_s)
            return call()
        ok = call()
        if fault == "corrupt" and ok.size:
            ok = np.array(ok, copy=True)
            with self._mtx:
                self.corruptions += 1
                lane = self._rng.randrange(ok.size)
            flat = ok.reshape(-1)
            flat[lane] = not bool(flat[lane])
        return ok

    # -- BatchVerifier surface -----------------------------------------------
    def verify_ed25519(self, items: Sequence) -> np.ndarray:
        return self._apply(lambda: self.inner.verify_ed25519(items))

    def verify_ed25519_raw(self, pubs, msgs, sigs) -> np.ndarray:
        return self._apply(lambda: self.inner.verify_ed25519_raw(pubs, msgs, sigs))

    def verify_secp256k1(self, items: Sequence) -> np.ndarray:
        return self._apply(lambda: self.inner.verify_secp256k1(items))

    # -- inspection ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mtx:
            return {
                "calls": self.calls,
                "failures": self.failures,
                "hangs": self.hangs,
                "corruptions": self.corruptions,
                "schedule_left": len(self._schedule),
            }
