"""The named chaos scenario matrix `scripts/chaos_smoke.py` executes.

Each entry is a zero-arg factory returning a fresh `Scenario`; factories
keep runs independent (mutable config/clock objects are per-run).  The
shared invariants (safety at every committed height, seeded-fault
replayability) are asserted by `run_scenario` for every scenario; the
per-scenario `check`/`drive` hooks add the scenario-specific claims named
in the table below.

====================  =====================================================
baseline_determinism  frozen clocks + blocktime_iota → commit hashes are a
                      pure function of the chain; the smoke runs it twice
                      and requires identical hashes
partition_heal        2-2 split: no quorum ⇒ no progress while split,
                      progress resumes within budget after heal
partition_heal_9      the same claim at n_vals=9 (4-5 split) — the larger
                      validator-set variant of the matrix
storm                 delay + jitter + 10% drop + duplicates + reorder on
                      every link; chain still advances
clock_skew            ±2s wall-clock skews; trace_merge's commit-anchor
                      math must recover the injected skews
churn                 validator removed via val-tx, then re-added; the
                      valset transitions apply and consensus keeps going
equivocation          Byzantine double-signer ⇒ DuplicateVoteEvidence is
                      minted, gossiped, included in a block, committed,
                      and marked committed in every pool
silence_watchdog      >1/3 power silenced ⇒ watchdog stall report names
                      the silenced validators' cumulative power; heals
mempool_flood         one node spams signed txs at ~10x the per-peer QoS
                      rate with batched TxFeed ingest on ⇒ honest priority
                      txs still commit, mempools stay bounded, drops land
                      in tendermint_mempool_qos_* counters
signed_flood          mixed valid/garbage/wrong-nonce/mutant signed txs
                      through the batched ingest path while the device
                      backend flaps ⇒ admit/reject codes bit-identical to
                      a serial-verify oracle mempool, committed app state
                      identical on every node, feed demonstrably engaged
device_flap           FaultyDevice behind the guarded verifier fails, hangs,
                      then silently corrupts ⇒ breaker walks closed→open→
                      half_open→closed, then quarantines on the audit
                      mismatch; the chain never stops committing and no
                      wrong verdict escapes
crash_restart         one node killed mid-height, rebuilt from its stores +
                      WAL ⇒ WAL messages replay, the ABCI handshake
                      re-applies committed blocks into the fresh app, and
                      the node catches back up to the chain
quorum_observatory    ±2s skews + seeded storm + one frozen-then-resumed
                      clock ⇒ fused vote journeys are monotone after
                      commit-anchor correction and each node's pivotal-
                      validator naming re-derives bit-identically from its
                      own dump
====================  =====================================================
"""

from __future__ import annotations

import base64
from typing import Callable, Dict, List

from tendermint_tpu.config.config import test_config
from tendermint_tpu.sim.byzantine import EquivocatingPV
from tendermint_tpu.sim.clock import SimClock
from tendermint_tpu.sim.node import SIM_GENESIS_TIME_NS
from tendermint_tpu.sim.scenario import FaultOp, Scenario, ScenarioRun

# injected clock skews for the clock_skew scenario, ns (index-aligned)
SKEWS_NS = [0, 2_000_000_000, -1_500_000_000, 500_000_000]


def _deterministic_config():
    """Frozen-clock determinism needs strictly increasing block times, which
    the iota floor provides (vote time = block time + iota when `now` is
    frozen), AND it needs every height to commit at round 0: a timeout-driven
    round bump changes the proposer and therefore the block hash, so all
    round timeouts are set far above in-proc delivery latency — progress is
    carried entirely by the has-all fast paths (skip_timeout_commit)."""
    cfg = test_config()
    cfg.consensus.blocktime_iota = 1.0
    cfg.consensus.timeout_propose = 10.0
    cfg.consensus.timeout_prevote = 5.0
    cfg.consensus.timeout_precommit = 5.0
    cfg.consensus.timeout_commit = 5.0
    return cfg


def baseline_determinism() -> Scenario:
    return Scenario(
        name="baseline_determinism",
        description="fault-free frozen-clock run; commit hashes must be "
                    "identical across nodes AND across runs of one seed",
        seed=1,
        target_height=4,
        timeout_s=60.0,
        config_factory=_deterministic_config,
        clock_factory=lambda i: SimClock(frozen_at_ns=SIM_GENESIS_TIME_NS),
        check=_check_all_nodes_agree_everywhere,
    )


def _check_all_nodes_agree_everywhere(run: ScenarioRun) -> List[str]:
    """Beyond pairwise safety: every node committed the SAME chain prefix."""
    failures = []
    maps = [n.committed_hashes() for n in run.nodes]
    shared = set(maps[0])
    for m in maps[1:]:
        shared &= set(m)
    if len(shared) < run.scenario.target_height:
        failures.append(
            f"only {len(shared)} heights committed by every node "
            f"(want >= {run.scenario.target_height})"
        )
    for h in sorted(shared):
        hashes = {m[h] for m in maps}
        if len(hashes) > 1:
            failures.append(f"height {h}: nodes disagree: {hashes}")
    return failures


def partition_heal(n_vals: int = 4) -> Scenario:
    split = n_vals // 2  # both halves < 2/3 quorum for any n_vals >= 4

    def drive(run: ScenarioRun) -> List[str]:
        failures = []
        if not run.wait_height(2, 30.0):
            return [f"never reached height 3 pre-partition: {run.heights()}"]
        run.fabric.set_partition(
            [{n.node_id for n in run.nodes[:split]},
             {n.node_id for n in run.nodes[split:]}]
        )
        # let in-flight messages settle, then sample the frozen heights
        run.wait_for(lambda: False, timeout=1.0)
        before = run.mark("partition_settled")["heights"]
        run.wait_for(lambda: False, timeout=3.0)
        after = run.mark("partition_end")["heights"]
        if before != after:
            failures.append(
                f"progress during {split}-{n_vals - split} partition: "
                f"{before} -> {after}"
            )
        run.fabric.heal_partition()
        # bigger nets pay 9 single-sig Python verifies per commit plus
        # post-partition round realignment: give them a wider heal budget
        heal_budget = 30.0 if n_vals == 4 else 90.0
        if not run.wait_height(max(after) + 2, heal_budget):
            failures.append(
                f"liveness: no progress within budget after heal: "
                f"{run.heights()} (was {after})"
            )
        return failures

    return Scenario(
        name="partition_heal" if n_vals == 4 else f"partition_heal_{n_vals}",
        description=f"{split}-{n_vals - split} partition freezes the chain "
                    "(no 2/3 quorum); healing restores progress within "
                    "budget",
        n_vals=n_vals,
        seed=2,
        timeout_s=90.0 if n_vals == 4 else 180.0,
        drive=drive,
    )


def storm() -> Scenario:
    storm_policy = dict(delay_s=0.005, jitter_s=0.015, drop=0.10,
                        duplicate=0.10, reorder=0.20, reorder_extra_s=0.05)
    return Scenario(
        name="storm",
        description="drop/duplicate/reorder storm on every link; the chain "
                    "still advances (gossip retransmission heals losses)",
        seed=3,
        target_height=4,
        timeout_s=120.0,
        ops=[FaultOp(at_s=0.0, op="policy",
                     kwargs={"src": None, "dst": None,
                             "policy": storm_policy})],
    )


def _skew_config():
    # non-zero iota keeps median block time strictly increasing even for
    # validators whose skewed clock lags the latest block time
    cfg = test_config()
    cfg.consensus.blocktime_iota = 1.0
    return cfg


def clock_skew() -> Scenario:
    def check(run: ScenarioRun) -> List[str]:
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "trace_merge",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "scripts", "trace_merge.py"),
        )
        tm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tm)

        failures = []
        dumps = [n.cs.flight.snapshot() for n in run.nodes]
        recovered = tm.compute_skews(dumps)
        for i, skew in enumerate(recovered):
            expected = SKEWS_NS[0] - SKEWS_NS[i]
            err_s = abs(skew - expected) / 1e9
            if err_s > 0.3:
                failures.append(
                    f"node {i}: recovered skew {skew / 1e9:+.3f}s vs "
                    f"injected {expected / 1e9:+.3f}s (err {err_s:.3f}s)"
                )
        return failures

    return Scenario(
        name="clock_skew",
        description="±2s wall-clock skews; commit-anchor skew recovery in "
                    "trace_merge must measure the injected offsets back out",
        seed=4,
        target_height=5,
        timeout_s=60.0,
        config_factory=_skew_config,
        clock_factory=lambda i: SimClock(skew_ns=SKEWS_NS[i]),
        check=check,
    )


def churn() -> Scenario:
    from tendermint_tpu.abci.examples.kvstore import PersistentKVStoreApp

    def drive(run: ScenarioRun) -> List[str]:
        failures = []
        # target the validator that sorts LAST in the valset (index 3)
        last_val = run.nodes[3].pv.get_pub_key()
        pub_b64 = base64.b64encode(last_val.bytes())

        if not run.wait_height(2, 30.0):
            return [f"never warmed up: {run.heights()}"]
        for node in run.nodes:
            try:
                node.mempool.check_tx(b"val:" + pub_b64 + b"!0")
            except Exception:
                pass
        removed = run.wait_for(
            lambda: all(n.cs.rs.validators.size == 3 for n in run.nodes),
            timeout=30.0,
        )
        if not removed:
            sizes = [n.cs.rs.validators.size for n in run.nodes]
            return [f"validator never removed: valset sizes {sizes}"]
        run.mark("removed")
        h_removed = max(run.heights())
        if not run.wait_height(h_removed + 1, 30.0):
            failures.append(
                f"3-validator net stopped: {run.heights()}"
            )
        for node in run.nodes:
            try:
                node.mempool.check_tx(b"val:" + pub_b64 + b"!10")
            except Exception:
                pass
        readded = run.wait_for(
            lambda: all(n.cs.rs.validators.size == 4 for n in run.nodes),
            timeout=30.0,
        )
        if not readded:
            sizes = [n.cs.rs.validators.size for n in run.nodes]
            failures.append(f"validator never re-added: valset sizes {sizes}")
        run.mark("readded")
        h_readded = max(run.heights())
        if not run.wait_height(h_readded + 1, 30.0):
            failures.append(
                f"4-validator net stopped after re-add: {run.heights()}"
            )
        return failures

    return Scenario(
        name="churn",
        description="validator removed via val-tx (applies at height+2), "
                    "chain keeps going on 3, validator re-added, back to 4",
        seed=5,
        timeout_s=120.0,
        app_factory=lambda i: PersistentKVStoreApp(),
        drive=drive,
    )


def equivocation() -> Scenario:
    def setup(run: ScenarioRun) -> None:
        run.nodes[3].start_equivocation_pump()

    def drive(run: ScenarioRun) -> List[str]:
        def pools_marked() -> bool:
            # the block lands in the store a beat before apply_block marks
            # the pool, so wait for BOTH before handing to the check hook
            for n in run.nodes:
                heights = n.committed_evidence_heights()
                if not heights:
                    return False
                for h in heights:
                    block = n.block_store.load_block(h)
                    for ev in block.evidence.evidence:
                        if not n.evpool.is_committed(ev):
                            return False
            return True

        failures = []
        if not run.wait_for(pools_marked, timeout=90.0):
            got = [n.committed_evidence_heights() for n in run.nodes]
            failures.append(
                f"evidence never committed+marked on every node: {got}"
            )
        return failures

    def check(run: ScenarioRun) -> List[str]:
        failures = []
        byz_addr = run.nodes[3].pv.get_pub_key().address()
        for node in run.nodes:
            for h in node.committed_evidence_heights():
                block = node.block_store.load_block(h)
                for ev in block.evidence.evidence:
                    if ev.address != byz_addr:
                        failures.append(
                            f"{node.node_id}: evidence at h={h} names "
                            f"{ev.address.hex()[:12]}, not the equivocator"
                        )
                    if not node.evpool.is_committed(ev):
                        failures.append(
                            f"{node.node_id}: committed evidence at h={h} "
                            f"not marked committed in the pool"
                        )
                    if ev in node.evpool.pending_evidence():
                        failures.append(
                            f"{node.node_id}: committed evidence still "
                            f"pending"
                        )
        return failures

    return Scenario(
        name="equivocation",
        description="Byzantine double-signer: honest nodes mint "
                    "DuplicateVoteEvidence, gossip it, a proposer includes "
                    "it, and every pool marks it committed",
        seed=6,
        timeout_s=120.0,
        byzantine={3: lambda pv: EquivocatingPV(pv, start_height=2)},
        setup=setup,
        drive=drive,
        check=check,
    )


def _vote_batch_config():
    # enable the live-vote micro-batcher (parallel/planner.VoteFeed):
    # every honest node's peer votes verify through batched dispatches
    cfg = test_config()
    cfg.verify.vote_batch_window_ms = 2.0
    cfg.verify.vote_batch_rows = 64
    return cfg


def vote_storm() -> Scenario:
    """Equivocation under a message storm WITH the vote micro-batcher on:
    the double-sign must still surface as ErrVoteConflictingVotes out of
    the batched path, mint DuplicateVoteEvidence, and commit — while the
    feed demonstrably carried the vote traffic."""

    storm_policy = dict(delay_s=0.002, jitter_s=0.008, drop=0.05,
                        duplicate=0.15, reorder=0.20, reorder_extra_s=0.03)

    def setup(run: ScenarioRun) -> None:
        run.nodes[3].start_equivocation_pump()

    def drive(run: ScenarioRun) -> List[str]:
        def pools_marked() -> bool:
            for n in run.nodes:
                heights = n.committed_evidence_heights()
                if not heights:
                    return False
                for h in heights:
                    block = n.block_store.load_block(h)
                    for ev in block.evidence.evidence:
                        if not n.evpool.is_committed(ev):
                            return False
            return True

        failures = []
        if not run.wait_for(pools_marked, timeout=90.0):
            got = [n.committed_evidence_heights() for n in run.nodes]
            failures.append(
                f"evidence never committed+marked through the batched "
                f"path: {got}"
            )
        return failures

    def check(run: ScenarioRun) -> List[str]:
        failures = []
        byz_addr = run.nodes[3].pv.get_pub_key().address()
        for node in run.nodes:
            for h in node.committed_evidence_heights():
                block = node.block_store.load_block(h)
                for ev in block.evidence.evidence:
                    if ev.address != byz_addr:
                        failures.append(
                            f"{node.node_id}: evidence at h={h} names "
                            f"{ev.address.hex()[:12]}, not the equivocator"
                        )
                    if not node.evpool.is_committed(ev):
                        failures.append(
                            f"{node.node_id}: committed evidence at h={h} "
                            f"not marked committed in the pool"
                        )
        # the batcher must have actually carried votes — a scenario that
        # silently fell back to serial would vacuously pass the above
        engaged = [n for n in run.nodes
                   if n.vote_feed is not None and n.vote_feed.dispatches > 0]
        if not engaged:
            feeds = [(n.node_id,
                      None if n.vote_feed is None
                      else (n.vote_feed.votes_in, n.vote_feed.dispatches))
                     for n in run.nodes]
            failures.append(f"vote feed never dispatched on any node: {feeds}")
        return failures

    return Scenario(
        name="vote_storm",
        description="message storm + double-signer with the vote "
                    "micro-batcher enabled: batched verification still "
                    "raises the conflict, evidence commits, and the feed "
                    "demonstrably carried the vote traffic",
        seed=12,
        timeout_s=120.0,
        config_factory=_vote_batch_config,
        byzantine={3: lambda pv: EquivocatingPV(pv, start_height=2)},
        setup=setup,
        drive=drive,
        check=check,
        ops=[FaultOp(at_s=0.0, op="policy",
                     kwargs={"src": None, "dst": None,
                             "policy": storm_policy})],
    )


def silence_watchdog() -> Scenario:
    def drive(run: ScenarioRun) -> List[str]:
        failures = []
        if not run.wait_height(3, 30.0):
            return [f"never warmed up: {run.heights()}"]
        # start the watchdog only after JAX compile warm-up, then give it a
        # couple of healthy samples to seed the block-interval EWMA
        wd = run.nodes[0].start_watchdog(
            interval=0.2, stall_factor=3.0, min_stall_seconds=1.5
        )
        run.wait_for(lambda: False, timeout=1.0)
        run.fabric.silence({run.nodes[2].node_id, run.nodes[3].node_id})
        if not run.wait_for(lambda: wd.report() is not None, timeout=10.0):
            failures.append("watchdog never reported the >1/3-silence stall")
        else:
            report = wd.report()
            missing = report["missing_precommits"]
            if missing["total_power"] <= 0:
                failures.append("stall report has no total power")
            elif missing["power"] * 3 < missing["total_power"]:
                failures.append(
                    f"stall report names {missing['power']}/"
                    f"{missing['total_power']} missing power (< 1/3)"
                )
            silenced_addrs = {
                run.nodes[2].pv.get_pub_key().address().hex().upper(),
                run.nodes[3].pv.get_pub_key().address().hex().upper(),
            }
            named = {v["address"] for v in missing["validators"]}
            if not silenced_addrs <= named:
                failures.append(
                    f"stall report names {named}, missing silenced "
                    f"{silenced_addrs}"
                )
            if "wall_time_ns" not in report:
                failures.append("stall report lacks wall_time_ns stamp")
        run.fabric.unsilence()
        h = max(run.heights())
        if not run.wait_height(h + 1, 30.0):
            failures.append(
                f"no recovery after unsilence: {run.heights()}"
            )
        return failures

    return Scenario(
        name="silence_watchdog",
        description=">1/3 voting power silenced: the liveness watchdog must "
                    "name the silenced validators' cumulative power, then "
                    "the net recovers when they return",
        seed=7,
        timeout_s=90.0,
        drive=drive,
    )


def mempool_flood() -> Scenario:
    """One node floods signed spam txs well above the per-peer QoS budget
    while consensus runs, with the batched TxFeed ingest path on (the
    signed workload makes admission expensive enough that the QoS verdicts
    matter).  Honest high-priority signed txs must still commit, every
    node's mempool stays bounded at `size`, the spammer's bucket saturates
    (per-peer drop counts on honest nodes), and the drops are visible in
    the tendermint_mempool_qos_* metric exposition — the PR-8 fairness
    story must survive batched ingest unchanged."""
    from tendermint_tpu.abci.examples.kvstore import (
        SignedKVStoreApp,
        make_signed_tx,
    )
    from tendermint_tpu.crypto.keys import PrivKeyEd25519
    from tendermint_tpu.mempool.mempool import MempoolError

    MAX_TXS = 100
    SPAM = 600  # >> qos_peer_tx_burst + rate x run-length: must saturate

    def config():
        cfg = test_config()
        cfg.mempool.size = MAX_TXS
        # the signed workload paces the spammer's own gossip: its walker
        # forwards txs only as fast as its batched admission admits them,
        # so the budget must sit below that delivery rate (~30-100 tx/s
        # in-sim) for the honest buckets to saturate
        cfg.mempool.qos_peer_tx_rate = 10.0
        cfg.mempool.qos_peer_tx_burst = 10.0
        # keep peers unmuted so the scenario measures steady-state rate
        # limiting, not the (separately unit-tested) mute escalation
        cfg.mempool.qos_mute_after = 0
        # batched signature ingest: admission windows pre-verify on the
        # planner feed instead of one serial verify per tx in the app
        cfg.mempool.checktx_batch = 16
        cfg.mempool.tx_batch_window_ms = 2.0
        cfg.mempool.tx_batch_rows = 64
        return cfg

    def _key(i: int) -> PrivKeyEd25519:
        return PrivKeyEd25519.generate(b"flood-key-%04d" % i + b"\x00" * 18)

    # 8 spam senders x SPAM/8 sequential nonces; honest txs are one
    # high-priority payload per distinct sender
    spam_privs = [_key(i) for i in range(8)]
    honest_payloads = [b"pri2000:hon%d=x" % i for i in range(5)]
    honest_txs = [
        make_signed_tx(_key(100 + i), 1, p)
        for i, p in enumerate(honest_payloads)
    ]
    honest_keys = [p.split(b"=", 1)[0] for p in honest_payloads]

    def drive(run: ScenarioRun) -> List[str]:
        failures = []
        if not run.wait_height(1, 30.0):
            return [f"never warmed up: {run.heights()}"]
        spammer = run.nodes[3]
        # sign outside the submission loop: the flood's arrival RATE at the
        # honest peers is what saturates their buckets, so the loop must
        # stay tight
        spam_txs = [
            make_signed_tx(spam_privs[i % 8], i // 8 + 1, b"spam%06d=x" % i)
            for i in range(SPAM)
        ]
        # local submissions bypass QoS (it guards the peer boundary); the
        # flood reaches honest nodes via gossip, where their buckets bite
        for tx in spam_txs:
            try:
                spammer.mempool.check_tx(tx)
            except MempoolError:
                pass
        for tx in honest_txs:
            try:
                run.nodes[0].mempool.check_tx(tx)
            except MempoolError as e:
                failures.append(f"honest tx rejected at submission: {e}")
        committed = run.wait_for(
            lambda: all(
                all(k in n.app.state for k in honest_keys)
                for n in run.nodes
            ),
            timeout=60.0,
        )
        if not committed:
            missing = {
                n.node_id: [k.decode() for k in honest_keys
                            if k not in n.app.state]
                for n in run.nodes
            }
            failures.append(
                f"honest txs not committed everywhere under flood: {missing}"
            )
        return failures

    def check(run: ScenarioRun) -> List[str]:
        failures = []
        spammer_id = run.nodes[3].node_id
        for node in run.nodes:
            if node.mempool.size() > MAX_TXS:
                failures.append(
                    f"{node.node_id}: mempool size {node.mempool.size()} "
                    f"exceeds max_txs {MAX_TXS}"
                )
        # the spammer's bucket must have saturated on at least one honest
        # node (gossip dedup means not every node necessarily hears the
        # full flood directly from the spammer)
        drops = {}
        for node in run.nodes[:3]:
            peers = node.mempool_reactor.qos_snapshot()["peers"]
            drops[node.node_id] = peers.get(spammer_id, {}).get("dropped", 0)
        if not any(d > 0 for d in drops.values()):
            failures.append(
                f"no honest node rate-limited the spammer: drops={drops}"
            )
        # ...and the decision must be visible on the wire format operators
        # actually scrape
        for node in run.nodes[:3]:
            if drops[node.node_id] == 0:
                continue
            text = node.metrics.registry.expose_text()
            if "tendermint_mempool_qos_dropped_total" not in text:
                failures.append(
                    f"{node.node_id}: qos drop counter missing from "
                    f"metric exposition"
                )
        # the flood must actually have ridden the batched ingest path
        if not any(
            n.tx_feed is not None and n.tx_feed.dispatches > 0
            for n in run.nodes
        ):
            failures.append("tx feed never dispatched under flood")
        return failures

    return Scenario(
        name="mempool_flood",
        description="one node spams signed txs at ~10x the per-peer QoS "
                    "rate through the batched TxFeed ingest path; honest "
                    "priority txs still commit, mempools stay bounded, and "
                    "the spammer's drops land in the "
                    "tendermint_mempool_qos_* counters",
        seed=8,
        timeout_s=120.0,
        config_factory=config,
        app_factory=lambda i: SignedKVStoreApp(),
        drive=drive,
        check=check,
    )


def signed_flood() -> Scenario:
    """A mixed stream of valid / garbage-sig / wrong-nonce / mutant /
    undecodable signed txs rides the batched TxFeed ingest path while the
    device backend behind the guarded verifier flaps mid-stream.  Claims:
    every admit/reject code is bit-identical to a serial-verify oracle
    mempool fed the same stream, the committed app state ends identical on
    every node, the feed demonstrably dispatched (and fell back host-side
    through the flap), and the whole episode lands in the
    tendermint_mempool_batch_* exposition."""
    from tendermint_tpu.abci.examples.kvstore import SignedKVStoreApp

    def config():
        cfg = test_config()
        cfg.mempool.checktx_batch = 16
        cfg.mempool.tx_batch_window_ms = 2.0
        cfg.mempool.tx_batch_rows = 64
        return cfg

    def setup(run: ScenarioRun) -> None:
        from tendermint_tpu.crypto import batch as _batch
        from tendermint_tpu.libs import breaker as _brk
        from tendermint_tpu.sim.faults import FaultyDevice

        br = _brk.configure_device_guard(
            breaker_threshold=3, breaker_backoff=0.2,
            breaker_backoff_max=0.4, dispatch_deadline=0.3,
            audit_sample_rate=1.0, retries=0,
        )
        prev = _batch.get_batch_verifier()
        dev = FaultyDevice(_batch.HostBatchVerifier(),
                           seed=run.scenario.seed, hang_s=1.0)
        _batch.set_batch_verifier(_batch.GuardedBatchVerifier(dev, breaker=br))
        run.device, run.breaker = dev, br
        run.defer(_brk.reset_device_guard)
        run.defer(lambda: _batch.set_batch_verifier(prev))

    def _stream():
        from tendermint_tpu.abci.examples.kvstore import make_signed_tx
        from tendermint_tpu.crypto.keys import PrivKeyEd25519, PrivKeySecp256k1

        privs = [
            PrivKeyEd25519.generate(b"signed-flood-%03d" % i + b"\x00" * 16)
            for i in range(6)
        ]
        secp = PrivKeySecp256k1.generate(b"signed-flood-secp" + b"\x00" * 15)
        txs = []
        for i, p in enumerate(privs):
            txs.append(make_signed_tx(p, 1, b"sf%02d=a" % i))
            garbage = bytearray(make_signed_tx(p, 2, b"sg%02d=b" % i))
            garbage[-6] ^= 0x55  # flips a payload byte -> sig mismatch
            txs.append(bytes(garbage))
            txs.append(make_signed_tx(p, 9, b"sw%02d=c" % i))  # nonce gap
            txs.append(make_signed_tx(p, 2, b"sk%02d=d" % i))
        txs.append(make_signed_tx(secp, 1, b"sfsecp=e"))  # host-lane algo
        txs.append(b"\x00garbage-not-a-signed-tx")  # undecodable
        valid_keys = (
            [b"sf%02d" % i for i in range(6)]
            + [b"sk%02d" % i for i in range(6)]
            + [b"sfsecp"]
        )
        return txs, valid_keys

    def drive(run: ScenarioRun) -> List[str]:
        import time as _time

        from tendermint_tpu.mempool.mempool import Mempool, MempoolError
        from tendermint_tpu.proxy.app_conn import (
            LocalClientCreator,
            MultiAppConn,
        )

        failures = []
        if not run.wait_height(1, 30.0):
            return [f"never warmed up: {run.heights()}"]
        txs, valid_keys = _stream()

        # serial oracle: same app, same stream, no feed — the app verifies
        # every signature inline; its codes are the ground truth
        oracle_conn = MultiAppConn(LocalClientCreator(SignedKVStoreApp()))
        oracle_conn.start()
        run.defer(oracle_conn.stop)
        oracle_mp = Mempool(oracle_conn.mempool, checktx_batch=1)
        oracle_codes = []
        for tx in txs:
            try:
                oracle_mp.check_tx(
                    tx, lambda res, _c=oracle_codes: _c.append(res.code))
            except MempoolError:
                oracle_codes.append(-1)

        node = run.nodes[0]
        codes = [None] * len(txs)
        dev = run.device
        for i, tx in enumerate(txs):
            if i == len(txs) // 3:
                dev.fail_rate = 1.0  # device flap mid-flood -> host fallback
            if i == 2 * len(txs) // 3:
                dev.fail_rate = 0.0
            try:
                node.mempool.check_tx(
                    tx,
                    lambda res, _i=i: codes.__setitem__(_i, res.code),
                )
            except MempoolError:
                codes[i] = -1
            _time.sleep(0.002)  # let windows close across the flap phases
        if not run.wait_for(lambda: all(c is not None for c in codes), 30.0):
            return [f"CheckTx verdicts never settled: {codes}"]
        if codes != oracle_codes:
            failures.append(
                "batched admit/reject codes diverged from the serial "
                f"oracle: {oracle_codes} vs {codes}"
            )
        # every valid tx must commit everywhere, and the committed state
        # must be bit-identical across nodes (DeliverTx re-verifies
        # serially, so state equality IS serial-path equality)
        if not run.wait_for(
            lambda: all(
                all(k in n.app.state for k in valid_keys)
                for n in run.nodes
            ),
            timeout=60.0,
        ):
            missing = {
                n.node_id: [k.decode() for k in valid_keys
                            if k not in n.app.state]
                for n in run.nodes
            }
            failures.append(f"valid signed txs not committed: {missing}")
        states = {tuple(sorted(n.app.state.items())) for n in run.nodes}
        if len(states) != 1:
            failures.append("committed app state diverged across nodes")
        nonces = {tuple(sorted(n.app.nonces.items())) for n in run.nodes}
        if len(nonces) != 1:
            failures.append("committed nonce maps diverged across nodes")
        return failures

    def check(run: ScenarioRun) -> List[str]:
        failures = []
        node = run.nodes[0]
        if node.tx_feed is None or node.tx_feed.dispatches == 0:
            failures.append("tx feed never dispatched")
        if run.device.snapshot()["failures"] == 0:
            failures.append("device flap never fired")
        text = node.metrics.registry.expose_text()
        for name in ("tendermint_mempool_batch_rows",
                     "tendermint_mempool_batch_flush_total"):
            if name not in text:
                failures.append(f"{name} missing from metric exposition")
        return failures

    return Scenario(
        name="signed_flood",
        description="mixed valid/garbage/wrong-nonce/mutant signed txs "
                    "through batched TxFeed ingest while the device "
                    "backend flaps; codes bit-identical to a serial "
                    "oracle, committed state identical on every node",
        seed=11,
        timeout_s=180.0,
        config_factory=config,
        app_factory=lambda i: SignedKVStoreApp(),
        setup=setup,
        drive=drive,
        check=check,
    )


def device_flap() -> Scenario:
    """The guarded batch verifier's device backend fails, hangs, recovers,
    then silently corrupts — mid-run, with consensus live on top of it.
    The breaker must walk the whole state machine (open on errors, open on
    timeouts, half-open probe, re-close, quarantine latch on the audit
    mismatch, operator reset), verdicts must stay bit-identical to the
    host path throughout (asserted indirectly: safety + uninterrupted
    liveness), and the episode must land in the metric exposition."""

    def setup(run: ScenarioRun) -> None:
        from tendermint_tpu.crypto import batch as _batch
        from tendermint_tpu.libs import breaker as _brk
        from tendermint_tpu.sim.faults import FaultyDevice

        # small backoffs so every transition fits a smoke-test budget;
        # audit every lane so the first corrupt window is always caught
        br = _brk.configure_device_guard(
            breaker_threshold=3, breaker_backoff=0.2,
            breaker_backoff_max=0.4, dispatch_deadline=0.3,
            audit_sample_rate=1.0, retries=0,
        )
        prev = _batch.get_batch_verifier()  # host, pinned by build_sim_net
        dev = FaultyDevice(_batch.HostBatchVerifier(),
                           seed=run.scenario.seed, hang_s=1.0)
        _batch.set_batch_verifier(_batch.GuardedBatchVerifier(dev, breaker=br))
        run.device, run.breaker = dev, br
        run.defer(_brk.reset_device_guard)
        run.defer(lambda: _batch.set_batch_verifier(prev))

    def drive(run: ScenarioRun) -> List[str]:
        from tendermint_tpu.libs import breaker as _brk

        failures = []
        br, dev = run.breaker, run.device
        if not run.wait_height(1, 30.0):
            return [f"never warmed up: {run.heights()}"]

        def phase(label: str, want_state: str, budget: float = 20.0,
                  progress: int = 2) -> None:
            run.mark(label)
            if not run.wait_for(lambda: br.state == want_state, budget):
                failures.append(
                    f"{label}: breaker stuck in {br.state!r}, "
                    f"wanted {want_state!r}"
                )
            h = max(run.heights())
            if progress and not run.wait_height(h + progress, 45.0):
                failures.append(
                    f"{label}: chain stalled at {run.heights()} "
                    f"(breaker {br.state})"
                )

        dev.fail_rate = 1.0          # crashing device -> open, host fallback
        phase("fail", _brk.OPEN)
        dev.fail_rate = 0.0          # recovery -> half-open probe re-closes
        phase("recover_fail", _brk.CLOSED, progress=0)
        dev.hang_rate = 1.0          # wedged device -> timeouts -> open again
        phase("hang", _brk.OPEN)
        dev.hang_rate = 0.0
        phase("recover_hang", _brk.CLOSED, progress=0)
        dev.corrupt_rate = 1.0       # silent corruption -> quarantine latch
        phase("corrupt", _brk.QUARANTINED)
        dev.corrupt_rate = 0.0
        br.reset()                   # the operator runbook step
        phase("operator_reset", _brk.CLOSED)
        return failures

    def check(run: ScenarioRun) -> List[str]:
        from tendermint_tpu.libs.metrics import get_verify_metrics

        failures = []
        snap = run.breaker.snapshot()
        walked = {(h["from"], h["to"]) for h in snap["history"]}
        for want in [("closed", "open"), ("open", "half_open"),
                     ("half_open", "closed"), ("closed", "quarantined"),
                     ("quarantined", "closed")]:
            if want not in walked:
                failures.append(f"breaker never transitioned {want}: {walked}")
        reasons = " ".join(h["reason"] for h in snap["history"])
        if "timeout" not in reasons:
            failures.append(f"no timeout-driven open in history: {reasons}")
        dsnap = run.device.snapshot()
        if dsnap["failures"] == 0 or dsnap["hangs"] == 0 \
                or dsnap["corruptions"] == 0:
            failures.append(f"fault injection never fired: {dsnap}")
        text = get_verify_metrics().registry.expose_text()
        for name in ("tendermint_verify_device_breaker_state",
                     "tendermint_verify_device_fallback_total",
                     "tendermint_verify_device_audit_total"):
            if name not in text:
                failures.append(f"{name} missing from metric exposition")
        return failures

    return Scenario(
        name="device_flap",
        description="device backend fails/hangs/corrupts mid-run; breaker "
                    "walks its full state machine, consensus keeps "
                    "committing on host fallback, audit quarantines the "
                    "corruptor before a wrong verdict escapes",
        seed=9,
        timeout_s=180.0,
        setup=setup,
        drive=drive,
        check=check,
    )


def crash_restart() -> Scenario:
    """Kill one validator mid-height and rebuild it from its surviving
    stores + WAL file: the replacement must replay WAL messages into the
    round state, re-apply committed blocks into the fresh app over the
    ABCI handshake, and catch back up to the live chain."""
    import os
    import shutil
    import tempfile

    VICTIM = 2

    def setup(run: ScenarioRun) -> None:
        from tendermint_tpu.sim.node import SimNode

        tmp = tempfile.mkdtemp(prefix="tm-sim-crash-")
        run.defer(lambda: shutil.rmtree(tmp, ignore_errors=True))
        # rebuild the victim with a real on-disk WAL before anything
        # starts (build_sim_net wires WAL-less nodes); peers' handles to
        # the node id stay valid, the fabric just re-points the id
        old = run.nodes[VICTIM]
        old.crash()
        node = SimNode(
            index=old.index, node_id=old.node_id, doc=old.doc, pv=old.pv,
            fabric=run.fabric, config=old.config, clock=old.clock,
            wal_path=os.path.join(tmp, "cs.wal"),
        )
        for other in run.nodes:
            if other is not old:
                node.switch.connect(other.node_id)
        run.nodes[VICTIM] = node

    def drive(run: ScenarioRun) -> List[str]:
        failures = []
        if not run.wait_height(2, 45.0):
            return [f"never warmed up: {run.heights()}"]
        pre_crash = dict(run.nodes[VICTIM].committed_hashes())
        node = run.crash_restart(VICTIM)
        if node.handshake_blocks <= 0:
            failures.append(
                "ABCI handshake replayed no blocks into the fresh app"
            )
        if node.cs.wal_replayed <= 0:
            failures.append("WAL replay re-fed no messages after the crash")
        for h, hh in pre_crash.items():
            if node.committed_hashes().get(h) != hh:
                failures.append(
                    f"restart lost/changed committed block at height {h}"
                )
        h = max(run.heights())
        if not run.wait_for(lambda: node.height > h + 2, 60.0):
            failures.append(
                f"restarted node never rejoined: victim at {node.height}, "
                f"net at {run.heights()}"
            )
        return failures

    return Scenario(
        name="crash_restart",
        description="node killed mid-height, rebuilt from stores + WAL; "
                    "WAL replay + ABCI handshake bring it back and it "
                    "catches up to the chain",
        seed=10,
        timeout_s=180.0,
        setup=setup,
        drive=drive,
    )


def quorum_observatory() -> Scenario:
    """The observability stack under its designed-for conditions: ±2s
    wall-clock skews, a seeded gossip storm (duplicates + reorder feed the
    waste ledger), and one node's clock frozen mid-run then resumed (the
    worst distortion commit-anchor math must survive).  Claims: every
    fused vote journey presents a monotone sign -> send -> arrival
    timeline after anchor correction (with the raw stamps of unfrozen
    nodes landing within a small residual of the signer's corrected
    stamp — i.e. the injected ±2s really was measured back out), the
    freeze demonstrably distorted stamps (some journey got clamped), and
    every live quorum record's pivotal-validator naming re-derives
    bit-identically from the node's own flight dump — identification is a
    deterministic pure function of the stamps, not of analysis timing."""

    FROZEN = 2  # index into SKEWS_NS: the -1.5s node also gets frozen
    storm_policy = dict(delay_s=0.002, jitter_s=0.008, drop=0.05,
                        duplicate=0.15, reorder=0.15, reorder_extra_s=0.03)

    def drive(run: ScenarioRun) -> List[str]:
        import time as _time

        failures = []
        if not run.wait_height(2, 45.0):
            return [f"never warmed up: {run.heights()}"]
        clk = run.nodes[FROZEN].clock
        # freeze at the current instant: now_ns() keeps returning
        # frozen + skew, so the node's stamps stop advancing while the
        # chain (driven by real-time timers, not wall stamps) keeps going
        clk.freeze(_time.time_ns())
        run.mark("frozen")
        h = max(run.heights())
        if not run.wait_height(h + 2, 60.0):
            failures.append(
                f"no progress while node {FROZEN}'s clock was frozen: "
                f"{run.heights()}"
            )
        clk.freeze(0)  # resume
        run.mark("resumed")
        h2 = max(run.heights())
        if not run.wait_height(h2 + 2, 60.0):
            failures.append(
                f"no progress after clock resume: {run.heights()}"
            )
        return failures

    def check(run: ScenarioRun) -> List[str]:
        import importlib.util
        import os

        from tendermint_tpu.libs import quorumtrace as qt

        spec = importlib.util.spec_from_file_location(
            "trace_merge",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "scripts", "trace_merge.py"),
        )
        tm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tm)

        failures = []
        frozen_id = run.nodes[FROZEN].node_id
        dumps = [n.cs.flight.snapshot() for n in run.nodes]
        skews = tm.compute_skews(dumps)
        # anchor recovery must still measure the injected skews out of the
        # UNFROZEN nodes; the frozen node's freeze-window anchors are
        # legitimately bogus (the median absorbs them, but only within
        # whatever share of heights the freeze covered — don't gate on it)
        for i, skew in enumerate(skews):
            if i == FROZEN:
                continue
            expected = SKEWS_NS[0] - SKEWS_NS[i]
            err_s = abs(skew - expected) / 1e9
            if err_s > 0.5:
                failures.append(
                    f"node {i}: recovered skew {skew / 1e9:+.3f}s vs "
                    f"injected {expected / 1e9:+.3f}s (err {err_s:.3f}s)"
                )
        skew_map = {
            (d.get("node_id") or f"node{i}"): skews[i]
            for i, d in enumerate(dumps)
        }
        journeys = qt.build_journeys(dumps, skew_map)
        with_arrivals = [
            j for j in journeys
            if j["signed_ns"] is not None and j["arrivals"]
        ]
        if len(with_arrivals) < 4:
            failures.append(
                f"only {len(with_arrivals)} journeys fused with both a "
                f"sign stamp and arrivals"
            )
        clamped_on_frozen = False
        for j in with_arrivals:
            floor = j["signed_ns"]
            send = j["first_send"]
            if send is not None:
                if send["t_mono_ns"] < floor:
                    failures.append(
                        f"h={j['height']} {j['kind']} vi="
                        f"{j['validator_index']}: first_send precedes "
                        f"sign in the monotone view"
                    )
                floor = send["t_mono_ns"]
            for node, mark in j["arrivals"].items():
                if mark["t_mono_ns"] < floor:
                    failures.append(
                        f"h={j['height']} {j['kind']} vi="
                        f"{j['validator_index']}: arrival at {node} "
                        f"precedes its upstream leg in the monotone view"
                    )
                # unfrozen raw stamps must sit within a small residual of
                # the signer's corrected stamp: uncorrected, the -1.5s /
                # +2s skews would invert these legs by whole seconds
                if (node != frozen_id and j["origin"] != frozen_id
                        and mark["t_ns"] < j["signed_ns"] - 350_000_000):
                    failures.append(
                        f"h={j['height']} {j['kind']} vi="
                        f"{j['validator_index']}: arrival at {node} "
                        f"{(mark['t_ns'] - j['signed_ns']) / 1e9:+.3f}s "
                        f"before signing — skew not corrected out"
                    )
                if j["clamped"] and (node == frozen_id
                                     or j["origin"] == frozen_id):
                    clamped_on_frozen = True
        if not clamped_on_frozen:
            failures.append(
                "freeze never distorted a journey (no clamped stamp "
                "touching the frozen node) — the scenario lost its bite"
            )
        # pivotal-validator determinism: every live record's curves must
        # re-derive bit-identically from the node's own flight record —
        # the naming is a pure function of the dump, so any consumer
        # (report, RPC, re-analysis) reproduces it exactly
        named = 0
        for node in run.nodes:
            for rec in node.cs.quorumtrace.records():
                frec = node.cs.flight.peek(rec["height"])
                if frec is None:
                    continue  # ring evicted it; nothing to re-derive
                for kind, curve in rec["curves"].items():
                    redo = qt.completion_curve(
                        frec, kind, curve["total_power"]
                    )
                    if redo is None or (
                        redo["pivotal_validator"]
                        != curve["pivotal_validator"]
                        or redo["crossings"] != curve["crossings"]
                    ):
                        failures.append(
                            f"{node.node_id}: h={rec['height']} {kind} "
                            f"re-derived pivotal "
                            f"{redo and redo['pivotal_validator']} != "
                            f"recorded {curve['pivotal_validator']}"
                        )
                    if curve["pivotal_validator"] is not None:
                        named += 1
        if named == 0:
            failures.append("no height named a pivotal validator")
        return failures

    return Scenario(
        name="quorum_observatory",
        description="±2s skews + seeded storm + one frozen-then-resumed "
                    "clock: fused vote journeys stay monotone after "
                    "commit-anchor correction and pivotal-validator "
                    "naming re-derives bit-identically from the dumps",
        seed=13,
        timeout_s=180.0,
        config_factory=_skew_config,
        clock_factory=lambda i: SimClock(skew_ns=SKEWS_NS[i]),
        drive=drive,
        check=check,
        ops=[FaultOp(at_s=0.0, op="policy",
                     kwargs={"src": None, "dst": None,
                             "policy": storm_policy})],
    )


SCENARIOS: Dict[str, Callable[[], Scenario]] = {
    "baseline_determinism": baseline_determinism,
    "partition_heal": partition_heal,
    "partition_heal_9": lambda: partition_heal(n_vals=9),
    "storm": storm,
    "clock_skew": clock_skew,
    "churn": churn,
    "equivocation": equivocation,
    "vote_storm": vote_storm,
    "silence_watchdog": silence_watchdog,
    "mempool_flood": mempool_flood,
    "signed_flood": signed_flood,
    "device_flap": device_flap,
    "crash_restart": crash_restart,
    "quorum_observatory": quorum_observatory,
}
