"""Batched Ed25519 verification as a JAX kernel — the data-plane moat.

The reference verifies one signature at a time on the host CPU
(`/root/reference/types/validator_set.go:281-296` serial loop over precommits;
single-verify at `/root/reference/crypto/ed25519/ed25519.go:151`).  Here the
whole batch — every precommit of a height, or a whole fast-sync window of
heights — is verified in ONE device dispatch.

TPU-first design, not a port:

  * Field arithmetic over GF(2^255-19) in **20 radix-2^13 uint32 limbs** so every
    partial product and every 20-term partial-product column fits a 32-bit lane
    (TPU has no native 64-bit integer multiply; the VPU is 32-bit).  All limb
    ops are elementwise over a ``(batch, 20)`` tensor → the batch axis
    vectorizes across VPU lanes and shards across the device mesh.
  * One interleaved double-scalar ladder computes ``[s]B + [h](-A)`` with
    *complete* extended-coordinate formulas (add-2008-hwcd-3 / dbl-2008-hwcd),
    so adversarial low-order points need no special-casing and there is no
    data-dependent control flow — the whole ladder is a single
    ``lax.fori_loop`` that XLA compiles once.
  * Accept/reject is bit-exact with the Go fork of golang.org/x/crypto/ed25519
    (see tendermint_tpu/crypto/ed25519.py for the quirk list): only the top 3
    bits of s are range-checked, non-canonical A/R encodings are accepted, and
    the final check compares the canonical encoding of R' against sig[:32]
    byte-for-byte (done here in limb space against the *raw* R bytes).
  * Host prologue (cheap, latency-hidden): SHA-512 of the ~110-byte sign-bytes
    via hashlib, point decompression of pubkeys with an LRU cache (validator
    keys repeat across every height of a sync window), bit-unpacking of
    scalars.  Device does all the exponent work (~6.5k field muls/signature).

Sharding: pass ``mesh=`` to shard the batch axis over ``mesh.axis_names[0]``
with jax.sharding.NamedSharding — the kernel is embarrassingly data-parallel,
collectives only appear in the commit-tally layer above
(tendermint_tpu/parallel/).
"""

from __future__ import annotations

import hashlib
import sys
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.ops import fe_common as _fc

P = _ed.P
L = _ed.L
D2 = _ed.D2

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1  # 8191
NBITS = 253  # scalars s, h < 2^253

# fold factor: 2^260 ≡ 19·2^5 (mod p)
FOLD = 19 << 5  # 608


def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> 20 radix-2^13 uint32 limbs (little-endian limb order)."""
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMB)], dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    return sum(int(v) << (BITS * i) for i, v in enumerate(np.asarray(limbs)))


# K ≡ 0 (mod p) with every limb large enough that (a + K - b) never underflows
# for carried a, b:  K_i = 4·8191 = 32764 except K_0 = 32764 - 2428
# (4·(2^260-1) ≡ 2428 mod p).
_K_SUB = np.full((NLIMB,), 4 * MASK, dtype=np.uint32)
_K_SUB[0] = 4 * MASK - 2428
assert limbs_to_int(_K_SUB) % P == 0

_D2_LIMBS = int_to_limbs(D2)
_BX_LIMBS = int_to_limbs(_ed.B_AFFINE)
_BY_LIMBS = int_to_limbs(_ed._BY)
_BT_LIMBS = int_to_limbs(_ed.B_AFFINE * _ed._BY % P)

# bits of p-2 (MSB first) for Fermat inversion
_P2_BITS = np.array(
    [(P - 2) >> i & 1 for i in reversed(range(255))], dtype=np.uint32
)


# ---------------------------------------------------------------------------
# Field element ops.  A "carried" fe has every limb <= ~8800, so 20-term
# partial-product columns stay < 2^31.  All fns keep uint32 dtype.
# ---------------------------------------------------------------------------


def fe_carry(x: jnp.ndarray, rounds: int = 4) -> jnp.ndarray:
    """Parallel carry propagation with the 2^260 ≡ 608 wraparound fold."""
    for _ in range(rounds):
        c = x >> BITS
        x = (x & MASK).at[..., 1:].add(c[..., :-1]).at[..., 0].add(c[..., -1] * FOLD)
    return x


def fe_add(a, b):
    return fe_carry(a + b, rounds=2)


def fe_sub(a, b):
    return fe_carry(a + _K_SUB - b, rounds=2)


# Limb-multiplier backend for this module's kernels: "vpu" is the shifted
# multiply-accumulate schoolbook below; "mxu" computes the same columns as 4
# int8 matmuls (fe_common.mul_columns_batch) so the 400 row-products land on
# the matrix unit. Set only via _compiled_kernel's trace-time wrapper — the
# jit cache is keyed on it, so each backend traces its own kernel.
_FE_BACKEND = "vpu"

# Carry schedule for the ladder's point ops: "eager" is the full per-op
# ripple below; "lazy" defers carries per fe_common.derive_carry_plan (one
# reduction per point op). Swapped the same trace-time way as _FE_BACKEND
# (fe_common.trace_with_modes); module-level fe_mul/fe_add/fe_sub are always
# the eager ops regardless.
_CARRY_MODE = "eager"

_PLAN = _fc.derive_carry_plan("ed25519")
# wide zero dominating the lazy class-D operands (plan-derived analog of
# _K_SUB, which dominates carried eager values only)
_KD_SUB = np.asarray(_PLAN.kd, dtype=np.uint32)


def fe_mul(a, b):
    """Schoolbook product via 20 shifted multiply-accumulates, then reduce.

    Bounds (audited; regression-pinned in tests/test_ops_ed25519.py and
    recomputed mechanically by fe_common.bound_* in tests/test_fe_common.py):
    carried inputs have limbs ≤ ~8800 (fe_sub's limb-0 wraparound term is
    the max — see fe_carry), and fe_mul is proven well past that (stressed
    to 13000). The 41st product row is REQUIRED: carries ripple one row
    per round, so with a 40-limb buffer the carry out of row 39 — reachable
    at the margin, e.g. top limbs 8192·8192 = 2^26 — would be silently
    dropped (the same mechanism as the secp bug fixed in
    secp256k1_verify.fe_mul). Row 40 folds as 2^520 ≡ 608² (mod p)."""
    if _FE_BACKEND != "vpu":
        # identical integers per column (exact int32 recombination), so the
        # carry/fold tail below is untouched — bit-exact with the VPU path
        prod = _fc.mul_columns_batch(a, b, 2 * NLIMB + 1, split=7)
    else:
        shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
        prod = jnp.zeros(shape + (2 * NLIMB + 1,), dtype=jnp.uint32)
        for i in range(NLIMB):
            prod = prod.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    # local carries inside the 41-limb product (no wrap needed: value < 2^520)
    for _ in range(3):
        c = prod >> BITS
        prod = (prod & MASK).at[..., 1:].add(c[..., :-1])
    # fold limbs 20..39 down (2^(260+13j) ≡ 608·2^13j), row 40 as 608²
    lo = prod[..., :NLIMB] + prod[..., NLIMB : 2 * NLIMB] * FOLD
    lo = lo.at[..., 0].add(prod[..., 2 * NLIMB] * (FOLD * FOLD))
    return fe_carry(lo, rounds=4)


def fe_sq(a):
    return fe_mul(a, a)


# --- deferred-carry (lazy) ops: batch-leading twins of the Pallas row ops,
# used by the ladder's point ops when _CARRY_MODE == "lazy".  Operand-class
# bounds are certified at import by fe_common.derive_carry_plan; lazy-mode
# operands exceed the int8 plane bound, so mxu uses uint8 planes (split=8).


def _mul_cols(a, b, out_cols):
    if _FE_BACKEND != "vpu":
        return _fc.mul_columns_batch(a, b, out_cols, split=8)
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    prod = jnp.zeros(shape + (out_cols,), dtype=jnp.uint32)
    for i in range(NLIMB):
        prod = prod.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    return prod


def fe_mul_f(a, b):
    """Full lazy multiply: fused fold, plan.mulf_wide wide rounds, fixups —
    output lands in the certified class C."""
    lo = _fc.ed_fold_fused_batch(_mul_cols(a, b, 2 * NLIMB))
    for _ in range(_PLAN.mulf_wide):
        lo = _fc.wide_carry_batch(lo, _fc.ED_WRAP)
    return _fc.fix_batch(lo, _PLAN.mulf_fix)


def fe_mul_l(a, b):
    """Lazy multiply with a single wide round: output stays in class D."""
    lo = _fc.ed_fold_fused_batch(_mul_cols(a, b, 2 * NLIMB))
    lo = _fc.wide_carry_batch(lo, _fc.ED_WRAP)
    return _fc.fix_batch(lo, _PLAN.mull_fix)


def fe_norm1(raw):
    """One wide round + fixups: raw limb sum -> class C."""
    return _fc.fix_batch(_fc.wide_carry_batch(raw, _fc.ED_WRAP), _PLAN.norm_fix)


def fe_add_l(a, b):
    return fe_norm1(a + b)


def fe_sub_l(a, b):
    # always against the class-D wide zero: dominates class-C operands too
    return fe_norm1(a + _KD_SUB - b)


def fe_mul4_f(*pairs):
    """Four mulF schedules stacked into ONE wide round (PERF.md carry-tail
    vectorization): the four output products of a point op share the exact
    same fold/wide/fixup schedule, so stacking them on a new leading axis
    runs one (4, ..., 20) reduction instead of four — bit-identical per
    slice (every fe op is elementwise over leading axes)."""
    a = jnp.stack([p[0] for p in pairs])
    b = jnp.stack([p[1] for p in pairs])
    out = fe_mul_f(a, b)
    return tuple(out[k] for k in range(len(pairs)))


def fe_inv(z):
    """z^(p-2) by square-and-multiply over the fixed bit pattern of p-2."""

    def body(acc, bit):
        acc = fe_sq(acc)
        acc = jnp.where(bit.astype(bool), fe_mul(acc, z), acc)
        return acc, None

    one = jnp.zeros_like(z).at[..., 0].set(1)
    acc, _ = lax.scan(body, one, jnp.asarray(_P2_BITS))
    return acc


def fe_canonical(x):
    """Fully reduce a carried fe into [0, p), exact limbs <= MASK."""

    def seq_carry(v):
        for i in range(NLIMB - 1):
            c = v[..., i] >> BITS
            v = v.at[..., i].set(v[..., i] & MASK).at[..., i + 1].add(c)
        return v

    def fold_top(v):
        # bits >= 255 live in limb 19 at offset 8
        q = v[..., NLIMB - 1] >> 8
        v = v.at[..., NLIMB - 1].set(v[..., NLIMB - 1] & 0xFF)
        return v.at[..., 0].add(q * 19)

    x = fe_carry(x, rounds=2)
    for _ in range(3):
        x = fold_top(seq_carry(x))
    x = seq_carry(x)  # now x < 2^255
    # conditional subtract p:  t = x + 19;  if t >= 2^255 then x - p = t - 2^255
    t = seq_carry(x.at[..., 0].add(19))
    ge = (t[..., NLIMB - 1] >> 8) > 0
    t = t.at[..., NLIMB - 1].set(t[..., NLIMB - 1] & 0xFF)
    return jnp.where(ge[..., None], t, x)


# ---------------------------------------------------------------------------
# Point ops: extended coords (X, Y, Z, T), x=X/Z, y=Y/Z, T=XY/Z.
# Complete for a=-1, d non-square — valid for ALL curve points.
# ---------------------------------------------------------------------------


def pt_add(p, q, d2):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    if _CARRY_MODE == "lazy":
        # one full reduction per point op: operand products ride as class D,
        # E/F/G/H carry once, only the four output muls run the full mulF
        # schedule.  The inner T1*d2 must be mulF — a class-D operand would
        # overflow the product columns.
        A = fe_mul_l(fe_sub_l(Y1, X1), fe_sub_l(Y2, X2))
        B = fe_mul_l(fe_add_l(Y1, X1), fe_add_l(Y2, X2))
        C = fe_mul_l(fe_mul_f(T1, d2), T2)
        Dv = fe_mul_l(Z1 + Z1, Z2)
        E = fe_sub_l(B, A)
        F = fe_sub_l(Dv, C)
        G = fe_add_l(Dv, C)
        H = fe_add_l(B, A)
        return fe_mul4_f((E, F), (G, H), (F, G), (E, H))
    A = fe_mul(fe_sub(Y1, X1), fe_sub(Y2, X2))
    B = fe_mul(fe_add(Y1, X1), fe_add(Y2, X2))
    C = fe_mul(fe_mul(T1, d2), T2)
    Dv = fe_mul(fe_add(Z1, Z1), Z2)
    E = fe_sub(B, A)
    F = fe_sub(Dv, C)
    G = fe_add(Dv, C)
    H = fe_add(B, A)
    return fe_mul(E, F), fe_mul(G, H), fe_mul(F, G), fe_mul(E, H)


def pt_double(p):
    X1, Y1, Z1, _ = p
    if _CARRY_MODE == "lazy":
        A = fe_mul_l(X1, X1)
        B = fe_mul_l(Y1, Y1)
        ZZ = fe_mul_l(Z1, Z1)
        C = ZZ + ZZ
        H = fe_add_l(A, B)
        xy = fe_add_l(X1, Y1)
        E = fe_sub_l(H, fe_mul_l(xy, xy))
        G = fe_sub_l(A, B)
        F = fe_add_l(C, G)
        return fe_mul4_f((E, F), (G, H), (F, G), (E, H))
    A = fe_sq(X1)
    B = fe_sq(Y1)
    ZZ = fe_sq(Z1)
    C = fe_add(ZZ, ZZ)
    H = fe_add(A, B)
    xy = fe_add(X1, Y1)
    E = fe_sub(H, fe_sq(xy))
    G = fe_sub(A, B)
    F = fe_add(C, G)
    return fe_mul(E, F), fe_mul(G, H), fe_mul(F, G), fe_mul(E, H)


def pt_select(cond, p, q):
    """cond (batch,) bool: p where true else q, across all 4 coords."""
    c = cond[..., None]
    return tuple(jnp.where(c, a, b) for a, b in zip(p, q))


# ---------------------------------------------------------------------------
# The verify kernel
# ---------------------------------------------------------------------------


def _get_bit(words: jnp.ndarray, i) -> jnp.ndarray:
    """Bit i (0 = LSB) of little-endian packed (..., 8) uint32 words."""
    w = lax.dynamic_slice_in_dim(words, i // 32, 1, axis=-1)[..., 0]
    return (w >> (i % 32).astype(jnp.uint32)) & jnp.uint32(1)


def _verify_kernel(neg_ax, ay, s_words, h_words, r_limbs, r_sign):
    """Device side: R' = [s]B + [h](-A); compare enc(R') with raw R bytes.

    All inputs share an arbitrary leading batch shape (1-D for flat batches,
    (heights, validators) for sharded commit windows):
      neg_ax, ay : (..., 20) limbs of -A affine (x negated mod p)
      s_words, h_words : (..., 8) uint32 LE bit-packed scalars
      r_limbs : (..., 20) raw (unreduced) 255-bit y of sig[:32]
      r_sign  : (...)   sign bit of sig[:32]
    Returns (...) bool.
    """
    batch = neg_ax.shape[:-1]
    one = jnp.zeros(batch + (NLIMB,), jnp.uint32).at[..., 0].set(1)
    zero = jnp.zeros(batch + (NLIMB,), jnp.uint32)
    d2 = jnp.asarray(_D2_LIMBS)

    # the T coordinate must land in the lazy class C when the ladder defers
    # carries (eager-carried limbs can exceed it — limb 0 tops at ~11231)
    t_mul = fe_mul_f if _CARRY_MODE == "lazy" else fe_mul
    neg_a = (neg_ax, ay, one, t_mul(neg_ax, ay))
    b_pt = (
        jnp.broadcast_to(jnp.asarray(_BX_LIMBS), batch + (NLIMB,)),
        jnp.broadcast_to(jnp.asarray(_BY_LIMBS), batch + (NLIMB,)),
        one,
        jnp.broadcast_to(jnp.asarray(_BT_LIMBS), batch + (NLIMB,)),
    )

    def body(t, acc):
        i = NBITS - 1 - t  # MSB -> LSB
        acc = pt_double(acc)
        with_b = pt_add(acc, b_pt, d2)
        acc = pt_select(_get_bit(s_words, i).astype(bool), with_b, acc)
        with_a = pt_add(acc, neg_a, d2)
        acc = pt_select(_get_bit(h_words, i).astype(bool), with_a, acc)
        return acc

    ident = (zero, one, one, zero)
    X, Y, Z, _ = lax.fori_loop(0, NBITS, body, ident)

    zinv = fe_inv(Z)
    x = fe_canonical(fe_mul(X, zinv))
    y = fe_canonical(fe_mul(Y, zinv))
    sign = x[..., 0] & 1
    # byte-exact compare: canonical enc(R') vs raw sig[:32] (limbs + sign bit)
    return jnp.all(y == r_limbs, axis=-1) & (sign == r_sign.astype(jnp.uint32))


_kernel_cache = {}


def _compiled_kernel(batch: int, mesh=None, fe_backend: str = "vpu",
                     carry_mode: str = "eager"):
    # Mesh hashes by devices+axis_names — safe cache key (id() could be reused
    # by a new Mesh after gc and serve a stale sharding)
    carry_mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    if fe_backend not in ("vpu", "mxu"):
        fe_backend = "mxu" if fe_backend == "mxu16" else "vpu"
    key = (batch, mesh, fe_backend, carry_mode)
    fn = _kernel_cache.get(key)
    if fn is None:
        kernel = _fc.trace_with_modes(
            sys.modules[__name__], _verify_kernel, fe_backend, carry_mode
        )
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            data = NamedSharding(mesh, PS(mesh.axis_names[0]))
            fn = jax.jit(kernel, in_shardings=(data,) * 6, out_shardings=data)
        else:
            fn = jax.jit(kernel)
        _kernel_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Host prologue: parse/hash/decompress/pack, then one device dispatch.
# ---------------------------------------------------------------------------

_decompress_cache: dict = {}
_DECOMPRESS_CACHE_MAX = 1 << 16


def _decompress_neg_cached(pub: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(-x, y) limb arrays for pubkey A, or None if A fails decompression.
    Validator keys repeat across heights — cache pays for itself immediately."""
    hit = _decompress_cache.get(pub, False)
    if hit is not False:
        return hit
    xy = _ed._decompress_xy(pub)
    if xy is None:
        out = None
    else:
        x, y = xy
        out = (int_to_limbs((P - x) % P), int_to_limbs(y))
    if len(_decompress_cache) >= _DECOMPRESS_CACHE_MAX:
        _decompress_cache.clear()
    _decompress_cache[pub] = out
    return out


def _bytes_to_raw_limbs(r32: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 LE -> (N, 20) raw 13-bit limbs of the low 255 bits."""
    bits = np.unpackbits(r32, axis=1, bitorder="little")  # (N, 256)
    bits[:, 255] = 0
    bits = np.pad(bits, ((0, 0), (0, NLIMB * BITS - 256)))  # 260 bits
    limbs = np.zeros((r32.shape[0], NLIMB), dtype=np.uint32)
    w = (1 << np.arange(BITS, dtype=np.uint32))
    for i in range(NLIMB):
        limbs[:, i] = bits[:, BITS * i : BITS * (i + 1)].astype(np.uint32) @ w
    return limbs


def _bucket(n: int) -> int:
    """Pad size: powers of two up to 4096, then multiples of 4096 (bounds
    recompiles while capping pad waste at large batch)."""
    b = 64
    while b < n and b < 4096:
        b *= 2
    if n <= b:
        return b
    return ((n + 4095) // 4096) * 4096


def host_prologue(
    pubs: np.ndarray, msgs: Sequence[bytes], sigs: np.ndarray
) -> Tuple[np.ndarray, ...]:
    """Flat host-side packing shared by verify_batch and the commit-window
    packer: decompress+negate pubkeys (cached), SHA-512 h mod L, bit-pack
    scalars, raw-limb R.  Returns
    (neg_ax, ay, s_words, h_words, r_limbs, r_sign, valid) with batch leading.
    """
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    sigs = np.ascontiguousarray(sigs, dtype=np.uint8)
    n = pubs.shape[0]

    valid = np.ones((n,), dtype=bool)
    # s range check: reject if top 3 bits set (Go checks only sig[63]&224)
    if n:
        valid &= (sigs[:, 63] & 224) == 0

    neg_ax = np.zeros((n, NLIMB), dtype=np.uint32)
    ay = np.zeros((n, NLIMB), dtype=np.uint32)
    h_bytes = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        if not valid[i]:
            continue
        pk = pubs[i].tobytes()
        dec = _decompress_neg_cached(pk)
        if dec is None:
            valid[i] = False
            continue
        neg_ax[i] = dec[0]
        ay[i] = dec[1]
        sig = sigs[i]
        h = (
            int.from_bytes(
                hashlib.sha512(sig[:32].tobytes() + pk + bytes(msgs[i])).digest(),
                "little",
            )
            % L
        )
        h_bytes[i] = np.frombuffer(h.to_bytes(32, "little"), dtype=np.uint8)

    s_words = np.ascontiguousarray(sigs[:, 32:]).view(np.dtype("<u4")).astype(np.uint32)
    h_words = h_bytes.view(np.dtype("<u4")).astype(np.uint32)
    # zero out scalars of invalid rows (keeps device work well-defined)
    s_words[~valid] = 0
    h_words[~valid] = 0
    r_limbs = _bytes_to_raw_limbs(np.ascontiguousarray(sigs[:, :32]))
    r_sign = (sigs[:, 31] >> 7).astype(np.uint32)
    return neg_ax, ay, s_words, h_words, r_limbs, r_sign, valid


def verify_batch(
    pubs: np.ndarray,
    msgs: Sequence[bytes],
    sigs: np.ndarray,
    mesh=None,
    fe_backend: str = "vpu",
    carry_mode: str = "lazy",
) -> np.ndarray:
    """Batched Go-exact ed25519 verify.

    pubs (N, 32) uint8, msgs list of N byte strings, sigs (N, 64) uint8.
    Returns (N,) bool.  One device dispatch per call (padded to a size bucket
    to bound recompiles).  fe_backend picks the limb multiplier ("vpu" |
    "mxu"; "mxu16" degrades to "mxu" here — the 16-limb repack is row-layout
    only); carry_mode "lazy" (default) defers limb carries between the
    ladder's point ops, "eager" keeps the full per-op ripple; every
    combination is bit-exact.
    """
    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    n = len(pubs)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    neg_ax, ay, s_words, h_words, r_limbs, r_sign, valid = host_prologue(
        pubs, msgs, sigs
    )

    b = _bucket(n)
    if mesh is not None:
        nd = int(mesh.devices.size)
        if b % nd:
            b = ((b + nd - 1) // nd) * nd

    def pad(a):
        if a.shape[0] == b:
            return a
        return np.concatenate(
            [a, np.zeros((b - a.shape[0],) + a.shape[1:], dtype=a.dtype)], axis=0
        )

    args = [pad(a) for a in (neg_ax, ay, s_words, h_words, r_limbs, r_sign)]
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as PS

        data = NamedSharding(mesh, PS(mesh.axis_names[0]))
        args = [jax.device_put(a, data) for a in args]
    ok = np.asarray(_compiled_kernel(b, mesh, fe_backend, carry_mode)(*args))[:n]
    return ok & valid


def rlc_seed(pubs: np.ndarray, sigs: np.ndarray) -> int:
    """Deterministic RLC coefficient seed: SHA-256 over the batch content.
    The coefficients must only be unpredictable *before* the signatures are
    fixed (Fiat–Shamir style), so hashing the batch keeps the 2^-128
    soundness while making audit/replay runs reproduce the same verdict
    path bit-for-bit."""
    dig = hashlib.sha256(
        b"ed25519-rlc" + pubs.tobytes() + sigs.tobytes()
    ).digest()
    return int.from_bytes(dig[:8], "little")


def rlc_verify_batch(
    pubs: np.ndarray,
    msgs: Sequence[bytes],
    sigs: np.ndarray,
    fe_backend: str = "vpu",
    carry_mode: str = "lazy",
    seed: Optional[int] = None,
) -> np.ndarray:
    """Batched Go-exact verify via ONE device multi-scalar multiplication.

    Same contract as ``verify_batch`` (per-row verdicts, every Go edge
    honored) at a fraction of the curve work: the whole batch is accepted
    by a single random-linear-combination MSM (ops/ed25519_msm); a rejected
    batch localizes through host chunk RLCs and re-runs only the dirty rows
    on the exact per-row ladder above.  ``seed`` pins the RLC coefficients
    (default: derived from the batch content — deterministic replay)."""
    from tendermint_tpu.ops import ed25519_msm as _msm

    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    sigs = np.ascontiguousarray(sigs, dtype=np.uint8)
    n = pubs.shape[0]
    if n == 0:
        return np.zeros((0,), dtype=bool)
    items = [(pubs[i].tobytes(), bytes(msgs[i]), sigs[i].tobytes())
             for i in range(n)]
    parsed, out = _ed._parse_batch(items)
    if seed is None:
        seed = rlc_seed(pubs, sigs)

    def ladder_fn(idx: List[int]) -> np.ndarray:
        return verify_batch(
            pubs[idx], [msgs[i] for i in idx], sigs[idx],
            fe_backend=fe_backend, carry_mode=carry_mode,
        )

    _msm.rlc_resolve(parsed, out, ladder_fn, seed=seed,
                     fe_backend=fe_backend, carry_mode=carry_mode)
    return np.asarray(out, dtype=bool)
