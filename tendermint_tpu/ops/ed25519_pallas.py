"""Fused Pallas TPU kernel for batched Ed25519 verification.

This is the performance path behind the BatchVerifier boundary (the XLA kernel
in ops/ed25519_verify.py remains the portable fallback and the multi-chip
shard_map path). The reference verifies serially on host
(`/root/reference/types/validator_set.go:281-296`,
`/root/reference/crypto/ed25519/ed25519.go:151`); here everything after point
decompression — SHA-512 of the sign-bytes, reduction mod L, scalar digit
extraction, the double-scalar ladder, and the canonical-encoding compare —
runs on device in one jit, with the ladder as a single VMEM-resident Pallas
kernel (the XLA version materializes every field-op intermediate to HBM; on the
v5e-1 bench chip this path verifies 10k signatures in ~4.5x less wall-clock
than the XLA kernel — see bench.py for the driver-captured number).

Algorithm (per 128-lane block, batch on lanes, limbs on sublanes):

  * Field arithmetic over GF(2^255-19), 20 radix-2^13 uint32 limbs in a
    (20, B) layout — shared with the secp256k1 kernel via ops/fe_common.py,
    which also provides the MXU limb multiplier (int8-plane fe_mul behind
    the [verify] fe_backend knob; the VPU schoolbook remains the default).
    Overflow bounds are no longer hand-stated here: fe_common's bound_*
    propagators recompute the closed set mechanically for every backend,
    and tests/test_fe_common.py asserts closure (carried limbs <= 13000)
    and that no intermediate reaches 2^32.
  * Double-scalar mult R' = [s]B + [h](-A) via 4-bit windowed Straus:
    64 MSB-first windows sharing 252 doublings; per window one mixed add
    from a constant niels table [0..15]B (affine, identity at digit 0) and
    one extended add from a per-signature table [0..15](-A) built with
    7 doublings + 7 adds. Complete extended formulas throughout (adversarial
    low-order/identity points need no special case).
  * Accept iff canonical-enc(R') equals sig[:32] byte-for-byte — the exact
    Go accept set (see crypto/ed25519.py quirk list): s range-checked only
    on the top 3 bits (host), A decompressed with Go's non-canonical
    acceptance (host, cached per validator set), raw R bytes compared
    without reducing them (non-canonical R never matches a canonical
    encoding, matching Go).

The XLA prologue (same jit, upstream of pallas_call) emulates 64-bit SHA-512
on uint32 pairs, Barrett-reduces the 512-bit digest mod L in radix-2^13, and
unpacks scalar digits — so the host contribution is one cached decompression
lookup plus byte packing.
"""

from __future__ import annotations

import hashlib
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.ops import ed25519_verify as _xla
from tendermint_tpu.ops import fe_common as _fc

P = _ed.P
L_ORDER = _ed.L
NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1
FOLD = _fc.ED_FOLD  # 2^260 = 608 (mod p)
LANES = 128  # batch lanes per pallas grid block
NWIN = 64  # 4-bit windows covering s, h < 2^256

_K_SUB = _xla._K_SUB  # 4p-aligned constant for borrow-free subtraction
_D2_LIMBS = _xla._D2_LIMBS

int_to_limbs = _xla.int_to_limbs

# Field ops live in ops/fe_common.py now (one copy serves both curves and
# all fe backends); these module-level names keep the original surface.
# Namespaces are built on demand per (backend, carry mode) — the lazy ones
# run derive_carry_plan's chain certification on first use.
_FE = {(b, "eager"): _fc.make_fe("ed25519", b) for b in _fc.FE_BACKENDS}
_FE_VPU = _FE[("vpu", "eager")]


def _get_fe(backend: str, carry_mode: str = "eager"):
    mode = _fc.effective_carry_mode(backend, carry_mode)
    key = (backend, mode)
    if key not in _FE:
        _FE[key] = _fc.make_fe("ed25519", backend, carry_mode=mode)
    return _FE[key]

_shift_rows_down = _fc.shift_rows_down
fe_carry1 = _fc.ed_fe_carry1
fe_add = _fc.ed_fe_add
fe_sub = _fc.ed_fe_sub
fe_mul = _fc.ed_fe_mul
fe_sq = _fc.ed_fe_sq
fe_inv = _fc.ed_fe_inv


# ---------------------------------------------------------------------------
# Point ops — extended coordinates, complete formulas (all in (20, B) limbs)
# ---------------------------------------------------------------------------


def pt_add(p, q, d2, ksub, fe=_FE_VPU, kd=None):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    if fe.carry_mode == "lazy":
        # One full reduction per point op: the four operand products stay in
        # the deferred class D (mul_lazy), E/F/G/H carry once (against kd —
        # the wide zero sized for D), and only the four output muls run the
        # full mulF schedule.  The inner T1*d2 must be mulF: a class-D
        # operand would overflow the product columns.
        A = fe.mul_lazy(fe.sub(Y1, X1, ksub), fe.sub(Y2, X2, ksub))
        B = fe.mul_lazy(fe.add(Y1, X1), fe.add(Y2, X2))
        C = fe.mul_lazy(fe.mul(T1, d2), T2)
        Dv = fe.mul_lazy(fe.add_raw(Z1, Z1), Z2)
        E = fe.sub(B, A, kd)
        F = fe.sub(Dv, C, kd)
        G = fe.add(Dv, C)
        H = fe.add(B, A)
        return fe.mul4(((E, F), (G, H), (F, G), (E, H)))
    A = fe.mul(fe.sub(Y1, X1, ksub), fe.sub(Y2, X2, ksub))
    B = fe.mul(fe.add(Y1, X1), fe.add(Y2, X2))
    C = fe.mul(fe.mul(T1, d2), T2)
    Dv = fe.mul(fe.add(Z1, Z1), Z2)
    E = fe.sub(B, A, ksub)
    F = fe.sub(Dv, C, ksub)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H)


def pt_madd(p, ypx, ymx, t2d, ksub, fe=_FE_VPU, kd=None):
    """Mixed add with a precomputed niels point (y+x, y-x, 2dxy), Z=1.
    Digit 0 maps to (1, 1, 0) and yields p unchanged (scaled) — identity-safe."""
    X1, Y1, Z1, T1 = p
    if fe.carry_mode == "lazy":
        A = fe.mul_lazy(fe.sub(Y1, X1, ksub), ymx)
        B = fe.mul_lazy(fe.add_raw(Y1, X1), ypx)
        C = fe.mul_lazy(T1, t2d)
        Dv = fe.add_raw(Z1, Z1)
        E = fe.sub(B, A, kd)
        F = fe.sub(Dv, C, kd)
        G = fe.add(Dv, C)
        H = fe.add(B, A)
        return fe.mul4(((E, F), (G, H), (F, G), (E, H)))
    A = fe.mul(fe.sub(Y1, X1, ksub), ymx)
    B = fe.mul(fe.add(Y1, X1), ypx)
    C = fe.mul(T1, t2d)
    Dv = fe.add(Z1, Z1)
    E = fe.sub(B, A, ksub)
    F = fe.sub(Dv, C, ksub)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H)


def pt_add_cached(p, c, ksub, kd, fe):
    """Lazy-only add against a cached-niels table entry (y+x, y-x, Z, 2dxy·T
    pre-scaled): the pt_madd shape plus a projective Z2, so the per-window
    table add carries once instead of the nine times the extended formula
    spent."""
    X1, Y1, Z1, T1 = p
    ypx2, ymx2, Z2, t2d2 = c
    A = fe.mul_lazy(fe.sub(Y1, X1, ksub), ymx2)
    B = fe.mul_lazy(fe.add_raw(Y1, X1), ypx2)
    C = fe.mul_lazy(T1, t2d2)
    Dv = fe.mul_lazy(fe.add_raw(Z1, Z1), Z2)
    E = fe.sub(B, A, kd)
    F = fe.sub(Dv, C, kd)
    G = fe.add(Dv, C)
    H = fe.add(B, A)
    return fe.mul4(((E, F), (G, H), (F, G), (E, H)))


def pt_to_cached(p, d2, ksub, fe):
    """Extended -> cached-niels (y+x, y-x, Z, 2d·T); identity-safe
    ((0,1,1,0) -> (1,1,1,0))."""
    X, Y, Z, T = p
    return fe.add(Y, X), fe.sub(Y, X, ksub), Z, fe.mul(T, d2)


def pt_double(p, ksub, fe=_FE_VPU, kd=None):
    X1, Y1, Z1, _ = p
    if fe.carry_mode == "lazy":
        A = fe.mul_lazy(X1, X1)
        B = fe.mul_lazy(Y1, Y1)
        ZZ = fe.mul_lazy(Z1, Z1)
        C = fe.add_raw(ZZ, ZZ)
        H = fe.add(A, B)
        xy = fe.add(X1, Y1)
        E = fe.sub(H, fe.mul_lazy(xy, xy), kd)
        G = fe.sub(A, B, kd)
        F = fe.add(C, G)
        return fe.mul4(((E, F), (G, H), (F, G), (E, H)))
    A = fe.sq(X1)
    B = fe.sq(Y1)
    ZZ = fe.sq(Z1)
    C = fe.add(ZZ, ZZ)
    H = fe.add(A, B)
    xy = fe.add(X1, Y1)
    E = fe.sub(H, fe.sq(xy), ksub)
    G = fe.sub(A, B, ksub)
    F = fe.add(C, G)
    return fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H)


# ---------------------------------------------------------------------------
# Constant tables: [0..15]B in niels form
# ---------------------------------------------------------------------------


def _build_b_niels() -> np.ndarray:
    """(16, 3, 20) uint32: (y+x, y-x, 2dxy) limbs of j*B, identity at j=0."""
    out = np.zeros((16, 3, NLIMB), dtype=np.uint32)
    Bpt = (_ed.B_AFFINE, _ed._BY)
    for j in range(16):
        if j == 0:
            x, y = 0, 1
        else:
            ext = _ed.pt_scalar_mult(_ed._to_extended(Bpt), j)
            zinv = pow(ext[2], P - 2, P)
            x, y = ext[0] * zinv % P, ext[1] * zinv % P
        out[j, 0] = int_to_limbs((y + x) % P)
        out[j, 1] = int_to_limbs((y - x) % P)
        out[j, 2] = int_to_limbs(2 * _ed.D * x * y % P)
    return out


_B_NIELS = _build_b_niels()

# All per-limb constants bundled into one (20, 52) kernel input (Pallas
# kernels cannot capture array constants): columns 0..15 = ypx of [j]B,
# 16..31 = ymx, 32..47 = t2d, 48 = 2d, 49 = the fe_sub K constant, 50 = KD
# (the wide zero the lazy carry plan sizes for deferred-class subtraction).
_CONSTS = np.zeros((NLIMB, 52), dtype=np.uint32)
for _j in range(16):
    _CONSTS[:, _j] = _B_NIELS[_j, 0]
    _CONSTS[:, 16 + _j] = _B_NIELS[_j, 1]
    _CONSTS[:, 32 + _j] = _B_NIELS[_j, 2]
_CONSTS[:, 48] = _D2_LIMBS
_CONSTS[:, 49] = _K_SUB
_CONSTS[:, 50] = np.asarray(_fc.derive_carry_plan("ed25519").kd, np.uint32)


# ---------------------------------------------------------------------------
# The Pallas ladder kernel
# ---------------------------------------------------------------------------


def _seq_carry_ref(ref):
    """Exact sequential carry over a (20, B) scratch ref (no wraparound)."""
    for i in range(NLIMB - 1):
        c = ref[i : i + 1, :] >> BITS
        ref[i : i + 1, :] = ref[i : i + 1, :] & MASK
        ref[i + 1 : i + 2, :] = ref[i + 1 : i + 2, :] + c


def _fold_top_ref(ref):
    """Bits >= 255 (limb 19, offset 8) wrap to limb 0 times 19."""
    q = ref[NLIMB - 1 : NLIMB, :] >> 8
    ref[NLIMB - 1 : NLIMB, :] = ref[NLIMB - 1 : NLIMB, :] & 0xFF
    ref[0:1, :] = ref[0:1, :] + q * 19


def _canonical_ref(v, s1, s2):
    """Fully reduce carried v (limbs <= M) into [0, p) using scratch refs.
    Mirrors the proven XLA fe_canonical (ed25519_verify.py:145)."""
    s1[:] = v
    for _ in range(3):
        _seq_carry_ref(s1)
        _fold_top_ref(s1)
    _seq_carry_ref(s1)  # now < 2^255
    # conditional subtract p: t = x + 19; x >= p iff t >= 2^255
    s2[:] = s1[:]
    s2[0:1, :] = s2[0:1, :] + 19
    _seq_carry_ref(s2)
    ge = (s2[NLIMB - 1 : NLIMB, :] >> 8) > 0
    s2[NLIMB - 1 : NLIMB, :] = s2[NLIMB - 1 : NLIMB, :] & 0xFF
    return jnp.where(ge, s2[:], s1[:])


def ladder_math(consts, negax, ay, digs_get, digh_get, nwin: int = NWIN,
                loop=lax.fori_loop, fe_backend: str = "vpu",
                carry_mode: str = "lazy"):
    """The windowed-Straus double-scalar multiply [s]B + [h](-A) — pure jnp,
    shared by the pallas kernel (on ref values) and the CPU parity tests
    (tests/test_pallas_interpret.py).  digs_get/digh_get: t -> (1, B)
    digit row accessors (a ref slice in-kernel, an array row in tests).
    nwin < NWIN drives the identical code with small scalars; tests also
    swap `loop` for a plain Python loop so the whole thing evaluates
    eagerly (XLA's CPU compile of these graphs runs minutes — its
    simplifier thrashes on the carry patterns).  fe_backend picks the limb
    multiplier (fe_common.FE_BACKENDS); carry_mode picks eager (one carry
    ripple per field op) or lazy (one per point op; the default — mxu16
    degrades to eager).  Returns (X, Y, Z, T) with limbs in the certified
    carried class of the active mode (congruent mod p across modes)."""
    mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    fe = _get_fe(fe_backend, mode)
    lazy = mode == "lazy"
    B = negax.shape[1]
    zero = jnp.zeros((NLIMB, B), jnp.uint32)
    one = jnp.pad(jnp.ones((1, B), jnp.uint32), ((0, NLIMB - 1), (0, 0)))
    d2 = consts[:, 48:49]
    ksub = consts[:, 49:50]
    kd = consts[:, 50:51] if lazy else None

    ident = (zero, one, one, zero)
    a1 = (negax, ay, one, fe.mul(negax, ay))

    # per-signature table [0..15](-A): evens by doubling, odds by +(-A)
    tbl = [ident, a1]
    for j in range(2, 16):
        tbl.append(pt_double(tbl[j // 2], ksub, fe, kd) if j % 2 == 0
                   else pt_add(tbl[j - 1], a1, d2, ksub, fe, kd))
    if lazy:
        # cached-niels conversion: one mulF + two carries per entry buys a
        # pt_add_cached per window (353 vs 457 row-slots of carry work)
        tbl = [pt_to_cached(t, d2, ksub, fe) for t in tbl]
    tbl_x = jnp.stack([t[0] for t in tbl])  # (16, 20, B)
    tbl_y = jnp.stack([t[1] for t in tbl])
    tbl_z = jnp.stack([t[2] for t in tbl])
    tbl_t = jnp.stack([t[3] for t in tbl])

    def select16(stacked, mask16):
        # stacked (16, 20, B), mask16 list of (1, B) uint32 one-hot masks
        acc = stacked[0] * mask16[0]
        for j in range(1, 16):
            acc = acc + stacked[j] * mask16[j]
        return acc

    def body(t, acc):
        for _ in range(4):
            acc = pt_double(acc, ksub, fe, kd)
        ds = digs_get(t)  # (1, B)
        dh = digh_get(t)
        mk_s = [(ds == j).astype(jnp.uint32) for j in range(16)]
        mk_h = [(dh == j).astype(jnp.uint32) for j in range(16)]
        # constant niels entry for the B part: (20, 1) x (1, B) masked sum
        ypx = sum(consts[:, j : j + 1] * mk_s[j] for j in range(16))
        ymx = sum(consts[:, 16 + j : 17 + j] * mk_s[j] for j in range(16))
        t2d = sum(consts[:, 32 + j : 33 + j] * mk_s[j] for j in range(16))
        acc = pt_madd(acc, ypx, ymx, t2d, ksub, fe, kd)
        q = (select16(tbl_x, mk_h), select16(tbl_y, mk_h),
             select16(tbl_z, mk_h), select16(tbl_t, mk_h))
        acc = (pt_add_cached(acc, q, ksub, kd, fe) if lazy
               else pt_add(acc, q, d2, ksub, fe))
        return acc

    return loop(0, nwin, body, ident)


def _ladder_kernel(consts_ref, negax_ref, ay_ref, digs_ref, digh_ref,
                   rlimb_ref, rsign_ref, out_ref, s1, s2,
                   fe_backend: str = "vpu", carry_mode: str = "lazy"):
    # window count comes from the digit rows: production always passes
    # (NWIN, B), while reduced parity tests drive the identical math with
    # fewer windows (small scalars)
    X, Y, Z, _T = ladder_math(
        consts_ref[:], negax_ref[:], ay_ref[:],
        lambda t: digs_ref[pl.ds(t, 1), :],
        lambda t: digh_ref[pl.ds(t, 1), :],
        nwin=digs_ref.shape[0],
        fe_backend=fe_backend,
        carry_mode=carry_mode,
    )

    # Under lazy, fe.inv/fe.mul run on mulF and keep the epilogue inside the
    # certified class C (max limb < M), so _canonical_ref's domain holds.
    fe = _get_fe(fe_backend, carry_mode)
    zinv = fe.inv(Z)
    x = _canonical_ref(fe.mul(X, zinv), s1, s2)
    y = _canonical_ref(fe.mul(Y, zinv), s1, s2)
    ok = jnp.all(y == rlimb_ref[:], axis=0, keepdims=True)
    ok = ok & ((x[0:1, :] & 1) == rsign_ref[:])
    out_ref[:] = ok.astype(jnp.uint32)


def _ladder_call(negax, ay, digs, digh, rlimb, rsign, *, interpret=False,
                 lanes=LANES, fe_backend="vpu", carry_mode="lazy"):
    """negax/ay/rlimb (20, N), digs/digh (nwin, N) — NWIN=64 in production,
    fewer in the reduced interpret tests — rsign (1, N); N % lanes == 0."""
    n = negax.shape[1]
    nwin = digs.shape[0]
    cspec = pl.BlockSpec(_CONSTS.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
    spec20 = pl.BlockSpec((NLIMB, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec64 = pl.BlockSpec((nwin, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec1 = pl.BlockSpec((1, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        partial(_ladder_kernel, fe_backend=fe_backend, carry_mode=carry_mode),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.uint32),
        grid=(n // lanes,),
        in_specs=[cspec, spec20, spec20, spec64, spec64, spec20, spec1],
        out_specs=spec1,
        scratch_shapes=[pltpu.VMEM((NLIMB, lanes), jnp.uint32)] * 2,
        interpret=interpret,
    )(jnp.asarray(_CONSTS), negax, ay, digs, digh, rlimb, rsign)


# ---------------------------------------------------------------------------
# Device prologue (second Pallas kernel): SHA-512 on uint32 pairs, Barrett
# mod L, scalar digit extraction. A plain XLA version of the same graph ran
# ~100x slower (thousands of thin unfused uint32 ops); in Pallas the whole
# hash stays in VMEM.
# ---------------------------------------------------------------------------

_H0_PAIRS = np.array(
    [[v >> 32, v & 0xFFFFFFFF] for v in (
        0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
        0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
        0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179)],
    dtype=np.uint32,
)
from tendermint_tpu.ops.sha512_batch import _K as _K64  # round constants

_K_PAIRS = np.stack([(_K64 >> np.uint64(32)).astype(np.uint32),
                     (_K64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)], axis=1)


def _add64(a, b):
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def _rotr64(a, n):
    hi, lo = a
    if n == 32:
        return (lo, hi)
    if n < 32:
        return ((hi >> n) | (lo << (32 - n)), (lo >> n) | (hi << (32 - n)))
    m = n - 32
    return ((lo >> m) | (hi << (32 - m)), (hi >> m) | (lo << (32 - m)))


def _shr64(a, n):
    hi, lo = a
    if n < 32:
        return (hi >> n, (lo >> n) | (hi << (32 - n)))
    return (jnp.zeros_like(hi), hi >> (n - 32))


def _xor64(*vs):
    hi = vs[0][0]
    lo = vs[0][1]
    for v in vs[1:]:
        hi = hi ^ v[0]
        lo = lo ^ v[1]
    return (hi, lo)


def _one_round(flat, wt, kt):
    a, b, c, d, e, f, g, h = [(flat[2 * i], flat[2 * i + 1]) for i in range(8)]
    S1 = _xor64(_rotr64(e, 14), _rotr64(e, 18), _rotr64(e, 41))
    ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
    t1 = _add64(_add64(h, S1), _add64(ch, _add64(kt, wt)))
    S0 = _xor64(_rotr64(a, 28), _rotr64(a, 34), _rotr64(a, 39))
    maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
           (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
    t2 = _add64(S0, maj)
    a, b, c, d, e, f, g, h = _add64(t1, t2), a, b, c, _add64(d, t1), e, f, g
    out = []
    for v in (a, b, c, d, e, f, g, h):
        out.extend(v)
    return tuple(out)


def _sha512_rounds(state, w_ref, k_ref):
    """One SHA-512 compression on (1, B)-row uint32 pairs. w_ref holds the
    80-entry message schedule (hi at row 2t, lo at 2t+1). Round 0 is peeled so
    the fori carry starts from data-dependent values — Mosaic refuses loop
    carries whose initial layout is a replicated constant."""

    def rbody(t, flat):
        wp = w_ref[pl.ds(2 * t, 2), :]
        kp = k_ref[pl.ds(t, 1), :]
        return _one_round(flat, (wp[0:1, :], wp[1:2, :]), (kp[:, 0:1], kp[:, 1:2]))

    flat = []
    for v in state:
        flat.extend(v)
    flat = tuple(flat)
    # peel 4 rounds: the state rotates one slot per round, so after 4 every
    # carry entry is a computed value (the a/e outputs of rounds 0..3)
    for t in range(4):
        flat = _one_round(
            flat,
            (w_ref[2 * t : 2 * t + 1, :], w_ref[2 * t + 1 : 2 * t + 2, :]),
            (k_ref[t : t + 1, 0:1], k_ref[t : t + 1, 1:2]),
        )
    flat = lax.fori_loop(4, 80, rbody, flat)
    vals = [(flat[2 * i], flat[2 * i + 1]) for i in range(8)]
    return [_add64(s, v) for s, v in zip(state, vals)]


def _sha512_in_kernel(msgw_ref, k_ref, w_ref, nblocks, B):
    """Full SHA-512 over (nblocks*32, B) big-endian word rows -> 8 (1,B) pairs."""
    B_ = msgw_ref.shape[1]
    state = [(jnp.full((1, B_), int(_H0_PAIRS[i, 0]), jnp.uint32),
              jnp.full((1, B_), int(_H0_PAIRS[i, 1]), jnp.uint32))
             for i in range(8)]
    for blk in range(nblocks):
        # message schedule, statically unrolled into the scratch ref
        w = []
        for t in range(16):
            hi = msgw_ref[blk * 32 + 2 * t : blk * 32 + 2 * t + 1, :]
            lo = msgw_ref[blk * 32 + 2 * t + 1 : blk * 32 + 2 * t + 2, :]
            w.append((hi, lo))
        for t in range(16, 80):
            s0 = _xor64(_rotr64(w[t - 15], 1), _rotr64(w[t - 15], 8), _shr64(w[t - 15], 7))
            s1 = _xor64(_rotr64(w[t - 2], 19), _rotr64(w[t - 2], 61), _shr64(w[t - 2], 6))
            w.append(_add64(_add64(w[t - 16], s0), _add64(w[t - 7], s1)))
        for t in range(80):
            w_ref[2 * t : 2 * t + 1, :] = w[t][0]
            w_ref[2 * t + 1 : 2 * t + 2, :] = w[t][1]
        state = _sha512_rounds(state, w_ref, k_ref)
    return state


def _digest_byte(state, m):
    """Byte m (0..63) of the digest (big-endian per 64-bit word)."""
    word, j = divmod(m, 8)
    hi, lo = state[word]
    src = hi if j < 4 else lo
    shift = 24 - 8 * (j % 4)
    return (src >> shift) & 0xFF


# Barrett constants in radix-2^13 (see sha512_batch.py for the host mirror)
_QL = 21
_MU_LIMBS_D = np.array(
    [( ((1 << (BITS * 40)) // L_ORDER) >> (BITS * i)) & MASK for i in range(_QL + 1)],
    dtype=np.uint32)
_L_LIMBS_D = np.array([(L_ORDER >> (BITS * i)) & MASK for i in range(NLIMB)],
                      dtype=np.uint32)
_LC_LIMBS_D = np.array(
    [(((1 << (BITS * _QL)) - L_ORDER) >> (BITS * i)) & MASK for i in range(_QL)],
    dtype=np.uint32)


def _seq_carry_cols(cols):
    """Exact sequential carry over a list of (N,) uint32 columns (radix 2^13).
    Max values stay well under 2^32 (callers bound the inputs)."""
    out = []
    carry = jnp.zeros_like(cols[0])
    for v in cols:
        v = v + carry
        out.append(v & MASK)
        carry = v >> BITS
    return out, carry


def _mul_limbs_const(cols, const_limbs):
    """Columns (list of (N,)) times a constant limb vector -> carried columns.
    Column sums <= len(cols) * 8191^2 < 2^32 for <= 64 columns."""
    al, bl = len(cols), len(const_limbs)
    prod = [jnp.zeros_like(cols[0]) for _ in range(al + bl)]
    for j in range(bl):
        cj = int(const_limbs[j])
        if cj == 0:
            continue
        for i in range(al):
            prod[i + j] = prod[i + j] + cols[i] * cj
    out, _ = _seq_carry_cols(prod)
    return out


def _mod_l_device(digest_state):
    """512-bit digest (8 uint32 hi/lo pairs, little-endian int interpretation
    of the big-endian digest bytes) -> 20 radix-2^13 columns of digest mod L."""
    # h limbs: 40 columns of 13 bits over the 64 little-endian digest bytes
    def h_limb(k):
        lo_bit = BITS * k
        byte0 = lo_bit // 8
        sh = lo_bit % 8
        v = _digest_byte(digest_state, byte0)
        if byte0 + 1 < 64:
            v = v | (_digest_byte(digest_state, byte0 + 1) << 8)
        if byte0 + 2 < 64:
            v = v | (_digest_byte(digest_state, byte0 + 2) << 16)
        return (v >> sh) & MASK

    h = [h_limb(k) for k in range(40)]
    q1 = h[NLIMB - 1 :]  # >> b^19, 21 limbs
    q2 = _mul_limbs_const(q1, _MU_LIMBS_D)
    q3 = q2[_QL:][: _QL + 1]
    q3l = _mul_limbs_const(q3, _L_LIMBS_D)[:_QL]
    # r = (h - q3*L) mod b^21 in [0, 3L)
    r = []
    borrow = jnp.zeros_like(h[0])
    for i in range(_QL):
        v = h[i] - q3l[i] - borrow
        borrow = v >> 31  # wrapped negative
        r.append(v & MASK)  # 2^32 = 0 (mod 2^13)
    for _ in range(2):  # conditional subtract L, twice
        t = [r[i] + int(_LC_LIMBS_D[i]) for i in range(_QL)]
        t, carry = _seq_carry_cols(t)
        ge = carry > 0
        r = [jnp.where(ge, t[i], r[i]) for i in range(_QL)]
    return r[:NLIMB]  # r < L < 2^253


def _limbs_to_words8(limbs20):
    """20 radix-2^13 columns -> 8 (N,) uint32 LE words (value < 2^256)."""
    words = []
    for j in range(8):
        lo_bit = 32 * j
        k0 = lo_bit // BITS
        sh = lo_bit - BITS * k0
        acc = limbs20[k0] >> sh
        pos = BITS - sh
        k = k0 + 1
        while pos < 32 and k < NLIMB:
            acc = acc | (limbs20[k] << pos)
            pos += BITS
            k += 1
        words.append(acc)
    return words


def _prologue_kernel(k_ref, msgw_ref, sigw_ref,
                     digs_ref, digh_ref, rlimb_ref, rsign_ref, w_scr):
    """SHA-512(R||A||M) -> mod L -> 4-bit digits; scalar digits + raw R limbs
    from the signature words. Layout: everything (rows, B)."""
    B = msgw_ref.shape[1]
    nblocks = msgw_ref.shape[0] // 32
    state = _sha512_in_kernel(msgw_ref, k_ref, w_scr, nblocks, B)
    h_limbs = _mod_l_device(state)  # 20 (1,B) columns
    h_words = _limbs_to_words8(h_limbs)

    s_words = [sigw_ref[8 + j : 9 + j, :] for j in range(8)]
    r_words = [sigw_ref[j : j + 1, :] for j in range(8)]

    for t in range(NWIN):  # MSB-first 4-bit windows
        k = NWIN - 1 - t
        digh_ref[t : t + 1, :] = (h_words[k // 8] >> (4 * (k % 8))) & 15
        digs_ref[t : t + 1, :] = (s_words[k // 8] >> (4 * (k % 8))) & 15

    for k in range(NLIMB):  # raw R limbs (low 255 bits), sign bit dropped
        lo_bit = BITS * k
        w0 = lo_bit // 32
        sh = lo_bit % 32
        v = r_words[w0] >> sh
        if sh + BITS > 32 and w0 + 1 < 8:
            v = v | (r_words[w0 + 1] << (32 - sh))
        rlimb_ref[k : k + 1, :] = v & (0xFF if k == NLIMB - 1 else MASK)
    rsign_ref[:] = r_words[7] >> 31


def _prologue_call(msg_words, sig_words, *, interpret=False, lanes=LANES):
    """msg_words (nblocks*32, N) BE uint32; sig_words (16, N) LE uint32."""
    rows, n = msg_words.shape
    mspec = pl.BlockSpec((rows, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((16, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((80, 2), lambda i: (0, 0), memory_space=pltpu.VMEM)
    spec64 = pl.BlockSpec((NWIN, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec20 = pl.BlockSpec((NLIMB, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec1 = pl.BlockSpec((1, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _prologue_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((NWIN, n), jnp.uint32),
            jax.ShapeDtypeStruct((NWIN, n), jnp.uint32),
            jax.ShapeDtypeStruct((NLIMB, n), jnp.uint32),
            jax.ShapeDtypeStruct((1, n), jnp.uint32),
        ],
        grid=(n // lanes,),
        in_specs=[kspec, mspec, sspec],
        out_specs=[spec64, spec64, spec20, spec1],
        scratch_shapes=[pltpu.VMEM((160, lanes), jnp.uint32)],
        interpret=interpret,
    )(jnp.asarray(_K_PAIRS), msg_words, sig_words)


def _device_verify(negax, ay, sig_words, msg_words, interpret=False,
                   lanes=LANES, fe_backend="vpu", carry_mode="lazy"):
    """negax/ay (N, 20) uint32; sig_words (N, 16) uint32 LE; msg_words
    (N, nblocks*32) uint32 BE padded SHA-512 input. Returns (N,) bool."""
    digs, digh, rlimb, rsign = _prologue_call(
        msg_words.T, sig_words.T, interpret=interpret, lanes=lanes
    )
    ok = _ladder_call(
        negax.T, ay.T, digs, digh, rlimb, rsign, interpret=interpret,
        lanes=lanes, fe_backend=fe_backend, carry_mode=carry_mode,
    )
    return ok[0].astype(bool)


# Compiled entry for the real-device path. In interpret mode the plain
# function is called eagerly instead: tracing the interpreted kernels into one
# jit graph explodes into thousands of scalar XLA ops (a 6-minute CPU compile).
_device_verify_jit = partial(
    jax.jit, static_argnames=("interpret", "lanes", "fe_backend", "carry_mode")
)(_device_verify)


@partial(jax.jit, static_argnames=("lanes", "fe_backend", "carry_mode"))
def _device_verify_packed(negax, ay, pub_words, sig_words, tmpl, vidx, vwords,
                          lanes=LANES, fe_backend="vpu", carry_mode="lazy"):
    """Transfer-minimizing verify: the padded SHA-512 input is ASSEMBLED ON
    DEVICE instead of shipped over the wire.

    The bench chip sits behind a network tunnel (~100ms dispatch round-trip,
    single-digit MB/s host->device), so bytes on the wire — not FLOPs —
    dominate wall clock. Steady-state per-signature transfer here is 64B of
    signature + ~16B of message words that actually differ across the batch
    (for commit verification: the fixed64 timestamp), against ~480B for the
    naive path. Pubkey limbs + compressed words are device-cached per
    validator set (_upload_valset).

    negax/ay (b, 20) u32 limbs; pub_words (b, 8) / sig_words (b, 16) LE u32;
    tmpl (rows,) BE u32 — padded SHA input of batch row 0; vidx (k,) i32 —
    word rows >= 16 whose value varies per signature; vwords (b, k) BE u32 —
    those rows' values. Rows 0..15 (R || A) always come from sig/pub words.
    """
    b = negax.shape[0]
    rows = tmpl.shape[0]

    def bswap(x):
        return ((x >> 24) | ((x >> 8) & 0xFF00)
                | ((x << 8) & 0xFF0000) | (x << 24))

    mw = jnp.broadcast_to(tmpl[:, None], (rows, b))
    mw = mw.at[0:8, :].set(bswap(sig_words[:, 0:8].T))
    mw = mw.at[8:16, :].set(bswap(pub_words.T))
    mw = mw.at[vidx, :].set(vwords.T)
    digs, digh, rlimb, rsign = _prologue_call(mw, sig_words.T, lanes=lanes)
    ok = _ladder_call(negax.T, ay.T, digs, digh, rlimb, rsign, lanes=lanes,
                      fe_backend=fe_backend, carry_mode=carry_mode)
    return ok[0].astype(bool)


# ---------------------------------------------------------------------------
# Host wrapper: decompression cache + packing
# ---------------------------------------------------------------------------

_valset_cache: dict = {}
_VALSET_CACHE_MAX = 64


def _decompress_valset(pubs: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(N, 32) pubkeys -> (neg_ax, ay, valid) with whole-set caching: commit
    verification hits the same validator-set array every height."""
    key = hashlib.sha256(pubs.tobytes()).digest()
    hit = _valset_cache.get(key)
    if hit is not None:
        return hit
    n = pubs.shape[0]
    neg_ax = np.zeros((n, NLIMB), dtype=np.uint32)
    ay = np.zeros((n, NLIMB), dtype=np.uint32)
    valid = np.ones((n,), dtype=bool)
    for i in range(n):
        dec = _xla._decompress_neg_cached(pubs[i].tobytes())
        if dec is None:
            valid[i] = False
        else:
            neg_ax[i] = dec[0]
            ay[i] = dec[1]
    if len(_valset_cache) >= _VALSET_CACHE_MAX:
        _valset_cache.clear()
    _valset_cache[key] = (neg_ax, ay, valid)
    return neg_ax, ay, valid


def _pad_rows(a: np.ndarray, b: int) -> np.ndarray:
    if a.shape[0] == b:
        return a
    return np.concatenate(
        [a, np.zeros((b - a.shape[0],) + a.shape[1:], dtype=a.dtype)], axis=0
    )


_dev_valset_cache: dict = {}
_DEV_VALSET_CACHE_MAX = 32


def _upload_valset(pubs, neg_ax, ay, b, device):
    """Device-resident (negax, ay, pub_words) padded to bucket b, cached per
    (valset, bucket, device). Commit verification reuses the same validator
    set every height, so after the first call the pubkey material never
    crosses the tunnel again."""
    key = (hashlib.sha256(pubs.tobytes()).digest(), b,
           device if device is not None else "default")
    hit = _dev_valset_cache.get(key)
    if hit is not None:
        return hit
    put = (lambda a: jax.device_put(a, device)) if device is not None else jnp.asarray
    pub_words = np.ascontiguousarray(pubs).view("<u4").astype(np.uint32)
    entry = (
        put(_pad_rows(neg_ax, b)),
        put(_pad_rows(ay, b)),
        put(_pad_rows(pub_words, b)),
    )
    if len(_dev_valset_cache) >= _DEV_VALSET_CACHE_MAX:
        _dev_valset_cache.clear()
    _dev_valset_cache[key] = entry
    return entry


def _bucket(n: int, lanes: int = LANES) -> int:
    b = lanes
    while b < n and b < 4096:
        b *= 2
    if n <= b:
        return b
    # past 4096, pad at 2048 granularity: the wall number is tunnel-transfer
    # bound, and 4096-steps cost up to +25% bytes (10k signatures padded to
    # 12288 instead of 10240) for no compile-cache benefit at these sizes
    return ((n + 2047) // 2048) * 2048


def verify_batch(pubs: np.ndarray, msgs: Sequence[bytes], sigs: np.ndarray,
                 interpret: bool = False, device=None,
                 fe_backend: str = "vpu",
                 carry_mode: str = "lazy") -> np.ndarray:
    """Go-exact batched verify on the Pallas path. Same contract as
    ops.ed25519_verify.verify_batch. `device` pins the dispatch to a specific
    jax device (used by tests that run on the real chip while the default
    backend is the virtual CPU mesh). `fe_backend` selects the limb
    multiplier (fe_common.FE_BACKENDS); every backend is bit-exact.
    `carry_mode` picks the eager or deferred (lazy) carry schedule — both
    bit-exact at the canonical boundary; mxu16 silently runs eager."""
    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    sigs = np.ascontiguousarray(sigs, dtype=np.uint8)
    n = pubs.shape[0]
    if n == 0:
        return np.zeros((0,), dtype=bool)

    neg_ax, ay, valid = _decompress_valset(pubs)
    valid = valid & ((sigs[:, 63] & 224) == 0)  # Go's only s range check

    lens = np.array([len(m) for m in msgs]) if msgs else np.zeros((0,), int)
    out = np.zeros((n,), dtype=bool)
    for ln in np.unique(lens):
        idx = np.nonzero(lens == ln)[0]
        out[idx] = _verify_uniform(
            pubs[idx], [msgs[i] for i in idx], sigs[idx],
            neg_ax[idx], ay[idx], valid[idx], int(ln), interpret, device,
            fe_backend, carry_mode,
        )
    return out


def _prologue_h(pubs, msgs, sigs, interpret=False, device=None) -> list:
    """h_i = SHA-512(R || A || M) mod L for every row, computed by the
    ON-DEVICE prologue kernel: one _prologue_call per uniform-msg-length
    group, then the (NWIN, b) MSB-first 4-bit digit matrix reassembles to
    host ints for the MSM schedule builder.  This keeps the hash stage of
    the RLC path on the same kernel the ladder uses."""
    n = pubs.shape[0]
    lanes = 8 if interpret else LANES
    lens = np.array([len(m) for m in msgs]) if msgs else np.zeros((0,), int)
    hs = [0] * n
    put = (lambda a: jax.device_put(a, device)) if device is not None else jnp.asarray
    for ln in np.unique(lens):
        idx = np.nonzero(lens == ln)[0]
        k = len(idx)
        b = _bucket(k, lanes)
        total = 64 + int(ln)
        nblocks = (total + 1 + 16 + 127) // 128
        padded = np.zeros((b, nblocks * 128), dtype=np.uint8)
        padded[:k, :32] = sigs[idx, :32]
        padded[:k, 32:64] = pubs[idx]
        if ln:
            m = np.frombuffer(
                b"".join(bytes(msgs[i]) for i in idx), np.uint8
            ).reshape(k, int(ln))
            padded[:k, 64:total] = m
        padded[:, total] = 0x80
        padded[:, -16:] = np.frombuffer((total * 8).to_bytes(16, "big"), np.uint8)
        msg_words = padded.reshape(b, -1, 4)[:, :, ::-1].reshape(b, -1)
        msg_words = np.ascontiguousarray(msg_words).view("<u4").astype(np.uint32)
        sig_words = np.ascontiguousarray(sigs[idx]).view("<u4").astype(np.uint32)
        _, digh, _, _ = _prologue_call(
            put(msg_words.T), put(_pad_rows(sig_words, b).T),
            interpret=interpret, lanes=lanes,
        )
        digh = np.asarray(digh)
        for j, i in enumerate(idx):
            h = 0
            for t in range(NWIN):
                h = (h << 4) | int(digh[t, j])
            hs[i] = h
    return hs


def rlc_verify_batch(pubs: np.ndarray, msgs: Sequence[bytes],
                     sigs: np.ndarray, interpret: bool = False, device=None,
                     fe_backend: str = "vpu", carry_mode: str = "lazy",
                     seed: Optional[int] = None) -> np.ndarray:
    """Batched Go-exact verify via ONE multi-scalar multiplication on the
    Pallas path: the SHA-512/mod-L stage runs in the existing prologue
    kernel (_prologue_h), the MSM itself in the shared device engine
    (ops/ed25519_msm), and a rejected window localizes through chunk RLCs
    down to exact rows on this module's ladder ``verify_batch``.  Same
    contract as ``verify_batch``; ``seed`` pins the RLC coefficients."""
    from tendermint_tpu.ops import ed25519_msm as _msm

    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    sigs = np.ascontiguousarray(sigs, dtype=np.uint8)
    n = pubs.shape[0]
    if n == 0:
        return np.zeros((0,), dtype=bool)
    items = [(pubs[i].tobytes(), bytes(msgs[i]), sigs[i].tobytes())
             for i in range(n)]
    parsed, out = _ed._parse_batch(items, compute_h=False)
    if parsed:
        hs = _prologue_h(pubs, msgs, sigs, interpret=interpret, device=device)
        parsed = [(i, na, nr, int(hs[i]), s) for (i, na, nr, _h, s) in parsed]
    if seed is None:
        seed = _xla.rlc_seed(pubs, sigs)

    def ladder_fn(idx):
        return verify_batch(
            pubs[idx], [msgs[i] for i in idx], sigs[idx],
            interpret=interpret, device=device,
            fe_backend=fe_backend, carry_mode=carry_mode,
        )

    _msm.rlc_resolve(parsed, out, ladder_fn, seed=seed,
                     fe_backend=fe_backend, carry_mode=carry_mode)
    return np.asarray(out, dtype=bool)


def pack_variable_words(pubs, msgs, sigs, ln: int, b: int):
    """Host-side packing for the transfer-minimizing dispatch: returns
    (tmpl, vrows, vwords) — the padded-SHA-input template of batch row 0,
    the word rows (>= 16) that vary across the batch, and each signature's
    values at those rows. Pure numpy (shared by _verify_uniform and the
    bench's device-resident re-dispatch timing)."""
    n = pubs.shape[0]
    total = 64 + ln
    nblocks = (total + 1 + 16 + 127) // 128
    rows = nblocks * 32
    m = (
        np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, ln)
        if ln else np.zeros((n, 0), np.uint8)
    )
    # template = row 0's padded SHA input, as BE words
    pad0 = np.zeros((nblocks * 128,), dtype=np.uint8)
    pad0[:32] = sigs[0, :32]
    pad0[32:64] = pubs[0]
    pad0[64:total] = m[0]
    pad0[total] = 0x80
    pad0[-16:] = np.frombuffer((total * 8).to_bytes(16, "big"), np.uint8)
    tmpl = (
        np.ascontiguousarray(pad0.reshape(-1, 4)[:, ::-1].reshape(-1))
        .view("<u4").astype(np.uint32)
    )
    # message byte columns that differ across the batch -> padded word rows
    diff_cols = np.nonzero((m != m[0]).any(axis=0))[0]
    vrows = np.unique((64 + diff_cols) // 4).astype(np.int32)
    if vrows.size == 0:
        vrows = np.array([16], np.int32)  # row 16 always exists (rows>=32)
    k = int(vrows.size)
    k_pad = 1 << (k - 1).bit_length()
    # per-signature BE words at the varying rows
    mpad = np.zeros((b, (rows - 16) * 4), dtype=np.uint8)
    mpad[:n, : total - 64] = m
    mpad[:, total - 64] = 0x80
    mpad[:, -16:] = np.frombuffer((total * 8).to_bytes(16, "big"), np.uint8)
    mwords = (
        np.ascontiguousarray(mpad.reshape(b, -1, 4)[:, :, ::-1].reshape(b, -1))
        .view("<u4").astype(np.uint32)
    )
    vwords = mwords[:, vrows - 16]
    if k_pad > k:  # duplicate scatter rows carry identical values
        vrows = np.concatenate([vrows, np.full((k_pad - k,), vrows[0], np.int32)])
        vwords = np.concatenate(
            [vwords, np.tile(vwords[:, :1], (1, k_pad - k))], axis=1
        )
    return tmpl, vrows, vwords


def _verify_uniform(pubs, msgs, sigs, neg_ax, ay, valid, ln, interpret,
                    device=None, fe_backend="vpu", carry_mode="lazy"):
    n = pubs.shape[0]
    # interpret mode (CPU tests) has no tile-alignment constraint: shrink the
    # lane count so the eager interpreter does 16x less padded work.
    lanes = 8 if interpret else LANES
    b = _bucket(n, lanes)
    total = 64 + ln  # R || A || M
    nblocks = (total + 1 + 16 + 127) // 128
    rows = nblocks * 32

    sig_words = np.ascontiguousarray(sigs).view("<u4").astype(np.uint32)
    # zero invalid rows' scalars to keep device work defined
    sig_words = sig_words.copy()
    sig_words[~valid] = 0

    put = (lambda a: jax.device_put(a, device)) if device is not None else jnp.asarray

    if not interpret:
        # packed path: ship only signatures + the message words that actually
        # vary across the batch; everything else is device-cached or template
        tmpl, vrows, vwords = pack_variable_words(pubs, msgs, sigs, ln, b)
        negax_d, ay_d, pubw_d = _upload_valset(pubs, neg_ax, ay, b, device)
        ok = np.asarray(
            _device_verify_packed(
                negax_d, ay_d, pubw_d,
                put(_pad_rows(sig_words, b)),
                put(tmpl), put(vrows), put(vwords),
                lanes=lanes, fe_backend=fe_backend, carry_mode=carry_mode,
            )
        )[:n]
        return ok & valid

    # reference path (interpret mode): full padded input assembled on host
    padded = np.zeros((b, nblocks * 128), dtype=np.uint8)
    padded[:n, :32] = sigs[:, :32]
    padded[:n, 32:64] = pubs
    if ln:
        m = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, ln)
        padded[:n, 64:total] = m
    padded[:, total] = 0x80
    padded[:, -16:] = np.frombuffer((total * 8).to_bytes(16, "big"), np.uint8)
    # big-endian 32-bit words
    msg_words = padded.reshape(b, -1, 4)[:, :, ::-1].reshape(b, -1)
    msg_words = np.ascontiguousarray(msg_words).view("<u4").astype(np.uint32)

    ok = np.asarray(
        _device_verify(
            put(_pad_rows(neg_ax, b)),
            put(_pad_rows(ay, b)),
            put(_pad_rows(sig_words, b)),
            put(msg_words),
            interpret=interpret,
            lanes=lanes,
            fe_backend=fe_backend,
            carry_mode=carry_mode,
        )
    )[:n]
    return ok & valid
