"""Fused Pallas TPU kernel for batched secp256k1 ECDSA verification.

The performance path behind TPUBatchVerifier.verify_secp256k1 on a real
chip (ops/secp256k1_verify.py stays the portable XLA fallback and the
mesh/shard_map path; the reference verifies serially via btcec at
crypto/secp256k1/secp256k1.go:140). Same skeleton as ops/ed25519_pallas:
batch on lanes, limbs on sublanes, the whole double-scalar computation in
one VMEM-resident kernel.

Differences from the bit-serial XLA kernel (768 complete adds/signature):

  * 4-bit windowed Straus: 64 MSB-first windows sharing 252 doublings; per
    window one add from a constant projective table [0..15]·G and one from
    a per-signature table [0..15]·Q built in-kernel (14 additions). Total
    ≈ 384 complete adds — half the work, none of it HBM-materialized.
  * the affine-x check multiplies instead of inverting: with Z ≠ 0,
    x(R) ≡ r (mod p)  ⇔  X ≡ r·Z — so accept is
    Z ≢ 0  ∧  (canon(X − r·Z) = 0 ∨ (r+n < p ∧ canon(X − (r+n)·Z) = 0)),
    removing the 256-squaring fe_inv entirely.

Field arithmetic is the row-layout port of the (carry-safe) XLA ops: radix
2^13, 20 uint32 limb rows, two-term fold 2^260 ≡ 2^36 + 15632 (mod p),
shared with the ed25519 kernel through ops/fe_common — which also provides
the MXU int8-plane multiplier selected by the `[verify] fe_backend` knob
(threaded through verify_batch below). Overflow bounds are recomputed
mechanically by fe_common.bound_* and asserted in tests/test_fe_common.py;
parity with the host oracle over randomized and adversarial batches is
enforced by tests/test_ops_secp256k1.

The host prologue is shared with the XLA kernel verbatim
(secp256k1_verify.prep_item): strict-DER, low-s, w = s⁻¹ mod n, cached
decompression — accept/reject cannot drift between backends.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.crypto import secp256k1 as _s
from tendermint_tpu.ops import secp256k1_verify as _xla

P = _xla.P
N = _xla.N
NLIMB = _xla.NLIMB
BITS = _xla.BITS
MASK = _xla.MASK
FOLD_SMALL = _xla.FOLD_SMALL  # 2^260 ≡ 2^36 + 15632: the +15632 term
FOLD_SHIFT = _xla.FOLD_SHIFT  # ... and 2^36 = 2^10 · 2^26 → << 10, 2 rows up
B3 = _xla.B3
LANES = 128
NWIN = 64  # 4-bit windows over 256-bit scalars

int_to_limbs = _xla.int_to_limbs
_K_SUB = _xla._K_SUB


# ---------------------------------------------------------------------------
# Row-layout field ops: (20, B) blocks, batch on lanes — shared with the
# ed25519 kernel via ops/fe_common (the VPU schoolbook and the MXU int8-plane
# multipliers live there; overflow bounds are recomputed mechanically by
# fe_common.bound_* and asserted in tests/test_fe_common.py)
# ---------------------------------------------------------------------------

from tendermint_tpu.ops import fe_common as _fc

_FE = {(b, "eager"): _fc.make_fe("secp256k1", b) for b in _fc.FE_BACKENDS}
_FE_VPU = _FE[("vpu", "eager")]


def _get_fe(backend: str, carry_mode: str = "eager"):
    mode = _fc.effective_carry_mode(backend, carry_mode)
    key = (backend, mode)
    if key not in _FE:
        _FE[key] = _fc.make_fe("secp256k1", backend, carry_mode=mode)
    return _FE[key]

# backward-compatible module-level surface (tests/test_ops_secp256k1.py and
# the XLA kernel's parity checks import these directly)
_shift_down = _fc.shift_rows_down
fe_carry = _fc.secp_fe_carry
fe_add = _fc.secp_fe_add
fe_sub = _fc.secp_fe_sub
fe_mul = _fc.secp_fe_mul
fe_mul_small = _fc.secp_fe_mul_small


# ---------------------------------------------------------------------------
# Complete point addition, projective (X:Y:Z), a=0 (RCB16 algorithm 7) —
# identical structure to the XLA pt_add, row-layout ops
# ---------------------------------------------------------------------------


def pt_add(p, q, ksub, fe=_FE_VPU, kd=None):
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    if fe.carry_mode == "lazy":
        return _pt_add_lazy(p, q, fe, kd)
    t0 = fe.mul(X1, X2)
    t1 = fe.mul(Y1, Y2)
    t2 = fe.mul(Z1, Z2)
    t3 = fe.mul(fe.add(X1, Y1), fe.add(X2, Y2))
    t3 = fe.sub(t3, fe.add(t0, t1), ksub)
    t4 = fe.mul(fe.add(Y1, Z1), fe.add(Y2, Z2))
    t4 = fe.sub(t4, fe.add(t1, t2), ksub)
    X3 = fe.mul(fe.add(X1, Z1), fe.add(X2, Z2))
    Y3 = fe.sub(X3, fe.add(t0, t2), ksub)
    t0x3 = fe.add(fe.add(t0, t0), t0)
    t2b = fe.mul_small(t2, B3)
    Z3 = fe.add(t1, t2b)
    t1 = fe.sub(t1, t2b, ksub)
    Y3b = fe.mul_small(Y3, B3)
    X3 = fe.sub(fe.mul(t3, t1), fe.mul(t4, Y3b), ksub)
    Y3 = fe.add(fe.mul(Y3b, t0x3), fe.mul(t1, Z3))
    Z3 = fe.add(fe.mul(Z3, t4), fe.mul(t0x3, t3))
    return X3, Y3, Z3


def _pt_add_lazy(p, q, fe, kd):
    """RCB16 with deferred carries: point coordinates stay in the certified
    class C; multiply outputs ride as class D between the single-round
    norm1 folds. 12 of 14 fe_muls drop to the one-wide-round mulL tail; the
    per-op chain is certified by fe_common.derive_carry_plan at import."""
    if kd is None:
        kd = jnp.asarray(fe.kd)[:, None]
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = fe.mul_lazy(X1, X2)                               # D
    t1 = fe.mul_lazy(Y1, Y2)                               # D
    t2 = fe.mul(Z1, Z2)                                    # C (feeds mul_small)
    t3 = fe.sub(fe.mul_lazy(fe.add(X1, Y1), fe.add_raw(X2, Y2)),
                fe.add_raw(t0, t1), kd)                    # C
    t4 = fe.sub(fe.mul_lazy(fe.add(Y1, Z1), fe.add_raw(Y2, Z2)),
                fe.add_raw(t1, t2), kd)                    # C
    X3 = fe.mul_lazy(fe.add(X1, Z1), fe.add_raw(X2, Z2))   # D
    Y3 = fe.sub(X3, fe.add_raw(t0, t2), kd)                # C
    t0x3 = fe.add(fe.add_raw(t0, t0), t0)                  # C
    t2b = fe.mul_small(t2, B3)                             # C
    Z3 = fe.add(t1, t2b)                                   # C
    t1 = fe.sub(t1, t2b, kd)                               # C
    Y3b = fe.mul_small(Y3, B3)                             # C
    X3 = fe.sub(fe.mul_lazy(t3, t1), fe.mul_lazy(t4, Y3b), kd)
    Y3 = fe.add(fe.mul_lazy(Y3b, t0x3), fe.mul_lazy(t1, Z3))
    Z3 = fe.add(fe.mul_lazy(Z3, t4), fe.mul_lazy(t0x3, t3))
    return X3, Y3, Z3


# ---------------------------------------------------------------------------
# Constant table: [0..15]·G projective, identity (0:1:0) at digit 0
# ---------------------------------------------------------------------------


def _build_g_table() -> np.ndarray:
    """(20, 50) uint32 consts input: cols 0..15 = Gx of j·G, 16..31 = Gy,
    32..47 = Gz (1, or 0 for the identity), 48 = the fe_sub K constant,
    49 = the lazy-mode KD constant (dominates class-D operands)."""
    out = np.zeros((NLIMB, 50), dtype=np.uint32)
    for j in range(16):
        if j == 0:
            x, y, z = 0, 1, 0
        else:
            x, y = _s._to_affine(_s._jmul(_s._G, j))
            z = 1
        out[:, j] = int_to_limbs(x)
        out[:, 16 + j] = int_to_limbs(y)
        out[:, 32 + j] = int_to_limbs(z)
    out[:, 48] = _K_SUB
    out[:, 49] = np.asarray(_fc.derive_carry_plan("secp256k1").kd, np.uint32)
    return out


# ---------------------------------------------------------------------------
# In-kernel canonical reduction (scratch-ref based, mirrors the XLA
# fe_canonical: p = 2^256 - 2^32 - 977; bits ≥ 256 sit in limb 19, offset 9)
# ---------------------------------------------------------------------------


def _seq_carry_ref(ref):
    for i in range(NLIMB - 1):
        c = ref[i : i + 1, :] >> BITS
        ref[i : i + 1, :] = ref[i : i + 1, :] & MASK
        ref[i + 1 : i + 2, :] = ref[i + 1 : i + 2, :] + c


def _fold_top_ref(ref):
    q = ref[NLIMB - 1 : NLIMB, :] >> 9
    ref[NLIMB - 1 : NLIMB, :] = ref[NLIMB - 1 : NLIMB, :] & 0x1FF
    # 2^256 ≡ 2^32 + 977:  2^32 = 2^6·2^26 → (q << 6) at limb 2, 977·q at 0
    ref[0:1, :] = ref[0:1, :] + q * 977
    ref[2:3, :] = ref[2:3, :] + (q << 6)


def _canonical_ref(v, s1, s2):
    """Fully reduce carried v (limbs ≤ M) into [0, p)."""
    s1[:] = fe_carry(v, rounds=2)
    for _ in range(3):
        _seq_carry_ref(s1)
        _fold_top_ref(s1)
    _seq_carry_ref(s1)  # now < 2^256
    # conditional subtract p: t = x + (2^256 - p); x ≥ p iff t ≥ 2^256
    s2[:] = s1[:]
    s2[0:1, :] = s2[0:1, :] + 977
    s2[2:3, :] = s2[2:3, :] + (1 << 6)
    _seq_carry_ref(s2)
    ge = (s2[NLIMB - 1 : NLIMB, :] >> 9) > 0
    s2[NLIMB - 1 : NLIMB, :] = s2[NLIMB - 1 : NLIMB, :] & 0x1FF
    return jnp.where(ge, s2[:], s1[:])


# ---------------------------------------------------------------------------
# The ladder kernel
# ---------------------------------------------------------------------------


def ladder_math(consts, qx, qy, dig1_get, dig2_get, nwin: int = NWIN,
                loop=lax.fori_loop, fe_backend: str = "vpu",
                carry_mode: str = "lazy"):
    """The windowed-Straus double-scalar multiply u1·G + u2·Q — pure jnp,
    shared by the pallas kernel (on ref values) and the CPU parity tests.
    dig1_get/dig2_get: t -> (1, B) digit row accessors (a ref slice
    in-kernel, an array row in tests). nwin < NWIN drives the identical
    code with small scalars, and tests swap `loop` for a plain Python loop
    to evaluate eagerly (XLA's CPU compile of this graph thrashes for
    ~10 min in the simplifier). fe_backend picks the limb multiplier
    (fe_common.FE_BACKENDS); carry_mode "lazy" defers carries between
    point ops per fe_common.derive_carry_plan. Returns projective
    (X, Y, Z) — coordinates land in the certified class C under lazy,
    congruent mod p to the eager result."""
    mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    fe = _get_fe(fe_backend, mode)
    B = qx.shape[1]
    zero = jnp.zeros((NLIMB, B), jnp.uint32)
    one = jnp.pad(jnp.ones((1, B), jnp.uint32), ((0, NLIMB - 1), (0, 0)))
    ksub = consts[:, 48:49]
    kd = consts[:, 49:50] if mode == "lazy" else None

    q1 = (qx, qy, one)
    ident = (zero, one, zero)  # (0:1:0)

    # per-signature table [0..15]·Q — complete addition chains through the
    # identity at j=0, so tbl[1] = ident + Q = Q needs no special case
    tbl = [ident]
    for j in range(1, 16):
        tbl.append(pt_add(tbl[j - 1], q1, ksub, fe, kd))
    tbl_x = jnp.stack([t[0] for t in tbl])  # (16, 20, B)
    tbl_y = jnp.stack([t[1] for t in tbl])
    tbl_z = jnp.stack([t[2] for t in tbl])

    def select16(stacked, mask16):
        acc = stacked[0] * mask16[0]
        for j in range(1, 16):
            acc = acc + stacked[j] * mask16[j]
        return acc

    def body(t, acc):
        for _ in range(4):
            # the complete law doubles too
            acc = pt_add(acc, acc, ksub, fe, kd)
        d1 = dig1_get(t)  # (1, B)
        d2 = dig2_get(t)
        mk1 = [(d1 == j).astype(jnp.uint32) for j in range(16)]
        mk2 = [(d2 == j).astype(jnp.uint32) for j in range(16)]
        gx = sum(consts[:, j : j + 1] * mk1[j] for j in range(16))
        gy = sum(consts[:, 16 + j : 17 + j] * mk1[j] for j in range(16))
        gz = sum(consts[:, 32 + j : 33 + j] * mk1[j] for j in range(16))
        acc = pt_add(acc, (gx, gy, gz), ksub, fe, kd)
        q_sel = (select16(tbl_x, mk2), select16(tbl_y, mk2),
                 select16(tbl_z, mk2))
        acc = pt_add(acc, q_sel, ksub, fe, kd)
        return acc

    return loop(0, nwin, body, ident)


def _ladder_kernel(consts_ref, qx_ref, qy_ref, dig1_ref, dig2_ref,
                   rl_ref, rnl_ref, rnok_ref, out_ref, s1, s2,
                   fe_backend: str = "vpu", carry_mode: str = "lazy"):
    consts = consts_ref[:]
    ksub = consts[:, 48:49]
    X, _Y, Z = ladder_math(
        consts, qx_ref[:], qy_ref[:],
        lambda t: dig1_ref[pl.ds(t, 1), :],
        lambda t: dig2_ref[pl.ds(t, 1), :],
        nwin=dig1_ref.shape[0],
        fe_backend=fe_backend,
        carry_mode=carry_mode,
    )

    mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    fe = _get_fe(fe_backend, mode)
    # Under lazy, X/Z sit in the certified class C and fe.sub's norm1
    # output re-enters the eager closed set after _canonical_ref's two
    # opening carry rounds (the re-entry certificate in derive_carry_plan).
    ks = consts[:, 49:50] if mode == "lazy" else ksub
    z_can = _canonical_ref(Z, s1, s2)
    nonzero = jnp.any(z_can != 0, axis=0, keepdims=True)
    # x(R) ≡ r  ⇔  X ≡ r·Z  (Z ≠ 0); same for the r+n representative
    d_r = _canonical_ref(fe.sub(X, fe.mul(rl_ref[:], Z), ks), s1, s2)
    eq_r = jnp.all(d_r == 0, axis=0, keepdims=True)
    d_rn = _canonical_ref(fe.sub(X, fe.mul(rnl_ref[:], Z), ks), s1, s2)
    eq_rn = jnp.all(d_rn == 0, axis=0, keepdims=True) & (rnok_ref[:] != 0)
    out_ref[:] = (nonzero & (eq_r | eq_rn)).astype(jnp.uint32)


def _ladder_call(qx, qy, dig1, dig2, rl, rnl, rnok, *, interpret=False,
                 lanes=LANES, fe_backend="vpu", carry_mode="lazy"):
    """qx/qy/rl/rnl (20, N); dig1/dig2 (nwin, N) — NWIN=64 in production,
    fewer in the reduced interpret tests; rnok (1, N); N % lanes == 0."""
    n = qx.shape[1]
    nwin = dig1.shape[0]
    cspec = pl.BlockSpec(_CONSTS.shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
    spec20 = pl.BlockSpec((NLIMB, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec64 = pl.BlockSpec((nwin, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    spec1 = pl.BlockSpec((1, lanes), lambda i: (0, i), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        partial(_ladder_kernel, fe_backend=fe_backend, carry_mode=carry_mode),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.uint32),
        grid=(n // lanes,),
        in_specs=[cspec, spec20, spec20, spec64, spec64, spec20, spec20, spec1],
        out_specs=spec1,
        scratch_shapes=[pltpu.VMEM((NLIMB, lanes), jnp.uint32)] * 2,
        interpret=interpret,
    )(jnp.asarray(_CONSTS), qx, qy, dig1, dig2, rl, rnl, rnok)


_CONSTS = _build_g_table()

_ladder_jit = partial(
    jax.jit,
    static_argnames=("interpret", "lanes", "fe_backend", "carry_mode"),
)(_ladder_call)


# ---------------------------------------------------------------------------
# Host wrapper
# ---------------------------------------------------------------------------


def _digits_msb(x: int) -> np.ndarray:
    """64 4-bit digits of a 256-bit scalar, most significant first."""
    return np.array(
        [(x >> (252 - 4 * t)) & 0xF for t in range(NWIN)], dtype=np.uint32
    )


# padding-bucket policy shared with the ed25519 pallas path — one place to
# change jit-cache granularity for both kernels
from tendermint_tpu.ops.ed25519_pallas import _bucket  # noqa: E402


def verify_batch(
    pubkeys: Sequence[bytes],
    digests: Sequence[bytes],
    sigs: Sequence[bytes],
    interpret: bool = False,
    device=None,
    fe_backend: str = "vpu",
    carry_mode: str = "lazy",
) -> np.ndarray:
    """Batched ECDSA verify on the Pallas path — same contract (and the
    same host prologue) as secp256k1_verify.verify_batch. `fe_backend`
    selects the limb multiplier (fe_common.FE_BACKENDS); `carry_mode`
    "lazy" (default) defers limb carries between point ops, "eager" keeps
    the per-op full carry ripple; verdicts are bit-exact either way."""
    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    n = len(pubkeys)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    lanes = 8 if interpret else LANES
    b = _bucket(n, lanes)

    qx = np.zeros((b, NLIMB), np.uint32)
    qy = np.zeros((b, NLIMB), np.uint32)
    d1 = np.zeros((b, NWIN), np.uint32)
    d2 = np.zeros((b, NWIN), np.uint32)
    rl = np.zeros((b, NLIMB), np.uint32)
    rnl = np.zeros((b, NLIMB), np.uint32)
    rnok = np.zeros((b,), np.uint32)
    forced = np.full((b,), -1, np.int8)

    for i in range(n):
        item = _xla.prep_item(bytes(pubkeys[i]), bytes(digests[i]), bytes(sigs[i]))
        if item[0] == "forced":
            forced[i] = item[1]
            continue
        _, Q, u1, u2, r = item
        qx[i], qy[i] = Q
        d1[i] = _digits_msb(u1)
        d2[i] = _digits_msb(u2)
        rl[i] = int_to_limbs(r)
        if r + N < P:
            rnl[i] = int_to_limbs(r + N)
            rnok[i] = 1

    put = (lambda a: jax.device_put(a, device)) if device is not None else jnp.asarray
    args = [put(np.ascontiguousarray(a.T)) for a in (qx, qy, d1, d2, rl, rnl)]
    args.append(put(rnok[None, :]))
    if interpret:
        ok = np.asarray(
            _ladder_call(*args, interpret=True, lanes=lanes,
                         fe_backend=fe_backend, carry_mode=carry_mode)
        )[0, :n]
    else:
        ok = np.asarray(
            _ladder_jit(*args, lanes=lanes, fe_backend=fe_backend,
                        carry_mode=carry_mode)
        )[0, :n]

    f = forced[:n]
    return np.where(f >= 0, f.astype(bool), ok.astype(bool))
