"""Shared radix-2^13 field arithmetic for the ed25519/secp256k1 kernels.

Both Pallas ladders (ops/ed25519_pallas.py, ops/secp256k1_pallas.py) used to
carry their own copy of the row-layout field ops; this module owns them now,
plus the MXU limb multiplier that serves both curves:

  backend "vpu"    broadcast schoolbook row-products — 400 uint32 multiplies
                   per fe_mul, all on the vector unit (the original path).
  backend "mxu"    each 13-bit limb splits into two int8 planes
                   (lo = a & 0x7F, hi = a >> 7; hi <= 101 for carried limbs)
                   and the 400 row-products become 4 int8 batched outer
                   products via lax.dot_general with int32 accumulation.
                   The recombined columns are *identical integers* to the
                   VPU columns, so the existing carry/fold tails produce
                   bit-identical limbs.
  backend "mxu16"  radix-2^16 repack: operands fold below 2^256, repack to
                   16 rows of 16 bits, multiply as 4 uint8-plane outer
                   products (256 row-products per plane pair, -36% vs the
                   20-limb mapping), fold/carry in radix-16, convert back.
                   Congruent mod p (same residue, possibly a different
                   in-range representative) — the property suite checks it
                   against the bignum oracle, and canonical encoding is
                   unchanged.

Layouts: row (NLIMB, B) — limbs on sublanes, batch on lanes (Pallas);
batch-leading (..., NLIMB) for the XLA kernels (mul_columns_batch).

Every bound claimed here is recomputed mechanically by the pure-Python
propagators at the bottom (bound_*), which mirror the jnp code step by step
on per-row maxima; tests/test_fe_common.py asserts closure of the carried
set and that no intermediate reaches 2^32.  Carried-limb closed-set bounds:
ed25519 limbs <= M_ED = 13000; secp256k1 is non-uniform (the two-term fold
2^260 = 2^36 + 15632 re-enters at rows 0 and 2) — see bound_closed_set().
"""

from __future__ import annotations

from functools import partial
from types import SimpleNamespace
from typing import List, Sequence, Tuple

import jax.numpy as jnp
from jax import lax

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1

ED_P = (1 << 255) - 19
SECP_P = (1 << 256) - (1 << 32) - 977

# 2^260 mod p, used by the radix-13 carry wraps
ED_FOLD = 19 << 5  # 608
SECP_FOLD_SMALL = 15632
SECP_FOLD_SHIFT = 10  # ... + 2^36 = (c << 10) two rows up

# 2^256 mod p, used by the mxu16 pre-fold and radix-16 wraps:
# list of (row, multiplier, shift) placements in the target radix.
ED_FOLD256_13 = ((0, 19 << 1, 0),)  # 2^256 = 38 (mod p), radix-13 row 0
SECP_FOLD256_13 = ((0, 977, 0), (2, 1, 6))  # 2^32 = 2^(13*2) * 2^6
ED_FOLD256_16 = ((0, 38, 0),)
SECP_FOLD256_16 = ((0, 977, 0), (2, 1, 0))  # 2^32 = 2^(16*2)

ED_M = 13000  # uniform carried-limb bound (closed set, asserted in tests)

FE_BACKENDS = ("vpu", "mxu", "mxu16")

_R16 = 16  # radix-2^16 rows covering a value < 2^256
MASK16 = (1 << 16) - 1


def shift_rows_down(x, k=1):
    """Rows move +k (top k rows become 0) — carries to higher limbs."""
    return jnp.pad(x[:-k, :], ((k, 0), (0, 0)))


def _pad_row(x, row, nrows):
    return jnp.pad(x, ((row, nrows - 1 - row), (0, 0)))


# ---------------------------------------------------------------------------
# Product columns — the only part of fe_mul that differs between backends.
# cols[k] = sum_{i+j=k} a_i * b_j, exact in uint32 (callers guarantee the
# column bound; see bound_mul_columns).
# ---------------------------------------------------------------------------


def _columns_vpu_rows(a, b, out_rows):
    terms = []
    for i in range(NLIMB):
        p = a[i : i + 1, :] * b  # (NLIMB, B)
        terms.append(jnp.pad(p, ((i, out_rows - NLIMB - i), (0, 0))))
    return sum(terms)


def _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis):
    """4 batched outer products on the plane pairs, int32 accumulation.
    Returns (ll, lh, hl, hh), each (B, n, n) with batch dims leading."""
    dn = (((), ()), ((batch_axis,), (batch_axis,)))
    dot = partial(lax.dot_general, dimension_numbers=dn,
                  preferred_element_type=jnp.int32)
    return (dot(a_lo, b_lo), dot(a_lo, b_hi),
            dot(a_hi, b_lo), dot(a_hi, b_hi))


def _bcast_lanes(a, b):
    """Broadcast a (rows, 1) constant operand against a (rows, B) one — the
    VPU elementwise path broadcasts implicitly, but dot_general batch dims
    must match exactly."""
    if a.shape[-1] != b.shape[-1]:
        B = max(a.shape[-1], b.shape[-1])
        a = jnp.broadcast_to(a, a.shape[:-1] + (B,))
        b = jnp.broadcast_to(b, b.shape[:-1] + (B,))
    return a, b


def _columns_mxu_rows(a, b, out_rows, split=7):
    """Same columns as _columns_vpu_rows via the MXU mapping.  With split=7
    the planes are int8 (lo = x & 0x7F, hi = x >> 7; hi <= 127 needs limbs
    <= 16383 — the ed25519 carried set qualifies).  secp256k1's carried
    limb 0 can reach ~24k (the 15632 fold re-entry), so it uses split=8
    with uint8 planes (hi <= 93) — the MXU takes s8 and u8 operands alike.
    Recombination is exact in int32 either way:
    a_i*b_j = ll + ((lh + hl) << split) + (hh << 2*split) < 2^31."""
    a, b = _bcast_lanes(a, b)
    dt = jnp.int8 if split == 7 else jnp.uint8
    m = (1 << split) - 1
    a_lo = (a & m).astype(dt)
    a_hi = (a >> split).astype(dt)
    b_lo = (b & m).astype(dt)
    b_hi = (b >> split).astype(dt)
    ll, lh, hl, hh = _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis=1)
    op = (ll + ((lh + hl) << split) + (hh << (2 * split))).astype(jnp.uint32)
    op = jnp.transpose(op, (1, 2, 0))  # (i, j, B): op[i] == a_i * b (rows j)
    cols = jnp.zeros((out_rows, a.shape[1]), jnp.uint32)
    for i in range(NLIMB):
        cols = cols + jnp.pad(op[i], ((i, out_rows - NLIMB - i), (0, 0)))
    return cols


def mul_columns_rows(a, b, out_rows, backend="vpu", split=7):
    """(NLIMB, B) x (NLIMB, B) -> (out_rows, B) schoolbook product columns."""
    if backend == "vpu":
        return _columns_vpu_rows(a, b, out_rows)
    if backend == "mxu":
        return _columns_mxu_rows(a, b, out_rows, split=split)
    raise ValueError(f"unknown fe backend {backend!r}")


def trace_with_backend(mod, kernel, fe_backend):
    """Wrap `kernel` so its trace runs with mod._FE_BACKEND = fe_backend.

    The XLA verify modules branch on a module global inside fe_mul while
    BUILDING the graph (threading a parameter through every pt_* helper
    would churn their whole call tree); callers key their jit cache on the
    backend so each compiled artifact deterministically embeds one choice."""
    if fe_backend == "vpu":
        return kernel

    def traced(*args):
        prev = mod._FE_BACKEND
        mod._FE_BACKEND = fe_backend
        try:
            return kernel(*args)
        finally:
            mod._FE_BACKEND = prev

    return traced


def mul_columns_batch(a, b, out_cols, backend="mxu", split=7):
    """Batch-leading variant for the XLA kernels: (..., NLIMB) operands ->
    (..., out_cols) columns.  Only the MXU mapping lives here — the XLA
    kernels keep their own VPU-style column code.  split follows the same
    per-curve rule as _columns_mxu_rows (7 -> int8 planes for ed25519,
    8 -> uint8 planes for secp256k1's taller carried limbs)."""
    if backend != "mxu":
        raise ValueError(f"mul_columns_batch serves backend 'mxu', not {backend!r}")
    if a.shape != b.shape:
        shp = jnp.broadcast_shapes(a.shape, b.shape)
        a = jnp.broadcast_to(a, shp)
        b = jnp.broadcast_to(b, shp)
    dt = jnp.int8 if split == 7 else jnp.uint8
    m = (1 << split) - 1
    a_lo = (a & m).astype(dt)
    a_hi = (a >> split).astype(dt)
    b_lo = (b & m).astype(dt)
    b_hi = (b >> split).astype(dt)
    nb = a.ndim - 1
    dn = (((), ()), (tuple(range(nb)), tuple(range(nb))))
    dot = partial(lax.dot_general, dimension_numbers=dn,
                  preferred_element_type=jnp.int32)
    ll = dot(a_lo, b_lo)
    lh = dot(a_lo, b_hi)
    hl = dot(a_hi, b_lo)
    hh = dot(a_hi, b_hi)
    op = (ll + ((lh + hl) << split)
          + (hh << (2 * split))).astype(jnp.uint32)  # (..., i, j)
    cols = jnp.zeros(a.shape[:-1] + (out_cols,), jnp.uint32)
    for i in range(NLIMB):
        cols = cols.at[..., i : i + NLIMB].add(op[..., i, :])
    return cols


# ---------------------------------------------------------------------------
# ed25519 — GF(2^255 - 19), carry wrap 2^260 = 608 (mod p)
# ---------------------------------------------------------------------------


def ed_fe_carry1(x):
    """One parallel carry round with wraparound (NLIMB rows)."""
    c = x >> BITS
    return (x & MASK) + shift_rows_down(c) + _pad_row(
        c[NLIMB - 1 :, :] * ED_FOLD, 0, NLIMB
    )


def ed_fe_add(a, b):
    return ed_fe_carry1(a + b)


def ed_fe_sub(a, b, ksub):
    """ksub: (NLIMB, 1) multiple-of-p constant keeping the difference
    positive (a kernel input — Pallas kernels cannot capture array consts)."""
    return ed_fe_carry1(a + ksub - b)


def ed_fe_mul(a, b, backend="vpu"):
    """(NLIMB, B) x (NLIMB, B) -> carried limbs (<= M_ED; bound_fe_mul
    recomputes the chain mechanically)."""
    if backend == "mxu16":
        return _mul16_rows(a, b, ED_FOLD256_13, ED_FOLD256_16, ed_fe_carry1, 3)
    prod = mul_columns_rows(a, b, 2 * NLIMB, backend, split=7)  # (40, B)
    c = prod >> BITS
    prod = (prod & MASK) + shift_rows_down(c)  # carry within 40 limbs
    lo = prod[:NLIMB, :] + prod[NLIMB:, :] * ED_FOLD
    return ed_fe_carry1(ed_fe_carry1(lo))


def ed_fe_sq(a, backend="vpu"):
    return ed_fe_mul(a, a, backend)


def ed_fe_inv(z, backend="vpu"):
    """z^(p-2) via the standard curve25519 addition chain: 254 sq + 11 mul."""
    sq = partial(ed_fe_sq, backend=backend)
    mul = partial(ed_fe_mul, backend=backend)

    def sqn(x, n):
        return lax.fori_loop(0, n, lambda _, v: sq(v), x)

    z2 = sq(z)
    z8 = sqn(z2, 2)
    z9 = mul(z, z8)
    z11 = mul(z2, z9)
    z22 = sq(z11)
    z_5_0 = mul(z9, z22)
    z_10_0 = mul(sqn(z_5_0, 5), z_5_0)
    z_20_0 = mul(sqn(z_10_0, 10), z_10_0)
    z_40_0 = mul(sqn(z_20_0, 20), z_20_0)
    z_50_0 = mul(sqn(z_40_0, 10), z_10_0)
    z_100_0 = mul(sqn(z_50_0, 50), z_50_0)
    z_200_0 = mul(sqn(z_100_0, 100), z_100_0)
    z_250_0 = mul(sqn(z_200_0, 50), z_50_0)
    return mul(sqn(z_250_0, 5), z11)  # z^(2^255 - 21) = z^(p-2)


# ---------------------------------------------------------------------------
# secp256k1 — GF(2^256 - 2^32 - 977), two-term wrap 2^260 = 2^36 + 15632
# ---------------------------------------------------------------------------


def _secp_wrap_top(c_top, nrows):
    """Carry out of limb 19 (>= 2^260) re-enters as *15632 at row 0 and
    << 10 at row 2 (pad placements, no scatter — Mosaic-friendly)."""
    return _pad_row(c_top * SECP_FOLD_SMALL, 0, nrows) + _pad_row(
        c_top << SECP_FOLD_SHIFT, 2, nrows
    )


def secp_fe_carry(x, rounds=3):
    for _ in range(rounds):
        c = x >> BITS
        x = (x & MASK) + shift_rows_down(c) + _secp_wrap_top(
            c[NLIMB - 1 :, :], NLIMB
        )
    return x


def secp_fe_add(a, b):
    # 3 rounds: the two-term fold can leave limbs ~3*MASK after two
    return secp_fe_carry(a + b, rounds=3)


def secp_fe_sub(a, b, ksub):
    """ksub (NLIMB, 1): multiple-of-p constant with every limb >= 2*MASK."""
    return secp_fe_carry(a + ksub - b, rounds=3)


def secp_fe_mul(a, b, backend="vpu"):
    """Row port of secp256k1_verify.fe_mul (41-row product, 24-row fold
    temp — that docstring holds the ripple-carry proof; bound_fe_mul
    recomputes it for every backend)."""
    if backend == "mxu16":
        return _mul16_rows(
            a, b, SECP_FOLD256_13, SECP_FOLD256_16,
            partial(secp_fe_carry, rounds=1), 5,
        )
    prod = mul_columns_rows(a, b, 2 * NLIMB + 1, backend, split=8)  # (41, B)
    for _ in range(3):
        c = prod >> BITS
        prod = (prod & MASK) + shift_rows_down(c)
    hi = prod[NLIMB:, :]  # (21, B)
    # 24-row temp assembled from pads (no scatter):
    #   rows 0..19 = lo, += hi*15632 at rows 0..20, += hi<<10 at rows 2..22
    tmp = (
        jnp.pad(prod[:NLIMB, :], ((0, 4), (0, 0)))
        + jnp.pad(hi * SECP_FOLD_SMALL, ((0, 3), (0, 0)))
        + jnp.pad(hi << SECP_FOLD_SHIFT, ((2, 1), (0, 0)))
    )
    for _ in range(2):
        c = tmp >> BITS
        tmp = (tmp & MASK) + shift_rows_down(c)
    lo = tmp[:NLIMB, :]
    for t_idx in range(4):
        t = tmp[NLIMB + t_idx : NLIMB + t_idx + 1, :]
        lo = lo + _pad_row(t * SECP_FOLD_SMALL, t_idx, NLIMB)
        lo = lo + _pad_row(t << SECP_FOLD_SHIFT, t_idx + 2, NLIMB)
    return secp_fe_carry(lo, rounds=5)


def secp_fe_sq(a, backend="vpu"):
    return secp_fe_mul(a, a, backend)


def secp_fe_mul_small(a, k: int):
    return secp_fe_carry(a * jnp.uint32(k), rounds=4)


def secp_fe_inv(z, backend="vpu"):
    """z^(p-2), plain MSB-first square-and-multiply (tests only — the secp
    ladder kernel eliminated inversion; see secp256k1_pallas)."""
    mul = partial(secp_fe_mul, backend=backend)
    e = SECP_P - 2
    acc = z
    for bit in bin(e)[3:]:  # skip the leading 1
        acc = mul(acc, acc)
        if bit == "1":
            acc = mul(acc, z)
    return acc


# ---------------------------------------------------------------------------
# mxu16 — radix-2^16 repack shared by both curves
# ---------------------------------------------------------------------------


def _fold_bits256_13(a, terms):
    """Fold bits >= 256 of a radix-13 element (limb 19 covers bits 247..):
    value becomes < 2^256 with limbs <= ~max(in) + t*mult (exact)."""
    t = a[NLIMB - 1 :, :] >> 9
    out = a - _pad_row(t << 9, NLIMB - 1, NLIMB)
    for row, mult, shift in terms:
        out = out + _pad_row((t * mult) << shift, row, NLIMB)
    return out


def _seq_carry16(w):
    """Exact sequential carry over the 16 rows, 15 steps on the VPU."""
    for k in range(_R16 - 1):
        c = w[k : k + 1, :] >> 16
        w = w - _pad_row(c << 16, k, _R16) + _pad_row(c, k + 1, _R16)
    return w


def _repack_13to16(a, fold256_16):
    """(NLIMB, B) radix-13 rows (value < 2^256 + eps after the prefold) ->
    (16, B) radix-16 rows, each < 2^16.  The prefold clears bits >= 256 of
    limb 19, but the lower limbs can still sum just past 2^256 (all-MASK
    input is 2^260 - 1), so the carry out of row 15 — at most a couple of
    units — wraps through the 2^256 fold terms and a second sequential
    pass settles it; the value then provably fits 256 bits."""
    w = jnp.zeros((_R16, a.shape[1]), jnp.uint32)
    for i in range(NLIMB):
        q, r = divmod(BITS * i, 16)
        w = w + _pad_row(a[i : i + 1, :] << r, q, _R16)
    w = _seq_carry16(w)
    c = w[_R16 - 1 :, :] >> 16
    w = w - _pad_row(c << 16, _R16 - 1, _R16)
    for row, mult, shift in fold256_16:
        w = w + _pad_row((c * mult) << shift, row, _R16)
    return _seq_carry16(w)


def _columns16_mxu(wa, wb):
    """(16, B)^2 radix-16 rows -> (33, B) uint32 product columns.  uint8
    planes lo = w & 0xFF, hi = w >> 8; the hh plane re-enters one row up
    (hh << 16 is exactly one radix-16 limb) so no column crosses 2^32:
    col <= 16 * (255^2 + 2*255^2*256) + 16*255^2 ~ 5.4e8."""
    wa, wb = _bcast_lanes(wa, wb)
    a_lo = (wa & 0xFF).astype(jnp.uint8)
    a_hi = (wa >> 8).astype(jnp.uint8)
    b_lo = (wb & 0xFF).astype(jnp.uint8)
    b_hi = (wb >> 8).astype(jnp.uint8)
    ll, lh, hl, hh = _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis=1)
    low = (ll + ((lh + hl) << 8)).astype(jnp.uint32)
    hh = hh.astype(jnp.uint32)
    low = jnp.transpose(low, (1, 2, 0))  # (i, j, B)
    hh = jnp.transpose(hh, (1, 2, 0))
    nrows = 2 * _R16 + 1  # 33: columns 0..30 plus the hh/carry spill row
    cols = jnp.zeros((nrows, wa.shape[1]), jnp.uint32)
    for i in range(_R16):
        cols = cols + jnp.pad(low[i], ((i, nrows - _R16 - i), (0, 0)))
        cols = cols + jnp.pad(hh[i], ((i + 1, nrows - _R16 - i - 1), (0, 0)))
    return cols


def _carry16(x, rounds, wrap_terms=()):
    """Parallel radix-16 carry rounds.  With wrap_terms (16 rows = a value
    mod 2^256) the carry out of row 15 re-enters as 2^256's placements;
    without them the top row keeps its excess bits (nothing is dropped —
    exactness over tidiness for the intermediate stacks)."""
    nrows = x.shape[0]
    for _ in range(rounds):
        c = x >> 16
        if wrap_terms:
            x = (x & MASK16) + shift_rows_down(c)
            for row, mult, shift in wrap_terms:
                x = x + _pad_row((c[nrows - 1 :, :] * mult) << shift, row, nrows)
        else:
            keep = _pad_row(c[nrows - 1 :, :] << 16, nrows - 1, nrows)
            x = (x & MASK16) + shift_rows_down(c) + keep
    return x


def _fold16(cols, terms):
    """Fold rows >= 16 of the (33, B) column stack back under 2^256 using
    2^256 = sum(mult << 16*row) placements; returns (16, B).  Two passes:
    the first can land past row 15 again (secp's +2^32 term), so it carries
    and folds once more — bounded because the second-pass rows are small."""
    spill = max(row for row, _, _ in terms) + 1  # rows >= 16 after pass one
    hi = cols[_R16:, :]  # (17, B): multiples of 2^256
    lo = jnp.pad(cols[:_R16, :], ((0, spill), (0, 0)))  # (16+spill, B)
    for row, mult, _ in terms:
        lo = lo + jnp.pad(hi * mult, ((row, spill - row - 1), (0, 0)))
    lo = _carry16(lo, rounds=2)  # keeps the second fold's products < 2^32
    out = lo[:_R16, :]
    for j in range(spill):
        h = lo[_R16 + j : _R16 + j + 1, :]
        for row, mult, _ in terms:
            out = out + _pad_row(h * mult, row + j, _R16)
    return out


def _mul16_rows(a, b, fold256_13, fold256_16, carry13_1, tail_rounds):
    """The radix-2^16 fe_mul: pre-fold below 2^256, repack, uint8-plane
    multiply, radix-16 fold/carry, convert back to radix-13, final carry."""
    wa = _repack_13to16(_fold_bits256_13(a, fold256_13), fold256_16)
    wb = _repack_13to16(_fold_bits256_13(b, fold256_13), fold256_16)
    cols = _carry16(_columns16_mxu(wa, wb), rounds=2)
    w = _carry16(_fold16(cols, fold256_16), rounds=2, wrap_terms=fold256_16)
    out = jnp.zeros((NLIMB, a.shape[1]), jnp.uint32)
    for k in range(_R16):
        q, r = divmod(16 * k, BITS)
        out = out + _pad_row(w[k : k + 1, :] << r, q, NLIMB)
    x = out
    for _ in range(tail_rounds):
        x = carry13_1(x)
    return x


# ---------------------------------------------------------------------------
# Backend namespaces — what the Pallas kernels thread through their point ops
# ---------------------------------------------------------------------------


def make_fe(curve: str, backend: str = "vpu") -> SimpleNamespace:
    """Uniform op namespace: mul/sq/add/sub/inv/carry (+ mul_small on secp).
    add/sub/carry are backend-independent (pure VPU); mul/sq/inv honor the
    backend."""
    if backend not in FE_BACKENDS:
        raise ValueError(f"fe backend must be one of {FE_BACKENDS}, got {backend!r}")
    if curve == "ed25519":
        return SimpleNamespace(
            curve=curve, backend=backend,
            mul=partial(ed_fe_mul, backend=backend),
            sq=partial(ed_fe_sq, backend=backend),
            inv=partial(ed_fe_inv, backend=backend),
            add=ed_fe_add, sub=ed_fe_sub, carry=ed_fe_carry1,
        )
    if curve == "secp256k1":
        return SimpleNamespace(
            curve=curve, backend=backend,
            mul=partial(secp_fe_mul, backend=backend),
            sq=partial(secp_fe_sq, backend=backend),
            inv=partial(secp_fe_inv, backend=backend),
            add=secp_fe_add, sub=secp_fe_sub, carry=secp_fe_carry,
            mul_small=secp_fe_mul_small,
        )
    raise ValueError(f"unknown curve {curve!r}")


def normalize_backend(value) -> str:
    """Config/env -> backend name ('' / None / 'auto' mean the VPU path)."""
    v = (value or "vpu").strip().lower()
    if v in ("", "auto"):
        v = "vpu"
    if v not in FE_BACKENDS:
        raise ValueError(f"[verify] fe_backend must be one of {FE_BACKENDS}, got {value!r}")
    return v


# ---------------------------------------------------------------------------
# Bound propagation — pure-Python mirrors of the pipelines above on per-row
# maxima.  tests/test_fe_common.py drives these to re-prove, mechanically,
# the overflow-freedom claims that used to live in the ed25519_pallas header
# comment (ISSUE 10 satellite: assert the bounds instead of stating them).
# Every helper returns (bounds, max_intermediate_seen).
# ---------------------------------------------------------------------------

U32 = 1 << 32


def _b_shift_down(bounds: List[int], k=1) -> List[int]:
    return [0] * k + bounds[:-k]


def _b_carry_round(bounds, wrap_terms) -> Tuple[List[int], int]:
    """Mirror of one (x & MASK) + shift(c) + wrap(c_top) round."""
    c = [b >> BITS for b in bounds]
    out = [min(b, MASK) for b in bounds]
    out = [o + s for o, s in zip(out, _b_shift_down(c))]
    for row, mult, shift in wrap_terms:
        out[row] += (c[NLIMB - 1] * mult) << shift
    return out, max(out)


def bound_mul_columns(ba: Sequence[int], bb: Sequence[int], out_rows: int) -> List[int]:
    """Column maxima — identical for vpu and mxu (same integers)."""
    cols = [0] * out_rows
    for i in range(NLIMB):
        for j in range(NLIMB):
            cols[i + j] += ba[i] * bb[j]
    return cols


def bound_fe_mul(curve: str, ba: Sequence[int], bb: Sequence[int],
                 backend: str = "vpu") -> Tuple[List[int], int]:
    """Per-row output maxima of fe_mul plus the largest intermediate the
    pipeline can produce (callers assert < 2^32)."""
    hi_in = max(max(ba), max(bb))
    peak = 0

    def see(vals):
        nonlocal peak
        peak = max(peak, max(vals))
        return vals

    if backend == "mxu":
        # the plane split must fit its dtype: int8 (split=7) needs limbs
        # <= 16383, uint8 (split=8) <= 65535
        limit = 16383 if curve == "ed25519" else 65535
        if hi_in > limit:
            raise AssertionError(
                f"{curve} mxu planes need limbs <= {limit}, got {hi_in}")
    if backend == "mxu16":
        return _bound_mul16(curve, ba, bb)
    if curve == "ed25519":
        cols = see(bound_mul_columns(ba, bb, 2 * NLIMB))
        c = [b >> BITS for b in cols]
        prod = see([min(b, MASK) + s for b, s in
                    zip(cols, [0] + c[:-1])])
        lo = see([prod[k] + prod[NLIMB + k] * ED_FOLD for k in range(NLIMB)])
        for _ in range(2):
            lo, m = _b_carry_round(lo, ((0, ED_FOLD, 0),))
            peak = max(peak, m)
        return lo, peak
    if curve == "secp256k1":
        cols = see(bound_mul_columns(ba, bb, 2 * NLIMB + 1))
        prod = cols
        for _ in range(3):
            c = [b >> BITS for b in prod]
            prod = see([min(b, MASK) + s for b, s in
                        zip(prod, [0] + c[:-1])])
        hi = prod[NLIMB:]  # 21 rows
        tmp = [0] * 24
        for k in range(NLIMB):
            tmp[k] += prod[k]
        for k, h in enumerate(hi):
            tmp[k] += h * SECP_FOLD_SMALL
            tmp[k + 2] += h << SECP_FOLD_SHIFT
        see(tmp)
        for _ in range(2):
            c = [b >> BITS for b in tmp]
            tmp = see([min(b, MASK) + s for b, s in zip(tmp, [0] + c[:-1])])
        lo = tmp[:NLIMB]
        for t_idx in range(4):
            t = tmp[NLIMB + t_idx]
            lo[t_idx] += t * SECP_FOLD_SMALL
            lo[t_idx + 2] += t << SECP_FOLD_SHIFT
        see(lo)
        for _ in range(5):
            lo, m = _b_carry_round(
                lo, ((0, SECP_FOLD_SMALL, 0), (2, 1, SECP_FOLD_SHIFT)))
            peak = max(peak, m)
        return lo, peak
    raise ValueError(curve)


def _b_carry16(bs, rounds, wrap_terms=()):
    """Mirror of _carry16 on per-row maxima (same top-row semantics)."""
    seen = []
    n = len(bs)
    for _ in range(rounds):
        c = [b >> 16 for b in bs]
        nxt = [min(b, MASK16) + s for b, s in zip(bs, [0] + c[:-1])]
        if wrap_terms:
            for row, mult, shift in wrap_terms:
                nxt[row] += (c[n - 1] * mult) << shift
        else:
            nxt[n - 1] += c[n - 1] << 16  # top row keeps its excess
        bs = nxt
        seen.append(max(bs))
    return bs, max(seen)


def _bound_mul16(curve, ba, bb) -> Tuple[List[int], int]:
    fold13 = ED_FOLD256_13 if curve == "ed25519" else SECP_FOLD256_13
    fold16 = ED_FOLD256_16 if curve == "ed25519" else SECP_FOLD256_16
    peak = 0

    def see(vals):
        nonlocal peak
        peak = max(peak, max(vals))
        return list(vals)

    def prefold(bs):
        t = bs[NLIMB - 1] >> 9
        out = list(bs)
        out[NLIMB - 1] = min(out[NLIMB - 1], 0x1FF)
        for row, mult, shift in fold13:
            out[row] += (t * mult) << shift
        return see(out)

    def seq_carry(w):
        for k in range(_R16 - 1):
            c = w[k] >> 16
            w[k] = min(w[k], MASK16)
            w[k + 1] += c
            see([w[k + 1]])
        return w

    def repack(bs):
        w = [0] * _R16
        for i in range(NLIMB):
            q, r = divmod(BITS * i, 16)
            w[q] += bs[i] << r
        see(w)
        w = seq_carry(w)
        c = w[_R16 - 1] >> 16
        w[_R16 - 1] = min(w[_R16 - 1], MASK16)
        for row, mult, shift in fold16:
            w[row] += (c * mult) << shift
        w = seq_carry(see(w))
        # rows end < 2^16: after the wrap the value fits 256 bits (an
        # invariant of the prefold + wrap, not derivable from row maxima)
        return [min(x, MASK16) for x in w]

    wa = repack(prefold(ba))
    wb = repack(prefold(bb))
    # uint8 plane products: ll + ((lh+hl)<<8) at i+j, hh one row up
    nrows = 2 * _R16 + 1
    cols = [0] * nrows
    for i in range(_R16):
        for j in range(_R16):
            la, ha = min(wa[i], 0xFF), wa[i] >> 8
            lb, hb = min(wb[j], 0xFF), wb[j] >> 8
            cols[i + j] += la * lb + ((la * hb + ha * lb) << 8)
            cols[i + j + 1] += ha * hb
    see(cols)
    cols, m = _b_carry16(cols, rounds=2)
    peak = max(peak, m)
    # _fold16 mirror: pass one onto 16+spill rows, carry, pass two
    spill = max(row for row, _, _ in fold16) + 1
    lo = cols[:_R16] + [0] * spill
    hi = cols[_R16:]
    for row, mult, _ in fold16:
        for j, h in enumerate(hi):
            lo[row + j] += h * mult
    see(lo)
    lo, m = _b_carry16(lo, rounds=2)
    peak = max(peak, m)
    out16 = lo[:_R16]
    for j in range(spill):
        h = lo[_R16 + j]
        for row, mult, _ in fold16:
            out16[row + j] += h * mult
    see(out16)
    out16, m = _b_carry16(out16, rounds=2, wrap_terms=fold16)
    peak = max(peak, m)
    limbs = [0] * NLIMB
    for k in range(_R16):
        q, r = divmod(16 * k, BITS)
        limbs[q] += out16[k] << r
    see(limbs)
    wrap = ((0, ED_FOLD, 0),) if curve == "ed25519" else (
        (0, SECP_FOLD_SMALL, 0), (2, 1, SECP_FOLD_SHIFT))
    rounds = 3 if curve == "ed25519" else 5
    for _ in range(rounds):
        limbs, m = _b_carry_round(limbs, wrap)
        peak = max(peak, m)
    return limbs, peak


def bound_fe_add(curve: str, ba, bb) -> Tuple[List[int], int]:
    x = [a + b for a, b in zip(ba, bb)]
    peak = max(x)
    wrap = ((0, ED_FOLD, 0),) if curve == "ed25519" else (
        (0, SECP_FOLD_SMALL, 0), (2, 1, SECP_FOLD_SHIFT))
    rounds = 1 if curve == "ed25519" else 3
    for _ in range(rounds):
        x, m = _b_carry_round(x, wrap)
        peak = max(peak, m)
    return x, peak


def bound_fe_sub(curve: str, ba, bb, ksub: Sequence[int]) -> Tuple[List[int], int]:
    # worst case ignores the subtraction (b >= 0): a + ksub
    return bound_fe_add(curve, ba, list(ksub))


def bound_closed_set(curve: str, backend: str = "vpu",
                     ksub: Sequence[int] = (), iters: int = 64
                     ) -> Tuple[List[int], int]:
    """Fixed point of the op mix: starting from fresh-input bounds (MASK),
    iterate max(mul, add, sub) until the per-row bounds stop growing.
    Returns (closed-set bounds, peak intermediate).  Non-convergence or a
    peak >= 2^32 means the op mix is unsound — the test fails."""
    bounds = [MASK] * NLIMB
    peak = 0
    for _ in range(iters):
        bm, p1 = bound_fe_mul(curve, bounds, bounds, backend)
        ba, p2 = bound_fe_add(curve, bounds, bounds)
        bs, p3 = (bound_fe_sub(curve, bounds, bounds, ksub)
                  if len(ksub) else (bounds, 0))
        nxt = [max(a, b, c) for a, b, c in zip(bm, ba, bs)]
        peak = max(peak, p1, p2, p3)
        if nxt == bounds:
            return bounds, peak
        bounds = nxt
    raise AssertionError(f"{curve}/{backend}: carried bounds did not converge")
