"""Shared radix-2^13 field arithmetic for the ed25519/secp256k1 kernels.

Both Pallas ladders (ops/ed25519_pallas.py, ops/secp256k1_pallas.py) used to
carry their own copy of the row-layout field ops; this module owns them now,
plus the MXU limb multiplier that serves both curves:

  backend "vpu"    broadcast schoolbook row-products — 400 uint32 multiplies
                   per fe_mul, all on the vector unit (the original path).
  backend "mxu"    each 13-bit limb splits into two int8 planes
                   (lo = a & 0x7F, hi = a >> 7; hi <= 101 for carried limbs)
                   and the 400 row-products become 4 int8 batched outer
                   products via lax.dot_general with int32 accumulation.
                   The recombined columns are *identical integers* to the
                   VPU columns, so the existing carry/fold tails produce
                   bit-identical limbs.
  backend "mxu16"  radix-2^16 repack: operands fold below 2^256, repack to
                   16 rows of 16 bits, multiply as 4 uint8-plane outer
                   products (256 row-products per plane pair, -36% vs the
                   20-limb mapping), fold/carry in radix-16, convert back.
                   Congruent mod p (same residue, possibly a different
                   in-range representative) — the property suite checks it
                   against the bignum oracle, and canonical encoding is
                   unchanged.

Layouts: row (NLIMB, B) — limbs on sublanes, batch on lanes (Pallas);
batch-leading (..., NLIMB) for the XLA kernels (mul_columns_batch).

Every bound claimed here is recomputed mechanically by the pure-Python
propagators at the bottom (bound_*), which mirror the jnp code step by step
on per-row maxima; tests/test_fe_common.py asserts closure of the carried
set and that no intermediate reaches 2^32.  Carried-limb closed-set bounds:
ed25519 limbs <= M_ED = 13000; secp256k1 is non-uniform (the two-term fold
2^260 = 2^36 + 15632 re-enters at rows 0 and 2) — see bound_closed_set().
"""

from __future__ import annotations

from functools import lru_cache, partial
from types import SimpleNamespace
from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1

ED_P = (1 << 255) - 19
SECP_P = (1 << 256) - (1 << 32) - 977

# 2^260 mod p, used by the radix-13 carry wraps
ED_FOLD = 19 << 5  # 608
SECP_FOLD_SMALL = 15632
SECP_FOLD_SHIFT = 10  # ... + 2^36 = (c << 10) two rows up

# 2^256 mod p, used by the mxu16 pre-fold and radix-16 wraps:
# list of (row, multiplier, shift) placements in the target radix.
ED_FOLD256_13 = ((0, 19 << 1, 0),)  # 2^256 = 38 (mod p), radix-13 row 0
SECP_FOLD256_13 = ((0, 977, 0), (2, 1, 6))  # 2^32 = 2^(13*2) * 2^6
ED_FOLD256_16 = ((0, 38, 0),)
SECP_FOLD256_16 = ((0, 977, 0), (2, 1, 0))  # 2^32 = 2^(16*2)

ED_M = 13000  # uniform carried-limb bound (closed set, asserted in tests)

# Carry wraps as (row, multiplier, shift) placements — the single source the
# jnp ops and their bound mirrors share.
ED_WRAP = ((0, ED_FOLD, 0),)
SECP_WRAP = ((0, SECP_FOLD_SMALL, 0), (2, 1, SECP_FOLD_SHIFT))

# Eager carry-round counts.  These are DERIVED, not pinned: derive_eager_rounds
# (bottom of this module) reruns the bound propagators at import time and
# asserts each constant is the minimal round count under which the op's output
# on closed-set inputs stays inside the closed set — the docstring
# ripple-carry proofs, executed.
ED_MUL_TAIL_ROUNDS = 2
ED_ADD_ROUNDS = 1
SECP_MUL_TAIL_ROUNDS = 3  # was 5: the propagators prove 2 rounds were wasted
SECP_ADD_ROUNDS = 3
SECP_MUL_SMALL_ROUNDS = 3  # was 4, same derivation

FE_BACKENDS = ("vpu", "mxu", "mxu16")
CARRY_MODES = ("eager", "lazy")

_R16 = 16  # radix-2^16 rows covering a value < 2^256
MASK16 = (1 << 16) - 1


def shift_rows_down(x, k=1):
    """Rows move +k (top k rows become 0) — carries to higher limbs."""
    return jnp.pad(x[:-k, :], ((k, 0), (0, 0)))


def _pad_row(x, row, nrows):
    return jnp.pad(x, ((row, nrows - 1 - row), (0, 0)))


# ---------------------------------------------------------------------------
# Product columns — the only part of fe_mul that differs between backends.
# cols[k] = sum_{i+j=k} a_i * b_j, exact in uint32 (callers guarantee the
# column bound; see bound_mul_columns).
# ---------------------------------------------------------------------------


def _columns_vpu_rows(a, b, out_rows):
    terms = []
    for i in range(NLIMB):
        p = a[i : i + 1, :] * b  # (NLIMB, B)
        terms.append(jnp.pad(p, ((i, out_rows - NLIMB - i), (0, 0))))
    return sum(terms)


def _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis):
    """4 batched outer products on the plane pairs, int32 accumulation.
    Returns (ll, lh, hl, hh), each (B, n, n) with batch dims leading."""
    dn = (((), ()), ((batch_axis,), (batch_axis,)))
    dot = partial(lax.dot_general, dimension_numbers=dn,
                  preferred_element_type=jnp.int32)
    return (dot(a_lo, b_lo), dot(a_lo, b_hi),
            dot(a_hi, b_lo), dot(a_hi, b_hi))


def _bcast_lanes(a, b):
    """Broadcast a (rows, 1) constant operand against a (rows, B) one — the
    VPU elementwise path broadcasts implicitly, but dot_general batch dims
    must match exactly."""
    if a.shape[-1] != b.shape[-1]:
        B = max(a.shape[-1], b.shape[-1])
        a = jnp.broadcast_to(a, a.shape[:-1] + (B,))
        b = jnp.broadcast_to(b, b.shape[:-1] + (B,))
    return a, b


def _columns_mxu_rows(a, b, out_rows, split=7):
    """Same columns as _columns_vpu_rows via the MXU mapping.  With split=7
    the planes are int8 (lo = x & 0x7F, hi = x >> 7; hi <= 127 needs limbs
    <= 16383 — the ed25519 carried set qualifies).  secp256k1's carried
    limb 0 can reach ~24k (the 15632 fold re-entry), so it uses split=8
    with uint8 planes (hi <= 93) — the MXU takes s8 and u8 operands alike.
    Recombination is exact in int32 either way:
    a_i*b_j = ll + ((lh + hl) << split) + (hh << 2*split) < 2^31."""
    a, b = _bcast_lanes(a, b)
    dt = jnp.int8 if split == 7 else jnp.uint8
    m = (1 << split) - 1
    a_lo = (a & m).astype(dt)
    a_hi = (a >> split).astype(dt)
    b_lo = (b & m).astype(dt)
    b_hi = (b >> split).astype(dt)
    ll, lh, hl, hh = _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis=1)
    op = (ll + ((lh + hl) << split) + (hh << (2 * split))).astype(jnp.uint32)
    op = jnp.transpose(op, (1, 2, 0))  # (i, j, B): op[i] == a_i * b (rows j)
    cols = jnp.zeros((out_rows, a.shape[1]), jnp.uint32)
    for i in range(NLIMB):
        cols = cols + jnp.pad(op[i], ((i, out_rows - NLIMB - i), (0, 0)))
    return cols


def mul_columns_rows(a, b, out_rows, backend="vpu", split=7):
    """(NLIMB, B) x (NLIMB, B) -> (out_rows, B) schoolbook product columns."""
    if backend == "vpu":
        return _columns_vpu_rows(a, b, out_rows)
    if backend == "mxu":
        return _columns_mxu_rows(a, b, out_rows, split=split)
    raise ValueError(f"unknown fe backend {backend!r}")


def trace_with_backend(mod, kernel, fe_backend):
    """Wrap `kernel` so its trace runs with mod._FE_BACKEND = fe_backend.

    The XLA verify modules branch on a module global inside fe_mul while
    BUILDING the graph (threading a parameter through every pt_* helper
    would churn their whole call tree); callers key their jit cache on the
    backend so each compiled artifact deterministically embeds one choice."""
    if fe_backend == "vpu":
        return kernel

    def traced(*args):
        prev = mod._FE_BACKEND
        mod._FE_BACKEND = fe_backend
        try:
            return kernel(*args)
        finally:
            mod._FE_BACKEND = prev

    return traced


def trace_with_modes(mod, kernel, fe_backend, carry_mode):
    """Like trace_with_backend, but also swaps mod._CARRY_MODE — the XLA
    verify modules branch on both globals while building the graph.  Always
    wraps (even for vpu/lazy defaults) so the restore is unconditional."""

    def traced(*args):
        prev_be = mod._FE_BACKEND
        prev_cm = mod._CARRY_MODE
        mod._FE_BACKEND = fe_backend
        mod._CARRY_MODE = carry_mode
        try:
            return kernel(*args)
        finally:
            mod._FE_BACKEND = prev_be
            mod._CARRY_MODE = prev_cm

    return traced


def mul_columns_batch(a, b, out_cols, backend="mxu", split=7):
    """Batch-leading variant for the XLA kernels: (..., NLIMB) operands ->
    (..., out_cols) columns.  Only the MXU mapping lives here — the XLA
    kernels keep their own VPU-style column code.  split follows the same
    per-curve rule as _columns_mxu_rows (7 -> int8 planes for ed25519,
    8 -> uint8 planes for secp256k1's taller carried limbs)."""
    if backend != "mxu":
        raise ValueError(f"mul_columns_batch serves backend 'mxu', not {backend!r}")
    if a.shape != b.shape:
        shp = jnp.broadcast_shapes(a.shape, b.shape)
        a = jnp.broadcast_to(a, shp)
        b = jnp.broadcast_to(b, shp)
    dt = jnp.int8 if split == 7 else jnp.uint8
    m = (1 << split) - 1
    a_lo = (a & m).astype(dt)
    a_hi = (a >> split).astype(dt)
    b_lo = (b & m).astype(dt)
    b_hi = (b >> split).astype(dt)
    nb = a.ndim - 1
    dn = (((), ()), (tuple(range(nb)), tuple(range(nb))))
    dot = partial(lax.dot_general, dimension_numbers=dn,
                  preferred_element_type=jnp.int32)
    ll = dot(a_lo, b_lo)
    lh = dot(a_lo, b_hi)
    hl = dot(a_hi, b_lo)
    hh = dot(a_hi, b_hi)
    op = (ll + ((lh + hl) << split)
          + (hh << (2 * split))).astype(jnp.uint32)  # (..., i, j)
    cols = jnp.zeros(a.shape[:-1] + (out_cols,), jnp.uint32)
    for i in range(NLIMB):
        cols = cols.at[..., i : i + NLIMB].add(op[..., i, :])
    return cols


# ---------------------------------------------------------------------------
# ed25519 — GF(2^255 - 19), carry wrap 2^260 = 608 (mod p)
# ---------------------------------------------------------------------------


def ed_fe_carry1(x):
    """One parallel carry round with wraparound (NLIMB rows)."""
    c = x >> BITS
    return (x & MASK) + shift_rows_down(c) + _pad_row(
        c[NLIMB - 1 :, :] * ED_FOLD, 0, NLIMB
    )


def ed_fe_add(a, b):
    x = a + b
    for _ in range(ED_ADD_ROUNDS):
        x = ed_fe_carry1(x)
    return x


def ed_fe_sub(a, b, ksub):
    """ksub: (NLIMB, 1) multiple-of-p constant keeping the difference
    positive (a kernel input — Pallas kernels cannot capture array consts)."""
    x = a + ksub - b
    for _ in range(ED_ADD_ROUNDS):
        x = ed_fe_carry1(x)
    return x


def ed_fe_mul(a, b, backend="vpu"):
    """(NLIMB, B) x (NLIMB, B) -> carried limbs (<= M_ED; bound_fe_mul
    recomputes the chain mechanically)."""
    if backend == "mxu16":
        return _mul16_rows(a, b, ED_FOLD256_13, ED_FOLD256_16, ed_fe_carry1, 3)
    prod = mul_columns_rows(a, b, 2 * NLIMB, backend, split=7)  # (40, B)
    c = prod >> BITS
    prod = (prod & MASK) + shift_rows_down(c)  # carry within 40 limbs
    lo = prod[:NLIMB, :] + prod[NLIMB:, :] * ED_FOLD
    for _ in range(ED_MUL_TAIL_ROUNDS):
        lo = ed_fe_carry1(lo)
    return lo


def ed_fe_sq(a, backend="vpu"):
    return ed_fe_mul(a, a, backend)


def ed_fe_inv(z, backend="vpu", mul=None, sq=None):
    """z^(p-2) via the standard curve25519 addition chain: 254 sq + 11 mul.
    mul/sq overrides let the lazy namespaces run the chain on their fully
    reduced mulF (output class C stays closed under the chain)."""
    sq = sq if sq is not None else partial(ed_fe_sq, backend=backend)
    mul = mul if mul is not None else partial(ed_fe_mul, backend=backend)

    def sqn(x, n):
        return lax.fori_loop(0, n, lambda _, v: sq(v), x)

    z2 = sq(z)
    z8 = sqn(z2, 2)
    z9 = mul(z, z8)
    z11 = mul(z2, z9)
    z22 = sq(z11)
    z_5_0 = mul(z9, z22)
    z_10_0 = mul(sqn(z_5_0, 5), z_5_0)
    z_20_0 = mul(sqn(z_10_0, 10), z_10_0)
    z_40_0 = mul(sqn(z_20_0, 20), z_20_0)
    z_50_0 = mul(sqn(z_40_0, 10), z_10_0)
    z_100_0 = mul(sqn(z_50_0, 50), z_50_0)
    z_200_0 = mul(sqn(z_100_0, 100), z_100_0)
    z_250_0 = mul(sqn(z_200_0, 50), z_50_0)
    return mul(sqn(z_250_0, 5), z11)  # z^(2^255 - 21) = z^(p-2)


# ---------------------------------------------------------------------------
# secp256k1 — GF(2^256 - 2^32 - 977), two-term wrap 2^260 = 2^36 + 15632
# ---------------------------------------------------------------------------


def _secp_wrap_top(c_top, nrows):
    """Carry out of limb 19 (>= 2^260) re-enters as *15632 at row 0 and
    << 10 at row 2 (pad placements, no scatter — Mosaic-friendly)."""
    return _pad_row(c_top * SECP_FOLD_SMALL, 0, nrows) + _pad_row(
        c_top << SECP_FOLD_SHIFT, 2, nrows
    )


def secp_fe_carry(x, rounds=3):
    for _ in range(rounds):
        c = x >> BITS
        x = (x & MASK) + shift_rows_down(c) + _secp_wrap_top(
            c[NLIMB - 1 :, :], NLIMB
        )
    return x


def secp_fe_add(a, b):
    # SECP_ADD_ROUNDS = 3: the two-term fold can leave limbs ~3*MASK after two
    return secp_fe_carry(a + b, rounds=SECP_ADD_ROUNDS)


def secp_fe_sub(a, b, ksub):
    """ksub (NLIMB, 1): multiple-of-p constant with every limb >= 2*MASK."""
    return secp_fe_carry(a + ksub - b, rounds=SECP_ADD_ROUNDS)


def secp_fe_mul(a, b, backend="vpu"):
    """Row port of secp256k1_verify.fe_mul (41-row product, 24-row fold
    temp — that docstring holds the ripple-carry proof; bound_fe_mul
    recomputes it for every backend)."""
    if backend == "mxu16":
        return _mul16_rows(
            a, b, SECP_FOLD256_13, SECP_FOLD256_16,
            partial(secp_fe_carry, rounds=1), 5,
        )
    prod = mul_columns_rows(a, b, 2 * NLIMB + 1, backend, split=8)  # (41, B)
    for _ in range(3):
        c = prod >> BITS
        prod = (prod & MASK) + shift_rows_down(c)
    hi = prod[NLIMB:, :]  # (21, B)
    # 24-row temp assembled from pads (no scatter):
    #   rows 0..19 = lo, += hi*15632 at rows 0..20, += hi<<10 at rows 2..22
    tmp = (
        jnp.pad(prod[:NLIMB, :], ((0, 4), (0, 0)))
        + jnp.pad(hi * SECP_FOLD_SMALL, ((0, 3), (0, 0)))
        + jnp.pad(hi << SECP_FOLD_SHIFT, ((2, 1), (0, 0)))
    )
    for _ in range(2):
        c = tmp >> BITS
        tmp = (tmp & MASK) + shift_rows_down(c)
    lo = tmp[:NLIMB, :]
    for t_idx in range(4):
        t = tmp[NLIMB + t_idx : NLIMB + t_idx + 1, :]
        lo = lo + _pad_row(t * SECP_FOLD_SMALL, t_idx, NLIMB)
        lo = lo + _pad_row(t << SECP_FOLD_SHIFT, t_idx + 2, NLIMB)
    return secp_fe_carry(lo, rounds=SECP_MUL_TAIL_ROUNDS)


def secp_fe_sq(a, backend="vpu"):
    return secp_fe_mul(a, a, backend)


def secp_fe_mul_small(a, k: int):
    return secp_fe_carry(a * jnp.uint32(k), rounds=SECP_MUL_SMALL_ROUNDS)


def secp_fe_inv(z, backend="vpu", mul=None):
    """z^(p-2), plain MSB-first square-and-multiply (tests only — the secp
    ladder kernel eliminated inversion; see secp256k1_pallas)."""
    mul = mul if mul is not None else partial(secp_fe_mul, backend=backend)
    e = SECP_P - 2
    acc = z
    for bit in bin(e)[3:]:  # skip the leading 1
        acc = mul(acc, acc)
        if bit == "1":
            acc = mul(acc, z)
    return acc


# ---------------------------------------------------------------------------
# mxu16 — radix-2^16 repack shared by both curves
# ---------------------------------------------------------------------------


def _fold_bits256_13(a, terms):
    """Fold bits >= 256 of a radix-13 element (limb 19 covers bits 247..):
    value becomes < 2^256 with limbs <= ~max(in) + t*mult (exact)."""
    t = a[NLIMB - 1 :, :] >> 9
    out = a - _pad_row(t << 9, NLIMB - 1, NLIMB)
    for row, mult, shift in terms:
        out = out + _pad_row((t * mult) << shift, row, NLIMB)
    return out


def _seq_carry16(w):
    """Exact sequential carry over the 16 rows, 15 steps on the VPU."""
    for k in range(_R16 - 1):
        c = w[k : k + 1, :] >> 16
        w = w - _pad_row(c << 16, k, _R16) + _pad_row(c, k + 1, _R16)
    return w


def _repack_13to16(a, fold256_16):
    """(NLIMB, B) radix-13 rows (value < 2^256 + eps after the prefold) ->
    (16, B) radix-16 rows, each < 2^16.  The prefold clears bits >= 256 of
    limb 19, but the lower limbs can still sum just past 2^256 (all-MASK
    input is 2^260 - 1), so the carry out of row 15 — at most a couple of
    units — wraps through the 2^256 fold terms and a second sequential
    pass settles it; the value then provably fits 256 bits."""
    w = jnp.zeros((_R16, a.shape[1]), jnp.uint32)
    for i in range(NLIMB):
        q, r = divmod(BITS * i, 16)
        w = w + _pad_row(a[i : i + 1, :] << r, q, _R16)
    w = _seq_carry16(w)
    c = w[_R16 - 1 :, :] >> 16
    w = w - _pad_row(c << 16, _R16 - 1, _R16)
    for row, mult, shift in fold256_16:
        w = w + _pad_row((c * mult) << shift, row, _R16)
    return _seq_carry16(w)


def _columns16_mxu(wa, wb):
    """(16, B)^2 radix-16 rows -> (33, B) uint32 product columns.  uint8
    planes lo = w & 0xFF, hi = w >> 8; the hh plane re-enters one row up
    (hh << 16 is exactly one radix-16 limb) so no column crosses 2^32:
    col <= 16 * (255^2 + 2*255^2*256) + 16*255^2 ~ 5.4e8."""
    wa, wb = _bcast_lanes(wa, wb)
    a_lo = (wa & 0xFF).astype(jnp.uint8)
    a_hi = (wa >> 8).astype(jnp.uint8)
    b_lo = (wb & 0xFF).astype(jnp.uint8)
    b_hi = (wb >> 8).astype(jnp.uint8)
    ll, lh, hl, hh = _plane_outer(a_lo, a_hi, b_lo, b_hi, batch_axis=1)
    low = (ll + ((lh + hl) << 8)).astype(jnp.uint32)
    hh = hh.astype(jnp.uint32)
    low = jnp.transpose(low, (1, 2, 0))  # (i, j, B)
    hh = jnp.transpose(hh, (1, 2, 0))
    nrows = 2 * _R16 + 1  # 33: columns 0..30 plus the hh/carry spill row
    cols = jnp.zeros((nrows, wa.shape[1]), jnp.uint32)
    for i in range(_R16):
        cols = cols + jnp.pad(low[i], ((i, nrows - _R16 - i), (0, 0)))
        cols = cols + jnp.pad(hh[i], ((i + 1, nrows - _R16 - i - 1), (0, 0)))
    return cols


def _carry16(x, rounds, wrap_terms=()):
    """Parallel radix-16 carry rounds.  With wrap_terms (16 rows = a value
    mod 2^256) the carry out of row 15 re-enters as 2^256's placements;
    without them the top row keeps its excess bits (nothing is dropped —
    exactness over tidiness for the intermediate stacks)."""
    nrows = x.shape[0]
    for _ in range(rounds):
        c = x >> 16
        if wrap_terms:
            x = (x & MASK16) + shift_rows_down(c)
            for row, mult, shift in wrap_terms:
                x = x + _pad_row((c[nrows - 1 :, :] * mult) << shift, row, nrows)
        else:
            keep = _pad_row(c[nrows - 1 :, :] << 16, nrows - 1, nrows)
            x = (x & MASK16) + shift_rows_down(c) + keep
    return x


def _fold16(cols, terms):
    """Fold rows >= 16 of the (33, B) column stack back under 2^256 using
    2^256 = sum(mult << 16*row) placements; returns (16, B).  Two passes:
    the first can land past row 15 again (secp's +2^32 term), so it carries
    and folds once more — bounded because the second-pass rows are small."""
    spill = max(row for row, _, _ in terms) + 1  # rows >= 16 after pass one
    hi = cols[_R16:, :]  # (17, B): multiples of 2^256
    lo = jnp.pad(cols[:_R16, :], ((0, spill), (0, 0)))  # (16+spill, B)
    for row, mult, _ in terms:
        lo = lo + jnp.pad(hi * mult, ((row, spill - row - 1), (0, 0)))
    lo = _carry16(lo, rounds=2)  # keeps the second fold's products < 2^32
    out = lo[:_R16, :]
    for j in range(spill):
        h = lo[_R16 + j : _R16 + j + 1, :]
        for row, mult, _ in terms:
            out = out + _pad_row(h * mult, row + j, _R16)
    return out


def _mul16_rows(a, b, fold256_13, fold256_16, carry13_1, tail_rounds):
    """The radix-2^16 fe_mul: pre-fold below 2^256, repack, uint8-plane
    multiply, radix-16 fold/carry, convert back to radix-13, final carry."""
    wa = _repack_13to16(_fold_bits256_13(a, fold256_13), fold256_16)
    wb = _repack_13to16(_fold_bits256_13(b, fold256_13), fold256_16)
    cols = _carry16(_columns16_mxu(wa, wb), rounds=2)
    w = _carry16(_fold16(cols, fold256_16), rounds=2, wrap_terms=fold256_16)
    out = jnp.zeros((NLIMB, a.shape[1]), jnp.uint32)
    for k in range(_R16):
        q, r = divmod(16 * k, BITS)
        out = out + _pad_row(w[k : k + 1, :] << r, q, NLIMB)
    x = out
    for _ in range(tail_rounds):
        x = carry13_1(x)
    return x


# ---------------------------------------------------------------------------
# Lazy (deferred-carry) ops — ISSUE 11.  The eager pipeline normalizes after
# every field op (a full parallel carry ripple per add/sub and 2-5 rounds per
# mul tail); on the closed set that carry work is ~40% of the op mix and all
# VPU.  The lazy representation defers it:
#
#   * mulL ("lazy mul")   fused fold + ONE wide round + a row-0 fixup.  The
#     output class D has limbs up to ~3e5 — fine for uint32 adds, never fed
#     back into a multiply.
#   * mulF ("final mul")  fused fold + `plan.mulf_wide` wide rounds + fixups.
#     Output class C (limbs <= ~8.8k ed / ~8.2k secp) — the class every
#     point-op output lands in, certified <= the eager closed set so the
#     eager epilogues (inv, canonical encode) accept it unchanged.
#   * add1/sub1 (norm1)   raw limb add (+ a wide-zero constant for sub), ONE
#     wide round + fixups — replaces the 1-3 round eager add/sub.
#   * add_raw             no carry at all; the bound chain proves which
#     consumers tolerate the doubled limbs.
#
# The "fused fold" folds product columns 20..39/40 directly during the fold
# (each high column split into 13-bit pieces so no pre-carry rounds are
# needed); the "wide round" is a parallel carry round whose wrap term
# re-enters in decomposed (lo, hi) halves, so an arbitrarily large top carry
# cannot rebuild a huge row 0 (the single-term eager wrap diverges on
# unreduced inputs).  Every bound is certified by derive_carry_plan(), which
# iterates the full kernel chain set to a fixed point with the mirrors below
# — there are no hand-stated numbers in this section.
# ---------------------------------------------------------------------------


def _pad_block(x, row, nrows):
    """Place a multi-row block at `row` within an nrows stack (row layout)."""
    return jnp.pad(x, ((row, nrows - row - x.shape[0]), (0, 0)))


def wide_carry_rows(x, wrap):
    """One parallel carry round with the wrap applied in decomposed (lo, hi)
    halves: top carry c splits as (c & MASK) at `row` and (c >> 13) at
    `row + 1`, exact because 2^13·(mult<<sh)·2^(13·row) = (mult<<sh)·2^(13·(row+1))."""
    c = x >> BITS
    out = (x & MASK) + shift_rows_down(c)
    top = c[NLIMB - 1 :, :]
    for row, mult, sh in wrap:
        out = out + _pad_row(((top & MASK) * mult) << sh, row, NLIMB)
        out = out + _pad_row(((top >> BITS) * mult) << sh, row + 1, NLIMB)
    return out


def fix_rows(x, rows):
    """Sequential single-row carries r -> r+1 (each touches two rows only —
    far cheaper than a full round; the plan says which rows need it)."""
    for r in rows:
        c = x[r : r + 1, :] >> BITS
        x = x - _pad_row(c << BITS, r, NLIMB) + _pad_row(c, r + 1, NLIMB)
    return x


def carry_drop_top_rows(x):
    """One parallel carry round over an (nrows, B) stack; the carry out of
    the last row is dropped — sound only where its bound is 0, which the
    plan mirror asserts (_b_carry_drop_top)."""
    c = x >> BITS
    return (x & MASK) + shift_rows_down(c)


def ed_fold_fused_rows(cols):
    """(40, B) raw product columns -> (20, B): rows 20..39 fold as
    2^(260+13k) = 608·2^13k with each high column split into (lo, hi) 13-bit
    pieces, so no pre-carry rounds are needed.  The hi piece of row 39 would
    land on row 40 — dropped; the plan mirror asserts its bound is 0."""
    hi = cols[NLIMB:, :]
    lo = cols[:NLIMB, :] + (hi & MASK) * ED_FOLD
    return lo + shift_rows_down((hi >> BITS) * ED_FOLD)


def ed_fe_mul_lazy(a, b, wide, fix=(0,), backend="vpu"):
    """Deferred-carry ed25519 multiply: fused fold + `wide` wide rounds +
    row fixups.  wide/fix come from derive_carry_plan — mulf_wide for the
    fully reduced class C, mull_wide (1) for the lazy class D.  Lazy-mode
    operands can exceed the int8 plane bound, so mxu uses uint8 (split=8) —
    columns are identical integers either way."""
    cols = mul_columns_rows(a, b, 2 * NLIMB, backend, split=8)
    lo = ed_fold_fused_rows(cols)
    for _ in range(wide):
        lo = wide_carry_rows(lo, ED_WRAP)
    return fix_rows(lo, fix)


def ed_fe_norm1(raw, fix=(0,)):
    """One wide round + fixups over a raw limb sum — the lazy add1/sub1."""
    return fix_rows(wide_carry_rows(raw, ED_WRAP), fix)


def shift_rows_up(x, k):
    """Rows move -k (bottom k rows become 0) — inverse of shift_rows_down."""
    if k == 0:
        return x
    return jnp.pad(x[k:, :], ((0, k), (0, 0)))


def wide_carry_rows_stacked(x, wrap):
    """wide_carry_rows over a (nblk·NLIMB, B) stack of independent operands
    (PERF.md carry-tail vectorization): carries ripple within each NLIMB-row
    block only — the ripple entering each block's row 0 is masked off, and
    each block's own top carry wraps back into its low rows via in-block
    up-shifts.  Bit-identical to nblk separate wide_carry_rows calls; built
    from pads/wheres/iota only, so it lowers inside Pallas kernels."""
    rows = x.shape[0]
    blockrow = lax.broadcasted_iota(jnp.uint32, (rows, 1), 0) % NLIMB
    c = x >> BITS
    out = (x & MASK) + jnp.where(blockrow == 0, 0, shift_rows_down(c))
    top = jnp.where(blockrow == NLIMB - 1, c, 0)
    for row, mult, sh in wrap:
        # top carry sits at local row NLIMB-1; its (lo, hi) wrap pieces land
        # at local rows `row` / `row + 1` of the SAME block
        out = out + shift_rows_up(((top & MASK) * mult) << sh,
                                  NLIMB - 1 - row)
        out = out + shift_rows_up(((top >> BITS) * mult) << sh,
                                  NLIMB - 2 - row)
    return out


def fix_rows_stacked(x, fix):
    """fix_rows over a (nblk·NLIMB, B) stack: each fixup row r carries to
    r + 1 within every block (plans only fix rows < NLIMB - 1, so the shift
    cannot cross a block boundary)."""
    rows = x.shape[0]
    blockrow = lax.broadcasted_iota(jnp.uint32, (rows, 1), 0) % NLIMB
    for r in fix:
        assert r < NLIMB - 1, "stacked fixup would cross a block boundary"
        c = jnp.where(blockrow == r, x >> BITS, 0)
        x = x - (c << BITS) + shift_rows_down(c)
    return x


def ed_fe_mul4_lazy(pairs, wide, fix=(0,), backend="vpu"):
    """Four deferred-carry multiplies sharing ONE stacked carry tail: the
    product columns and fold stay per-product (MXU/VPU bound), but the
    `wide` rounds and row fixups — the ~40% carry tail — run once over the
    (4·NLIMB, B) concatenation.  The four output products of a point op
    share the exact same schedule, which is what makes the stacking sound;
    bit-identical to four ed_fe_mul_lazy calls."""
    lo = jnp.concatenate(
        [ed_fold_fused_rows(mul_columns_rows(a, b, 2 * NLIMB, backend,
                                             split=8))
         for a, b in pairs],
        axis=0,
    )
    for _ in range(wide):
        lo = wide_carry_rows_stacked(lo, ED_WRAP)
    lo = fix_rows_stacked(lo, fix)
    return tuple(lo[k * NLIMB:(k + 1) * NLIMB, :] for k in range(len(pairs)))


def secp_fold_fused_rows(cols):
    """(41, B) raw product columns -> (24, B) temp: rows 20..40 fold as
    2^(260+13k) = (2^36 + 15632)·2^13k with each high column decomposed
    a + b·2^13 + c·2^26 (no pre-carry).  The c-piece of row 40 would land
    on temp row 24 — dropped; the plan mirror asserts its bound is 0."""
    hi = cols[NLIMB:, :]  # (21, B)
    a = hi & MASK
    b2 = (hi >> BITS) & MASK
    c3 = hi >> (2 * BITS)
    tmp = jnp.pad(cols[:NLIMB, :], ((0, 4), (0, 0)))
    tmp = tmp + jnp.pad(a * SECP_FOLD_SMALL, ((0, 3), (0, 0)))
    tmp = tmp + jnp.pad(b2 * SECP_FOLD_SMALL, ((1, 2), (0, 0)))
    tmp = tmp + jnp.pad(c3 * SECP_FOLD_SMALL + (a << SECP_FOLD_SHIFT),
                        ((2, 1), (0, 0)))
    tmp = tmp + jnp.pad(b2 << SECP_FOLD_SHIFT, ((3, 0), (0, 0)))
    tmp = tmp + jnp.pad((c3 << SECP_FOLD_SHIFT)[:NLIMB, :], ((4, 0), (0, 0)))
    return tmp


def secp_fold2_rows(tmp):
    """(24, B) temp -> (20, B): the 4 spill rows fold scalar-wise, each
    decomposed (lo, hi) so the result needs no extra pre-carry."""
    lo = tmp[:NLIMB, :]
    for t in range(4):
        h = tmp[NLIMB + t : NLIMB + t + 1, :]
        a = h & MASK
        b2 = h >> BITS
        lo = lo + _pad_row(a * SECP_FOLD_SMALL, t, NLIMB)
        lo = lo + _pad_row(b2 * SECP_FOLD_SMALL, t + 1, NLIMB)
        lo = lo + _pad_row(a << SECP_FOLD_SHIFT, t + 2, NLIMB)
        lo = lo + _pad_row(b2 << SECP_FOLD_SHIFT, t + 3, NLIMB)
    return lo


def secp_fe_mul_lazy(a, b, wide, fix=(0, 1, 2, 3), backend="vpu", mid=1):
    """Deferred-carry secp256k1 multiply: two-level fused fold with `mid`
    dropped-top rounds over the 24-row temp between the levels."""
    cols = mul_columns_rows(a, b, 2 * NLIMB + 1, backend, split=8)
    tmp = secp_fold_fused_rows(cols)
    for _ in range(mid):
        tmp = carry_drop_top_rows(tmp)
    lo = secp_fold2_rows(tmp)
    for _ in range(wide):
        lo = wide_carry_rows(lo, SECP_WRAP)
    return fix_rows(lo, fix)


def secp_fe_norm1(raw, wide=1, fix=(0, 1, 2, 3)):
    lo = raw
    for _ in range(wide):
        lo = wide_carry_rows(lo, SECP_WRAP)
    return fix_rows(lo, fix)


# --- batch-leading twins for the XLA kernels (..., NLIMB) ------------------


def wide_carry_batch(x, wrap):
    c = x >> BITS
    out = (x & MASK).at[..., 1:].add(c[..., :-1])
    top = c[..., -1]
    for row, mult, sh in wrap:
        out = out.at[..., row].add(((top & MASK) * mult) << sh)
        out = out.at[..., row + 1].add(((top >> BITS) * mult) << sh)
    return out


def fix_batch(x, rows):
    for r in rows:
        c = x[..., r] >> BITS
        x = x.at[..., r].set(x[..., r] & MASK).at[..., r + 1].add(c)
    return x


def carry_drop_top_batch(x):
    c = x >> BITS
    return (x & MASK).at[..., 1:].add(c[..., :-1])


def ed_fold_fused_batch(cols):
    """(..., 40) columns -> (..., 20); see ed_fold_fused_rows."""
    hi = cols[..., NLIMB:]
    lo = cols[..., :NLIMB] + (hi & MASK) * ED_FOLD
    return lo.at[..., 1:].add(((hi >> BITS) * ED_FOLD)[..., :-1])


def secp_fold_fused_batch(cols):
    """(..., 41) columns -> (..., 24); see secp_fold_fused_rows."""
    hi = cols[..., NLIMB:]  # (..., 21)
    a = hi & MASK
    b2 = (hi >> BITS) & MASK
    c3 = hi >> (2 * BITS)
    tmp = jnp.zeros(cols.shape[:-1] + (NLIMB + 4,), jnp.uint32)
    tmp = tmp.at[..., :NLIMB].set(cols[..., :NLIMB])
    tmp = tmp.at[..., 0 : NLIMB + 1].add(a * SECP_FOLD_SMALL)
    tmp = tmp.at[..., 1 : NLIMB + 2].add(b2 * SECP_FOLD_SMALL)
    tmp = tmp.at[..., 2 : NLIMB + 3].add(
        c3 * SECP_FOLD_SMALL + (a << SECP_FOLD_SHIFT))
    tmp = tmp.at[..., 3 : NLIMB + 4].add(b2 << SECP_FOLD_SHIFT)
    tmp = tmp.at[..., 4 : NLIMB + 4].add((c3 << SECP_FOLD_SHIFT)[..., :NLIMB])
    return tmp


def secp_fold2_batch(tmp):
    lo = tmp[..., :NLIMB]
    for t in range(4):
        h = tmp[..., NLIMB + t]
        a = h & MASK
        b2 = h >> BITS
        lo = (
            lo.at[..., t].add(a * SECP_FOLD_SMALL)
            .at[..., t + 1].add(b2 * SECP_FOLD_SMALL)
            .at[..., t + 2].add(a << SECP_FOLD_SHIFT)
            .at[..., t + 3].add(b2 << SECP_FOLD_SHIFT)
        )
    return lo


# ---------------------------------------------------------------------------
# Backend namespaces — what the Pallas kernels thread through their point ops
# ---------------------------------------------------------------------------


def make_fe(curve: str, backend: str = "vpu",
            carry_mode: str = "eager") -> SimpleNamespace:
    """Uniform op namespace: mul/sq/add/sub/inv/carry (+ mul_small on secp).
    add/sub/carry are backend-independent (pure VPU); mul/sq/inv honor the
    backend.

    carry_mode="lazy" swaps in the deferred-carry ops: mul becomes mulF
    (output in the certified fully-reduced class C), mul_lazy/add_raw expose
    the cheaper unreduced forms, add/sub carry once instead of fully, and
    sub against class-D operands must use fe.kd (the wide multiple of p
    sized for D) instead of the eager ksub.  The mxu16 backend keeps its own
    fused 16-limb pipeline and degrades to eager (effective_carry_mode)."""
    if backend not in FE_BACKENDS:
        raise ValueError(f"fe backend must be one of {FE_BACKENDS}, got {backend!r}")
    if carry_mode not in CARRY_MODES:
        raise ValueError(f"carry mode must be one of {CARRY_MODES}, got {carry_mode!r}")
    lazy = effective_carry_mode(backend, carry_mode) == "lazy"
    if curve == "ed25519":
        if not lazy:
            return SimpleNamespace(
                curve=curve, backend=backend, carry_mode="eager", plan=None,
                kd=None,
                mul=partial(ed_fe_mul, backend=backend),
                sq=partial(ed_fe_sq, backend=backend),
                inv=partial(ed_fe_inv, backend=backend),
                add=ed_fe_add, sub=ed_fe_sub, carry=ed_fe_carry1,
            )
        plan = derive_carry_plan(curve, backend)
        mul = partial(ed_fe_mul_lazy, wide=plan.mulf_wide, fix=plan.mulf_fix,
                      backend=backend)
        return SimpleNamespace(
            curve=curve, backend=backend, carry_mode="lazy", plan=plan,
            kd=np.asarray(plan.kd, np.uint32),
            mul=mul,
            mul4=partial(ed_fe_mul4_lazy, wide=plan.mulf_wide,
                         fix=plan.mulf_fix, backend=backend),
            mul_lazy=partial(ed_fe_mul_lazy, wide=plan.mull_wide,
                             fix=plan.mull_fix, backend=backend),
            sq=lambda a: mul(a, a),
            inv=partial(ed_fe_inv, mul=mul, sq=lambda a: mul(a, a)),
            add=lambda a, b: ed_fe_norm1(a + b, fix=plan.norm_fix),
            sub=lambda a, b, k: ed_fe_norm1(a + k - b, fix=plan.norm_fix),
            add_raw=lambda a, b: a + b,
            carry=ed_fe_carry1,
        )
    if curve == "secp256k1":
        if not lazy:
            return SimpleNamespace(
                curve=curve, backend=backend, carry_mode="eager", plan=None,
                kd=None,
                mul=partial(secp_fe_mul, backend=backend),
                sq=partial(secp_fe_sq, backend=backend),
                inv=partial(secp_fe_inv, backend=backend),
                add=secp_fe_add, sub=secp_fe_sub, carry=secp_fe_carry,
                mul_small=secp_fe_mul_small,
            )
        plan = derive_carry_plan(curve, backend)
        mul = partial(secp_fe_mul_lazy, wide=plan.mulf_wide,
                      fix=plan.mulf_fix, backend=backend, mid=plan.mid)
        return SimpleNamespace(
            curve=curve, backend=backend, carry_mode="lazy", plan=plan,
            kd=np.asarray(plan.kd, np.uint32),
            mul=mul,
            mul_lazy=partial(secp_fe_mul_lazy, wide=plan.mull_wide,
                             fix=plan.mull_fix, backend=backend, mid=plan.mid),
            sq=lambda a: mul(a, a),
            inv=partial(secp_fe_inv, mul=mul),
            add=lambda a, b: secp_fe_norm1(a + b, wide=plan.norm_wide,
                                           fix=plan.norm_fix),
            sub=lambda a, b, k: secp_fe_norm1(a + k - b, wide=plan.norm_wide,
                                              fix=plan.norm_fix),
            add_raw=lambda a, b: a + b,
            mul_small=lambda a, k: secp_fe_norm1(a * k, wide=plan.norm_wide,
                                                 fix=plan.norm_fix),
            carry=secp_fe_carry,
        )
    raise ValueError(f"unknown curve {curve!r}")


def normalize_backend(value) -> str:
    """Config/env -> backend name ('' / None / 'auto' mean the VPU path)."""
    v = (value or "vpu").strip().lower()
    if v in ("", "auto"):
        v = "vpu"
    if v not in FE_BACKENDS:
        raise ValueError(f"[verify] fe_backend must be one of {FE_BACKENDS}, got {value!r}")
    return v


def normalize_carry_mode(value) -> str:
    """Config/env -> carry mode ('' / None / 'auto' mean lazy, the default)."""
    v = (value or "lazy").strip().lower()
    if v in ("", "auto"):
        v = "lazy"
    if v not in CARRY_MODES:
        raise ValueError(f"carry mode must be one of {CARRY_MODES}, got {value!r}")
    return v


def effective_carry_mode(backend: str, carry_mode: str = "lazy") -> str:
    """mxu16's fused 16-limb pipeline has its own carry schedule and no lazy
    variant — it degrades gracefully to eager; everything else honors the
    requested mode."""
    return "eager" if backend == "mxu16" else carry_mode


# ---------------------------------------------------------------------------
# Bound propagation — pure-Python mirrors of the pipelines above on per-row
# maxima.  tests/test_fe_common.py drives these to re-prove, mechanically,
# the overflow-freedom claims that used to live in the ed25519_pallas header
# comment (ISSUE 10 satellite: assert the bounds instead of stating them).
# Every helper returns (bounds, max_intermediate_seen).
# ---------------------------------------------------------------------------

U32 = 1 << 32


def _b_shift_down(bounds: List[int], k=1) -> List[int]:
    return [0] * k + bounds[:-k]


def _b_carry_round(bounds, wrap_terms) -> Tuple[List[int], int]:
    """Mirror of one (x & MASK) + shift(c) + wrap(c_top) round."""
    c = [b >> BITS for b in bounds]
    out = [min(b, MASK) for b in bounds]
    out = [o + s for o, s in zip(out, _b_shift_down(c))]
    for row, mult, shift in wrap_terms:
        out[row] += (c[NLIMB - 1] * mult) << shift
    return out, max(out)


def bound_mul_columns(ba: Sequence[int], bb: Sequence[int], out_rows: int) -> List[int]:
    """Column maxima — identical for vpu and mxu (same integers)."""
    cols = [0] * out_rows
    for i in range(NLIMB):
        for j in range(NLIMB):
            cols[i + j] += ba[i] * bb[j]
    return cols


def bound_fe_mul(curve: str, ba: Sequence[int], bb: Sequence[int],
                 backend: str = "vpu", tail_rounds: int = None
                 ) -> Tuple[List[int], int]:
    """Per-row output maxima of fe_mul plus the largest intermediate the
    pipeline can produce (callers assert < 2^32).  tail_rounds overrides the
    module's final-carry count so derive_eager_rounds can search for the
    minimum (None -> the constant the jnp op uses)."""
    hi_in = max(max(ba), max(bb))
    peak = 0

    def see(vals):
        nonlocal peak
        peak = max(peak, max(vals))
        return vals

    if backend == "mxu":
        # the plane split must fit its dtype: int8 (split=7) needs limbs
        # <= 16383, uint8 (split=8) <= 65535
        limit = 16383 if curve == "ed25519" else 65535
        if hi_in > limit:
            raise AssertionError(
                f"{curve} mxu planes need limbs <= {limit}, got {hi_in}")
    if backend == "mxu16":
        return _bound_mul16(curve, ba, bb)
    if curve == "ed25519":
        cols = see(bound_mul_columns(ba, bb, 2 * NLIMB))
        c = [b >> BITS for b in cols]
        prod = see([min(b, MASK) + s for b, s in
                    zip(cols, [0] + c[:-1])])
        lo = see([prod[k] + prod[NLIMB + k] * ED_FOLD for k in range(NLIMB)])
        rounds = ED_MUL_TAIL_ROUNDS if tail_rounds is None else tail_rounds
        for _ in range(rounds):
            lo, m = _b_carry_round(lo, ED_WRAP)
            peak = max(peak, m)
        return lo, peak
    if curve == "secp256k1":
        cols = see(bound_mul_columns(ba, bb, 2 * NLIMB + 1))
        prod = cols
        for _ in range(3):
            c = [b >> BITS for b in prod]
            prod = see([min(b, MASK) + s for b, s in
                        zip(prod, [0] + c[:-1])])
        hi = prod[NLIMB:]  # 21 rows
        tmp = [0] * 24
        for k in range(NLIMB):
            tmp[k] += prod[k]
        for k, h in enumerate(hi):
            tmp[k] += h * SECP_FOLD_SMALL
            tmp[k + 2] += h << SECP_FOLD_SHIFT
        see(tmp)
        for _ in range(2):
            c = [b >> BITS for b in tmp]
            tmp = see([min(b, MASK) + s for b, s in zip(tmp, [0] + c[:-1])])
        lo = tmp[:NLIMB]
        for t_idx in range(4):
            t = tmp[NLIMB + t_idx]
            lo[t_idx] += t * SECP_FOLD_SMALL
            lo[t_idx + 2] += t << SECP_FOLD_SHIFT
        see(lo)
        rounds = SECP_MUL_TAIL_ROUNDS if tail_rounds is None else tail_rounds
        for _ in range(rounds):
            lo, m = _b_carry_round(lo, SECP_WRAP)
            peak = max(peak, m)
        return lo, peak
    raise ValueError(curve)


def _b_carry16(bs, rounds, wrap_terms=()):
    """Mirror of _carry16 on per-row maxima (same top-row semantics)."""
    seen = []
    n = len(bs)
    for _ in range(rounds):
        c = [b >> 16 for b in bs]
        nxt = [min(b, MASK16) + s for b, s in zip(bs, [0] + c[:-1])]
        if wrap_terms:
            for row, mult, shift in wrap_terms:
                nxt[row] += (c[n - 1] * mult) << shift
        else:
            nxt[n - 1] += c[n - 1] << 16  # top row keeps its excess
        bs = nxt
        seen.append(max(bs))
    return bs, max(seen)


def _bound_mul16(curve, ba, bb) -> Tuple[List[int], int]:
    fold13 = ED_FOLD256_13 if curve == "ed25519" else SECP_FOLD256_13
    fold16 = ED_FOLD256_16 if curve == "ed25519" else SECP_FOLD256_16
    peak = 0

    def see(vals):
        nonlocal peak
        peak = max(peak, max(vals))
        return list(vals)

    def prefold(bs):
        t = bs[NLIMB - 1] >> 9
        out = list(bs)
        out[NLIMB - 1] = min(out[NLIMB - 1], 0x1FF)
        for row, mult, shift in fold13:
            out[row] += (t * mult) << shift
        return see(out)

    def seq_carry(w):
        for k in range(_R16 - 1):
            c = w[k] >> 16
            w[k] = min(w[k], MASK16)
            w[k + 1] += c
            see([w[k + 1]])
        return w

    def repack(bs):
        w = [0] * _R16
        for i in range(NLIMB):
            q, r = divmod(BITS * i, 16)
            w[q] += bs[i] << r
        see(w)
        w = seq_carry(w)
        c = w[_R16 - 1] >> 16
        w[_R16 - 1] = min(w[_R16 - 1], MASK16)
        for row, mult, shift in fold16:
            w[row] += (c * mult) << shift
        w = seq_carry(see(w))
        # rows end < 2^16: after the wrap the value fits 256 bits (an
        # invariant of the prefold + wrap, not derivable from row maxima)
        return [min(x, MASK16) for x in w]

    wa = repack(prefold(ba))
    wb = repack(prefold(bb))
    # uint8 plane products: ll + ((lh+hl)<<8) at i+j, hh one row up
    nrows = 2 * _R16 + 1
    cols = [0] * nrows
    for i in range(_R16):
        for j in range(_R16):
            la, ha = min(wa[i], 0xFF), wa[i] >> 8
            lb, hb = min(wb[j], 0xFF), wb[j] >> 8
            cols[i + j] += la * lb + ((la * hb + ha * lb) << 8)
            cols[i + j + 1] += ha * hb
    see(cols)
    cols, m = _b_carry16(cols, rounds=2)
    peak = max(peak, m)
    # _fold16 mirror: pass one onto 16+spill rows, carry, pass two
    spill = max(row for row, _, _ in fold16) + 1
    lo = cols[:_R16] + [0] * spill
    hi = cols[_R16:]
    for row, mult, _ in fold16:
        for j, h in enumerate(hi):
            lo[row + j] += h * mult
    see(lo)
    lo, m = _b_carry16(lo, rounds=2)
    peak = max(peak, m)
    out16 = lo[:_R16]
    for j in range(spill):
        h = lo[_R16 + j]
        for row, mult, _ in fold16:
            out16[row + j] += h * mult
    see(out16)
    out16, m = _b_carry16(out16, rounds=2, wrap_terms=fold16)
    peak = max(peak, m)
    limbs = [0] * NLIMB
    for k in range(_R16):
        q, r = divmod(16 * k, BITS)
        limbs[q] += out16[k] << r
    see(limbs)
    wrap = ((0, ED_FOLD, 0),) if curve == "ed25519" else (
        (0, SECP_FOLD_SMALL, 0), (2, 1, SECP_FOLD_SHIFT))
    rounds = 3 if curve == "ed25519" else 5
    for _ in range(rounds):
        limbs, m = _b_carry_round(limbs, wrap)
        peak = max(peak, m)
    return limbs, peak


def bound_fe_add(curve: str, ba, bb, rounds: int = None) -> Tuple[List[int], int]:
    x = [a + b for a, b in zip(ba, bb)]
    peak = max(x)
    wrap = ED_WRAP if curve == "ed25519" else SECP_WRAP
    if rounds is None:
        rounds = ED_ADD_ROUNDS if curve == "ed25519" else SECP_ADD_ROUNDS
    for _ in range(rounds):
        x, m = _b_carry_round(x, wrap)
        peak = max(peak, m)
    return x, peak


def bound_fe_sub(curve: str, ba, bb, ksub: Sequence[int],
                 rounds: int = None, check: bool = True
                 ) -> Tuple[List[int], int]:
    # worst case ignores the subtraction (b >= 0): a + ksub.  That model
    # is only sound when ksub dominates the subtrahend limb-for-limb —
    # otherwise a + ksub - b wraps in uint32 and the result is garbage,
    # not merely unreduced.  The import-time ksub derivation below fixes
    # the constants to dominate their own closed set; check=False exists
    # solely for that derivation's intermediate iterates.
    if check:
        assert all(int(k) >= int(b) for k, b in zip(ksub, bb)), (
            curve, "ksub under-dominates the subtrahend bound")
    return bound_fe_add(curve, ba, list(ksub), rounds=rounds)


def bound_fe_mul_small(curve: str, ba, k: int,
                       rounds: int = None) -> Tuple[List[int], int]:
    """Mirror of secp_fe_mul_small: scalar limb scale + carry rounds."""
    assert curve == "secp256k1"
    x = [a * k for a in ba]
    peak = max(x)
    if rounds is None:
        rounds = SECP_MUL_SMALL_ROUNDS
    for _ in range(rounds):
        x, m = _b_carry_round(x, SECP_WRAP)
        peak = max(peak, m)
    return x, peak


def bound_closed_set(curve: str, backend: str = "vpu",
                     ksub: Sequence[int] = (), iters: int = 64,
                     check_ksub: bool = True) -> Tuple[List[int], int]:
    """Fixed point of the op mix: starting from fresh-input bounds (MASK),
    iterate max(mul, add, sub) until the per-row bounds stop growing.
    Returns (closed-set bounds, peak intermediate).  Non-convergence or a
    peak >= 2^32 means the op mix is unsound — the test fails."""
    bounds = [MASK] * NLIMB
    peak = 0
    for _ in range(iters):
        bm, p1 = bound_fe_mul(curve, bounds, bounds, backend)
        ba, p2 = bound_fe_add(curve, bounds, bounds)
        bs, p3 = (bound_fe_sub(curve, bounds, bounds, ksub,
                               check=check_ksub)
                  if len(ksub) else (bounds, 0))
        nxt = [max(a, b, c) for a, b, c in zip(bm, ba, bs)]
        peak = max(peak, p1, p2, p3)
        if nxt == bounds:
            return bounds, peak
        bounds = nxt
    raise AssertionError(f"{curve}/{backend}: carried bounds did not converge")


# ---------------------------------------------------------------------------
# Lazy-op bound mirrors + carry-plan derivation.  derive_carry_plan iterates
# the exact chain set the lazy kernels execute (every operand-class pairing
# of mulF/mulL/add1/sub1/add_raw) to a fixed point, producing the certified
# operand classes:
#   C — fully reduced outputs (mulF, add1, sub1); every point-op output.
#   D — deferred mulL outputs (one wide round only); add-only consumers.
# plus KD, a wide multiple of p with limb_i >= D_i so sub1 against class-D
# operands stays non-negative in uint32.  derive_eager_rounds runs the same
# machinery over the EAGER mirrors to find the minimal round count for each
# eager op — the import-time asserts at the bottom pin the module constants
# to those derived values.
# ---------------------------------------------------------------------------


def _b_wide_round(bounds, wrap_terms) -> Tuple[List[int], int]:
    """Mirror of wide_carry_rows (decomposed wrap re-entry)."""
    c = [b >> BITS for b in bounds]
    out = [min(b, MASK) for b in bounds]
    out = [o + s for o, s in zip(out, _b_shift_down(c))]
    top = c[NLIMB - 1]
    for row, mult, shift in wrap_terms:
        out[row] += (min(top, MASK) * mult) << shift
        out[row + 1] += ((top >> BITS) * mult) << shift
    return out, max(out)


def _b_fix(bounds, rows) -> List[int]:
    out = list(bounds)
    for r in rows:
        c = out[r] >> BITS
        out[r] = min(out[r], MASK)
        out[r + 1] += c
    return out


def _b_carry_drop_top(bounds) -> Tuple[List[int], int]:
    """Mirror of carry_drop_top_rows; proves the dropped top carry is 0."""
    c = [b >> BITS for b in bounds]
    assert c[-1] == 0, f"carry_drop_top would lose a bound-{c[-1]} carry"
    out = [min(b, MASK) + s for b, s in zip(bounds, [0] + c[:-1])]
    return out, max(out)


def bound_ed_fold_fused(cols) -> List[int]:
    """Mirror of ed_fold_fused_rows; proves the shifted-out hi piece of
    column 39 is 0 (jnp drops it via shift_rows_down)."""
    hi = cols[NLIMB:]
    lo = [cols[k] + min(hi[k], MASK) * ED_FOLD for k in range(NLIMB)]
    hh = [h >> BITS for h in hi]
    assert hh[NLIMB - 1] == 0, "ed fused fold would drop a non-zero hi piece"
    for k in range(NLIMB - 1):
        lo[k + 1] += hh[k] * ED_FOLD
    return lo


def bound_ed_mul_lazy(ba, bb, wide, fix=(0,)) -> Tuple[List[int], int]:
    cols = bound_mul_columns(ba, bb, 2 * NLIMB)
    peak = max(cols)
    assert cols[2 * NLIMB - 1] == 0  # col 39 is structurally empty
    lo = bound_ed_fold_fused(cols)
    peak = max(peak, max(lo))
    for _ in range(wide):
        lo, m = _b_wide_round(lo, ED_WRAP)
        peak = max(peak, m)
    lo = _b_fix(lo, fix)
    return lo, max(peak, max(lo))


def bound_ed_norm1(raw, fix=(0,)) -> Tuple[List[int], int]:
    peak = max(raw)
    lo, m = _b_wide_round(raw, ED_WRAP)
    lo = _b_fix(lo, fix)
    return lo, max(peak, m, max(lo))


def bound_secp_fold_fused(cols) -> List[int]:
    """Mirror of secp_fold_fused_rows; proves the c-piece that would land
    on temp row 24 is 0 (jnp slices it away)."""
    tmp = list(cols[:NLIMB]) + [0] * 4
    for k in range(NLIMB + 1):
        h = cols[NLIMB + k]
        a, b2, c3 = min(h, MASK), min(h >> BITS, MASK), h >> (2 * BITS)
        tmp[k] += a * SECP_FOLD_SMALL
        tmp[k + 1] += b2 * SECP_FOLD_SMALL
        tmp[k + 2] += c3 * SECP_FOLD_SMALL + (a << SECP_FOLD_SHIFT)
        tmp[k + 3] += b2 << SECP_FOLD_SHIFT
        if k + 4 < NLIMB + 4:
            tmp[k + 4] += c3 << SECP_FOLD_SHIFT
        else:
            assert c3 == 0, "secp fused fold would drop a non-zero c piece"
    return tmp


def bound_secp_fold2(tmp) -> List[int]:
    lo = list(tmp[:NLIMB])
    for t in range(4):
        h = tmp[NLIMB + t]
        a, b2 = min(h, MASK), h >> BITS
        lo[t] += a * SECP_FOLD_SMALL
        lo[t + 1] += b2 * SECP_FOLD_SMALL
        lo[t + 2] += a << SECP_FOLD_SHIFT
        lo[t + 3] += b2 << SECP_FOLD_SHIFT
    return lo


def bound_secp_mul_lazy(ba, bb, wide, fix=(0, 1, 2, 3),
                        mid=1) -> Tuple[List[int], int]:
    cols = bound_mul_columns(ba, bb, 2 * NLIMB + 1)
    peak = max(cols)
    assert cols[2 * NLIMB - 1] == 0 and cols[2 * NLIMB] == 0
    tmp = bound_secp_fold_fused(cols)
    peak = max(peak, max(tmp))
    for _ in range(mid):
        tmp, m = _b_carry_drop_top(tmp)
        peak = max(peak, m)
    lo = bound_secp_fold2(tmp)
    peak = max(peak, max(lo))
    for _ in range(wide):
        lo, m = _b_wide_round(lo, SECP_WRAP)
        peak = max(peak, m)
    lo = _b_fix(lo, fix)
    return lo, max(peak, max(lo))


def bound_secp_norm1(raw, wide=1, fix=(0, 1, 2, 3)) -> Tuple[List[int], int]:
    peak = max(raw)
    lo = list(raw)
    for _ in range(wide):
        lo, m = _b_wide_round(lo, SECP_WRAP)
        peak = max(peak, m)
    lo = _b_fix(lo, fix)
    return lo, max(peak, max(lo))


def mk_wide_multiple(p: int, floors: Sequence[int], mult0: int
                     ) -> Tuple[List[int], int]:
    """Smallest mult0-multiple of p whose radix-13 limbs can be raised (by
    borrowing 2^13 from the next limb) to limb_i >= floors[i] with every
    limb still < 2^31 — the wide-zero constant that keeps a - b + K
    non-negative in uint32 for operands bounded by floors."""
    mult = mult0
    while True:
        v = mult * p
        limbs = [(v >> (BITS * i)) & MASK for i in range(NLIMB + 2)]
        limbs[NLIMB - 1] += limbs[NLIMB] << BITS
        limbs[NLIMB - 1] += limbs[NLIMB + 1] << (2 * BITS)
        limbs = limbs[:NLIMB]
        for i in range(NLIMB - 1):
            if limbs[i] < floors[i]:
                t = ((floors[i] - limbs[i]) >> BITS) + 1
                limbs[i] += t << BITS
                limbs[i + 1] -= t
        if limbs[NLIMB - 1] >= floors[NLIMB - 1] and all(
                0 <= l < (1 << 31) for l in limbs):
            assert sum(l << (BITS * i) for i, l in enumerate(limbs)) % p == 0
            return limbs, mult
        mult += mult0
        assert mult < mult0 * 10000, "no wide multiple of p fits the floors"


# The eager wide-zero constants the kernels already use, re-derived here so
# the bound machinery and the lazy sub1 paths share one source of truth
# (tests assert these equal the verify modules' _K_SUB arrays).
ED_KSUB_LIMBS = [4 * MASK - 2428] + [4 * MASK] * (NLIMB - 1)
assert sum(v << (BITS * i) for i, v in enumerate(ED_KSUB_LIMBS)) % ED_P == 0


def _dominating_ksub(curve: str, prime: int, mult0: int) -> List[int]:
    """Wide zero whose limbs dominate the eager closed set it induces.

    The floor and the closed set are mutually dependent (sub's output
    bound is a + ksub), so iterate: derive a candidate from the current
    floor, recompute the closed set under it, and raise the floor to any
    limb the set exceeds.  A flat 2*MASK floor is NOT enough — the wrap
    fold can carry limb 0 up to MASK + fold (23823 on secp256k1), past
    the old hand-picked constant's 19392, and an under-dominated ksub
    makes a + ksub - b wrap in uint32."""
    floor = [2 * MASK] * NLIMB
    for _ in range(8):
        ks, _ = mk_wide_multiple(prime, floor, mult0)
        cs, _ = bound_closed_set(curve, "vpu", ksub=tuple(ks),
                                 check_ksub=False)
        if all(k >= b for k, b in zip(ks, cs)):
            return ks
        floor = [max(f, b) for f, b in zip(floor, cs)]
    raise AssertionError(f"{curve}: ksub/closed-set domination diverged")


SECP_KSUB_LIMBS = _dominating_ksub("secp256k1", SECP_P, 64)
# ed25519's 4*MASK floor already dominates its closed set — assert rather
# than trust (same soundness condition as the secp derivation above)
_ED_CS_CHECK, _ = bound_closed_set("ed25519", "vpu",
                                   ksub=tuple(ED_KSUB_LIMBS))
assert all(k >= b for k, b in zip(ED_KSUB_LIMBS, _ED_CS_CHECK))
del _ED_CS_CHECK


def _ed_lazy_closed(mulf_wide: int):
    """Fixed point of the ed25519 lazy chain set (see derive_carry_plan)."""
    peak = 0
    C = [MASK] * NLIMB
    KD = kd_floor = kd_mult = None
    for it in range(300):
        raw_cc = [x + y for x, y in zip(C, C)]
        d1, p1 = bound_ed_mul_lazy(C, C, wide=1)
        d2, p2 = bound_ed_mul_lazy(raw_cc, C, wide=1)
        # widen D to cover C row-wise so a class-C operand may always stand
        # in where the chain shapes below were certified with class D
        D = [max(a, b, c) for a, b, c in zip(d1, d2, C)]
        if KD is None or any(d > f for d, f in zip(D, kd_floor)):
            kd_floor = [max(1 << 18, d) for d in D]
            KD, kd_mult = mk_wide_multiple(ED_P, kd_floor, 32)
        raw_dd = [x + y for x, y in zip(D, D)]
        outs = [bound_ed_mul_lazy(C, C, wide=mulf_wide)]
        for raw in (
            [x + y for x, y in zip(C, C)],          # add1(C, C)
            [x + k for x, k in zip(C, ED_KSUB_LIMBS)],  # sub1(C, C)
            [x + y for x, y in zip(D, D)],          # add1(D, D)
            [x + k for x, k in zip(D, KD)],         # sub1(D, D)
            [x + k for x, k in zip(C, KD)],         # sub1(C, D)
            [x + y for x, y in zip(raw_dd, C)],     # add1(add_raw(D,D), C)
            [r + k for r, k in zip(raw_cc, KD)],    # sub1(add_raw(C,C), D)
            [r + c for r, c in zip(raw_cc, C)],     # add1(add_raw(C,C), C)
        ):
            outs.append(bound_ed_norm1(raw))
        peak = max([peak, p1, p2] + [p for _, p in outs])
        nxt = [max(vals) for vals in zip(*(b for b, _ in outs))]
        if nxt == C:
            return C, D, KD, kd_mult, peak, it
        if max(nxt) > 10 ** 7:
            return None, None, None, None, peak, it
        C = nxt
    return None, None, None, None, peak, it


def _secp_lazy_closed(mulf_wide: int):
    """Fixed point of the secp256k1 RCB16 lazy chain set."""
    peak = 0
    C = [MASK] * NLIMB
    KD = kd_floor = kd_mult = None
    for it in range(300):
        CC = [x + y for x, y in zip(C, C)]
        C1, pc = bound_secp_norm1(CC)
        d1, p1 = bound_secp_mul_lazy(C, C, wide=1, fix=(0,))
        d2, p2 = bound_secp_mul_lazy(C1, CC, wide=1, fix=(0,))
        d3, p3 = bound_secp_mul_lazy(C, CC, wide=1, fix=(0,))
        # widen D to cover C row-wise (same substitution lemma as ed25519)
        D = [max(vals) for vals in zip(d1, d2, d3, C)]
        DD = [x + y for x, y in zip(D, D)]
        if KD is None or any(d > f for d, f in zip(DD, kd_floor)):
            kd_floor = [max(1 << 18, d) for d in DD]
            KD, kd_mult = mk_wide_multiple(SECP_P, kd_floor, 16)
        outs = [bound_secp_mul_lazy(C, C, wide=mulf_wide), (C1, pc)]
        for raw in (
            [x + k for x, k in zip(C, SECP_KSUB_LIMBS)],       # sub1(C, C)
            [d + k + s for d, k, s in zip(D, KD, DD)],         # sub1(D, add_raw(D,D))
            [s + d for s, d in zip(DD, D)],                    # add1(add_raw(D,D), D)
            [x * B3_SMALL for x in C],                         # mul_small1(C)
            [d + c for d, c in zip(D, C)],                     # add1(D, C)
            [d + k + c for d, k, c in zip(D, KD, C)],          # sub1(D, C)
            [a + k + b for a, k, b in zip(D, KD, D)],          # sub1(D, D)
            [x + y for x, y in zip(D, D)],                     # add1(D, D)
        ):
            outs.append(bound_secp_norm1(raw))
        peak = max([peak, pc, p1, p2, p3] + [p for _, p in outs])
        nxt = [max(vals) for vals in zip(*(b for b, _ in outs))]
        if nxt == C:
            return C, D, KD, kd_mult, peak, it
        if max(nxt) > 10 ** 7:
            return None, None, None, None, peak, it
        C = nxt
    return None, None, None, None, peak, it


B3_SMALL = 21  # 3*b of the secp256k1 curve equation, RCB16's only scalar


@lru_cache(maxsize=None)
def derive_carry_plan(curve: str, backend: str = "vpu") -> SimpleNamespace:
    """Certified lazy carry plan: iterate the kernel's deferred-carry chain
    set to a fixed point and return the operand classes, KD constant, and
    per-op round/fixup schedule.  The mulF wide count is SEARCHED (smallest
    that converges), not stated.  Raises for mxu16 — callers degrade it to
    eager via effective_carry_mode."""
    if backend == "mxu16":
        raise ValueError("mxu16 has no lazy carry plan; use effective_carry_mode")
    if backend not in FE_BACKENDS:
        raise ValueError(f"fe backend must be one of {FE_BACKENDS}, got {backend!r}")
    closed = _ed_lazy_closed if curve == "ed25519" else _secp_lazy_closed
    if curve not in ("ed25519", "secp256k1"):
        raise ValueError(f"unknown curve {curve!r}")
    for mulf_wide in range(1, 5):
        C, D, KD, kd_mult, peak, iters = closed(mulf_wide)
        if C is not None:
            break
    else:
        raise AssertionError(f"{curve}: lazy chain set never converged")
    assert peak < U32, f"{curve} lazy peak {peak:.3e} overflows uint32"
    if backend == "mxu":
        # lazy-mode multiply operands (C and raw C+C sums) must fit the
        # uint8 plane split the lazy ops pin (split=8)
        worst = 2 * max(C) if curve == "ed25519" else max(
            max(C) * 2, max(bound_secp_norm1([2 * c for c in C])[0]))
        assert worst <= 65535, f"{curve} mxu lazy operands reach {worst}"
    ksub = ED_KSUB_LIMBS if curve == "ed25519" else SECP_KSUB_LIMBS
    eager_cs, _ = bound_closed_set(curve, "vpu", tuple(ksub))
    # Epilogue certificate: eager ops must accept class-C inputs.  Close the
    # eager op mix seeded at max(C, eager closed set) — this is the domain
    # the eager fe_inv / fe_canonical chains see when fed lazy outputs.
    cs_epi = [max(a, b) for a, b in zip(C, eager_cs)]
    epi_peak = 0
    for _ in range(64):
        bm, p1 = bound_fe_mul(curve, cs_epi, cs_epi, "vpu")
        ba, p2 = bound_fe_add(curve, cs_epi, cs_epi)
        bs, p3 = bound_fe_sub(curve, cs_epi, cs_epi, ksub)
        nxt = [max(vals) for vals in zip(bm, ba, bs)]
        epi_peak = max(epi_peak, p1, p2, p3)
        if nxt == cs_epi:
            break
        cs_epi = nxt
    else:
        raise AssertionError(f"{curve}: epilogue closure did not converge")
    assert epi_peak < U32
    if backend == "mxu":
        # the XLA eager epilogue keeps the curve's plane split (7 for ed)
        limit = 16383 if curve == "ed25519" else 65535
        assert max(cs_epi) <= limit, (
            f"{curve} mxu eager epilogue operands reach {max(cs_epi)}")
    if curve == "ed25519":
        assert max(cs_epi) <= ED_M, (
            f"ed25519 epilogue limbs {max(cs_epi)} leave _canonical_ref's "
            f"certified domain (<= {ED_M})")
    # Canonical-encode prologue certificate: two eager carry rounds bring
    # any epilogue-class value back inside the eager closed set (the domain
    # the canonical-reduction tests drive).
    back = cs_epi
    for _ in range(2):
        back, _ = _b_carry_round(
            back, ED_WRAP if curve == "ed25519" else SECP_WRAP)
    assert all(a <= b for a, b in zip(back, eager_cs)), (
        f"{curve}: lazy outputs do not re-enter the eager closed set")
    # C <= D row-wise lets chains substitute a class-C operand where the
    # certification used class D (e.g. add1(add_raw(C,C), D) is dominated by
    # the certified add1(add_raw(D,D), C)).
    assert all(a <= b for a, b in zip(C, D)), f"{curve}: class C exceeds D"
    if curve == "ed25519":
        return SimpleNamespace(
            curve=curve, backend=backend, c=C, d=D, kd=KD, kd_mult=kd_mult,
            ksub=list(ksub), mulf_wide=mulf_wide, mull_wide=1, norm_wide=1,
            mid=0, mulf_fix=(0,), mull_fix=(0,), norm_fix=(0,), split=8,
            peak=peak, iters=iters)
    return SimpleNamespace(
        curve=curve, backend=backend, c=C, d=D, kd=KD, kd_mult=kd_mult,
        ksub=list(ksub), mulf_wide=mulf_wide, mull_wide=1, norm_wide=1,
        mid=1, mulf_fix=(0, 1, 2, 3), mull_fix=(0,), norm_fix=(0, 1, 2, 3),
        split=8, peak=peak, iters=iters)


@lru_cache(maxsize=None)
def derive_eager_rounds(curve: str) -> dict:
    """Minimal eager carry rounds per op: smallest r whose output on
    closed-set inputs stays inside the closed set with every intermediate
    < 2^32.  The import-time asserts below pin the module constants (and so
    the jnp ops) to exactly these values."""
    ksub = ED_KSUB_LIMBS if curve == "ed25519" else SECP_KSUB_LIMBS
    cs, _ = bound_closed_set(curve, "vpu", tuple(ksub))

    def minimal(op):
        for r in range(1, 9):
            out, pk = op(r)
            if pk < U32 and all(o <= c for o, c in zip(out, cs)):
                return r
        raise AssertionError(f"{curve}: no round count <= 8 closes the set")

    derived = {
        "mul_tail": minimal(
            lambda r: bound_fe_mul(curve, cs, cs, "vpu", tail_rounds=r)),
        "add": minimal(lambda r: bound_fe_add(curve, cs, cs, rounds=r)),
        "sub": minimal(lambda r: bound_fe_sub(curve, cs, cs, ksub, rounds=r)),
    }
    if curve == "secp256k1":
        derived["mul_small"] = minimal(
            lambda r: bound_fe_mul_small(curve, cs, B3_SMALL, rounds=r))
    return derived


# ---------------------------------------------------------------------------
# Carry-round cost model — the three pools (multiply / deferred-carry /
# final-fold) in row-slot units: one limb-row processed by one carry round
# costs 1.  Per-op costs come from the certified schedules above; the op
# mixes are the literal op counts of the point formulas in the Pallas
# kernels.  PERF.md and the >= 30% acceptance gate in tests read from here.
# ---------------------------------------------------------------------------

_ED_POINT_MIX = {
    "eager": {
        "pt_double":     {"mul": 8, "addsub": 6},
        "pt_madd":       {"mul": 7, "addsub": 7},
        "pt_add_cached": {"mul": 9, "addsub": 9},
        "pt_add_ext":    {"mul": 9, "addsub": 9},
        "niels_convert": {},
    },
    "lazy": {
        "pt_double":     {"mulF": 4, "mulL": 4, "norm1": 5},
        "pt_madd":       {"mulF": 4, "mulL": 3, "norm1": 5},
        "pt_add_cached": {"mulF": 4, "mulL": 4, "norm1": 5},
        "pt_add_ext":    {"mulF": 5, "mulL": 4, "norm1": 8},
        "niels_convert": {"mulF": 1, "norm1": 2},
    },
}

_SECP_POINT_MIX = {
    "eager": {"pt_add": {"mul": 12, "mul_small": 2, "addsub": 18}},
    "lazy": {"pt_add": {"mulF": 1, "mulL": 11, "norm1": 12, "mul_small": 2}},
}


def _carry_op_costs(curve: str, carry_mode: str) -> dict:
    if curve == "ed25519":
        if carry_mode == "eager":
            return {"mul": (2 + ED_MUL_TAIL_ROUNDS) * NLIMB,
                    "addsub": ED_ADD_ROUNDS * NLIMB}
        plan = derive_carry_plan(curve)
        return {
            "mulF": plan.mulf_wide * NLIMB + len(plan.mulf_fix),
            "mulL": plan.mull_wide * NLIMB + len(plan.mull_fix),
            "norm1": plan.norm_wide * NLIMB + len(plan.norm_fix),
        }
    if curve == "secp256k1":
        if carry_mode == "eager":
            return {
                "mul": 3 * (2 * NLIMB + 1) + 2 * (NLIMB + 4)
                + SECP_MUL_TAIL_ROUNDS * NLIMB,
                "addsub": SECP_ADD_ROUNDS * NLIMB,
                "mul_small": SECP_MUL_SMALL_ROUNDS * NLIMB,
            }
        plan = derive_carry_plan(curve)
        norm1 = plan.norm_wide * NLIMB + len(plan.norm_fix)
        return {
            "mulF": plan.mid * (NLIMB + 4) + plan.mulf_wide * NLIMB
            + len(plan.mulf_fix),
            "mulL": plan.mid * (NLIMB + 4) + plan.mull_wide * NLIMB
            + len(plan.mull_fix),
            "norm1": norm1,
            "mul_small": norm1,
        }
    raise ValueError(curve)


def carry_cost_model(curve: str = "ed25519", carry_mode: str = "lazy") -> dict:
    """Per-signature carry-round cost in row-slots (see module comment).
    Composition mirrors the Pallas kernels: 64 windows of 4 doubles + 1
    niels madd + 1 table add for ed25519 (plus table build, cached-table
    conversion under lazy, and the 265-mul inversion); 64 windows of 6
    RCB16 adds for secp256k1 (plus the 15-add table and the inversion-free
    projective epilogue)."""
    if carry_mode not in CARRY_MODES:
        raise ValueError(f"carry mode must be one of {CARRY_MODES}, got {carry_mode!r}")
    costs = _carry_op_costs(curve, carry_mode)

    def op(mix):
        return sum(costs[k] * n for k, n in mix.items())

    mul1 = costs["mulF" if carry_mode == "lazy" else "mul"]
    if curve == "ed25519":
        mix = _ED_POINT_MIX[carry_mode]
        point = {name: op(m) for name, m in mix.items()}
        window = 4 * point["pt_double"] + point["pt_madd"] + point["pt_add_cached"]
        table = (mul1 + 7 * point["pt_double"] + 7 * point["pt_add_ext"]
                 + 16 * point["niels_convert"])
        inv = 265 * mul1
        per_sig = 64 * window + table + inv
        return {
            "curve": curve, "carry_mode": carry_mode, "unit": "row-slots",
            "per_op": costs, "per_point_op": point, "per_window": window,
            "table": table, "inv": inv, "per_signature": per_sig,
        }
    if curve == "secp256k1":
        mix = _SECP_POINT_MIX[carry_mode]
        point = {name: op(m) for name, m in mix.items()}
        window = 6 * point["pt_add"]
        table = 15 * point["pt_add"]
        epilogue = 2 * mul1 + 2 * costs["norm1" if carry_mode == "lazy"
                                        else "addsub"]
        per_sig = 64 * window + table + epilogue
        return {
            "curve": curve, "carry_mode": carry_mode, "unit": "row-slots",
            "per_op": costs, "per_point_op": point, "per_window": window,
            "table": table, "inv": epilogue, "per_signature": per_sig,
        }
    raise ValueError(curve)


# Satellite 1 (executed docstring proofs): the eager round constants above
# must be exactly the minimal counts the bound propagators derive.
_ED_EAGER_DERIVED = derive_eager_rounds("ed25519")
assert _ED_EAGER_DERIVED == {
    "mul_tail": ED_MUL_TAIL_ROUNDS,
    "add": ED_ADD_ROUNDS,
    "sub": ED_ADD_ROUNDS,
}, f"ed25519 eager rounds drifted: derived {_ED_EAGER_DERIVED}"
_SECP_EAGER_DERIVED = derive_eager_rounds("secp256k1")
assert _SECP_EAGER_DERIVED == {
    "mul_tail": SECP_MUL_TAIL_ROUNDS,
    "add": SECP_ADD_ROUNDS,
    "sub": SECP_ADD_ROUNDS,
    "mul_small": SECP_MUL_SMALL_ROUNDS,
}, f"secp256k1 eager rounds drifted: derived {_SECP_EAGER_DERIVED}"
