"""Batched secp256k1 ECDSA verification as a JAX kernel — the second
BatchVerifier backend (BASELINE config "secp256k1 validator set"; the
reference verifies serially via btcec at crypto/secp256k1/secp256k1.go:140).

Same TPU-first skeleton as ops/ed25519_verify:

  * field arithmetic over p = 2^256 - 2^32 - 977 in 20 radix-2^13 uint32
    limbs (32-bit lanes, no u64 multiplies). The wraparound here is
    two-term: 2^260 ≡ 2^36 + 15632 (mod p), so a carry c out of limb 19
    folds as (c << 10) into limb 2 plus c·15632 into limb 0 — both far
    inside a 32-bit lane;
  * ONE branchless double-scalar ladder computes u1·G + u2·Q using the
    Renes–Costello–Batina COMPLETE addition law for a=0 short-Weierstrass
    curves (2016/1054 algorithm 7; b3 = 3·7 = 21). Complete = identity and
    doubling need no special cases, so the whole 256-iteration ladder is a
    single lax.fori_loop with pt_select, exactly like the ed25519 kernel;
  * host prologue (cheap): strict-DER parse + low-s check, w = s⁻¹ mod n,
    u1/u2, pubkey decompression with an LRU cache;
  * accept check: affine x ≡ r (mod n) done in limb space — x == r or
    x == r+n (the only two representatives below p), Z == 0 rejects.

Accept/reject is bit-exact with crypto/secp256k1.verify (the host oracle).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tendermint_tpu.crypto import secp256k1 as _s
from tendermint_tpu.ops import fe_common as _fc

P = _s.P
N = _s.N
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1
NBITS = 256

# 2^260 mod p = 2^4 · (2^32 + 977) = 2^36 + 15632
FOLD_SMALL = 15632  # lands at the same limb
FOLD_SHIFT = 10  # 2^36 = 2^10 · 2^26 → (c << 10) two limbs up
B3 = 21  # 3·b for b = 7


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMB)], dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    return sum(int(v) << (BITS * i) for i, v in enumerate(np.asarray(limbs)))


# Wide zero for fe_sub, derived in fe_common so its limbs provably
# dominate the eager closed set (a hand-floored 2*MASK constant does not:
# the wrap fold carries limb 0 past it — see fe_common._dominating_ksub)
_K_SUB = np.asarray(_fc.SECP_KSUB_LIMBS, dtype=np.uint32)
assert limbs_to_int(_K_SUB) % P == 0

_GX_L = int_to_limbs(GX)
_GY_L = int_to_limbs(GY)

# bits of p-2 (MSB first) for Fermat inversion
_P2_BITS = np.array([(P - 2) >> i & 1 for i in reversed(range(256))], dtype=np.uint32)


# ---------------------------------------------------------------------------
# Field ops (see ed25519_verify for the layout discipline)
# ---------------------------------------------------------------------------


def fe_carry(x: jnp.ndarray, rounds: int = 4) -> jnp.ndarray:
    for _ in range(rounds):
        c = x >> BITS
        top = c[..., -1]
        x = (
            (x & MASK)
            .at[..., 1:]
            .add(c[..., :-1])
            .at[..., 0]
            .add(top * FOLD_SMALL)
            .at[..., 2]
            .add(top << FOLD_SHIFT)
        )
    return x


def fe_add(a, b):
    # rounds=3: the 2^260 fold reinjects c·15632 at limb 0 and c<<10 at
    # limb 2, so two rounds can leave limbs ~3·MASK — enough for 20-term
    # product columns in fe_mul to overflow 32 bits on rare inputs
    return fe_carry(a + b, rounds=3)


def fe_sub(a, b):
    return fe_carry(a + _K_SUB - b, rounds=3)


# Limb-multiplier backend, same trace-time mechanism as ed25519_verify:
# "mxu" swaps only the column computation for fe_common.mul_columns_batch
# (4 uint8-plane matmuls, split=8 — secp's carried limb 0 can exceed the
# int8 plane bound; see fe_common._columns_mxu_rows). Set exclusively by
# _compiled_kernel's wrapper; the jit cache is keyed on it.
_FE_BACKEND = "vpu"

# Carry schedule for the ladder's pt_add chain — swapped trace-time via
# fe_common.trace_with_modes exactly like _FE_BACKEND; the module-level
# fe_mul/fe_add/fe_sub/fe_mul_small stay the eager ops regardless.
_CARRY_MODE = "eager"

_PLAN = _fc.derive_carry_plan("secp256k1")
_KD_SUB = np.asarray(_PLAN.kd, dtype=np.uint32)


def fe_mul(a, b):
    """Bounds (limbs of carried inputs ≤ M = 13000, columns ≤ 20·M² < 2^32):

    The product occupies rows 0..38; carries ripple one row per round, so
    THREE rounds need rows out to 40 — a 40-limb buffer would silently drop
    the carry out of row 39 (≈2^520-weight value loss; miscomputed ~20% of
    near-bound products before this was widened). After 3 rounds: rows ≤
    MASK + ~50, row 39 ≤ ~50, row 40 = 0-or-tiny, nothing dropped.

    Fold rows 20..40 (v·2^(260+13j) ≡ v·2^13j·(2^36+15632)): the shift
    lands 2 rows up, so the temp needs 24 rows (fold touches ≤ row 22);
    temp rows ≤ 8191 + 8241·15632 + 8241·1024 < 1.4e8. Two carry rounds
    leave rows ≤ ~8200 and reach at most row 23 (no carry out of the last
    row: it is ≤ 6 after round 1). The 4 tail rows then fold scalar-wise
    into lo with FULL values (≤ 8200·15632 < 2^27 — nothing masked away).
    """
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    if _FE_BACKEND != "vpu":
        prod = _fc.mul_columns_batch(a, b, 2 * NLIMB + 1, split=8)
    else:
        prod = jnp.zeros(shape + (2 * NLIMB + 1,), dtype=jnp.uint32)
        for i in range(NLIMB):
            prod = prod.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    for _ in range(3):
        c = prod >> BITS
        prod = (prod & MASK).at[..., 1:].add(c[..., :-1])
    hi = prod[..., NLIMB:]  # 21 rows
    tmp = jnp.zeros(shape + (NLIMB + 4,), dtype=jnp.uint32)
    tmp = tmp.at[..., :NLIMB].set(prod[..., :NLIMB])
    tmp = tmp.at[..., : NLIMB + 1].add(hi * FOLD_SMALL)
    tmp = tmp.at[..., 2 : NLIMB + 3].add(hi << FOLD_SHIFT)
    for _ in range(2):
        c = tmp >> BITS
        tmp = (tmp & MASK).at[..., 1:].add(c[..., :-1])
    lo = tmp[..., :NLIMB]
    for t_idx in range(4):
        t = tmp[..., NLIMB + t_idx]
        lo = lo.at[..., t_idx].add(t * FOLD_SMALL)
        lo = lo.at[..., t_idx + 2].add(t << FOLD_SHIFT)
    return fe_carry(lo, rounds=5)


def fe_sq(a):
    return fe_mul(a, a)


def fe_mul_small(a, k: int):
    return fe_carry(a * jnp.uint32(k), rounds=4)


# --- deferred-carry (lazy) ops: batch-leading twins of the Pallas row ops,
# used by pt_add when _CARRY_MODE == "lazy".  Operand classes and round
# counts come from fe_common.derive_carry_plan (certified at import).


def _lazy_mul_cols(a, b):
    if _FE_BACKEND != "vpu":
        return _fc.mul_columns_batch(a, b, 2 * NLIMB + 1, split=8)
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    prod = jnp.zeros(shape + (2 * NLIMB + 1,), dtype=jnp.uint32)
    for i in range(NLIMB):
        prod = prod.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    return prod


def _lazy_mul(a, b, wide, fix):
    tmp = _fc.secp_fold_fused_batch(_lazy_mul_cols(a, b))
    for _ in range(_PLAN.mid):
        tmp = _fc.carry_drop_top_batch(tmp)
    lo = _fc.secp_fold2_batch(tmp)
    for _ in range(wide):
        lo = _fc.wide_carry_batch(lo, _fc.SECP_WRAP)
    return _fc.fix_batch(lo, fix)


def fe_mul_f(a, b):
    """Full lazy multiply — output lands in the certified class C."""
    return _lazy_mul(a, b, _PLAN.mulf_wide, _PLAN.mulf_fix)


def fe_mul_l(a, b):
    """Lazy multiply whose output stays in class D."""
    return _lazy_mul(a, b, _PLAN.mull_wide, _PLAN.mull_fix)


def fe_norm1(raw):
    """One wide round + fixups: raw limb sum -> class C."""
    return _fc.fix_batch(_fc.wide_carry_batch(raw, _fc.SECP_WRAP),
                         _PLAN.norm_fix)


def fe_add_l(a, b):
    return fe_norm1(a + b)


def fe_sub_l(a, b):
    # always against the class-D wide zero: dominates class-C operands too
    return fe_norm1(a + _KD_SUB - b)


def fe_mul_small_l(a, k: int):
    return fe_norm1(a * jnp.uint32(k))


def fe_inv(z):
    def body(acc, bit):
        acc = fe_sq(acc)
        acc = jnp.where(bit.astype(bool), fe_mul(acc, z), acc)
        return acc, None

    one = jnp.zeros_like(z).at[..., 0].set(1)
    acc, _ = lax.scan(body, one, jnp.asarray(_P2_BITS))
    return acc


def fe_canonical(x):
    """Fully reduce a carried fe into [0, p)."""

    def seq_carry(v):
        for i in range(NLIMB - 1):
            c = v[..., i] >> BITS
            v = v.at[..., i].set(v[..., i] & MASK).at[..., i + 1].add(c)
        return v

    def fold_top(v):
        # bits ≥ 256 live in limb 19 at offset 9; 2^256 ≡ 2^32 + 977
        q = v[..., NLIMB - 1] >> 9
        v = v.at[..., NLIMB - 1].set(v[..., NLIMB - 1] & 0x1FF)
        # 2^32 = 2^6·2^26 → (q << 6) at limb 2;  977·q at limb 0
        return v.at[..., 0].add(q * 977).at[..., 2].add(q << 6)

    x = fe_carry(x, rounds=2)
    for _ in range(3):
        x = fold_top(seq_carry(x))
    x = seq_carry(x)  # now x < 2^256
    # conditional subtract p: t = x + (2^256 - p); if t ≥ 2^256 then x-p
    t = x.at[..., 0].add(977).at[..., 2].add(1 << 6)
    t = seq_carry(t)
    ge = (t[..., NLIMB - 1] >> 9) > 0
    t = t.at[..., NLIMB - 1].set(t[..., NLIMB - 1] & 0x1FF)
    return jnp.where(ge[..., None], t, x)


# ---------------------------------------------------------------------------
# Complete point addition, projective (X:Y:Z), a=0 (RCB16 algorithm 7)
# ---------------------------------------------------------------------------


def pt_add(p, q):
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    if _CARRY_MODE == "lazy":
        # deferred carries: coordinates stay in class C, the 12 operand
        # products ride as class D between single-round norm1 folds; only
        # the Z1·Z2 product (feeding fe_mul_small) runs the full schedule
        t0 = fe_mul_l(X1, X2)
        t1 = fe_mul_l(Y1, Y2)
        t2 = fe_mul_f(Z1, Z2)
        t3 = fe_sub_l(fe_mul_l(fe_add_l(X1, Y1), X2 + Y2), t0 + t1)
        t4 = fe_sub_l(fe_mul_l(fe_add_l(Y1, Z1), Y2 + Z2), t1 + t2)
        X3 = fe_mul_l(fe_add_l(X1, Z1), X2 + Z2)
        Y3 = fe_sub_l(X3, t0 + t2)
        t0x3 = fe_add_l(t0 + t0, t0)
        t2b = fe_mul_small_l(t2, B3)
        Z3 = fe_add_l(t1, t2b)
        t1 = fe_sub_l(t1, t2b)
        Y3b = fe_mul_small_l(Y3, B3)
        X3 = fe_sub_l(fe_mul_l(t3, t1), fe_mul_l(t4, Y3b))
        Y3 = fe_add_l(fe_mul_l(Y3b, t0x3), fe_mul_l(t1, Z3))
        Z3 = fe_add_l(fe_mul_l(Z3, t4), fe_mul_l(t0x3, t3))
        return X3, Y3, Z3
    t0 = fe_mul(X1, X2)
    t1 = fe_mul(Y1, Y2)
    t2 = fe_mul(Z1, Z2)
    t3 = fe_mul(fe_add(X1, Y1), fe_add(X2, Y2))
    t3 = fe_sub(t3, fe_add(t0, t1))
    t4 = fe_mul(fe_add(Y1, Z1), fe_add(Y2, Z2))
    t4 = fe_sub(t4, fe_add(t1, t2))
    X3 = fe_mul(fe_add(X1, Z1), fe_add(X2, Z2))
    Y3 = fe_sub(X3, fe_add(t0, t2))
    t0x3 = fe_add(fe_add(t0, t0), t0)
    t2b = fe_mul_small(t2, B3)
    Z3 = fe_add(t1, t2b)
    t1 = fe_sub(t1, t2b)
    Y3b = fe_mul_small(Y3, B3)
    X3 = fe_sub(fe_mul(t3, t1), fe_mul(t4, Y3b))
    Y3 = fe_add(fe_mul(Y3b, t0x3), fe_mul(t1, Z3))
    Z3 = fe_add(fe_mul(Z3, t4), fe_mul(t0x3, t3))
    return X3, Y3, Z3


def pt_select(cond, p, q):
    c = cond[..., None]
    return tuple(jnp.where(c, a, b) for a, b in zip(p, q))


# ---------------------------------------------------------------------------
# Verify kernel
# ---------------------------------------------------------------------------


def _get_bit(words: jnp.ndarray, i) -> jnp.ndarray:
    w = lax.dynamic_slice_in_dim(words, i // 32, 1, axis=-1)[..., 0]
    return (w >> (i % 32).astype(jnp.uint32)) & jnp.uint32(1)


def _verify_kernel(qx, qy, u1_words, u2_words, r_limbs, rn_limbs, rn_ok):
    """R = u1·G + u2·Q;  accept iff Z≠0 and x(R) ∈ {r, r+n} (mod p).

    qx, qy      : (..., 20) affine pubkey limbs
    u1/u2_words : (..., 8) uint32 LE bit-packed scalars
    r_limbs     : (..., 20) canonical r
    rn_limbs    : (..., 20) canonical r+n (only meaningful where rn_ok)
    rn_ok       : (...) bool — r+n < p
    """
    batch = qx.shape[:-1]
    one = jnp.zeros(batch + (NLIMB,), jnp.uint32).at[..., 0].set(1)
    zero = jnp.zeros(batch + (NLIMB,), jnp.uint32)

    g_pt = (
        jnp.broadcast_to(jnp.asarray(_GX_L), batch + (NLIMB,)),
        jnp.broadcast_to(jnp.asarray(_GY_L), batch + (NLIMB,)),
        one,
    )
    q_pt = (qx, qy, one)

    def body(t, acc):
        i = NBITS - 1 - t
        acc = pt_add(acc, acc)  # complete law doubles too
        with_g = pt_add(acc, g_pt)
        acc = pt_select(_get_bit(u1_words, i).astype(bool), with_g, acc)
        with_q = pt_add(acc, q_pt)
        acc = pt_select(_get_bit(u2_words, i).astype(bool), with_q, acc)
        return acc

    ident = (zero, one, zero)  # (0:1:0)
    X, _, Z = lax.fori_loop(0, NBITS, body, ident)

    z_can = fe_canonical(Z)
    nonzero = jnp.any(z_can != 0, axis=-1)
    x_aff = fe_canonical(fe_mul(X, fe_inv(Z)))
    eq_r = jnp.all(x_aff == r_limbs, axis=-1)
    eq_rn = jnp.all(x_aff == rn_limbs, axis=-1) & rn_ok
    return nonzero & (eq_r | eq_rn)


_kernel_cache: dict = {}


def _compiled_kernel(batch: int, mesh=None, fe_backend: str = "vpu",
                     carry_mode: str = "eager"):
    carry_mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    if fe_backend not in ("vpu", "mxu"):
        fe_backend = "mxu" if fe_backend == "mxu16" else "vpu"
    key = (batch, mesh, fe_backend, carry_mode)
    fn = _kernel_cache.get(key)
    if fn is None:
        kernel = _fc.trace_with_modes(
            sys.modules[__name__], _verify_kernel, fe_backend, carry_mode
        )
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            data = NamedSharding(mesh, PS(mesh.axis_names[0]))
            fn = jax.jit(kernel, in_shardings=(data,) * 7, out_shardings=data)
        else:
            fn = jax.jit(kernel)
        _kernel_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Host prologue
# ---------------------------------------------------------------------------

_decompress_cache: dict = {}
_DECOMPRESS_CACHE_MAX = 1 << 16


def _decompress_cached(pub: bytes):
    hit = _decompress_cache.get(pub, False)
    if hit is not False:
        return hit
    xy = _s.decompress_pubkey(pub)
    if xy is None:
        out = None
    else:
        out = (int_to_limbs(xy[0]), int_to_limbs(xy[1]))
    if len(_decompress_cache) >= _DECOMPRESS_CACHE_MAX:
        _decompress_cache.clear()
    _decompress_cache[pub] = out
    return out


def _scalar_words(x: int) -> np.ndarray:
    return np.frombuffer(x.to_bytes(32, "little"), dtype="<u4").astype(np.uint32)


def _bucket(n: int, mesh=None) -> int:
    """Pad batches to power-of-two buckets (min 32) so the jit cache covers
    every small batch with ONE compilation — the 256-iteration ladder is
    expensive to compile and padding rows are nearly free to execute.
    With a mesh, the bucket must also divide across the batch axis."""
    if n <= 4096:
        b = 32
        while b < n:
            b <<= 1
    else:
        b = ((n + 4095) // 4096) * 4096
    if mesh is not None:
        m = int(np.prod(mesh.devices.shape))
        b = ((b + m - 1) // m) * m
    return b


def prep_item(pubkey: bytes, digest: bytes, sig: bytes):
    """Host prologue for ONE signature: strict-DER parse + range/low-s
    checks, w = s⁻¹ mod n, scalars, cached decompression. Returns either
    ("forced", 0|1) for host-decided items or
    ("kernel", (qx, qy), u1, u2, r) for device verification. Shared by the
    XLA kernel and the Pallas pipeline so accept/reject can never drift."""
    Q = _decompress_cached(pubkey)
    parsed = _s.der_decode_sig(sig)
    if Q is None or parsed is None:
        return ("forced", 0)
    r, s = parsed
    if not (0 < r < N and 0 < s < N) or s > _s._HALF_N:
        return ("forced", 0)
    e = int.from_bytes(digest, "big")
    w = pow(s, N - 2, N)
    u1 = e * w % N
    u2 = r * w % N
    if u1 == 0 or u2 == 0:
        # ladder degenerates to single-scalar — host decides (never
        # happens for honestly generated signatures)
        return ("forced", int(_s.verify(pubkey, digest, sig)))
    return ("kernel", Q, u1, u2, r)


def verify_batch(
    pubkeys: Sequence[bytes],
    digests: Sequence[bytes],
    sigs: Sequence[bytes],
    mesh=None,
    fe_backend: str = "vpu",
    carry_mode: str = "lazy",
) -> np.ndarray:
    """Batched ECDSA verify; bit-exact with crypto/secp256k1.verify.
    pubkeys: 33-byte compressed; digests: 32 bytes; sigs: DER.
    fe_backend: "vpu" | "mxu" limb multiplier ("mxu16" degrades to "mxu");
    carry_mode "lazy" (default) defers limb carries between point ops,
    "eager" keeps the full per-op ripple — verdicts are bit-exact both ways."""
    fe_backend = _fc.normalize_backend(fe_backend)
    carry_mode = _fc.normalize_carry_mode(carry_mode)
    n = len(pubkeys)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    b = _bucket(n, mesh)

    qx = np.zeros((b, NLIMB), np.uint32)
    qy = np.zeros((b, NLIMB), np.uint32)
    u1w = np.zeros((b, 8), np.uint32)
    u2w = np.zeros((b, 8), np.uint32)
    rl = np.zeros((b, NLIMB), np.uint32)
    rnl = np.zeros((b, NLIMB), np.uint32)
    rn_ok = np.zeros((b,), bool)
    # -1 = decided on device, else the host-decided 0/1
    forced = np.full((b,), -1, np.int8)

    for i in range(n):
        item = prep_item(bytes(pubkeys[i]), bytes(digests[i]), bytes(sigs[i]))
        if item[0] == "forced":
            forced[i] = item[1]
            continue
        _, Q, u1, u2, r = item
        qx[i], qy[i] = Q
        u1w[i] = _scalar_words(u1)
        u2w[i] = _scalar_words(u2)
        rl[i] = int_to_limbs(r)
        if r + N < P:
            rnl[i] = int_to_limbs(r + N)
            rn_ok[i] = True

    kernel = _compiled_kernel(b, mesh, fe_backend, carry_mode)
    host = (qx, qy, u1w, u2w, rl, rnl, rn_ok)
    if mesh is not None:
        # device_put the *numpy* arrays straight onto the mesh sharding: an
        # intermediate jnp.asarray would commit them to the default backend
        # (possibly a real TPU) even though the mesh lives on CPU devices —
        # the round-3 multichip dryrun regression.
        from jax.sharding import NamedSharding, PartitionSpec as PS

        sh = NamedSharding(mesh, PS(mesh.axis_names[0]))
        args = [jax.device_put(a, sh) for a in host]
    else:
        args = [jnp.asarray(a) for a in host]
    ok = np.asarray(kernel(*args))[:n]

    f = forced[:n]
    return np.where(f >= 0, f.astype(bool), ok)
