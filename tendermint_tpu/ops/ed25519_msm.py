"""One MSM per window: device-side random-linear-combination verification.

The ladder kernels (ops/ed25519_verify.py, ops/ed25519_pallas.py) pay a full
253-bit double-scalar ladder per signature (~3,850 fe_mul).  PR 14's host
``crypto.ed25519.verify_batch`` proved the random-linear-combination
alternative bit-identical at ~110 point-op equivalents per signature: accept
the whole batch iff

    [sum z_i s_i]B  +  sum_i [(z_i h_i) mod L](-A_i)  +  sum_i [z_i](-R_i)
        ==  identity

with fresh 128-bit z_i (a false accept needs a 2^-128 collision; a clean
batch can never falsely reject — the equation is exact).  This module is the
device port: ONE Pippenger multi-scalar multiplication over the whole window,
built from the batch-leading lazy-carry point ops of ops/ed25519_verify.py,
with the ``[s_b]B`` term folded off the precomputed B-window niels table
(ops/ed25519_pallas._build_b_niels).

Making Pippenger jit-shaped
---------------------------

Pippenger's bucket accumulation is a data-dependent segmented reduction —
the digit of each (scalar, point) pair decides which bucket its point sums
into.  The host resolves all data dependence into *index schedules* so the
device graph is static:

  * pool: ``(R0, 4, 20)`` extended points, row 0 = identity, rows 1..2n =
    the -A_i / -R_i columns (Z = 1, fully carried limbs);
  * tree levels: level l is ONE batched ``pt_add(prev[ia], prev[ib])`` over
    the previous level's array (level 0 = the pool).  Entries of the same
    bucket pair up within their segment; an odd leftover passes through
    paired with the identity row 0 (the complete addition law makes
    P + identity a projective scaling of P); a segment that reaches size 1
    "finalizes" and stays parked in that level's array;
  * bucket grid: one gather from the concatenation [pool, lvl1..lvlT] with
    host-computed global indices (empty buckets gather the identity row 0);
  * bucket-weighted fold: ``lax.fori_loop`` over digits 2^c-1..1, running
    the classic run/acc double accumulation at width W (one lane per
    window) — fori keeps the XLA graph small (unrolled carry graphs explode
    XLA CPU compile times; see ed25519_pallas.ladder_math);
  * window fold: Horner from the top window — c doubles + 1 add per step;
  * ``[s_b]B``: 64 MSB-first 4-bit digits against the niels table
    (4 doubles + 1 mixed add per digit), then one final add and a
    projective identity check (canonical X == 0 and Y == Z).

Index arrays ride as DYNAMIC jit arguments, so the compile cache keys only
on shapes + (fe_backend, carry_mode); level widths are padded to the
power-of-two/1024 ladder to keep those shapes stable across RLC coefficient
draws.  Scalars are sampled from a seeded ``random.Random`` so the
audit/replay paths stay deterministic.

Localization mirrors the host verifier: an MSM-rejected window re-runs
chunk RLCs (``crypto.ed25519._CHUNK`` = 32) on the host parse, then ships
all dirty-chunk rows to the exact per-row ladder in ONE device dispatch.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from tendermint_tpu.crypto import ed25519 as _ed
from tendermint_tpu.ops import ed25519_verify as _xla
from tendermint_tpu.ops import fe_common as _fc

P = _ed.P
L = _ed.L
NLIMB = _xla.NLIMB

# MSB-first 4-bit digit count of s_b (s_b < L < 2^253; 64 digits = 256 bits)
_SB_WIN = 64

_IDENT_LIMBS = np.zeros((4, NLIMB), dtype=np.uint32)
_IDENT_LIMBS[1, 0] = 1  # (X, Y, Z, T) = (0, 1, 1, 0)
_IDENT_LIMBS[2, 0] = 1

_SB_NIELS = None


def _sb_niels() -> np.ndarray:
    """(16, 3, 20) niels table of [j]B — shared with the Pallas ladder's
    per-window table ([s]B off _build_b_niels; lazy import avoids a module
    cycle, ed25519_pallas imports this module for its RLC entry)."""
    global _SB_NIELS
    if _SB_NIELS is None:
        from tendermint_tpu.ops import ed25519_pallas as _pl

        _SB_NIELS = np.asarray(_pl._B_NIELS, dtype=np.uint32)
    return _SB_NIELS


def _pt_madd(p, ypx, ymx, t2d):
    """Batch-leading mixed add with a niels point (y+x, y-x, 2dxy), Z2 = 1.
    Mirror of ed25519_pallas.pt_madd in the XLA batch layout; the j=0 table
    entry (1, 1, 0) yields p unchanged up to projective scale, so digit 0
    needs no special-casing.  Branches on ops/ed25519_verify's trace-time
    carry-mode global like its pt_add/pt_double."""
    X1, Y1, Z1, T1 = p
    if _xla._CARRY_MODE == "lazy":
        A = _xla.fe_mul_l(_xla.fe_sub_l(Y1, X1), ymx)
        B = _xla.fe_mul_l(Y1 + X1, ypx)
        C = _xla.fe_mul_l(T1, t2d)
        Dv = Z1 + Z1
        E = _xla.fe_sub_l(B, A)
        F = _xla.fe_sub_l(Dv, C)
        G = _xla.fe_add_l(Dv, C)
        H = _xla.fe_add_l(B, A)
        return _xla.fe_mul4_f((E, F), (G, H), (F, G), (E, H))
    A = _xla.fe_mul(_xla.fe_sub(Y1, X1), ymx)
    B = _xla.fe_mul(_xla.fe_add(Y1, X1), ypx)
    C = _xla.fe_mul(T1, t2d)
    Dv = _xla.fe_add(Z1, Z1)
    E = _xla.fe_sub(B, A)
    F = _xla.fe_sub(Dv, C)
    G = _xla.fe_add(Dv, C)
    H = _xla.fe_add(B, A)
    return (_xla.fe_mul(E, F), _xla.fe_mul(G, H),
            _xla.fe_mul(F, G), _xla.fe_mul(E, H))


# ---------------------------------------------------------------------------
# Host-side schedule builder
# ---------------------------------------------------------------------------


def _pad_width(x: int, cap: int = 1024, floor: int = 8) -> int:
    """Power-of-two up to ``cap`` then cap-multiples — level widths stay on a
    small shape ladder so the jit cache is stable across RLC draws."""
    b = floor
    while b < x and b < cap:
        b *= 2
    if x <= b:
        return b
    return ((x + cap - 1) // cap) * cap


def _digit_matrix(scalars: Sequence[int], c: int, nwin: int) -> np.ndarray:
    """(m, nwin) c-bit digit matrix, LSB window first, vectorized."""
    m = len(scalars)
    nbytes = (nwin * c + 7) // 8
    buf = np.frombuffer(
        b"".join(int(k).to_bytes(nbytes, "little") for k in scalars), np.uint8
    ).reshape(m, nbytes)
    bits = np.unpackbits(buf, axis=1, bitorder="little")[:, : nwin * c]
    w = 1 << np.arange(c, dtype=np.uint32)
    return bits.reshape(m, nwin, c).astype(np.uint32) @ w


def _bucket_c(m: int) -> int:
    """Pippenger window width from the pair count — the host _msm ladder."""
    return 4 if m < 32 else 5 if m < 128 else 6 if m < 512 else 7 if m < 2048 else 8


class _Schedule:
    """Device-ready index schedules for one MSM (all host numpy)."""

    __slots__ = ("c", "nwin", "ias", "ibs", "bkt")

    def __init__(self, c, nwin, ias, ibs, bkt):
        self.c = c
        self.nwin = nwin
        self.ias = ias  # [(M_l,) int32] per tree level, indices into level l-1
        self.ibs = ibs
        self.bkt = bkt  # (nwin, 2^c - 1) int32 into [pool, lvl1..lvlT]


def _build_schedule(digits: np.ndarray, pool_rows: int, c: int) -> _Schedule:
    """Resolve the bucket segmented reduction into per-level pair indices.

    ``digits`` is the (m, nwin) matrix of pair digits; pair j's point lives
    at pool row j+1 (row 0 is the identity).  Returns level schedules whose
    row 0 is always the (0, 0) identity anchor that odd leftovers and pad
    rows pair against."""
    m, nwin = digits.shape
    nb = (1 << c) - 1
    pj, pw = np.nonzero(digits)
    dg = digits[pj, pw].astype(np.int64)
    bucket = pw.astype(np.int64) * nb + (dg - 1)
    order = np.argsort(bucket, kind="stable")
    bucket = bucket[order]
    src = (pj[order] + 1).astype(np.int64)
    ub, seg_start = np.unique(bucket, return_index=True)
    seg_sizes = np.diff(np.append(seg_start, len(bucket)))

    finalized: dict = {}
    active: List[Tuple[int, List[int]]] = []
    for si in range(len(ub)):
        mem = src[seg_start[si]: seg_start[si] + seg_sizes[si]].tolist()
        if len(mem) == 1:
            finalized[si] = (0, mem[0])  # lives in the pool
        else:
            active.append((si, mem))

    ias: List[np.ndarray] = []
    ibs: List[np.ndarray] = []
    lvl = 0
    while active:
        lvl += 1
        ia = [0]
        ib = [0]
        nxt = []
        for si, mem in active:
            new_rows = []
            for k in range(0, len(mem) - 1, 2):
                new_rows.append(len(ia))
                ia.append(mem[k])
                ib.append(mem[k + 1])
            if len(mem) % 2:
                # odd leftover rides through paired with the identity row
                new_rows.append(len(ia))
                ia.append(mem[-1])
                ib.append(0)
            if len(new_rows) == 1:
                finalized[si] = (lvl, new_rows[0])
            else:
                nxt.append((si, new_rows))
        width = _pad_width(len(ia))
        ia += [0] * (width - len(ia))
        ib += [0] * (width - len(ib))
        ias.append(np.asarray(ia, np.int32))
        ibs.append(np.asarray(ib, np.int32))
        active = nxt

    # global row offsets of each level inside the device concat
    offs = [pool_rows]
    for a in ias[:-1]:
        offs.append(offs[-1] + len(a))
    bkt = np.zeros((nwin, nb), np.int64)  # 0 = identity (empty bucket)
    for si, b in enumerate(ub):
        w, dm1 = divmod(int(b), nb)
        flvl, frow = finalized[si]
        bkt[w, dm1] = frow if flvl == 0 else offs[flvl - 1] + frow
    return _Schedule(c, nwin, ias, ibs, bkt.astype(np.int32))


# ---------------------------------------------------------------------------
# The device kernel
# ---------------------------------------------------------------------------


def _unpack(a):
    return tuple(a[..., k, :] for k in range(4))


def _pack(p):
    return jnp.stack(p, axis=-2)


def _msm_kernel(pool, ias, ibs, bkt_idx, sb_digs):
    """pool (R0, 4, 20) uint32; ias/ibs lists of (M_l,) int32; bkt_idx
    (nwin, 2^c - 1) int32 global rows; sb_digs (64,) uint32 MSB-first 4-bit
    digits of s_b.  Returns a () bool window verdict."""
    d2 = jnp.asarray(_xla._D2_LIMBS)
    nwin, nb = bkt_idx.shape
    c = (nb + 1).bit_length() - 1

    # segmented pairwise-reduction tree: one batched pt_add per level
    levels = [pool]
    prev = pool
    for ia, ib in zip(ias, ibs):
        prev = _pack(_xla.pt_add(_unpack(prev[ia]), _unpack(prev[ib]), d2))
        levels.append(prev)
    allrows = jnp.concatenate(levels, axis=0) if len(levels) > 1 else pool
    grid = allrows[bkt_idx]  # (nwin, nb, 4, 20)

    # bucket-weighted fold at width nwin: acc = sum_d d * bucket[d] via the
    # classic descending run/acc double accumulation
    ident_w = jnp.broadcast_to(jnp.asarray(_IDENT_LIMBS), (nwin, 4, NLIMB))

    def bucket_body(t, carry):
        run, acc = carry
        g = lax.dynamic_index_in_dim(grid, nb - 1 - t, axis=1, keepdims=False)
        run = _pack(_xla.pt_add(_unpack(run), _unpack(g), d2))
        acc = _pack(_xla.pt_add(_unpack(acc), _unpack(run), d2))
        return run, acc

    _, acc = lax.fori_loop(0, nb, bucket_body, (ident_w, ident_w))

    # Horner over the windows, top first: c doubles + 1 add per step (the
    # doubles are their own nested fori — one pt_double graph, not c copies:
    # unrolled carry graphs explode XLA CPU compile, see ladder_math)
    def dbl(_, p):
        return _xla.pt_double(p)

    tot = _unpack(lax.dynamic_slice_in_dim(acc, nwin - 1, 1, axis=0))

    def win_body(t, tot):
        tot = lax.fori_loop(0, c, dbl, tot)
        g = lax.dynamic_slice_in_dim(acc, nwin - 2 - t, 1, axis=0)
        return _xla.pt_add(tot, _unpack(g), d2)

    tot = lax.fori_loop(0, nwin - 1, win_body, tot)

    # [s_b]B off the niels window table: 4 doubles + 1 mixed add per digit
    nt = jnp.asarray(_sb_niels())
    ident1 = _unpack(jnp.asarray(_IDENT_LIMBS)[None])

    def sb_body(t, sb):
        sb = lax.fori_loop(0, 4, dbl, sb)
        ent = nt[lax.dynamic_index_in_dim(sb_digs, t, keepdims=False)]
        return _pt_madd(sb, ent[0][None], ent[1][None], ent[2][None])

    sb = lax.fori_loop(0, _SB_WIN, sb_body, ident1)

    X, Y, Z, _ = _xla.pt_add(tot, sb, d2)
    xc = _xla.fe_canonical(X)
    return (jnp.all(xc == 0)
            & jnp.all(_xla.fe_canonical(Y) == _xla.fe_canonical(Z)))


_msm_cache: dict = {}


def _compiled_msm(fe_backend: str, carry_mode: str):
    """One jitted kernel per (fe_backend, carry_mode) — jax.jit's own cache
    keys the shape side (pool width, level widths, window count), so index
    schedules ride as dynamic arguments without retraces."""
    carry_mode = _fc.effective_carry_mode(fe_backend, carry_mode)
    if fe_backend not in ("vpu", "mxu"):
        fe_backend = "mxu" if fe_backend == "mxu16" else "vpu"
    key = (fe_backend, carry_mode)
    fn = _msm_cache.get(key)
    if fn is None:
        fn = jax.jit(_fc.trace_with_modes(_xla, _msm_kernel,
                                          fe_backend, carry_mode))
        _msm_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Host driver: one window RLC + chunk/ladder localization
# ---------------------------------------------------------------------------


def _device_rlc(rows, rng, fe_backend: str, carry_mode: str) -> bool:
    """One RLC over parsed rows [(neg_a, neg_r, h, s), ...] (extended-point
    int tuples) as a single device MSM dispatch.  z_i are drawn from ``rng``
    (seeded upstream — deterministic replay)."""
    n = len(rows)
    m = 2 * n
    c = _bucket_c(m)
    nwin = (253 + c - 1) // c
    s_b = 0
    scalars: List[int] = []
    pts = []
    for neg_a, neg_r, h, s in rows:
        z = rng.getrandbits(128) or 1
        s_b = (s_b + z * s) % L
        scalars.append((z * h) % L)
        pts.append(neg_a)
        scalars.append(z)
        pts.append(neg_r)
    digits = _digit_matrix(scalars, c, nwin)
    pool_rows = _pad_width(m + 1)
    sched = _build_schedule(digits, pool_rows, c)

    pool = np.zeros((pool_rows, 4, NLIMB), np.uint32)
    pool[0] = _IDENT_LIMBS
    for j, (x, y, _, t) in enumerate(pts):
        pool[j + 1, 0] = _xla.int_to_limbs(x)
        pool[j + 1, 1] = _xla.int_to_limbs(y)
        pool[j + 1, 2, 0] = 1
        pool[j + 1, 3] = _xla.int_to_limbs(t)
    sb_digs = np.asarray(
        [(s_b >> (4 * (_SB_WIN - 1 - t))) & 15 for t in range(_SB_WIN)],
        np.uint32,
    )
    fn = _compiled_msm(fe_backend, carry_mode)
    ok = fn(
        jnp.asarray(pool),
        [jnp.asarray(a) for a in sched.ias],
        [jnp.asarray(b) for b in sched.ibs],
        jnp.asarray(sched.bkt),
        jnp.asarray(sb_digs),
    )
    return bool(ok)


def _chunk_rlc_holds(chunk, rng) -> bool:
    """Seeded host chunk RLC (crypto.ed25519._rlc_holds with our rng): the
    localization sweep stays cheap — 32-row Pippenger on the host — and
    deterministic under the window seed."""
    s_b = 0
    pairs = []
    for _, neg_a, neg_r, h, s in chunk:
        z = rng.getrandbits(128) or 1
        s_b = (s_b + z * s) % L
        pairs.append(((z * h) % L, neg_a))
        pairs.append((z, neg_r))
    acc = _ed._msm(pairs)
    return _ed._is_identity(_ed.pt_add(acc, _ed._mul_b(s_b)))


def rlc_resolve(
    parsed: list,
    out: list,
    ladder_fn: Callable[[List[int]], np.ndarray],
    *,
    seed: int,
    fe_backend: str = "vpu",
    carry_mode: str = "lazy",
) -> None:
    """Verdict strategy for one window: device MSM accept-all on the clean
    path; on reject, host chunk RLCs (_CHUNK=32) localize the dirty spans
    and their rows ship to ``ladder_fn`` (the exact per-row device ladder)
    in ONE dispatch.  ``parsed``/``out`` as crypto.ed25519._parse_batch;
    mutates ``out`` in place."""
    if not parsed:
        return
    rng = random.Random(seed)
    rows = [(na, nr, h, s) for (_, na, nr, h, s) in parsed]
    if _device_rlc(rows, rng, fe_backend, carry_mode):
        for item in parsed:
            out[item[0]] = True
        return
    dirty: List[int] = []
    for lo in range(0, len(parsed), _ed._CHUNK):
        chunk = parsed[lo: lo + _ed._CHUNK]
        if len(chunk) > 4 and _chunk_rlc_holds(chunk, rng):
            for item in chunk:
                out[item[0]] = True
        else:
            dirty.extend(item[0] for item in chunk)
    if dirty:
        ok = np.asarray(ladder_fn(dirty))
        for j, i in enumerate(dirty):
            out[i] = bool(ok[j])
