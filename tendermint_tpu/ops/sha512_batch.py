"""Vectorized SHA-512 + scalar-field reduction for the ed25519 host prologue.

The reference hashes one vote's sign-bytes at a time inside each serial verify
(`/root/reference/crypto/ed25519/ed25519.go:151` via x/crypto sha512). Here the
whole batch's `h = SHA-512(R || A || M) mod L` is produced with numpy-vectorized
SHA-512 (one (N,) uint64 lane per message, 80 rounds shared) and a vectorized
Barrett reduction in radix-2^13 limbs — no per-signature Python in the hot path
once message lengths are uniform (vote sign-bytes are fixed-size per chain).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

L = (1 << 252) + 27742317777372353535851937790883648493

# SHA-512 round constants (FIPS 180-4).
_K = np.array(
    [
        0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
        0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
        0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
        0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
        0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
        0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
        0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
        0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
        0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
        0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
        0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
        0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
        0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
        0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
        0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
        0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
        0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
        0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
        0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
        0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
    ],
    dtype=np.uint64,
)

_H0 = np.array(
    [
        0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
        0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
    ],
    dtype=np.uint64,
)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    n = np.uint64(n)
    return (x >> n) | (x << (np.uint64(64) - n))


def sha512_batch(data: np.ndarray, lengths: int) -> np.ndarray:
    """SHA-512 of N equal-length messages.

    data: (N, lengths) uint8. Returns (N, 64) uint8 digests.
    """
    n = data.shape[0]
    # pad: 0x80, zeros, 16-byte big-endian bit length
    blocks = (lengths + 1 + 16 + 127) // 128
    padded = np.zeros((n, blocks * 128), dtype=np.uint8)
    padded[:, :lengths] = data
    padded[:, lengths] = 0x80
    bitlen = lengths * 8
    blen = bitlen.to_bytes(16, "big")
    padded[:, -16:] = np.frombuffer(blen, dtype=np.uint8)

    # big-endian 64-bit words: (N, blocks, 16)
    words = padded.reshape(n, blocks, 16, 8)
    w64 = np.zeros((n, blocks, 16), dtype=np.uint64)
    for b in range(8):
        w64 = (w64 << np.uint64(8)) | words[:, :, :, b].astype(np.uint64)

    state = np.broadcast_to(_H0, (n, 8)).copy()
    with np.errstate(over="ignore"):
        for blk in range(blocks):
            w = [w64[:, blk, t] for t in range(16)]
            for t in range(16, 80):
                s0 = _rotr(w[t - 15], 1) ^ _rotr(w[t - 15], 8) ^ (w[t - 15] >> np.uint64(7))
                s1 = _rotr(w[t - 2], 19) ^ _rotr(w[t - 2], 61) ^ (w[t - 2] >> np.uint64(6))
                w.append(w[t - 16] + s0 + w[t - 7] + s1)
            a, b_, c, d, e, f, g, h = (state[:, i].copy() for i in range(8))
            for t in range(80):
                S1 = _rotr(e, 14) ^ _rotr(e, 18) ^ _rotr(e, 41)
                ch = (e & f) ^ (~e & g)
                t1 = h + S1 + ch + _K[t] + w[t]
                S0 = _rotr(a, 28) ^ _rotr(a, 34) ^ _rotr(a, 39)
                maj = (a & b_) ^ (a & c) ^ (b_ & c)
                t2 = S0 + maj
                h, g, f, e, d, c, b_, a = g, f, e, d + t1, c, b_, a, t1 + t2
            for i, v in enumerate((a, b_, c, d, e, f, g, h)):
                state[:, i] += v

    # big-endian bytes out
    out = np.zeros((n, 64), dtype=np.uint8)
    for i in range(8):
        v = state[:, i]
        for b in range(8):
            out[:, 8 * i + b] = ((v >> np.uint64(56 - 8 * b)) & np.uint64(0xFF)).astype(np.uint8)
    return out


# ---------------------------------------------------------------------------
# Barrett reduction of 512-bit digests mod L, radix 2^13, vectorized.
# ---------------------------------------------------------------------------

_BITS = 13
_MASK = (1 << _BITS) - 1
_HL = 40  # 512-bit digest = 40 limbs
_LL = 20  # L < 2^260 = b^20
_QL = 21  # quotient-estimate limb count
_MU = (1 << (_BITS * 2 * _LL)) // L  # floor(b^40 / L), 21 limbs

def _int_limbs(x: int, n: int) -> np.ndarray:
    return np.array([(x >> (_BITS * i)) & _MASK for i in range(n)], dtype=np.uint64)

_MU_LIMBS = _int_limbs(_MU, _QL + 1)
_L_LIMBS = _int_limbs(L, _LL)
# b^21 - L  (for the borrow-free conditional subtract)
_LC_LIMBS = _int_limbs((1 << (_BITS * _QL)) - L, _QL)


def _bytes_to_limbs_le(b: np.ndarray, nlimb: int) -> np.ndarray:
    """(N, nbytes) uint8 little-endian -> (N, nlimb) uint64 radix-2^13."""
    bits = np.unpackbits(b, axis=1, bitorder="little").astype(np.uint64)
    need = nlimb * _BITS
    if bits.shape[1] < need:
        bits = np.pad(bits, ((0, 0), (0, need - bits.shape[1])))
    w = (np.uint64(1) << np.arange(_BITS, dtype=np.uint64))
    out = np.zeros((b.shape[0], nlimb), dtype=np.uint64)
    for i in range(nlimb):
        out[:, i] = bits[:, _BITS * i : _BITS * (i + 1)] @ w
    return out


def _limbs_to_bytes_le(limbs: np.ndarray, nbytes: int) -> np.ndarray:
    n, nl = limbs.shape
    lo = (limbs & np.uint64(0xFF)).astype(np.uint8)
    hi = ((limbs >> np.uint64(8)) & np.uint64(0xFF)).astype(np.uint8)
    pairs = np.stack([lo, hi], axis=2).reshape(n, nl * 2)  # 16-bit LE per limb
    bits = np.unpackbits(pairs, axis=1, bitorder="little").reshape(n, nl, 16)
    bits = bits[:, :, :_BITS].reshape(n, nl * _BITS)
    bits = bits[:, : nbytes * 8]
    return np.packbits(bits, axis=1, bitorder="little")


def _mul_limbs(a: np.ndarray, b_const: np.ndarray) -> np.ndarray:
    """(N, A) uint64 × (B,) const -> (N, A+B) uint64, carried to radix."""
    n, al = a.shape
    bl = b_const.shape[0]
    prod = np.zeros((n, al + bl), dtype=np.uint64)
    for j in range(bl):
        if int(b_const[j]) == 0:
            continue
        prod[:, j : j + al] += a * b_const[j]
    # carry (products <= 8191^2 * 40 ~ 2^38, safe in u64)
    carry = np.zeros(n, dtype=np.uint64)
    for i in range(al + bl):
        v = prod[:, i] + carry
        prod[:, i] = v & np.uint64(_MASK)
        carry = v >> np.uint64(_BITS)
    return prod


def reduce_mod_l(digests: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 SHA-512 digests (little-endian ints) -> (N, 32) uint8 of
    the digest mod L (little-endian)."""
    n = digests.shape[0]
    h = _bytes_to_limbs_le(digests, _HL)  # < b^40
    # Barrett: q1 = h >> b^(k-1), k = 20
    q1 = h[:, _LL - 1 :]  # 21 limbs
    q2 = _mul_limbs(q1, _MU_LIMBS)  # 43 limbs
    q3 = q2[:, _QL :]  # >> b^21
    q3l = _mul_limbs(q3, _L_LIMBS)[:, :_QL]  # mod b^21
    # r = (h - q3*L) mod b^21, guaranteed in [0, 3L)
    r = np.zeros((n, _QL), dtype=np.uint64)
    borrow = np.zeros(n, dtype=np.uint64)
    for i in range(_QL):
        v = h[:, i] - q3l[:, i] - borrow
        borrow = (v >> np.uint64(63)) & np.uint64(1)  # negative wrapped
        # 2^64 ≡ 0 (mod 2^13): masking the wrapped value is the mod-b residue
        r[:, i] = v & np.uint64(_MASK)
    # conditional subtract L twice: t = r + (b^21 - L); carry-out of top limb
    for _ in range(2):
        t = r + _LC_LIMBS
        carry = np.zeros(n, dtype=np.uint64)
        for i in range(_QL):
            v = t[:, i] + carry
            t[:, i] = v & np.uint64(_MASK)
            carry = v >> np.uint64(_BITS)
        ge = carry > 0  # r >= L
        r = np.where(ge[:, None], t, r)
    return _limbs_to_bytes_le(r, 32)


def compute_h_batch(r32: np.ndarray, pubs: np.ndarray, msgs: Sequence[bytes]) -> np.ndarray:
    """h = SHA-512(R||A||M) mod L for the whole batch -> (N, 32) uint8 LE.

    Uniform-length messages take the fully-vectorized path; mixed lengths are
    grouped by length (each group vectorized).
    """
    n = r32.shape[0]
    out = np.zeros((n, 32), dtype=np.uint8)
    lens = np.array([len(m) for m in msgs])
    for ln in np.unique(lens):
        idx = np.nonzero(lens == ln)[0]
        data = np.zeros((len(idx), 64 + int(ln)), dtype=np.uint8)
        data[:, :32] = r32[idx]
        data[:, 32:64] = pubs[idx]
        for row, i in enumerate(idx):
            data[row, 64:] = np.frombuffer(msgs[i], dtype=np.uint8)
        digests = sha512_batch(data, 64 + int(ln))
        out[idx] = reduce_mod_l(digests)
    return out
