"""Env-var-indexed crash injection (ref: libs/fail/fail.go).

Sprinkle fail_point() at crash-consistency-critical sites (finalizeCommit /
ApplyBlock); run the process with FAIL_TEST_INDEX=k to kill it at the k-th
call — the persistence test suite (test/persist/test_failure_indices.sh
pattern) iterates k and asserts recovery.
"""

from __future__ import annotations

import os
import sys
import threading

_mtx = threading.Lock()
_call_index = -1
_fail_index = None
_initialized = False


def _init() -> None:
    global _fail_index, _initialized
    v = os.environ.get("FAIL_TEST_INDEX")
    _fail_index = int(v) if v is not None else None
    _initialized = True


def reset(index=None) -> None:
    """Test hook: reprogram the kill index and reset the counter."""
    global _call_index, _fail_index, _initialized
    with _mtx:
        _call_index = -1
        _fail_index = index
        _initialized = True


def fail_point() -> None:
    """Kill the process (exit 1) if this is the FAIL_TEST_INDEX-th call."""
    global _call_index
    with _mtx:
        if not _initialized:
            _init()
        if _fail_index is None:
            return
        _call_index += 1
        if _call_index == _fail_index:
            sys.stderr.write(f"fail_point: exiting at index {_call_index}\n")
            sys.stderr.flush()
            os._exit(1)
