"""Flow-rate monitoring + limiting (ref: libs/flowrate/flowrate.go).

Tracks an EWMA transfer rate and offers a token-bucket style limit() used by
MConnection to pace channel sends/recvs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


@dataclass
class Status:
    bytes: int = 0
    duration: float = 0.0
    avg_rate: float = 0.0
    inst_rate: float = 0.0
    cur_rate: float = 0.0


class Monitor:
    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._mtx = threading.Lock()
        self._start = time.monotonic()
        self._total = 0
        self._sample_period = sample_period
        self._window = window
        self._sample_start = self._start
        self._sample_bytes = 0
        self._rate = 0.0  # EWMA bytes/s

    def update(self, n: int) -> int:
        with self._mtx:
            now = time.monotonic()
            self._total += n
            self._sample_bytes += n
            elapsed = now - self._sample_start
            if elapsed >= self._sample_period:
                inst = self._sample_bytes / elapsed
                w = min(1.0, elapsed / self._window)
                self._rate = self._rate * (1 - w) + inst * w
                self._sample_start = now
                self._sample_bytes = 0
        return n

    def status(self) -> Status:
        with self._mtx:
            dur = time.monotonic() - self._start
            return Status(
                bytes=self._total,
                duration=dur,
                avg_rate=self._total / dur if dur > 0 else 0.0,
                inst_rate=self._rate,
                cur_rate=self._rate,
            )

    def limit(self, want: int, rate_limit: int) -> int:
        """How many bytes may be transferred now to stay under rate_limit
        bytes/s; may sleep briefly (ref flowrate Limit)."""
        if rate_limit <= 0:
            return want
        with self._mtx:
            now = time.monotonic()
            dur = now - self._start
            allowed = int(rate_limit * dur) - self._total
        if allowed <= 0:
            time.sleep(min(0.05, (-allowed) / rate_limit))
            return max(0, min(want, allowed + int(rate_limit * 0.05)))
        return min(want, allowed)
