"""Per-height critical-path analyzer — fuses the four observability streams
into one commit-latency waterfall per committed height.

The repo measures consensus latency in four disconnected places: histograms
(libs/metrics.py), thread spans (libs/trace.py), lifecycle stamps
(consensus/flight.py), and the dispatch cost ledger (libs/profile.py).
None of them answers "where did each millisecond of height H go?".  This
module does the join:

* the flight record's wall-clock milestones are cut into disjoint timeline
  phases —

      propose_wait      new-round entry .. first proposal sighting
      block_parts       proposal .. block parts complete
      prevote_quorum    block parts .. polka (+2/3 prevotes)
      precommit_quorum  polka .. commit (+2/3 precommits)
      commit_persist    block-store save_block span (flight "persist")
      abci_exec         ABCI apply_block span (flight "exec")

  whose sum plus an explicit ``other_seconds`` residual reconciles with the
  wall height time exactly (it is an identity by construction; tests assert
  it against the raw stamps);

* per-height WAL costs (``wal_append`` / ``wal_fsync``) come from the
  height-tagged accumulators consensus/wal.py keeps next to its spans, and
  ``verify_dispatch`` comes from profiler entries whose ``window()`` height
  annotation covers the height.  These three are OVERLAY phases: they run
  concurrently with the timeline segments (a WAL fsync during
  prevote_quorum is counted in both), so they are reported but excluded
  from the reconciliation sum;

* the dominant phase is flagged as the height's critical path (ties break
  toward the earlier phase in chain order, so flagging is deterministic),
  and rolling per-phase samples in a ring buffer give p50/p99 without
  unbounded growth.

Like the flight recorder this is per-ConsensusState, piggybacks on the
flight recorder's enable gate (no stamps -> nothing to analyze), and its
``snapshot(limit)`` follows the standard dump contract (``limit`` newest,
``truncated``, ``total_records``).  Analysis runs once per committed height
on the consensus thread; any internal error is counted, never raised —
observability must not fail consensus.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence

from tendermint_tpu.libs.sketch import QuantileSketch

# Phase chain of the waterfall, in canonical (chain) order.  The order is
# load-bearing twice: trace_merge emits slices in it, and critical-path
# ties break toward the earlier entry.
PHASES = (
    "propose_wait",
    "block_parts",
    "prevote_quorum",
    "precommit_quorum",
    "wal_append",
    "wal_fsync",
    "abci_exec",
    "commit_persist",
)

# Disjoint timeline segments of [height start, height end]; their sum plus
# other_seconds equals wall_seconds exactly.
TIMELINE_PHASES = (
    "propose_wait",
    "block_parts",
    "prevote_quorum",
    "precommit_quorum",
    "commit_persist",
    "abci_exec",
)

# Joined per-height costs that overlap the timeline (reported, not summed).
OVERLAY_PHASES = ("wal_append", "wal_fsync", "verify_dispatch")

DEFAULT_CAPACITY = 256  # waterfalls remembered before the ring evicts
DEFAULT_SAMPLE_WINDOW = 512  # rolling per-phase percentile samples


def percentile(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0,100]); 0.0 on empty input."""
    if not samples:
        return 0.0
    xs = sorted(samples)
    rank = max(1, math.ceil(q / 100.0 * len(xs)))
    return xs[min(rank, len(xs)) - 1]


def verify_seconds_for_height(entries: Sequence[dict], height: int) -> float:
    """Verify-dispatch seconds attributable to `height` from profiler
    entries (libs/profile.py, entries() shape).

    Entries carry the window() annotation as (height_base, heights).  An
    entry whose height_base IS the height gets full attribution — that is
    the live-vote path, where VoteFeed annotates each flush with the batch's
    height span.  A multi-height window (fast-sync / state-sync replay)
    covering the height contributes its cost amortized evenly across the
    window; the first height of such a window is attributed in full, a
    documented imprecision that only affects replay traffic.
    """
    total = 0.0
    for e in entries:
        hb = e.get("height_base")
        if hb is None:
            continue
        cost = float(e.get("pack_seconds") or 0.0) + float(
            e.get("run_seconds") or 0.0
        )
        if hb == height:
            total += cost
            continue
        span = max(int(e.get("heights") or 0), 1)
        if hb < height < hb + span:
            total += cost / span
    return total


def build_waterfall(
    rec: dict,
    wal_costs: Optional[dict] = None,
    verify_seconds: float = 0.0,
) -> Optional[dict]:
    """One flight record -> one waterfall dict, or None if the height never
    committed (no reconciliation target exists without a commit stamp).

    Milestones are clamped monotonically non-decreasing before cutting:
    a proposer stamps block parts before its own proposal acceptance, and
    skewed sim clocks can invert neighbors — a negative phase would break
    the reconciliation identity, a zero-width one does not.
    """
    rounds = rec.get("rounds") or []
    commit = rec.get("commit")
    if not rounds or commit is None:
        return None
    t_start = min(r["t"] for r in rounds)
    marks = [t_start]
    for milestone in ("proposal", "block_parts", "polka"):
        m = rec.get(milestone)
        marks.append(max(m["t"] if m else marks[-1], marks[-1]))
    marks.append(max(commit["t"], marks[-1]))
    _, t_prop, t_parts, t_polka, t_commit = marks

    persist = rec.get("persist")
    ex = rec.get("exec")
    persist_ns = max(persist["dur_ns"], 0) if persist else 0
    exec_ns = max(ex["dur_ns"], 0) if ex else 0
    t_end = t_commit
    for m, dur in ((persist, persist_ns), (ex, exec_ns)):
        if m is not None:
            t_end = max(t_end, m["t"] + dur)

    wal_costs = wal_costs or {}
    phases: Dict[str, float] = {
        "propose_wait": (t_prop - t_start) / 1e9,
        "block_parts": (t_parts - t_prop) / 1e9,
        "prevote_quorum": (t_polka - t_parts) / 1e9,
        "precommit_quorum": (t_commit - t_polka) / 1e9,
        "commit_persist": persist_ns / 1e9,
        "abci_exec": exec_ns / 1e9,
        "wal_append": float(wal_costs.get("append_seconds") or 0.0),
        "wal_fsync": float(wal_costs.get("fsync_seconds") or 0.0),
    }
    wall = (t_end - t_start) / 1e9
    other = wall - sum(phases[p] for p in TIMELINE_PHASES)
    critical = max(PHASES, key=lambda p: (phases[p], -PHASES.index(p)))

    # timeline segments with their absolute stamps, for trace_merge's
    # nested Chrome slices (finalize segments sit after the commit stamp;
    # persist runs before exec in _do_finalize_commit)
    segments = [
        {"phase": "propose_wait", "t0_ns": t_start, "t1_ns": t_prop},
        {"phase": "block_parts", "t0_ns": t_prop, "t1_ns": t_parts},
        {"phase": "prevote_quorum", "t0_ns": t_parts, "t1_ns": t_polka},
        {"phase": "precommit_quorum", "t0_ns": t_polka, "t1_ns": t_commit},
    ]
    if persist is not None:
        segments.append({
            "phase": "commit_persist",
            "t0_ns": persist["t"],
            "t1_ns": persist["t"] + persist_ns,
        })
    if ex is not None:
        segments.append({
            "phase": "abci_exec",
            "t0_ns": ex["t"],
            "t1_ns": ex["t"] + exec_ns,
        })

    return {
        "height": rec["height"],
        "commit_round": commit.get("round", 0),
        "t_start_ns": t_start,
        "t_end_ns": t_end,
        "wall_seconds": wall,
        # signing-to-commit latency: the bench/gate metric
        "commit_seconds": (t_commit - t_start) / 1e9,
        "phases": phases,
        "other_seconds": other,
        "critical_path": critical,
        "verify_dispatch_seconds": verify_seconds,
        "wal_appends": int(wal_costs.get("appends") or 0),
        "wal_fsyncs": int(wal_costs.get("fsyncs") or 0),
        "segments": segments,
    }


class CritPath:
    """Ring of per-height waterfalls plus rolling per-phase percentile
    windows.  One per ConsensusState (``cs.critpath``), mutated only from
    the consensus thread's finalize path; snapshots may come from RPC
    threads, so every access takes one lock — and every derived count in a
    snapshot is computed under that SINGLE acquisition, the contract the
    flight recorder's wraparound fix established."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        sample_window: int = DEFAULT_SAMPLE_WINDOW,
        metrics=None,
        profiler_entries=None,
    ):
        self._mtx = threading.Lock()
        self.metrics = metrics  # NodeMetrics (height_phase_seconds) or None
        self.node_id = ""  # refreshed from the flight recorder on analyze
        self.sample_window = max(int(sample_window), 1)
        # injectable for tests; defaults to the process profiler ledger
        self._profiler_entries = profiler_entries
        self.analysis_errors = 0
        self._configure(capacity)

    def _configure(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"critpath capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._records: List[dict] = []  # oldest first
        self._evicted = 0
        self._samples: Dict[str, List[float]] = {}
        self._commit_samples: List[float] = []
        # whole-run mergeable sketches next to the exact rolling windows:
        # the windows answer "lately", the sketches answer "this run" in
        # bounded memory and pool exactly across nodes (fixed gamma)
        self._sketches: Dict[str, QuantileSketch] = {
            phase: QuantileSketch() for phase in PHASES
        }
        self._sketches["commit"] = QuantileSketch()

    # control ---------------------------------------------------------------
    def reset(self, capacity: Optional[int] = None) -> None:
        with self._mtx:
            self._configure(capacity if capacity is not None else self.capacity)
            self.analysis_errors = 0

    def __len__(self) -> int:
        with self._mtx:
            return len(self._records)

    # ingestion -------------------------------------------------------------
    def _entries(self) -> List[dict]:
        if self._profiler_entries is not None:
            return self._profiler_entries()
        from tendermint_tpu.libs.profile import get_profiler

        return get_profiler().entries()

    def on_height_complete(self, height: int, flight, wal=None) -> Optional[dict]:
        """Analyze one committed height.  Called from _do_finalize_commit
        right after flight.on_execute; returns the waterfall (tests use it)
        or None when the flight recorder is off / the record is gone."""
        if not getattr(flight, "enabled", False):
            return None
        try:
            rec = flight.peek(height)
            if rec is None:
                return None
            wal_costs = None
            if wal is not None and hasattr(wal, "pop_height_costs"):
                wal_costs = wal.pop_height_costs(height)
            verify_s = verify_seconds_for_height(self._entries(), height)
            wf = build_waterfall(rec, wal_costs, verify_s)
            if wf is None:
                return None
            self.node_id = getattr(flight, "node_id", "") or self.node_id
            self._ingest(wf)
            if self.metrics is not None:
                for phase, secs in wf["phases"].items():
                    self.metrics.height_phase_seconds.observe(secs, (phase,))
            return wf
        except Exception:
            # never let the analyzer take down the consensus thread
            self.analysis_errors += 1
            return None

    def _ingest(self, wf: dict) -> None:
        with self._mtx:
            self._records.append(wf)
            if len(self._records) > self.capacity:
                del self._records[: len(self._records) - self.capacity]
                self._evicted += 1
            win = self.sample_window
            for phase, secs in wf["phases"].items():
                ring = self._samples.setdefault(phase, [])
                ring.append(secs)
                if len(ring) > win:
                    del ring[: len(ring) - win]
                sk = self._sketches.get(phase)
                if sk is not None:
                    sk.add(secs)
            self._commit_samples.append(wf["commit_seconds"])
            if len(self._commit_samples) > win:
                del self._commit_samples[: len(self._commit_samples) - win]
            self._sketches["commit"].add(wf["commit_seconds"])

    # export ----------------------------------------------------------------
    def records(self, limit: Optional[int] = None) -> List[dict]:
        """Copied waterfalls, oldest first (newest N when limit is set)."""
        with self._mtx:
            return self._records_locked(limit)

    def _records_locked(self, limit: Optional[int]) -> List[dict]:
        recs = self._records
        if limit is not None and limit >= 0:
            recs = recs[-limit:] if limit else []
        return [dict(r) for r in recs]

    def phase_stats(self) -> Dict[str, dict]:
        with self._mtx:
            return self._phase_stats_locked()

    def _phase_stats_locked(self) -> Dict[str, dict]:
        out = {}
        for phase in PHASES:
            xs = self._samples.get(phase, ())
            out[phase] = self._stats_entry(self._sketches[phase], xs)
        out["commit"] = self._stats_entry(
            self._sketches["commit"], self._commit_samples)
        return out

    @staticmethod
    def _stats_entry(sk: QuantileSketch, xs: Sequence[float]) -> dict:
        """p50/p99 from the whole-run sketch; the exact rolling-window
        values ride alongside under window_* for continuity."""
        return {
            "n": sk.count,
            "p50_seconds": sk.p50(),
            "p99_seconds": sk.p99(),
            "window_n": len(xs),
            "window_p50_seconds": percentile(xs, 50),
            "window_p99_seconds": percentile(xs, 99),
        }

    def sketches(self) -> Dict[str, dict]:
        """Serialized per-phase + commit sketches (spool / fleet merge)."""
        with self._mtx:
            return self._sketches_locked()

    def _sketches_locked(self) -> Dict[str, dict]:
        return {name: sk.to_dict() for name, sk in self._sketches.items()}

    def snapshot(self, limit: Optional[int] = None) -> dict:
        """The dump_critpath RPC payload.  total/records/evicted/stats are
        all read under ONE lock acquisition so the truncated flag can never
        contradict the record list it ships with."""
        with self._mtx:
            total = len(self._records)
            recs = self._records_locked(limit)
            return {
                "node_id": self.node_id,
                "capacity": self.capacity,
                "sample_window": self.sample_window,
                "evicted": self._evicted,
                "analysis_errors": self.analysis_errors,
                "total_records": total,
                "truncated": len(recs) < total,
                "records": recs,
                "phase_stats": self._phase_stats_locked(),
                "sketches": self._sketches_locked(),
            }
