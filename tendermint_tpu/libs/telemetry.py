"""Crash-safe telemetry spool — the soak observatory's durable memory.

Every observability surface in this repo is an in-memory ring dumped
point-in-time over RPC: a node restart erases its history and a
thousand-height soak silently forgets its tails.  The spool fixes both.
A background flusher appends one **snapshot** — metrics-derived gauges,
the critpath/quorum whole-run sketches (libs/sketch.py), profile-ledger
totals, device-breaker health, and the eviction counts of every bounded
store — every N committed heights or T seconds, whichever fires first,
to a rotating on-disk segment group (libs/autofile.py).

Record framing (one frame per snapshot, frames never span a rotation
because Group.write appends whole buffers to the head):

    4 bytes  big-endian payload length
    4 bytes  big-endian CRC32 of the payload
    N bytes  payload: one compact JSON line (sort_keys, trailing \\n)

A torn final frame — the node died mid-write — is TOLERATED on reopen:
readers verify length + CRC and stop at the first bad frame of the last
segment.  A bad frame before the tail is corruption and is counted, not
raised.  Appending after a torn tail is safe for readers of the NEW
frames only via the recovery truncate in ``TelemetrySpool.__init__``:
the spool re-scans its head segment on open and truncates the torn tail
so the next frame starts clean.

``TelemetrySpool.snapshot(limit)`` follows the established dump contract
(``limit`` newest, ``truncated``, ``total_records``, ONE lock
acquisition) over an in-memory ring of recent snapshots; the on-disk
spool is the long horizon scripts/soak_report.py reads offline.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from tendermint_tpu.libs.autofile import (
    DEFAULT_HEAD_SIZE_LIMIT,
    DEFAULT_TOTAL_SIZE_LIMIT,
    Group,
)

_HEADER = struct.Struct(">II")  # (payload_len, crc32)

# a single snapshot larger than this is a serialization bug, not data;
# the bound also stops a corrupt length field from allocating gigabytes
MAX_RECORD_BYTES = 16 * 1024 * 1024

DEFAULT_RING_CAPACITY = 256  # in-memory snapshots behind dump_telemetry
DEFAULT_INTERVAL_HEIGHTS = 20
DEFAULT_INTERVAL_SECONDS = 5.0

# store labels of the eviction counters surfaced into metrics + snapshots
EVICTION_STORES = ("flight", "profile", "critpath", "quorum")


def encode_record(payload: bytes) -> bytes:
    """One spool frame: length + CRC32 header, then the payload."""
    return _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _scan_segment(path: str, is_tail: bool) -> Tuple[List[bytes], int, int]:
    """Parse one segment file into (payloads, corrupt_frames, valid_bytes).

    ``valid_bytes`` is the offset of the first bad byte (== file size when
    the segment is clean).  A bad frame is tolerated silently when it is
    the torn tail of the LAST segment (``is_tail``); anywhere else it
    counts as corruption.  Either way parsing stops: a bad frame loses
    framing sync for the rest of the file.
    """
    payloads: List[bytes] = []
    corrupt = 0
    offset = 0
    try:
        data = open(path, "rb").read()
    except OSError:
        return payloads, corrupt, offset
    size = len(data)
    while offset + _HEADER.size <= size:
        length, crc = _HEADER.unpack_from(data, offset)
        end = offset + _HEADER.size + length
        if length > MAX_RECORD_BYTES or end > size:
            if not is_tail:
                corrupt += 1
            break
        payload = data[offset + _HEADER.size:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            # checksum mismatch: torn tail if nothing follows, corruption
            # otherwise (and on any non-tail segment)
            if not is_tail or end < size:
                corrupt += 1
            break
        payloads.append(payload)
        offset = end
    return payloads, corrupt, offset


def spool_segments(head_path: str) -> List[str]:
    """All on-disk segment paths of a spool, oldest first, head last."""
    d = os.path.dirname(os.path.abspath(head_path)) or "."
    base = os.path.basename(head_path)
    pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
    idxs = []
    if os.path.isdir(d):
        for fn in os.listdir(d):
            m = pat.match(fn)
            if m:
                idxs.append(int(m.group(1)))
    out = [f"{head_path}.{i:03d}" for i in sorted(idxs)]
    if os.path.exists(head_path):
        out.append(head_path)
    return out


def read_spool(head_path: str) -> dict:
    """Read every decodable snapshot from a spool on disk (offline path —
    the node may be dead; no Group is opened, nothing is created).

    Returns ``{"snapshots": [dict...], "corrupt_frames": n,
    "segments": n}``; a torn tail on the final segment is tolerated
    silently, per the crash-safety contract.
    """
    segments = spool_segments(head_path)
    snapshots: List[dict] = []
    corrupt = 0
    for i, path in enumerate(segments):
        payloads, bad, _ = _scan_segment(path, is_tail=(i == len(segments) - 1))
        corrupt += bad
        for payload in payloads:
            try:
                snapshots.append(json.loads(payload))
            except ValueError:
                corrupt += 1
    return {
        "snapshots": snapshots,
        "corrupt_frames": corrupt,
        "segments": len(segments),
    }


class TelemetrySpool:
    """Periodic snapshot spooler for one node.

    ``sources`` maps section name -> zero-arg callable returning a JSON-
    safe value; each flush calls every source (each takes its own lock)
    and frames the combined snapshot onto the autofile group.  Sources
    that raise are skipped for that snapshot (their error is counted) —
    telemetry must not fail the node.

    Thread model: the flusher thread and RPC threads share ``_mtx``; the
    single-lock snapshot contract of the other dump surfaces applies to
    ``snapshot(limit)`` and ``status()``.
    """

    def __init__(
        self,
        path: str,
        node_id: str = "",
        interval_heights: int = DEFAULT_INTERVAL_HEIGHTS,
        interval_seconds: float = DEFAULT_INTERVAL_SECONDS,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_TOTAL_SIZE_LIMIT,
        ring_capacity: int = DEFAULT_RING_CAPACITY,
        metrics=None,
        height_fn: Optional[Callable[[], int]] = None,
        now=time.monotonic,
    ):
        if ring_capacity < 1:
            raise ValueError(
                f"telemetry ring capacity must be >= 1, got {ring_capacity}")
        self.path = path
        self.node_id = node_id
        self.interval_heights = max(int(interval_heights), 1)
        self.interval_seconds = float(interval_seconds)
        self.metrics = metrics  # TelemetryMetrics or None
        self._height_fn = height_fn
        self._now = now
        self._mtx = threading.Lock()
        self._sources: Dict[str, Callable[[], object]] = {}
        # recover a torn tail BEFORE the Group opens the head for append:
        # frames written after garbage would be unreachable to readers
        self._recovered_bytes = self._truncate_torn_tail(path)
        self._group = Group(
            path,
            head_size_limit=head_size_limit,
            total_size_limit=total_size_limit,
        )
        self._configure(ring_capacity)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_flush_t = self._now()
        self._last_flush_height = self._current_height()
        # last-seen eviction totals, for delta feeding the counter family
        self._evicted_seen: Dict[str, int] = {s: 0 for s in EVICTION_STORES}

    def _configure(self, ring_capacity: int) -> None:
        self.ring_capacity = int(ring_capacity)
        self._ring: List[dict] = []  # oldest first
        self._ring_evicted = 0
        self.snapshots_written = 0
        self.write_errors = 0
        self.dropped = 0
        self.source_errors = 0

    @staticmethod
    def _truncate_torn_tail(path: str) -> int:
        """Drop a torn final frame from the head segment so appended
        frames stay reachable; returns the bytes discarded (0 normally)."""
        if not os.path.exists(path):
            return 0
        _, _, valid = _scan_segment(path, is_tail=True)
        size = os.path.getsize(path)
        if valid < size:
            with open(path, "r+b") as f:
                f.truncate(valid)
            return size - valid
        return 0

    # -- sources ------------------------------------------------------------

    def set_source(self, name: str, fn: Callable[[], object]) -> None:
        with self._mtx:
            self._sources[name] = fn

    def _current_height(self) -> int:
        if self._height_fn is None:
            return 0
        try:
            return int(self._height_fn())
        except Exception:
            return 0

    # -- flushing -----------------------------------------------------------

    def _build_snapshot(self, reason: str) -> dict:
        height = self._current_height()
        snap = {
            "v": 1,
            "node_id": self.node_id,
            "seq": self.snapshots_written,
            "height": height,
            "wall_time": time.time(),
            "reason": reason,
        }
        with self._mtx:
            sources = list(self._sources.items())
        for name, fn in sources:
            try:
                snap[name] = fn()
            except Exception:
                # a failed section must not lose the rest of the snapshot
                with self._mtx:
                    self.source_errors += 1
                snap[name] = None
        return snap

    def _observe_evictions(self, evicted: Optional[dict]) -> None:
        """Feed eviction-count DELTAS into the per-store counter family
        (the stores report monotone totals; counters need increments)."""
        if self.metrics is None or not isinstance(evicted, dict):
            return
        for store in EVICTION_STORES:
            total = evicted.get(store)
            if not isinstance(total, (int, float)):
                continue
            delta = int(total) - self._evicted_seen.get(store, 0)
            if delta > 0:
                self.metrics.evicted.add(float(delta), (store,))
                self._evicted_seen[store] = int(total)

    def flush(self, reason: str = "manual") -> Optional[dict]:
        """Build + append one snapshot now.  Returns the snapshot dict, or
        None when it could not even be serialized (counted as dropped)."""
        snap = self._build_snapshot(reason)
        try:
            payload = (
                json.dumps(snap, sort_keys=True, separators=(",", ":"))
                + "\n"
            ).encode()
        except (TypeError, ValueError):
            with self._mtx:
                self.dropped += 1
            if self.metrics is not None:
                self.metrics.dropped.add(1.0)
            return None
        frame = encode_record(payload)
        try:
            self._group.write(frame)
            self._group.flush()
            self._group.maybe_rotate()
            spool_bytes = self._group.total_size()
        except OSError:
            with self._mtx:
                self.write_errors += 1
            if self.metrics is not None:
                self.metrics.write_errors.add(1.0)
            return snap
        with self._mtx:
            self.snapshots_written += 1
            self._ring.append(snap)
            if len(self._ring) > self.ring_capacity:
                del self._ring[: len(self._ring) - self.ring_capacity]
                self._ring_evicted += 1
        self._last_flush_t = self._now()
        self._last_flush_height = snap["height"]
        if self.metrics is not None:
            self.metrics.snapshots.add(1.0)
            self.metrics.spool_bytes.set(float(spool_bytes))
        self._observe_evictions(snap.get("evicted"))
        return snap

    def _due(self) -> Optional[str]:
        if self.interval_seconds > 0 and (
            self._now() - self._last_flush_t >= self.interval_seconds
        ):
            return "interval"
        if self.interval_heights > 0:
            h = self._current_height()
            if h - self._last_flush_height >= self.interval_heights:
                return "heights"
        return None

    def maybe_flush(self) -> Optional[dict]:
        """Flush if an interval elapsed (the flusher's tick; tests and the
        sim harness call it directly for determinism)."""
        reason = self._due()
        return self.flush(reason) if reason else None

    def _run(self) -> None:
        # tick well below the flush interval so height-triggered flushes
        # land promptly even when the wall interval is long
        tick = min(0.25, self.interval_seconds / 4.0 or 0.25)
        while not self._stop.wait(max(tick, 0.01)):
            try:
                self.maybe_flush()
            except Exception:
                with self._mtx:
                    self.write_errors += 1

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-spool", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the flusher and append one final snapshot (clean shutdown
        marks the end of a leg for soak_report)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)
        try:
            self.flush("shutdown")
        finally:
            try:
                self._group.sync()
            except OSError:
                pass
            self._group.close()

    def kill(self) -> None:
        """Crash-style stop: halt the flusher and drop the file handle with
        NO shutdown snapshot — what a kill -9 leaves behind.  Exists for
        crash-safety tests; production shutdown is ``stop``."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)
        self._group.close()

    # -- export -------------------------------------------------------------

    def reset(self, capacity: Optional[int] = None) -> dict:
        """telemetry_reset RPC: clear the in-memory ring + health counters
        (optionally resizing the ring).  The on-disk spool is history and
        is deliberately NOT touched."""
        with self._mtx:
            cap = capacity if capacity is not None else self.ring_capacity
            if cap < 1:
                raise ValueError(
                    f"telemetry ring capacity must be >= 1, got {cap}")
            self._configure(cap)
            return {"ring_capacity": self.ring_capacity}

    def status(self) -> dict:
        """Health summary under ONE lock acquisition (tm_monitor column,
        included in every snapshot via the node's 'spool' source)."""
        with self._mtx:
            return {
                "node_id": self.node_id,
                "path": self.path,
                "snapshots_written": self.snapshots_written,
                "write_errors": self.write_errors,
                "dropped": self.dropped,
                "source_errors": self.source_errors,
                "recovered_bytes": self._recovered_bytes,
                "interval_heights": self.interval_heights,
                "interval_seconds": self.interval_seconds,
            }

    def spool_bytes(self) -> int:
        try:
            return self._group.total_size()
        except (OSError, ValueError):
            return 0

    def snapshot(self, limit: Optional[int] = None) -> dict:
        """The dump_telemetry RPC payload: newest ``limit`` in-memory
        snapshots, all derived counts under ONE lock acquisition so the
        truncated flag can never contradict the record list."""
        with self._mtx:
            total = len(self._ring)
            recs = self._ring
            if limit is not None and limit >= 0:
                recs = recs[-limit:] if limit else []
            recs = [dict(r) for r in recs]
            return {
                "node_id": self.node_id,
                "path": self.path,
                "ring_capacity": self.ring_capacity,
                "ring_evicted": self._ring_evicted,
                "snapshots_written": self.snapshots_written,
                "write_errors": self.write_errors,
                "dropped": self.dropped,
                "source_errors": self.source_errors,
                "total_records": total,
                "truncated": len(recs) < total,
                "records": recs,
            }


def node_sources(node) -> Dict[str, Callable[[], object]]:
    """The standard snapshot sections for a running Node — everything
    soak_report fuses.  Separated from node.py so the sim harness can wire
    the same sections onto a SimNode-owned spool."""
    cs = node.consensus_state

    def _sketches() -> dict:
        return {
            "critpath": cs.critpath.sketches(),
            "quorum": cs.quorumtrace.sketches(),
        }

    def _evicted() -> dict:
        from tendermint_tpu.libs.profile import get_profiler

        return {
            "flight": cs.flight.evicted(),
            "profile": get_profiler().dropped,
            "critpath": cs.critpath.snapshot(limit=0)["evicted"],
            "quorum": cs.quorumtrace.snapshot(limit=0)["evicted"],
        }

    def _profile() -> dict:
        from tendermint_tpu.libs.profile import get_profiler

        p = get_profiler()
        rows = p.ledger()
        return {
            "rows": len(rows),
            "dispatches": sum(r["dispatches"] for r in rows),
            "pack_seconds": sum(r["pack_seconds"] for r in rows),
            "run_seconds": sum(r["run_seconds"] for r in rows),
            "compile_seconds": sum(r["compile_seconds"] for r in rows),
            "bytes_to_device": sum(r["bytes_to_device"] for r in rows),
            "dropped": p.dropped,
        }

    def _device() -> Optional[dict]:
        try:
            from tendermint_tpu.libs.breaker import get_device_breaker

            return get_device_breaker().snapshot()
        except Exception:
            return None

    def _stats() -> dict:
        return {
            "height": cs.rs.height,
            "phase_stats": cs.critpath.phase_stats(),
            "quorum_stats": cs.quorumtrace.quorum_stats(),
        }

    return {
        "sketches": _sketches,
        "evicted": _evicted,
        "profile_ledger": _profile,
        "device_health": _device,
        "stats": _stats,
    }
