"""Synchronous in-proc event switch (ref: libs/events/events.go, 247 LoC).

Consensus fires step events through this to the reactor's gossip routines —
the fast path that bypasses the queued EventBus (consensus/state.go:122).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List


Listener = Callable[[Any], None]


class EventSwitch:
    def __init__(self):
        self._mtx = threading.RLock()
        # event name -> listener id -> callback
        self._listeners: Dict[str, Dict[str, Listener]] = {}

    def add_listener_for_event(self, listener_id: str, event: str, cb: Listener) -> None:
        with self._mtx:
            self._listeners.setdefault(event, {})[listener_id] = cb

    def remove_listener_for_event(self, event: str, listener_id: str) -> None:
        with self._mtx:
            self._listeners.get(event, {}).pop(listener_id, None)

    def remove_listener(self, listener_id: str) -> None:
        with self._mtx:
            for cbs in self._listeners.values():
                cbs.pop(listener_id, None)

    def fire_event(self, event: str, data: Any = None) -> None:
        with self._mtx:
            cbs = list(self._listeners.get(event, {}).values())
        for cb in cbs:
            cb(data)
