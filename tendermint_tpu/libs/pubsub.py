"""Topic pub/sub with a query language (ref: libs/pubsub/pubsub.go + query/).

Queries are the reference's subscription language:
    tm.event = 'NewBlock' AND tx.height > 5 AND account.name CONTAINS 'igor'
Operators: = < <= > >= != CONTAINS, conjunctions with AND.  Values: 'strings'
or numbers.  (The reference compiles a PEG — query/query.peg.go; here a small
recursive-descent parser over the same grammar.)

The server delivers published (message, tags) pairs to every subscription
whose query matches the tags, each subscriber getting its own queue.
"""

from __future__ import annotations

import logging
import queue
import re
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

# ---------------------------------------------------------------------------
# Query language
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<op><=|>=|!=|=|<|>)|(?P<and>\bAND\b)|(?P<contains>\bCONTAINS\b)"
    r"|(?P<str>'[^']*')|(?P<num>-?\d+(?:\.\d+)?)|(?P<tag>[A-Za-z_][\w.]*))"
)


@dataclass(frozen=True)
class Condition:
    tag: str
    op: str  # '=', '<', '<=', '>', '>=', '!=', 'CONTAINS'
    value: Union[str, float]

    def matches(self, tags: Dict[str, str]) -> bool:
        if self.tag not in tags:
            return False
        actual = tags[self.tag]
        if self.op == "CONTAINS":
            return str(self.value) in actual
        if isinstance(self.value, float):
            try:
                a = float(actual)
            except ValueError:
                return False
            return {
                "=": a == self.value,
                "!=": a != self.value,
                "<": a < self.value,
                "<=": a <= self.value,
                ">": a > self.value,
                ">=": a >= self.value,
            }[self.op]
        if self.op == "=":
            return actual == self.value
        if self.op == "!=":
            return actual != self.value
        # ordered string comparison for non-numeric values
        return {
            "<": actual < self.value,
            "<=": actual <= self.value,
            ">": actual > self.value,
            ">=": actual >= self.value,
        }[self.op]


class QueryError(ValueError):
    pass


class Query:
    """Conjunction of conditions (the reference grammar has no OR)."""

    def __init__(self, s: str):
        self._s = s.strip()
        self.conditions = self._parse(self._s)

    @staticmethod
    def _tokens(s: str) -> List[Tuple[str, str]]:
        out, pos = [], 0
        while pos < len(s):
            m = _TOKEN_RE.match(s, pos)
            if not m or m.end() == pos:
                if s[pos:].strip():
                    raise QueryError(f"bad query near {s[pos:]!r}")
                break
            pos = m.end()
            for kind in ("op", "and", "contains", "str", "num", "tag"):
                if m.group(kind):
                    out.append((kind, m.group(kind)))
                    break
        return out

    @classmethod
    def _parse(cls, s: str) -> List[Condition]:
        if not s:
            raise QueryError("empty query")
        toks = cls._tokens(s)
        conds = []
        i = 0
        while i < len(toks):
            if toks[i][0] != "tag":
                raise QueryError(f"expected tag, got {toks[i]!r}")
            tag = toks[i][1]
            if i + 2 >= len(toks):
                raise QueryError("truncated condition")
            kind, opval = toks[i + 1]
            if kind == "op":
                op = opval
            elif kind == "contains":
                op = "CONTAINS"
            else:
                raise QueryError(f"expected operator, got {opval!r}")
            vkind, vraw = toks[i + 2]
            if vkind == "str":
                value: Union[str, float] = vraw[1:-1]
            elif vkind == "num":
                value = float(vraw)
            else:
                raise QueryError(f"expected value, got {vraw!r}")
            conds.append(Condition(tag, op, value))
            i += 3
            if i < len(toks):
                if toks[i][0] != "and":
                    raise QueryError(f"expected AND, got {toks[i]!r}")
                i += 1
        return conds

    def matches(self, tags: Dict[str, str]) -> bool:
        return all(c.matches(tags) for c in self.conditions)

    def __str__(self) -> str:
        return self._s

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and str(self) == str(other)

    def __hash__(self) -> int:
        return hash(self._s)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class DuplicateSubscriptionError(Exception):
    pass


class SubscriptionNotFoundError(Exception):
    pass


@dataclass
class Message:
    data: Any
    tags: Dict[str, str]


class Subscription:
    def __init__(self, maxsize: int = 0):
        self.queue: "queue.Queue[Message]" = queue.Queue(maxsize)
        self.cancelled = threading.Event()

    def get(self, timeout: Optional[float] = None) -> Message:
        return self.queue.get(timeout=timeout)


class Server:
    """clientID × query → Subscription (ref pubsub.go Server)."""

    def __init__(self, buffer: int = 0,
                 on_drop: Optional[Callable[[str], None]] = None):
        self._mtx = threading.RLock()
        self._subs: Dict[str, Dict[Query, Subscription]] = {}
        self._buffer = buffer
        # slow-subscriber drop accounting: per-client counts, a warning on
        # the FIRST drop per client (silent shedding hides real bugs), and
        # an optional callback (node.py feeds the
        # tendermint_pubsub_dropped_events_total{client_id} counter)
        self._dropped: Dict[str, int] = {}
        self._on_drop = on_drop
        self._logger = logging.getLogger("pubsub")

    def set_on_drop(self, fn: Optional[Callable[[str], None]]) -> None:
        with self._mtx:
            self._on_drop = fn

    def dropped_events(self, client_id: Optional[str] = None):
        """Total drops for one client, or a {client_id: count} copy."""
        with self._mtx:
            if client_id is not None:
                return self._dropped.get(client_id, 0)
            return dict(self._dropped)

    def _note_drop(self, client_id: str) -> None:
        with self._mtx:
            n = self._dropped.get(client_id, 0) + 1
            self._dropped[client_id] = n
            on_drop = self._on_drop
        if n == 1:
            self._logger.warning(
                "dropping events for slow subscriber %r (buffer full); "
                "further drops counted silently", client_id
            )
        if on_drop is not None:
            try:
                on_drop(client_id)
            except Exception:
                self._logger.exception("pubsub on_drop callback failed")

    def subscribe(self, client_id: str, q: Union[str, Query], maxsize: int = 0) -> Subscription:
        q = Query(q) if isinstance(q, str) else q
        with self._mtx:
            by_client = self._subs.setdefault(client_id, {})
            if q in by_client:
                raise DuplicateSubscriptionError(f"{client_id}/{q}")
            sub = Subscription(maxsize or self._buffer)
            by_client[q] = sub
            return sub

    def unsubscribe(self, client_id: str, q: Union[str, Query]) -> None:
        q = Query(q) if isinstance(q, str) else q
        with self._mtx:
            by_client = self._subs.get(client_id, {})
            if q not in by_client:
                raise SubscriptionNotFoundError(f"{client_id}/{q}")
            by_client.pop(q).cancelled.set()
            if not by_client:
                self._subs.pop(client_id, None)

    def unsubscribe_all(self, client_id: str) -> None:
        with self._mtx:
            by_client = self._subs.pop(client_id, None)
            if by_client is None:
                raise SubscriptionNotFoundError(client_id)
            for sub in by_client.values():
                sub.cancelled.set()

    def publish(self, data: Any, tags: Optional[Dict[str, str]] = None) -> None:
        tags = tags or {}
        with self._mtx:
            targets = [
                (client_id, sub)
                for client_id, by_client in self._subs.items()
                for q, sub in by_client.items()
                if q.matches(tags)
            ]
        msg = Message(data=data, tags=tags)
        for client_id, sub in targets:
            try:
                sub.queue.put_nowait(msg)
            except queue.Full:
                # slow subscriber: drop (reference blocks; we shed load) —
                # but never silently
                self._note_drop(client_id)

    def num_clients(self) -> int:
        with self._mtx:
            return len(self._subs)
