"""Circuit breaker + supervised dispatch for the device verification path.

The north star puts a JAX/TPU batched signature backend behind the
consensus verification boundary, which turns accelerator failure modes
into consensus hazards:

- a **crashed/preempted** device raises mid-dispatch — retrying a dead
  chip on every window burns the consensus routine's time budget;
- a **hung** device (wedged tunnel, stuck DMA) blocks the calling thread
  forever — worse than an error, because nothing propagates;
- a **silently corrupting** device returns wrong verdicts — a safety
  bug, not a perf bug, and must never be retried back into service.

``CircuitBreaker`` is the shared health model for all three.  It is a
deterministic state machine — every transition is a pure function of the
recorded events and an injectable monotonic clock, so the sim fabric can
replay schedules bit-for-bit:

    closed ── N consecutive failures ──► open
    open ── backoff elapsed, one probe granted ──► half_open
    half_open ── probe succeeds ──► closed
    half_open ── probe fails ──► open (backoff doubled)
    any ── corruption detected ──► quarantined   (operator reset only)

``quarantined`` is deliberately latched: a device that *mis-computes*
must not be re-admitted by timers, only by an explicit operator
``reset()`` (the ``device_breaker_reset`` unsafe RPC).

``supervised_call`` bounds a single dispatch with a wall-clock deadline
by running it on a worker thread; a hung call surfaces as
``DispatchTimeout`` so the caller can fall back to the host path instead
of stalling consensus.  The abandoned worker thread is daemonic and left
to the wedged runtime — there is no safe way to kill it, and the breaker
ensures we stop handing work to it.

Callers (parallel/planner.py, crypto/batch.py GuardedBatchVerifier)
share one process-wide breaker via ``get_device_breaker()`` — one
physical device per host means one health state, configured from the
``[verify]`` config section via ``configure_device_guard``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass
from typing import Callable, List, Optional

# state-machine states; GAUGE value encoding used by
# tendermint_verify_device_breaker_state (see libs/metrics.py)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
QUARANTINED = "quarantined"

STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2, QUARANTINED: 3}

_HISTORY_CAPACITY = 64


class BreakerOpen(Exception):
    """Dispatch refused: the breaker is open or quarantined."""


class DispatchTimeout(Exception):
    """A supervised device call exceeded its wall-clock deadline."""


class CircuitBreaker:
    """Deterministic circuit breaker with an injectable monotonic clock.

    Thread-safe: concurrent dispatchers may call ``allow`` /
    ``record_success`` / ``record_failure`` freely; exactly one caller
    wins the half-open probe slot.
    """

    def __init__(
        self,
        name: str = "device",
        threshold: int = 3,
        backoff_base: float = 1.0,
        backoff_max: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str, str], None]] = None,
    ):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        if backoff_base <= 0:
            raise ValueError("backoff_base must be > 0")
        self.name = name
        self.threshold = int(threshold)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.clock = clock
        self.on_transition = on_transition
        self._mtx = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opens = 0            # open transitions since last close/reset
        self._retry_at = 0.0       # clock() after which a probe is granted
        self._probe_inflight = False
        self._quarantine_reason: Optional[str] = None
        # lifetime counters (survive transitions; cleared by reset())
        self._n_failures = 0
        self._n_successes = 0
        self._n_probes = 0
        self._history: List[dict] = []
        self._history_dropped = 0

    # -- internals (lock held) -------------------------------------------------

    def _transition(self, new: str, reason: str) -> None:
        old = self._state
        if old == new:
            return
        self._state = new
        self._history.append({
            "t": float(self.clock()),
            "from": old,
            "to": new,
            "reason": reason,
        })
        if len(self._history) > _HISTORY_CAPACITY:
            del self._history[0]
            self._history_dropped += 1
        cb = self.on_transition
        if cb is not None:
            # fire outside any caller expectation of purity but inside the
            # lock: transitions are rare and ordering matters for the gauge
            try:
                cb(old, new, reason)
            except Exception:
                pass

    def _open(self, reason: str) -> None:
        self._opens += 1
        backoff = min(
            self.backoff_max,
            self.backoff_base * (2.0 ** (self._opens - 1)),
        )
        self._retry_at = float(self.clock()) + backoff
        self._probe_inflight = False
        self._transition(OPEN, reason)

    # -- dispatch protocol -----------------------------------------------------

    def allow(self) -> bool:
        """May the caller dispatch to the device right now?

        In ``open`` state, the first caller after the backoff elapses is
        granted the half-open probe (returns True); everyone else gets
        False until the probe reports.
        """
        with self._mtx:
            if self._state == CLOSED:
                return True
            if self._state == QUARANTINED:
                return False
            if self._state == OPEN:
                if self.clock() >= self._retry_at:
                    self._probe_inflight = True
                    self._n_probes += 1
                    self._transition(HALF_OPEN, "backoff_elapsed")
                    return True
                return False
            # HALF_OPEN: a single probe owns the state
            if not self._probe_inflight:
                self._probe_inflight = True
                self._n_probes += 1
                return True
            return False

    def record_success(self) -> None:
        with self._mtx:
            self._n_successes += 1
            self._consecutive_failures = 0
            if self._state == QUARANTINED:
                return  # only reset() leaves quarantine
            if self._state in (HALF_OPEN, OPEN):
                self._opens = 0
                self._probe_inflight = False
                self._transition(CLOSED, "probe_ok")

    def record_failure(self, reason: str = "error") -> None:
        with self._mtx:
            self._n_failures += 1
            self._consecutive_failures += 1
            if self._state == QUARANTINED:
                return
            if self._state == HALF_OPEN:
                self._open(f"probe_failed:{reason}")
            elif self._state == CLOSED and (
                self._consecutive_failures >= self.threshold
            ):
                self._open(f"threshold:{reason}")

    def trip(self, reason: str = "forced") -> None:
        """Force the breaker open immediately (e.g. device init failure),
        regardless of the consecutive-failure count."""
        with self._mtx:
            if self._state in (QUARANTINED, OPEN):
                return
            self._open(f"trip:{reason}")

    def quarantine(self, reason: str) -> None:
        """Latch the breaker: the device returned a verdict that disagrees
        with the host oracle.  Only an operator ``reset()`` re-arms it."""
        with self._mtx:
            self._quarantine_reason = reason
            self._probe_inflight = False
            self._transition(QUARANTINED, reason)

    def reset(self) -> None:
        """Operator reset: back to closed with clean counters."""
        with self._mtx:
            self._consecutive_failures = 0
            self._opens = 0
            self._retry_at = 0.0
            self._probe_inflight = False
            self._quarantine_reason = None
            self._transition(CLOSED, "operator_reset")

    # -- inspection ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._mtx:
            return self._state

    def snapshot(self) -> dict:
        with self._mtx:
            now = float(self.clock())
            return {
                "name": self.name,
                "state": self._state,
                "state_code": STATE_GAUGE[self._state],
                "threshold": self.threshold,
                "backoff_base": self.backoff_base,
                "backoff_max": self.backoff_max,
                "consecutive_failures": self._consecutive_failures,
                "opens_since_close": self._opens,
                "retry_in_seconds": (
                    max(0.0, round(self._retry_at - now, 6))
                    if self._state == OPEN else 0.0
                ),
                "probe_inflight": self._probe_inflight,
                "quarantine_reason": self._quarantine_reason,
                "failures_total": self._n_failures,
                "successes_total": self._n_successes,
                "probes_total": self._n_probes,
                "history": [dict(h) for h in self._history],
                "history_dropped": self._history_dropped,
            }


# -- supervised dispatch -------------------------------------------------------


def supervised_call(fn: Callable[[], object], deadline: float,
                    name: str = "device-dispatch"):
    """Run ``fn`` with a wall-clock deadline.

    ``deadline <= 0`` disables supervision (direct call).  Otherwise the
    call runs on a daemon worker thread; if it does not finish within
    ``deadline`` seconds, ``DispatchTimeout`` is raised and the worker is
    abandoned to the wedged runtime (it cannot be killed safely — the
    breaker's job is to stop sending work its way).

    The caller's profiler window annotation (libs/profile.py is
    thread-local) is propagated into the worker so ledger rows still fold
    into the right per-height group.
    """
    if deadline is None or deadline <= 0:
        return fn()

    from tendermint_tpu.libs import profile as _profile

    win = getattr(_profile._tls, "window", None)
    box: dict = {}
    done = threading.Event()

    def _run():
        if win is not None:
            _profile._tls.window = win
        try:
            box["result"] = fn()
        except BaseException as e:  # propagate to the supervising thread
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, name=f"supervised-{name}", daemon=True)
    t.start()
    if not done.wait(deadline):
        raise DispatchTimeout(
            f"{name} exceeded {deadline:.3f}s deadline (worker abandoned)"
        )
    if "error" in box:
        raise box["error"]
    return box.get("result")


# -- process-wide device guard -------------------------------------------------


@dataclass
class GuardConfig:
    """Knobs for the device dispatch guard — the ``[verify]`` config
    section (config/config.py VerifyConfig) mirrors these names."""

    breaker_threshold: int = 3
    breaker_backoff: float = 1.0
    breaker_backoff_max: float = 60.0
    dispatch_deadline: float = 30.0
    audit_sample_rate: float = 0.05
    audit_seed: int = 0
    retries: int = 1

    def as_dict(self) -> dict:
        return asdict(self)


_guard_mtx = threading.Lock()
_guard_config = GuardConfig()
_device_breaker: Optional[CircuitBreaker] = None


def _default_on_transition(old: str, new: str, reason: str) -> None:
    """Wire breaker transitions into the gauge + the profiler event ring."""
    try:
        from tendermint_tpu.libs.metrics import get_verify_metrics

        get_verify_metrics().device_breaker_state.set(float(STATE_GAUGE[new]))
    except Exception:
        pass
    try:
        from tendermint_tpu.libs.profile import get_profiler

        get_profiler().record_event(
            "breaker", old=old, new=new, reason=reason
        )
    except Exception:
        pass


def get_device_breaker() -> CircuitBreaker:
    """The process-wide breaker guarding the (single) device verify path."""
    global _device_breaker
    with _guard_mtx:
        if _device_breaker is None:
            c = _guard_config
            _device_breaker = CircuitBreaker(
                name="device",
                threshold=c.breaker_threshold,
                backoff_base=c.breaker_backoff,
                backoff_max=c.breaker_backoff_max,
                on_transition=_default_on_transition,
            )
        return _device_breaker


def guard_config() -> GuardConfig:
    with _guard_mtx:
        return _guard_config


def configure_device_guard(
    verify_config=None,
    clock: Optional[Callable[[], float]] = None,
    **overrides,
) -> CircuitBreaker:
    """(Re)build the process-wide breaker + guard config.

    ``verify_config`` is duck-typed (config/config.py VerifyConfig or any
    object carrying the GuardConfig field names); keyword overrides win.
    Called from the node composition root with ``config.verify``, and from
    tests/scenarios with explicit small knobs + an injectable clock.
    """
    global _device_breaker, _guard_config
    fields = {}
    for f in GuardConfig.__dataclass_fields__:
        if verify_config is not None and hasattr(verify_config, f):
            fields[f] = getattr(verify_config, f)
        if f in overrides:
            fields[f] = overrides.pop(f)
    if overrides:
        raise TypeError(f"unknown guard knobs: {sorted(overrides)}")
    with _guard_mtx:
        _guard_config = GuardConfig(**fields)
        _device_breaker = CircuitBreaker(
            name="device",
            threshold=_guard_config.breaker_threshold,
            backoff_base=_guard_config.breaker_backoff,
            backoff_max=_guard_config.breaker_backoff_max,
            clock=clock or time.monotonic,
            on_transition=_default_on_transition,
        )
        return _device_breaker


def reset_device_guard() -> None:
    """Restore defaults (tests/scenarios teardown)."""
    global _device_breaker, _guard_config
    with _guard_mtx:
        _guard_config = GuardConfig()
        _device_breaker = None
