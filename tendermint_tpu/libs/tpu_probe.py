"""Hang-proof TPU discovery.

Failure mode this module exists for: when the TPU is reached through a
network tunnel and the remote side is down, jax backend discovery does not
error — it HANGS indefinitely (observed: `jax.devices()` blocked > 60 s on a
dead tunnel).  Any production path that lazily calls `jax.devices("tpu")`
in-process therefore hangs a validator at its first commit verify instead of
degrading to the host/XLA backend.

The fix is the same stance the p2p layer takes toward unresponsive peers
(ref `/root/reference/p2p/conn/connection.go` ping/pong timeouts), applied to
our own device layer: liveness is established by a *disposable subprocess*
with a hard deadline, and the verdict is cached process-wide (and exported in
the environment so child processes skip the probe).  Only after a live
verdict does the calling process touch jax device discovery itself.

Cache protocol: env var ``TM_AXON_ALIVE`` ("1"/"0").  tests/conftest.py uses
the same variable, so a test session's probe is shared with every node
subprocess it spawns, and vice versa.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

_PROBE_TIMEOUT_S = 45.0

_lock = threading.Lock()
_verdict: bool | None = None


def _probe_subprocess(timeout: float) -> bool:
    """Run TPU discovery in a throwaway child with a hard deadline.

    The child performs full backend discovery (including any force-registered
    tunnel platform) and exits 0 iff a TPU device is visible.  A hang is
    converted into TimeoutExpired -> dead verdict; the child is killed."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the child discover everything
    try:
        res = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; assert len(jax.devices('tpu')) > 0",
            ],
            timeout=timeout,
            capture_output=True,
            env=env,
        )
        return res.returncode == 0
    except Exception:
        return False


def tpu_alive(timeout: float = _PROBE_TIMEOUT_S, use_cache: bool = True) -> bool:
    """True iff a TPU device is reachable, established without ever risking an
    in-process hang.  Verdict is cached (module global + TM_AXON_ALIVE env)."""
    global _verdict
    with _lock:
        if use_cache:
            if _verdict is not None:
                return _verdict
            cached = os.environ.get("TM_AXON_ALIVE")
            if cached in ("0", "1"):
                _verdict = cached == "1"
                return _verdict
        alive = _probe_subprocess(timeout)
        _verdict = alive
        os.environ["TM_AXON_ALIVE"] = "1" if alive else "0"
        return alive


def pin_cpu_platform() -> None:
    """Best-effort: pin this process's jax to the CPU platform so that no
    later discovery (ours or a library's) can touch the dead tunnel.  A
    no-op once backends are initialized — callers pin before first use."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def safe_tpu_device(timeout: float = _PROBE_TIMEOUT_S):
    """The real TPU device, or None — never hangs.

    Dead-tunnel path: returns None AND pins this process to the CPU platform,
    so subsequent jax use (the XLA fallback kernels) cannot stumble into
    discovery of the wedged platform either."""
    if not tpu_alive(timeout):
        pin_cpu_platform()
        return None
    try:
        import jax

        return jax.devices("tpu")[0]
    except Exception:
        return None


def clear_cache() -> None:
    """Forget the cached liveness verdict (module global + env) so the next
    ``tpu_alive`` re-probes.  The operator-facing device reprobe seam
    (crypto/batch.reprobe(force=True)) uses this: a tunnel that came back
    must be rediscoverable without a process restart.  Costly on a
    still-dead tunnel (the next probe pays the full subprocess timeout), so
    only explicit operator action clears the cache — the breaker-driven
    automatic reprobe leaves it intact."""
    global _verdict
    with _lock:
        _verdict = None
        os.environ.pop("TM_AXON_ALIVE", None)


def _reset_for_tests() -> None:
    global _verdict
    with _lock:
        _verdict = None
