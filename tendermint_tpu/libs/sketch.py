"""Mergeable streaming quantile sketch for long-horizon soak telemetry.

DDSketch-style relative-error buckets (Masson et al., "DDSketch: a fast
and fully-mergeable quantile sketch with relative-error guarantees"): a
value x > 0 lands in bucket ceil(log_gamma(x)), so every bucket spans
(gamma^(i-1), gamma^i] and any value reported for a rank is within a
RELATIVE error alpha of the true value, where gamma = (1+alpha)/(1-alpha).

Two properties matter for the soak observatory:

* **Fixed gamma.** Unlike collapsing DDSketch variants, the accuracy
  parameter is fixed at construction and never renegotiated, so merging
  two sketches is bucket-wise integer addition — two nodes' sketches pool
  EXACTLY, and merge order cannot change a single bucket count.  The
  fleet-merged sketch is bit-identical to any association of pairwise
  merges.
* **Bounded memory.** Consensus latencies span roughly 1e-6 .. 1e3
  seconds; at the default alpha=0.01 that is ~1050 buckets worst case
  (log_gamma(1e9) ≈ 1036), a few KB per tracked distribution regardless
  of how many million samples a soak run feeds it — versus a rolling
  window that forgets everything older than its capacity.

The sketch is deterministic: same samples (in any order) -> same bucket
counts, same quantile answers.  The running ``sum`` is the only
order-sensitive field (float addition), which is why quantiles and the
merge identity never depend on it.

``WindowedCounter`` is the companion for rates: integer counts bucketed
by a fixed-width window index (heights or seconds), mergeable by the
same bucket-wise addition, with bounded retention.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

# Default relative accuracy: a reported quantile q~ satisfies
# |q~ - q| <= alpha * q.  0.01 keeps p99 of a 1s commit within 10ms.
DEFAULT_RELATIVE_ACCURACY = 0.01

# Values at or below this magnitude collapse into the zero bucket —
# nanosecond-scale noise is below anything the observatory reasons about.
MIN_INDEXABLE = 1e-9


class QuantileSketch:
    """Fixed-gamma DDSketch over non-negative samples.

    Not thread-safe: owners (CritPath/QuorumTrace/TelemetrySpool) already
    serialize ingest under their own lock.
    """

    __slots__ = (
        "alpha", "_gamma", "_log_gamma", "_buckets", "_zero",
        "_count", "_sum", "_min", "_max",
    )

    def __init__(self, alpha: float = DEFAULT_RELATIVE_ACCURACY):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self._gamma)
        self._buckets: Dict[int, int] = {}
        self._zero = 0  # samples <= MIN_INDEXABLE (incl. exact zeros)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    # -- ingest -------------------------------------------------------------

    def add(self, value: float, count: int = 1) -> None:
        """Fold ``count`` occurrences of ``value`` into the sketch.
        Negative samples are clamped to the zero bucket (durations cannot
        be negative; a clamped clock glitch should not poison the index)."""
        if count <= 0:
            return
        v = float(value)
        if not math.isfinite(v):
            return
        if v < 0.0:
            v = 0.0
        if v <= MIN_INDEXABLE:
            self._zero += count
        else:
            idx = math.ceil(math.log(v) / self._log_gamma)
            self._buckets[idx] = self._buckets.get(idx, 0) + count
        self._count += count
        self._sum += v * count
        self._min = v if self._min is None else min(self._min, v)
        self._max = v if self._max is None else max(self._max, v)

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    # -- queries ------------------------------------------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> Optional[float]:
        return self._min

    @property
    def max(self) -> Optional[float]:
        return self._max

    def bucket_count(self) -> int:
        """Number of live buckets — the memory footprint proxy."""
        return len(self._buckets) + (1 if self._zero else 0)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate, within ``alpha`` relative error
        of the exact nearest-rank value.  q in [0, 1]; 0.0 on empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self._count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self._count))
        if rank <= self._zero:
            # everything in the zero bucket is below observability noise;
            # report the smallest sample actually seen
            return self._min if self._min is not None else 0.0
        cum = self._zero
        est = 0.0
        for idx in sorted(self._buckets):
            cum += self._buckets[idx]
            if cum >= rank:
                # midpoint of (gamma^(i-1), gamma^i] in log space:
                # 2*gamma^i / (gamma + 1), the canonical DDSketch estimate
                est = 2.0 * math.pow(self._gamma, idx) / (self._gamma + 1.0)
                break
        # clamp to the observed envelope: never report outside [min, max]
        # (this also makes the single-sample sketch exact)
        if self._min is not None:
            est = max(est, self._min)
        if self._max is not None:
            est = min(est, self._max)
        return est

    def p50(self) -> float:
        return self.quantile(0.50)

    def p99(self) -> float:
        return self.quantile(0.99)

    # -- merge --------------------------------------------------------------

    def merge(self, other: "QuantileSketch") -> None:
        """Bucket-wise fold of ``other`` into self.  Exact: the merged
        bucket counts are independent of merge order/association."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha: "
                f"{self.alpha} vs {other.alpha}"
            )
        for idx, n in other._buckets.items():
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        self._zero += other._zero
        self._count += other._count
        self._sum += other._sum
        if other._min is not None:
            self._min = other._min if self._min is None else min(
                self._min, other._min)
        if other._max is not None:
            self._max = other._max if self._max is None else max(
                self._max, other._max)

    @classmethod
    def merged(cls, sketches: Iterable["QuantileSketch"],
               alpha: Optional[float] = None) -> "QuantileSketch":
        """A fresh sketch holding the bucket-wise sum of ``sketches``."""
        out = None
        for sk in sketches:
            if out is None:
                out = cls(alpha if alpha is not None else sk.alpha)
            out.merge(sk)
        return out if out is not None else cls(
            alpha if alpha is not None else DEFAULT_RELATIVE_ACCURACY)

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict:
        """Compact JSON-safe form: buckets as a sorted [index, count] pair
        list (deterministic byte-for-byte when json-dumped with sort_keys)."""
        return {
            "kind": "ddsketch",
            "alpha": self.alpha,
            "count": self._count,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "zero": self._zero,
            "buckets": [[idx, self._buckets[idx]]
                        for idx in sorted(self._buckets)],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        if d.get("kind") != "ddsketch":
            raise ValueError(f"not a ddsketch dict: kind={d.get('kind')!r}")
        sk = cls(alpha=float(d["alpha"]))
        sk._count = int(d["count"])
        sk._sum = float(d["sum"])
        sk._min = None if d.get("min") is None else float(d["min"])
        sk._max = None if d.get("max") is None else float(d["max"])
        sk._zero = int(d.get("zero", 0))
        sk._buckets = {int(idx): int(n) for idx, n in d.get("buckets", [])}
        return sk

    def __len__(self) -> int:
        return self._count

    def __repr__(self) -> str:
        return (
            f"QuantileSketch(alpha={self.alpha}, count={self._count}, "
            f"buckets={len(self._buckets)}, p50={self.p50():.6g}, "
            f"p99={self.p99():.6g})"
        )


class WindowedCounter:
    """Integer event counts bucketed by fixed-width windows.

    ``observe(pos)`` increments the window containing ``pos`` (heights,
    seconds — any monotone axis).  Merge is bucket-wise addition with the
    same exactness argument as the sketch.  Retention is bounded: only the
    newest ``max_windows`` windows are kept, evictions are counted so a
    lossy report can say so.
    """

    __slots__ = ("window", "max_windows", "_counts", "_evicted")

    def __init__(self, window: float = 1.0, max_windows: int = 4096):
        if window <= 0:
            raise ValueError(f"window must be > 0, got {window}")
        if max_windows < 1:
            raise ValueError(f"max_windows must be >= 1, got {max_windows}")
        self.window = float(window)
        self.max_windows = int(max_windows)
        self._counts: Dict[int, int] = {}
        self._evicted = 0

    def observe(self, pos: float, count: int = 1) -> None:
        if count <= 0:
            return
        idx = int(math.floor(float(pos) / self.window))
        self._counts[idx] = self._counts.get(idx, 0) + count
        self._prune()

    def _prune(self) -> None:
        while len(self._counts) > self.max_windows:
            oldest = min(self._counts)
            self._evicted += self._counts.pop(oldest)

    @property
    def total(self) -> int:
        return sum(self._counts.values())

    @property
    def evicted(self) -> int:
        return self._evicted

    def windows(self) -> List[Tuple[int, int]]:
        """Sorted (window_index, count) pairs."""
        return [(idx, self._counts[idx]) for idx in sorted(self._counts)]

    def merge(self, other: "WindowedCounter") -> None:
        if abs(other.window - self.window) > 1e-12:
            raise ValueError(
                f"cannot merge counters with different window: "
                f"{self.window} vs {other.window}"
            )
        for idx, n in other._counts.items():
            self._counts[idx] = self._counts.get(idx, 0) + n
        self._evicted += other._evicted
        self._prune()

    def to_dict(self) -> dict:
        return {
            "kind": "windowed_counter",
            "window": self.window,
            "max_windows": self.max_windows,
            "evicted": self._evicted,
            "counts": [[idx, self._counts[idx]]
                       for idx in sorted(self._counts)],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WindowedCounter":
        if d.get("kind") != "windowed_counter":
            raise ValueError(
                f"not a windowed_counter dict: kind={d.get('kind')!r}")
        wc = cls(window=float(d["window"]),
                 max_windows=int(d.get("max_windows", 4096)))
        wc._evicted = int(d.get("evicted", 0))
        wc._counts = {int(idx): int(n) for idx, n in d.get("counts", [])}
        return wc

    def __repr__(self) -> str:
        return (
            f"WindowedCounter(window={self.window}, "
            f"windows={len(self._counts)}, total={self.total})"
        )
