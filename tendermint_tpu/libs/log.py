"""Structured leveled logging with per-module filtering
(ref: libs/log/ go-kit logger + filter.go).

Thin layer over stdlib logging: key=value structured suffixes, per-module
level overrides (`filter.go`'s AllowLevelWith semantics), and a tracing mode
that records callsites.
"""

from __future__ import annotations

import logging
import sys
from typing import Any, Dict, Optional

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"


def _kv(kwargs: Dict[str, Any]) -> str:
    if not kwargs:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in kwargs.items())


class Logger:
    def __init__(self, name: str = "tm", base: Optional[logging.Logger] = None):
        self._log = base or logging.getLogger(name)

    def with_module(self, module: str) -> "Logger":
        return Logger(base=self._log.getChild(module))

    def debug(self, msg: str, **kw) -> None:
        self._log.debug("%s%s", msg, _kv(kw))

    def info(self, msg: str, **kw) -> None:
        self._log.info("%s%s", msg, _kv(kw))

    def error(self, msg: str, **kw) -> None:
        self._log.error("%s%s", msg, _kv(kw))


def setup(
    level: str = "info",
    module_levels: Optional[Dict[str, str]] = None,
    stream=None,
) -> Logger:
    """Configure root 'tm' logger; module_levels maps e.g. {'consensus':'debug'}
    (the reference's log_level 'consensus:debug,*:error' filter syntax)."""
    # configure the real root: services log under many top-level names
    # ("tm.*", "Switch", "consensus.State", "MConn-..."); attaching only to
    # "tm" would silently drop every p2p/consensus service log
    root = logging.getLogger()
    root.setLevel(getattr(logging, level.upper()))
    if not root.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
    for noisy in ("jax", "jax._src"):  # jax debug spam at tm debug levels
        logging.getLogger(noisy).setLevel(logging.WARNING)
    for mod, lvl in (module_levels or {}).items():
        logging.getLogger(f"tm.{mod}").setLevel(getattr(logging, lvl.upper()))
    return Logger()


def parse_log_level(spec: str) -> tuple:
    """'consensus:debug,state:info,*:error' -> (default, {module: level})."""
    default = "info"
    mods: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            mod, lvl = part.split(":", 1)
            if mod == "*":
                default = lvl
            else:
                mods[mod] = lvl
        else:
            default = part
    return default, mods
