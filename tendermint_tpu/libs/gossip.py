"""Shared wait-iteration walker for CList-backed gossip
(the reference duplicates this loop in mempool/reactor.go:118-166 and
evidence/reactor.go:109-160; here both reactors share one implementation).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

IDLE_SLEEP = 0.01
RETRY_SLEEP = 0.1


def walk_and_send(
    alive: Callable[[], bool],
    front: Callable[[], Optional[object]],
    send: Callable[[object], bool],
    hold_back: Optional[Callable[[object], bool]] = None,
) -> None:
    """Walk a CList forever, delivering each element exactly once per walker:

    * ``alive()`` — loop guard (reactor + peer running);
    * ``front()`` — list head accessor;
    * ``send(value)`` — deliver; False = retry later;
    * ``hold_back(value)`` — True = not yet (e.g. peer height lags).

    Advancing blocks on next_wait (new elements wake the walker); a removed
    tail anchor restarts from the front — consumers must tolerate the
    occasional duplicate (both pools dedup)."""
    el = None
    while alive():
        if el is None:
            el = front()
            if el is None:
                time.sleep(IDLE_SLEEP)
                continue
        value = el.value
        if hold_back is not None and hold_back(value):
            time.sleep(RETRY_SLEEP)
            continue
        if not send(value):
            time.sleep(RETRY_SLEEP)
            continue
        # sent exactly once — block until a successor exists
        while alive():
            nxt = el.next_wait(timeout=0.1)
            if nxt is not None:
                el = nxt
                break
            if el.removed:
                el = None
                break
