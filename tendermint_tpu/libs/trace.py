"""Span tracer — a lock-protected ring buffer of timed spans exported in
Chrome trace-event JSON (load via chrome://tracing or https://ui.perfetto.dev).

The Go reference leans on pprof/go-trace for this (node/node.go:474-479);
here the interesting timelines are host-side seams the device profiler never
sees: consensus step transitions, WAL fsync, the fast-sync window pipeline,
mempool recheck, RPC dispatch.  Usage:

    from tendermint_tpu.libs import trace
    with trace.span("fastsync.window", h0=h, n=n):
        ...
    trace.instant("consensus.step", height=h, round=r, step=s)

Disabled (the default) the hot-path cost is one attribute check and a shared
no-op context manager — nothing is allocated and nothing is recorded; the
host fast-sync bench gates this at <1% overhead.  Enable with TM_TRACE=1 in
the environment, trace.enable(), or the `trace_reset` RPC; export with the
`dump_trace` RPC or trace.chrome_trace().

The buffer is a fixed-size ring: recording never blocks on a consumer and
never grows memory — old spans are overwritten (dropped() counts them).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

_now_ns = time.perf_counter_ns

DEFAULT_CAPACITY = 8192


class _NoopSpan:
    """Shared do-nothing context manager — the disabled-path return value."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self._t0 = _now_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer.record(self.name, self._t0, _now_ns(), self.args)
        return False


class Tracer:
    """The ring buffer.  One module-level instance serves the process; tests
    construct their own."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._mtx = threading.Lock()
        self.enabled = False
        self._configure(capacity)

    def _configure(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: List[Optional[tuple]] = [None] * capacity
        self._next = 0  # total records ever written; ring slot = _next % cap

    # control ---------------------------------------------------------------
    def enable(self, capacity: Optional[int] = None) -> None:
        with self._mtx:
            if capacity is not None and capacity != self.capacity:
                self._configure(capacity)
            self.enabled = True

    def disable(self) -> None:
        with self._mtx:
            self.enabled = False

    def reset(self, capacity: Optional[int] = None) -> None:
        with self._mtx:
            self._configure(capacity if capacity is not None else self.capacity)

    def dropped(self) -> int:
        """Spans overwritten by ring wraparound since the last reset."""
        with self._mtx:
            return max(0, self._next - self.capacity)

    def __len__(self) -> int:
        with self._mtx:
            return min(self._next, self.capacity)

    # recording -------------------------------------------------------------
    def span(self, name: str, **args) -> object:
        if not self.enabled:
            return _NOOP
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        t = _now_ns()
        self.record(name, t, None, args)

    def record(self, name: str, t0_ns: int, t1_ns: Optional[int],
               args: dict) -> None:
        """t1_ns None marks an instant event.  Called from arbitrary threads;
        the lock covers one list store + one increment."""
        if not self.enabled:
            return
        ident = threading.get_ident()
        tname = threading.current_thread().name
        with self._mtx:
            self._buf[self._next % self.capacity] = (
                name, t0_ns, t1_ns, ident, tname, args
            )
            self._next += 1

    # export ----------------------------------------------------------------
    def export(self) -> List[dict]:
        """Chrome trace-event list, oldest first.  ts/dur are microseconds
        (the trace-event spec's unit); tid carries the Python thread ident
        with thread names emitted as metadata events."""
        with self._mtx:
            n = self._next
            if n <= self.capacity:
                records = [r for r in self._buf[:n]]
            else:
                cut = n % self.capacity
                records = self._buf[cut:] + self._buf[:cut]
        pid = os.getpid()
        events: List[dict] = []
        seen_tids = {}
        for rec in records:
            if rec is None:
                continue
            name, t0, t1, tid, tname, args = rec
            seen_tids.setdefault(tid, tname)
            ev = {
                "name": name,
                "cat": name.split(".", 1)[0],
                "pid": pid,
                "tid": tid,
                "ts": t0 / 1000.0,
            }
            if t1 is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = (t1 - t0) / 1000.0
            if args:
                ev["args"] = args
            events.append(ev)
        meta = [
            {
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in seen_tids.items()
        ]
        return meta + events

    def chrome_trace(self) -> dict:
        return {"traceEvents": self.export(), "displayTimeUnit": "ms"}


# -- module-level default tracer ------------------------------------------------

_tracer = Tracer(
    int(os.environ.get("TM_TRACE_BUFFER", "") or DEFAULT_CAPACITY)
)
if os.environ.get("TM_TRACE", "") not in ("", "0"):
    _tracer.enable()


def get_tracer() -> Tracer:
    return _tracer


def enabled() -> bool:
    return _tracer.enabled


def enable(capacity: Optional[int] = None) -> None:
    _tracer.enable(capacity)


def disable() -> None:
    _tracer.disable()


def reset(capacity: Optional[int] = None) -> None:
    _tracer.reset(capacity)


def dropped() -> int:
    return _tracer.dropped()


def span(name: str, **args) -> object:
    """`with trace.span("fastsync.window", h0=.., n=..): ...` — returns the
    shared no-op when disabled (zero allocation beyond the kwargs the caller
    already built)."""
    if not _tracer.enabled:
        return _NOOP
    return _Span(_tracer, name, args)


def instant(name: str, **args) -> None:
    if not _tracer.enabled:
        return
    _tracer.instant(name, **args)


def export() -> List[dict]:
    return _tracer.export()


def chrome_trace() -> dict:
    return _tracer.chrome_trace()
