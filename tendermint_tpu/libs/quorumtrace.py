"""Quorum observatory — cross-node vote-propagation fusion and the live
per-height quorum-formation analyzer.

The flight recorder (consensus/flight.py) stamps each vote's full journey
with wall-clock ns:

    signed      our own vote the instant the privval signature lands
    first_send  first gossip send of each validator's vote to any peer
    arrivals    first sighting of each validator's vote at the reactor
                receive seam (BEFORE VoteSet dedup)
    contrib     the instant each validator's vote was ADDED to the vote
                set, with its voting power (the quorum contribution)
    dup_by_peer duplicate votes per gossiping peer (amplification waste)

This module fuses those stamps two ways:

* **Pure fusion functions** (`build_journeys`, `completion_curve`,
  `gossip_ledger`, `flush_attribution`) operate on dump dicts — the
  `dump_flight` / `dump_quorum` RPC payloads after a JSON round trip —
  with per-node clock corrections supplied by the caller (the commit-
  anchor median math in scripts/trace_merge.py).  scripts/quorum_report.py
  composes them into the operator-facing report.

* **`QuorumTrace`** is the live per-ConsensusState analyzer: once per
  committed height (from `_do_finalize_commit`, right after the critpath
  analyzer) it cuts the height's contrib stamps into a quorum completion
  curve — time for arriving voting power to cross 1/3, 1/2, 2/3 of the
  valset total, with the pivotal validator named — feeds the
  `tendermint_consensus_quorum_time_to_{third,two_thirds}_seconds`
  histograms, joins the VoteFeed flush ledger for batching attribution,
  and keeps a ring of per-height records behind the standard
  ``snapshot(limit)`` dump contract (`dump_quorum` RPC).

Like the critpath analyzer it piggybacks on the flight recorder's enable
gate and never raises into the consensus thread — internal errors are
counted, not propagated.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from tendermint_tpu.libs.critpath import percentile
from tendermint_tpu.libs.sketch import QuantileSketch

VOTE_KINDS = ("prevote", "precommit")

DEFAULT_CAPACITY = 256  # heights remembered before the ring evicts
DEFAULT_SAMPLE_WINDOW = 512  # rolling time-to-quorum percentile samples

# quorum thresholds as (numerator, denominator) of total voting power;
# "two_thirds" uses the STRICT Tendermint rule (cum * 3 > total * 2)
_THRESHOLDS = (
    ("third", 1, 3),
    ("half", 1, 2),
    ("two_thirds", 2, 3),
)


def _crossed(cum: int, total: int, num: int, den: int, name: str) -> bool:
    if name == "two_thirds":
        return cum * den > total * num  # strict: exactly 2/3 must NOT cross
    return cum * den >= total * num


# ---------------------------------------------------------------------------
# pure fusion over dump dicts
# ---------------------------------------------------------------------------


def _vote_slot(rec: dict, kind: str) -> dict:
    slot = rec.get(kind)
    return slot if isinstance(slot, dict) else {}


def _int_keys(d: Optional[dict]) -> dict:
    """Validator-index maps survive a JSON round trip with string keys —
    coerce back to int so fusion joins across transports."""
    if not d:
        return {}
    return {int(k): v for k, v in d.items()}


def build_journeys(
    dumps: Sequence[dict], skews: Optional[Dict[str, int]] = None
) -> List[dict]:
    """Fuse flight dumps into per-(height, kind, validator) vote journeys.

    Every node's stamps are shifted onto the reference timeline by its
    entry in ``skews`` (node_id -> ns to ADD, trace_merge.compute_skews
    convention).  Each journey carries:

        origin / signed_ns   the signer node and its corrected sign stamp
        first_send           the origin's first gossip send (corrected)
        arrivals             per receiving node: the receive-seam first
                             sighting — ``t_ns`` is the raw corrected
                             stamp (reconciles EXACTLY with the
                             receiver's record), ``t_mono_ns`` is clamped
                             so sign <= send <= arrival always holds even
                             when residual skew inverts neighbors
                             (``clamped`` flags it)
        contrib              per node: when the vote entered that node's
                             vote set, with its voting power

    Journeys are sorted (height, kind, validator_index); a journey with no
    known origin (the signer's dump is missing or evicted) still fuses its
    arrivals — ``origin`` is None and arrivals are not clamped.
    """
    skews = skews or {}
    # (height, kind, vi) -> journey
    out: Dict[tuple, dict] = {}

    def journey(height: int, kind: str, vi: int) -> dict:
        key = (height, kind, vi)
        j = out.get(key)
        if j is None:
            j = {
                "height": height,
                "kind": kind,
                "validator_index": vi,
                "origin": None,
                "signed_ns": None,
                "round": None,
                "first_send": None,
                "arrivals": {},
                "contrib": {},
                "clamped": False,
            }
            out[key] = j
        return j

    for dump in dumps:
        node = dump.get("node_id", "")
        skew = int(skews.get(node, 0))
        for rec in dump.get("records") or []:
            height = rec.get("height")
            if height is None:
                continue
            for kind in VOTE_KINDS:
                slot = _vote_slot(rec, kind)
                signed = slot.get("signed")
                if signed is not None:
                    vi = int(signed.get("validator_index", -1))
                    if vi >= 0:
                        j = journey(height, kind, vi)
                        j["origin"] = node
                        j["signed_ns"] = int(signed["t"]) + skew
                        j["round"] = signed.get("round")
                        send = _int_keys(slot.get("first_send")).get(vi)
                        if send is not None:
                            j["first_send"] = {
                                "t_ns": int(send["t"]) + skew,
                                "peer": send.get("peer", ""),
                            }
                for vi, mark in _int_keys(slot.get("arrivals")).items():
                    j = journey(height, kind, vi)
                    j["arrivals"][node] = {
                        "t_ns": int(mark["t"]) + skew,
                        "peer": mark.get("peer", ""),
                        "round": mark.get("round"),
                    }
                for vi, mark in _int_keys(slot.get("contrib")).items():
                    j = journey(height, kind, vi)
                    j["contrib"][node] = {
                        "t_ns": int(mark["t"]) + skew,
                        "power": int(mark.get("power") or 0),
                    }

    # monotone view: clamp each leg to its predecessor (residual skew after
    # anchor correction can invert real sub-ms gaps; the raw t_ns is kept
    # for exact per-node reconciliation)
    for j in out.values():
        floor = j["signed_ns"]
        if j["first_send"] is not None and floor is not None:
            mono = max(j["first_send"]["t_ns"], floor)
            j["first_send"]["t_mono_ns"] = mono
            if mono != j["first_send"]["t_ns"]:
                j["clamped"] = True
            floor = mono
        for mark in j["arrivals"].values():
            if floor is None:
                mark["t_mono_ns"] = mark["t_ns"]
                continue
            mono = max(mark["t_ns"], floor)
            mark["t_mono_ns"] = mono
            if mono != mark["t_ns"]:
                j["clamped"] = True

    return [out[k] for k in sorted(out)]


def completion_curve(
    rec: dict, kind: str, total_power: int, skew_ns: int = 0
) -> Optional[dict]:
    """One node's quorum completion curve for (height, kind): sort the
    contrib stamps, accumulate power, and mark the instants arriving power
    crossed 1/3, 1/2 and (strictly) 2/3 of ``total_power``.

    t0 is the height's round entry (first round stamp); returns None when
    the record has no rounds or no contributions.  The validator whose
    contribution crossed 2/3 is the height's **pivotal** validator — the
    one the commit actually waited for.
    """
    rounds = rec.get("rounds") or []
    contrib = _int_keys(_vote_slot(rec, kind).get("contrib"))
    if not rounds or not contrib or total_power <= 0:
        return None
    t0 = min(int(r["t"]) for r in rounds) + skew_ns
    arrivals = sorted(
        (int(m["t"]) + skew_ns, vi, int(m.get("power") or 0))
        for vi, m in contrib.items()
    )
    crossings: Dict[str, Optional[dict]] = {
        name: None for name, _, _ in _THRESHOLDS
    }
    cum = 0
    for t, vi, power in arrivals:
        cum += power
        for name, num, den in _THRESHOLDS:
            if crossings[name] is None and _crossed(
                cum, total_power, num, den, name
            ):
                crossings[name] = {
                    "t_ns": t,
                    "seconds": max(0.0, (t - t0) / 1e9),
                    "validator_index": vi,
                    "cum_power": cum,
                }
    present = [vi for _, vi, _ in arrivals]
    pivotal = crossings["two_thirds"]
    return {
        "height": rec.get("height"),
        "kind": kind,
        "t0_ns": t0,
        "total_power": int(total_power),
        "present_power": cum,
        "present": sorted(present),
        "crossings": crossings,
        "pivotal_validator": (
            pivotal["validator_index"] if pivotal is not None else None
        ),
    }


def gossip_ledger(
    dumps: Sequence[dict],
    skews: Optional[Dict[str, int]] = None,
    journeys: Optional[Sequence[dict]] = None,
) -> dict:
    """Gossip-efficiency accounting across all dumps.

    Per link (gossiping peer -> receiving node): first sightings (the
    arrivals slots), duplicates (dup_by_peer), and — when ``journeys`` are
    supplied — median/p99 sign-to-arrival propagation latency over that
    link.  The amplification **waste ratio** is duplicates divided by
    first sightings: 0 means every vote traveled each edge once, 1 means
    half the vote traffic was redundant re-gossip.
    """
    links: Dict[tuple, dict] = {}

    def link(peer: str, node: str) -> dict:
        entry = links.get((peer, node))
        if entry is None:
            entry = {"first": 0, "dup": 0, "latency_s": []}
            links[(peer, node)] = entry
        return entry

    first_total = dup_total = 0
    for dump in dumps:
        node = dump.get("node_id", "")
        for rec in dump.get("records") or []:
            for kind in VOTE_KINDS:
                slot = _vote_slot(rec, kind)
                for mark in _int_keys(slot.get("arrivals")).values():
                    link(mark.get("peer", ""), node)["first"] += 1
                    first_total += 1
                for peer, n in (slot.get("dup_by_peer") or {}).items():
                    link(peer, node)["dup"] += int(n)
                    dup_total += int(n)

    if journeys:
        for j in journeys:
            signed = j.get("signed_ns")
            if signed is None:
                continue
            for node, mark in j["arrivals"].items():
                links.get((mark.get("peer", ""), node), {}).setdefault(
                    "latency_s", []
                ).append(max(0.0, (mark["t_ns"] - signed) / 1e9))

    out_links = []
    for (peer, node), entry in sorted(links.items()):
        lat = entry.pop("latency_s")
        # per-link latency through the mergeable sketch (soak_report pools
        # links fleet-wide); the exact values stay under window_* keys
        sk = QuantileSketch()
        sk.extend(lat)
        out_links.append({
            "peer": peer,
            "node": node,
            "first_sightings": entry["first"],
            "duplicates": entry["dup"],
            "latency_p50_s": sk.p50(),
            "latency_p99_s": sk.p99(),
            "window_latency_p50_s": percentile(lat, 50),
            "window_latency_p99_s": percentile(lat, 99),
            "latency_samples": len(lat),
            "latency_sketch": sk.to_dict(),
        })
    return {
        "links": out_links,
        "first_sightings": first_total,
        "duplicates": dup_total,
        "waste_ratio": (dup_total / first_total) if first_total else 0.0,
    }


def flush_attribution(
    flush_dump: Optional[dict], height: int
) -> List[dict]:
    """VoteFeed flush records whose group list covers ``height`` — the
    batching-added spans to subtract from the height's quorum tail.
    ``flush_dump`` is VoteFeed.flush_records() (possibly JSON round-
    tripped); group keys are [height, round, vote_type] lists."""
    if not flush_dump:
        return []
    out = []
    for rec in flush_dump.get("records") or []:
        for gk in rec.get("groups") or []:
            if (
                isinstance(gk, (list, tuple))
                and gk
                and int(gk[0]) == height
            ):
                out.append(dict(rec))
                break
    return out


# ---------------------------------------------------------------------------
# live per-node analyzer
# ---------------------------------------------------------------------------


class QuorumTrace:
    """Ring of per-height quorum-formation records plus rolling
    time-to-quorum percentile windows.  One per ConsensusState
    (``cs.quorumtrace``), fed from the consensus thread's finalize path;
    snapshots may come from RPC threads, so every derived count in a
    snapshot is computed under ONE lock acquisition (the flight recorder's
    wraparound contract)."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        sample_window: int = DEFAULT_SAMPLE_WINDOW,
        metrics=None,
    ):
        self._mtx = threading.Lock()
        self.metrics = metrics  # NodeMetrics (quorum_time_to_*) or None
        self.node_id = ""  # refreshed from the flight recorder on analyze
        self.sample_window = max(int(sample_window), 1)
        self.analysis_errors = 0
        self._configure(capacity)

    def _configure(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(
                f"quorumtrace capacity must be >= 1, got {capacity}"
            )
        self.capacity = capacity
        self._records: List[dict] = []  # oldest first
        self._evicted = 0
        # kind -> rolling [seconds] rings for the two crossing thresholds
        self._third_samples: Dict[str, List[float]] = {}
        self._two_thirds_samples: Dict[str, List[float]] = {}
        # whole-run mergeable sketches next to the exact rolling windows
        # (fixed gamma: two nodes' sketches pool exactly in soak_report)
        self._sketches: Dict[str, QuantileSketch] = {
            f"{kind}_{name}": QuantileSketch()
            for kind in VOTE_KINDS
            for name in ("third", "two_thirds")
        }

    # control ---------------------------------------------------------------
    def reset(self, capacity: Optional[int] = None) -> None:
        with self._mtx:
            self._configure(
                capacity if capacity is not None else self.capacity
            )
            self.analysis_errors = 0

    def __len__(self) -> int:
        with self._mtx:
            return len(self._records)

    # ingestion -------------------------------------------------------------
    def on_height_complete(
        self, height: int, flight, validators=None, vote_feed=None
    ) -> Optional[dict]:
        """Analyze one committed height.  Called from _do_finalize_commit
        right after the critpath analyzer, while ``validators`` is still
        the committed height's valset (its total power scales the curve).
        Returns the record (tests use it) or None when the flight recorder
        is off / the record is gone."""
        if not getattr(flight, "enabled", False):
            return None
        try:
            rec = flight.peek(height)
            if rec is None:
                return None
            total_power = 0
            if validators is not None:
                try:
                    total_power = int(validators.total_voting_power())
                except Exception:
                    total_power = 0
            curves = {}
            for kind in VOTE_KINDS:
                if total_power <= 0:
                    # no valset in sight: scale by the power that DID
                    # arrive, so crossings still mark relative progress
                    contrib = _int_keys(
                        _vote_slot(rec, kind).get("contrib")
                    )
                    total = sum(
                        int(m.get("power") or 0) for m in contrib.values()
                    )
                else:
                    total = total_power
                curve = completion_curve(rec, kind, total)
                if curve is not None:
                    curves[kind] = curve
            if not curves:
                return None
            first = dup = 0
            dup_by_peer: Dict[str, int] = {}
            for kind in VOTE_KINDS:
                slot = _vote_slot(rec, kind)
                first += len(slot.get("arrivals") or {})
                for peer, n in (slot.get("dup_by_peer") or {}).items():
                    dup += int(n)
                    dup_by_peer[peer] = dup_by_peer.get(peer, 0) + int(n)
            out = {
                "height": height,
                "node_id": getattr(flight, "node_id", ""),
                "total_power": int(total_power),
                "curves": curves,
                "gossip": {
                    "first_sightings": first,
                    "duplicates": dup,
                    "dup_by_peer": dup_by_peer,
                },
                "flushes": (
                    flush_attribution(vote_feed.flush_records(), height)
                    if vote_feed is not None
                    and hasattr(vote_feed, "flush_records")
                    else []
                ),
            }
            self.node_id = getattr(flight, "node_id", "") or self.node_id
            self._ingest(out)
            if self.metrics is not None:
                for kind, curve in curves.items():
                    third = curve["crossings"]["third"]
                    if third is not None:
                        self.metrics.quorum_time_to_third.observe(
                            third["seconds"], (kind,)
                        )
                    two = curve["crossings"]["two_thirds"]
                    if two is not None:
                        self.metrics.quorum_time_to_two_thirds.observe(
                            two["seconds"], (kind,)
                        )
            return out
        except Exception:
            # never let the analyzer take down the consensus thread
            self.analysis_errors += 1
            return None

    def _ingest(self, out: dict) -> None:
        with self._mtx:
            self._records.append(out)
            if len(self._records) > self.capacity:
                del self._records[: len(self._records) - self.capacity]
                self._evicted += 1
            win = self.sample_window
            for kind, curve in out["curves"].items():
                for name, ring in (
                    ("third", self._third_samples),
                    ("two_thirds", self._two_thirds_samples),
                ):
                    mark = curve["crossings"][name]
                    if mark is None:
                        continue
                    xs = ring.setdefault(kind, [])
                    xs.append(mark["seconds"])
                    if len(xs) > win:
                        del xs[: len(xs) - win]
                    self._sketches[f"{kind}_{name}"].add(mark["seconds"])

    # export ----------------------------------------------------------------
    def records(self, limit: Optional[int] = None) -> List[dict]:
        """Copied records, oldest first (newest N when limit is set)."""
        with self._mtx:
            return self._records_locked(limit)

    def _records_locked(self, limit: Optional[int]) -> List[dict]:
        recs = self._records
        if limit is not None and limit >= 0:
            recs = recs[-limit:] if limit else []
        return [dict(r) for r in recs]

    def quorum_stats(self) -> Dict[str, dict]:
        with self._mtx:
            return self._quorum_stats_locked()

    def _quorum_stats_locked(self) -> Dict[str, dict]:
        out = {}
        for kind in VOTE_KINDS:
            third = self._third_samples.get(kind, ())
            two = self._two_thirds_samples.get(kind, ())
            sk_third = self._sketches[f"{kind}_third"]
            sk_two = self._sketches[f"{kind}_two_thirds"]
            out[kind] = {
                # whole-run sketch values lead; the exact rolling-window
                # values ride alongside under window_* for continuity
                "n": sk_two.count,
                "third_p50_seconds": sk_third.p50(),
                "third_p99_seconds": sk_third.p99(),
                "two_thirds_p50_seconds": sk_two.p50(),
                "two_thirds_p99_seconds": sk_two.p99(),
                "window_n": len(two),
                "window_third_p50_seconds": percentile(third, 50),
                "window_third_p99_seconds": percentile(third, 99),
                "window_two_thirds_p50_seconds": percentile(two, 50),
                "window_two_thirds_p99_seconds": percentile(two, 99),
            }
        return out

    def sketches(self) -> Dict[str, dict]:
        """Serialized time-to-quorum sketches (spool / fleet merge)."""
        with self._mtx:
            return self._sketches_locked()

    def _sketches_locked(self) -> Dict[str, dict]:
        return {name: sk.to_dict() for name, sk in self._sketches.items()}

    def snapshot(self, limit: Optional[int] = None) -> dict:
        """The dump_quorum RPC payload, under ONE lock acquisition so the
        truncated flag can never contradict the record list."""
        with self._mtx:
            total = len(self._records)
            recs = self._records_locked(limit)
            return {
                "node_id": self.node_id,
                "capacity": self.capacity,
                "sample_window": self.sample_window,
                "evicted": self._evicted,
                "analysis_errors": self.analysis_errors,
                "total_records": total,
                "truncated": len(recs) < total,
                "records": recs,
                "quorum_stats": self._quorum_stats_locked(),
                "sketches": self._sketches_locked(),
            }
