"""Bech32 address encoding (ref: libs/bech32/bech32.go, which wraps
btcutil's BIP-0173 implementation).

`convert_and_encode(hrp, data)` / `decode_and_convert(bech)` mirror the
reference's two exports; the BIP-0173 primitives are implemented here.
"""

from __future__ import annotations

from typing import List, Tuple

_CHARSET = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"
_GEN = (0x3B6A57B2, 0x26508E6D, 0x1EA119FA, 0x3D4233DD, 0x2A1462B3)


def _polymod(values) -> int:
    chk = 1
    for v in values:
        top = chk >> 25
        chk = (chk & 0x1FFFFFF) << 5 ^ v
        for i in range(5):
            chk ^= _GEN[i] if ((top >> i) & 1) else 0
    return chk


def _hrp_expand(hrp: str) -> List[int]:
    return [ord(c) >> 5 for c in hrp] + [0] + [ord(c) & 31 for c in hrp]


def _create_checksum(hrp: str, data: List[int]) -> List[int]:
    values = _hrp_expand(hrp) + data
    mod = _polymod(values + [0, 0, 0, 0, 0, 0]) ^ 1
    return [(mod >> 5 * (5 - i)) & 31 for i in range(6)]


def _verify_checksum(hrp: str, data: List[int]) -> bool:
    return _polymod(_hrp_expand(hrp) + data) == 1


def bech32_encode(hrp: str, data: List[int]) -> str:
    combined = data + _create_checksum(hrp, data)
    return hrp + "1" + "".join(_CHARSET[d] for d in combined)


def bech32_decode(bech: str) -> Tuple[str, List[int]]:
    if bech.lower() != bech and bech.upper() != bech:
        raise ValueError("bech32: mixed case")
    bech = bech.lower()
    pos = bech.rfind("1")
    if pos < 1 or pos + 7 > len(bech) or len(bech) > 90:
        raise ValueError("bech32: invalid separator position or length")
    hrp = bech[:pos]
    if any(ord(c) < 33 or ord(c) > 126 for c in hrp):
        raise ValueError("bech32: invalid hrp character")
    try:
        data = [_CHARSET.index(c) for c in bech[pos + 1 :]]
    except ValueError:
        raise ValueError("bech32: invalid data character")
    if not _verify_checksum(hrp, data):
        raise ValueError("bech32: checksum mismatch")
    return hrp, data[:-6]


def convert_bits(data, from_bits: int, to_bits: int, pad: bool) -> List[int]:
    """General power-of-2 base conversion (bech32.go ConvertBits)."""
    acc = 0
    bits = 0
    ret: List[int] = []
    maxv = (1 << to_bits) - 1
    max_acc = (1 << (from_bits + to_bits - 1)) - 1
    for value in data:
        if value < 0 or value >> from_bits:
            raise ValueError("bech32: invalid data range")
        acc = ((acc << from_bits) | value) & max_acc
        bits += from_bits
        while bits >= to_bits:
            bits -= to_bits
            ret.append((acc >> bits) & maxv)
    if pad:
        if bits:
            ret.append((acc << (to_bits - bits)) & maxv)
    elif bits >= from_bits or ((acc << (to_bits - bits)) & maxv):
        raise ValueError("bech32: invalid incomplete group")
    return ret


def convert_and_encode(hrp: str, data: bytes) -> str:
    """bech32.go:9 ConvertAndEncode: bytes -> 5-bit groups -> bech32."""
    return bech32_encode(hrp, convert_bits(data, 8, 5, True))


def decode_and_convert(bech: str) -> Tuple[str, bytes]:
    """bech32.go:19 DecodeAndConvert: bech32 -> 5-bit groups -> bytes."""
    hrp, data = bech32_decode(bech)
    return hrp, bytes(convert_bits(data, 5, 8, False))
