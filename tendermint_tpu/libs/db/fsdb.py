"""FSDB — file-per-key persistence (ref: libs/db/fsdb.go).

Each key is one file in the directory, filename = percent-escaped key
(fsdb.go escapeKey via url.QueryEscape). Human-inspectable and trivially
greppable; for debugging and tiny stores, not the hot path (the reference
carries the same warning).
"""

from __future__ import annotations

import os
import threading
import urllib.parse
from typing import Dict, Iterator, Optional, Tuple

from tendermint_tpu.libs.db.kv import DB, Batch


class FSDB(DB):
    def __init__(self, dir: str):
        self._dir = dir
        self._mtx = threading.Lock()
        os.makedirs(dir, exist_ok=True)

    # -- paths -------------------------------------------------------------
    def _path(self, key: bytes) -> str:
        name = urllib.parse.quote_from_bytes(bytes(key), safe="")
        # quote() leaves '.' unescaped, so the keys b"." / b".." would
        # resolve to the directory itself / its parent — escape any all-dots
        # name (round-trips fine: unquote maps %2E back to '.')
        if name and set(name) == {"."}:
            name = name.replace(".", "%2E")
        return os.path.join(self._dir, name)

    @staticmethod
    def _unescape(name: str) -> bytes:
        return urllib.parse.unquote_to_bytes(name)

    # -- DB interface ------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        with self._mtx:
            try:
                with open(self._path(key), "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None

    def has(self, key: bytes) -> bool:
        with self._mtx:
            return os.path.exists(self._path(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._set(key, value, sync=False)

    def set_sync(self, key: bytes, value: bytes) -> None:
        self._set(key, value, sync=True)

    # Temp files must be impossible to confuse with stored keys: escaped key
    # filenames only ever contain %XX hex escapes, so a "%!" prefix (invalid
    # percent-encoding) can never collide with any key's file. (A plain
    # ".tmp" suffix DID collide: writing key b"foo" went through "foo.tmp",
    # destroying the data of an actual key b"foo.tmp".)
    _TMP_PREFIX = "%!tmp-"

    def _set(self, key: bytes, value: bytes, sync: bool) -> None:
        path = self._path(key)
        tmp = os.path.join(
            self._dir, f"{self._TMP_PREFIX}{os.getpid()}-{threading.get_ident()}"
        )
        with self._mtx:
            with open(tmp, "wb") as f:
                f.write(bytes(value))
                if sync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            try:
                os.unlink(self._path(key))
            except FileNotFoundError:
                pass

    def delete_sync(self, key: bytes) -> None:
        self.delete(key)

    def iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        with self._mtx:
            names = [
                n for n in os.listdir(self._dir)
                if not n.startswith(self._TMP_PREFIX)
            ]
        keys = sorted(self._unescape(n) for n in names)
        if reverse:
            keys = list(reversed(keys))
        out = []
        for k in keys:
            if start is not None and k < start:
                continue
            if end is not None and k >= end:
                continue
            v = self.get(k)
            if v is not None:
                out.append((k, v))
        return iter(out)

    def apply_batch(self, ops) -> None:
        for op, k, v in ops:
            if op == "set":
                self.set(k, v)
            else:
                self.delete(k)

    def close(self) -> None:
        pass

    def stats(self) -> Dict[str, str]:
        with self._mtx:
            n = len(
                [x for x in os.listdir(self._dir)
                 if not x.startswith(self._TMP_PREFIX)]
            )
        return {"keys": str(n), "dir": self._dir}
