"""KV database abstraction (ref: libs/db/db.go, types.go).

Backends:
  * MemDB      — sorted in-memory dict (ref memdb.go); test default
  * SQLiteDB   — durable single-file store on sqlite3 (stdlib) — fills the
                 role of the reference's default goleveldb backend
  * PrefixDB   — namespaced view over another DB (ref prefix_db.go)

Iteration is ordered by raw bytes, [start, end) with None = unbounded, same
contract as the reference's Iterator.
"""

from __future__ import annotations

import bisect
import os
import sqlite3
import threading
from abc import ABC, abstractmethod
from typing import Dict, Iterator, List, Optional, Tuple


class DB(ABC):
    @abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abstractmethod
    def iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete_sync(self, key: bytes) -> None:
        self.delete(key)

    def close(self) -> None: ...

    def batch(self) -> "Batch":
        return Batch(self)

    def stats(self) -> Dict[str, str]:
        return {}


class Batch:
    """Write batch (ref types.go Batch): buffered ops applied atomically-ish."""

    def __init__(self, db: DB):
        self._db = db
        self._ops: List[Tuple[str, bytes, Optional[bytes]]] = []

    def set(self, key: bytes, value: bytes) -> "Batch":
        self._ops.append(("set", bytes(key), bytes(value)))
        return self

    def delete(self, key: bytes) -> "Batch":
        self._ops.append(("del", bytes(key), None))
        return self

    def write(self) -> None:
        if hasattr(self._db, "apply_batch"):
            self._db.apply_batch(self._ops)
        else:
            for op, k, v in self._ops:
                if op == "set":
                    self._db.set(k, v)
                else:
                    self._db.delete(k)
        self._ops.clear()

    def write_sync(self) -> None:
        self.write()


class MemDB(DB):
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []  # sorted
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mtx:
            return self._data.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        key, value = bytes(key), bytes(value)
        with self._mtx:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._mtx:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                self._keys.pop(i)

    def iterator(self, start=None, end=None, reverse=False):
        with self._mtx:
            lo = bisect.bisect_left(self._keys, start) if start is not None else 0
            hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
            keys = self._keys[lo:hi]
            items = [(k, self._data[k]) for k in keys]
        return iter(reversed(items) if reverse else items)

    def apply_batch(self, ops) -> None:
        with self._mtx:
            for op, k, v in ops:
                if op == "set":
                    self.set(k, v)
                else:
                    self.delete(k)

    def stats(self) -> Dict[str, str]:
        with self._mtx:
            return {"keys": str(len(self._data))}


class SQLiteDB(DB):
    """Durable KV on sqlite3 — the framework's disk backend (role of
    goleveldb in the reference; cgo-leveldb equivalent would be the C++
    native extension)."""

    def __init__(self, name: str, dir: str = "."):
        os.makedirs(dir, exist_ok=True)
        self.path = os.path.join(dir, name + ".db")
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mtx:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute("PRAGMA synchronous=FULL")
            try:
                self.set(key, value)
            finally:
                self._conn.execute("PRAGMA synchronous=NORMAL")

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def iterator(self, start=None, end=None, reverse=False):
        q = "SELECT k, v FROM kv"
        cond, args = [], []
        if start is not None:
            cond.append("k >= ?")
            args.append(bytes(start))
        if end is not None:
            cond.append("k < ?")
            args.append(bytes(end))
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        with self._mtx:
            rows = self._conn.execute(q, args).fetchall()
        return iter([(bytes(k), bytes(v)) for k, v in rows])

    def apply_batch(self, ops) -> None:
        with self._mtx:
            for op, k, v in ops:
                if op == "set":
                    self._conn.execute(
                        "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (k, v)
                    )
                else:
                    self._conn.execute("DELETE FROM kv WHERE k = ?", (k,))
            self._conn.commit()

    def close(self) -> None:
        with self._mtx:
            self._conn.close()

    def stats(self) -> Dict[str, str]:
        with self._mtx:
            n = self._conn.execute("SELECT COUNT(*) FROM kv").fetchone()[0]
        return {"keys": str(n), "path": self.path}


class PrefixDB(DB):
    """View of db where every key is namespaced by prefix (ref prefix_db.go)."""

    def __init__(self, db: DB, prefix: bytes):
        self._db = db
        self._prefix = bytes(prefix)

    def _k(self, key: bytes) -> bytes:
        return self._prefix + bytes(key)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._db.get(self._k(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._db.set(self._k(key), value)

    def set_sync(self, key: bytes, value: bytes) -> None:
        self._db.set_sync(self._k(key), value)

    def delete(self, key: bytes) -> None:
        self._db.delete(self._k(key))

    def iterator(self, start=None, end=None, reverse=False):
        p = self._prefix
        s = p + start if start is not None else p
        if end is not None:
            e = p + end
        else:
            # end of prefix range: increment last byte that can be incremented
            e = None
            pe = bytearray(p)
            for i in reversed(range(len(pe))):
                if pe[i] != 0xFF:
                    pe[i] += 1
                    e = bytes(pe[: i + 1])
                    break
        return (
            (k[len(p):], v) for k, v in self._db.iterator(s, e, reverse)
        )


def _fsdb_factory(name: str, dir: str):
    import os

    from tendermint_tpu.libs.db.fsdb import FSDB

    return FSDB(os.path.join(dir, f"{name}.db"))


_BACKENDS = {
    "memdb": lambda name, dir: MemDB(),
    "sqlite": SQLiteDB,
    "goleveldb": SQLiteDB,  # config-compat alias for the reference's default
    "fsdb": _fsdb_factory,  # file-per-key (libs/db/fsdb.go)
}


def new_db(name: str, backend: str = "sqlite", dir: str = ".") -> DB:
    try:
        factory = _BACKENDS[backend]
    except KeyError:
        raise ValueError(f"unknown db backend {backend!r}") from None
    return factory(name, dir)
