"""RemoteDB — the KV store served over gRPC (ref: libs/db/remotedb/ +
remotedb/grpcdb/: a DB service a node can keep on another machine).

Like the ABCI gRPC transport (abci/grpc.py), no generated protobuf stubs:
grpc's generic handler API with this framework's deterministic codec as the
message serializer (wire compatibility with the reference's proto schema is
a non-goal; the contract — named DBs behind one server, the full DB method
set over the network — is what's mirrored). Iterators are collected
server-side and returned in one response rather than streamed: remote
iteration in the reference exists for operator tooling over bounded ranges,
and a single framed response keeps the client's DB interface synchronous.
"""

from __future__ import annotations

import hmac
import threading
from typing import Dict, Iterator, Optional, Tuple

import grpc

from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.libs.db.kv import DB, Batch, new_db
from tendermint_tpu.libs.service import BaseService

_SERVICE = "tendermint.db.RemoteDB"
_METHODS = ("InitRemote", "Get", "Has", "Set", "SetSync", "Delete",
            "DeleteSync", "Iterator", "BatchWrite", "Stats")


def _enc(*fields) -> bytes:
    w = Writer()
    for f in fields:
        if isinstance(f, bool):
            w.bool(f)
        elif isinstance(f, int):
            w.svarint(f)
        elif isinstance(f, str):
            w.string(f)
        elif f is None:
            w.bool(False)
        else:
            w.bytes(bytes(f))
    return w.build()


def _opt_bytes(w: Writer, b: Optional[bytes]) -> None:
    if b is None:
        w.bool(False)
    else:
        w.bool(True)
        w.bytes(b)


def _read_opt_bytes(r: Reader) -> Optional[bytes]:
    return r.bytes() if r.bool() else None


class _TokenAuthInterceptor(grpc.ServerInterceptor):
    """Rejects any call whose `authorization` metadata doesn't carry the
    shared bearer token (constant-time compare).  The reference secures this
    exact surface with credentialed dials (grpcdb.go:31-41 TLS cert/key);
    the token is the transport-independent half — TLS wraps the channel
    below when cert/key are configured."""

    def __init__(self, token: str):
        self._want = f"Bearer {token}".encode()

        def _deny(request, context):
            context.abort(
                grpc.StatusCode.UNAUTHENTICATED, "missing or invalid db token"
            )

        self._deny_handler = grpc.unary_unary_rpc_method_handler(
            _deny,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )

    def intercept_service(self, continuation, handler_call_details):
        for key, value in handler_call_details.invocation_metadata or ():
            if key == "authorization":
                got = value.encode() if isinstance(value, str) else value
                if hmac.compare_digest(got, self._want):
                    return continuation(handler_call_details)
                break
        return self._deny_handler


class RemoteDBServer(BaseService):
    """Serves named databases; a client InitRemote(name, type, dir) selects
    (creating on first use) which one its handle operates on — the handle's
    identity travels as the name on every call (the reference binds one DB
    per connection; a name per request is the stateless equivalent).

    auth_token: required bearer token; None serves unauthenticated (loopback
    dev only).  tls_cert/tls_key: PEM file paths — when given the port is a
    TLS port (clients pass the CA cert as tls_ca), matching the reference's
    credentialed listener (remotedb/grpcdb/grpcdb.go ListenAndServe)."""

    def __init__(self, addr: str, dir: str = ".",
                 auth_token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        super().__init__("db.RemoteDBServer")
        self.addr = addr.replace("tcp://", "")
        self.dir = dir
        self.auth_token = auth_token
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._dbs: Dict[str, DB] = {}
        self._backends: Dict[str, str] = {}
        self._mtx = threading.Lock()
        self._server = None
        self.bound_port: Optional[int] = None

    def _db(self, name: str) -> DB:
        with self._mtx:
            db = self._dbs.get(name)
            if db is None:
                raise KeyError(f"remote db {name!r} not initialized")
            return db

    # -- handlers ----------------------------------------------------------
    _NAME_RE = None  # compiled lazily

    def _init_remote(self, req: bytes) -> bytes:
        import re

        r = Reader(req)
        # the client's dir is part of the reference protocol shape but the
        # SERVER owns placement: every store lives under self.dir
        name, typ, _client_dir = r.string(), r.string(), r.string()
        if RemoteDBServer._NAME_RE is None:
            RemoteDBServer._NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9._-]*$")
        # the name becomes a path component — an unauthenticated client must
        # not be able to traverse outside the server's data dir
        if (
            not RemoteDBServer._NAME_RE.match(name)
            or ".." in name
            or len(name) > 128
        ):
            raise ValueError(f"invalid remote db name {name!r}")
        with self._mtx:
            existing = self._backends.get(name)
            if existing is not None:
                if existing != typ:
                    # silently handing a memdb to a client that asked for a
                    # durable backend loses data with no error anywhere
                    raise ValueError(
                        f"remote db {name!r} already initialized with "
                        f"backend {existing!r}, not {typ!r}"
                    )
            else:
                self._dbs[name] = new_db(name, typ, self.dir)
                self._backends[name] = typ
        return _enc(True)

    def _get(self, req: bytes) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        v = db.get(r.bytes())
        w = Writer()
        _opt_bytes(w, v)
        return w.build()

    def _has(self, req: bytes) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        return _enc(bool(db.has(r.bytes())))

    def _set(self, req: bytes, sync: bool) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        k, v = r.bytes(), r.bytes()
        (db.set_sync if sync else db.set)(k, v)
        return _enc(True)

    def _delete(self, req: bytes, sync: bool) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        (db.delete_sync if sync else db.delete)(r.bytes())
        return _enc(True)

    def _iterator(self, req: bytes) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        start = _read_opt_bytes(r)
        end = _read_opt_bytes(r)
        reverse = r.bool()
        w = Writer()
        pairs = list(db.iterator(start, end, reverse))
        w.uvarint(len(pairs))
        for k, v in pairs:
            w.bytes(k)
            w.bytes(v)
        return w.build()

    def _batch_write(self, req: bytes) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        n = r.uvarint()
        ops = []
        for _ in range(n):
            op = r.string()
            k = r.bytes()
            v = r.bytes()
            ops.append((op, k, v))
        db.apply_batch(ops)
        return _enc(True)

    def _stats(self, req: bytes) -> bytes:
        r = Reader(req)
        db = self._db(r.string())
        st = db.stats()
        w = Writer()
        w.uvarint(len(st))
        for k, v in sorted(st.items()):
            w.string(k)
            w.string(v)
        return w.build()

    # -- lifecycle ---------------------------------------------------------
    def on_start(self) -> None:
        from concurrent import futures

        dispatch = {
            "InitRemote": self._init_remote,
            "Get": self._get,
            "Has": self._has,
            "Set": lambda b: self._set(b, sync=False),
            "SetSync": lambda b: self._set(b, sync=True),
            "Delete": lambda b: self._delete(b, sync=False),
            "DeleteSync": lambda b: self._delete(b, sync=True),
            "Iterator": self._iterator,
            "BatchWrite": self._batch_write,
            "Stats": self._stats,
        }

        def make_handler(fn):
            def handler(request, context):
                try:
                    return fn(request)
                except Exception as e:
                    context.abort(grpc.StatusCode.INTERNAL, str(e))

            return handler

        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                make_handler(fn),
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
            for name, fn in dispatch.items()
        }
        interceptors = (
            (_TokenAuthInterceptor(self.auth_token),) if self.auth_token else ()
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4), interceptors=interceptors
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        if self.tls_cert and self.tls_key:
            with open(self.tls_key, "rb") as f:
                key_pem = f.read()
            with open(self.tls_cert, "rb") as f:
                cert_pem = f.read()
            creds = grpc.ssl_server_credentials(((key_pem, cert_pem),))
            self.bound_port = self._server.add_secure_port(self.addr, creds)
        else:
            self.bound_port = self._server.add_insecure_port(self.addr)
        if self.bound_port == 0:
            raise OSError(f"could not bind RemoteDB server to {self.addr}")
        self._server.start()
        self.logger.info(
            "RemoteDB server on %s (auth=%s, tls=%s)",
            self.addr, bool(self.auth_token), bool(self.tls_cert),
        )

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
        with self._mtx:
            for db in self._dbs.values():
                try:
                    db.close()
                except Exception:
                    pass


class RemoteDB(DB):
    """Client handle implementing the DB interface against a RemoteDBServer
    (ref remotedb.go NewRemoteDB + InitRemote)."""

    def __init__(self, addr: str, name: str, backend: str = "memdb",
                 dir: str = ".", timeout: float = 10.0,
                 auth_token: Optional[str] = None,
                 tls_ca: Optional[str] = None):
        self.addr = addr.replace("tcp://", "")
        self.name = name
        self._timeout = timeout
        self._metadata = (
            (("authorization", f"Bearer {auth_token}"),) if auth_token else ()
        )
        if tls_ca:
            with open(tls_ca, "rb") as f:
                creds = grpc.ssl_channel_credentials(root_certificates=f.read())
            self._channel = grpc.secure_channel(self.addr, creds)
        else:
            self._channel = grpc.insecure_channel(self.addr)
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            self._stubs = {
                m: self._channel.unary_unary(
                    f"/{_SERVICE}/{m}",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
                for m in _METHODS
            }
            self._call("InitRemote", _enc(name, backend, dir))
        except BaseException:
            # a failed handshake/auth must not leak the live channel — a
            # reconnect-with-backoff caller would accumulate fds forever
            self._channel.close()
            raise

    def _call(self, method: str, payload: bytes) -> bytes:
        return self._stubs[method](
            payload, timeout=self._timeout, metadata=self._metadata
        )

    # -- DB interface ------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        resp = self._call("Get", _enc(self.name, key))
        return _read_opt_bytes(Reader(resp))

    def has(self, key: bytes) -> bool:
        return Reader(self._call("Has", _enc(self.name, key))).bool()

    def set(self, key: bytes, value: bytes) -> None:
        self._call("Set", _enc(self.name, key, value))

    def set_sync(self, key: bytes, value: bytes) -> None:
        self._call("SetSync", _enc(self.name, key, value))

    def delete(self, key: bytes) -> None:
        self._call("Delete", _enc(self.name, key))

    def delete_sync(self, key: bytes) -> None:
        self._call("DeleteSync", _enc(self.name, key))

    def iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        w = Writer()
        w.string(self.name)
        _opt_bytes(w, start)
        _opt_bytes(w, end)
        w.bool(reverse)
        r = Reader(self._call("Iterator", w.build()))
        n = r.uvarint()
        return iter([(r.bytes(), r.bytes()) for _ in range(n)])

    def apply_batch(self, ops) -> None:
        w = Writer()
        w.string(self.name)
        w.uvarint(len(ops))
        for op, k, v in ops:
            w.string(op)
            w.bytes(k)
            w.bytes(v if v is not None else b"")
        self._call("BatchWrite", w.build())

    def close(self) -> None:
        self._channel.close()

    def stats(self) -> Dict[str, str]:
        r = Reader(self._call("Stats", _enc(self.name)))
        return {r.string(): r.string() for _ in range(r.uvarint())}
