"""Concurrent linked list with wait-for-next semantics
(ref: libs/clist/clist.go, 407 LoC).

The mempool and evidence reactors iterate txs while gossiping: an iterator can
block until a next element is appended.  Elements can be detached from the
middle on removal while existing iterators keep a grip on their node.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator, Optional


class CElement:
    def __init__(self, value: Any):
        self.value = value
        self._prev: Optional[CElement] = None
        self._next: Optional[CElement] = None
        self._removed = False
        self._mtx = threading.Lock()
        self._next_wait = threading.Condition(self._mtx)

    @property
    def removed(self) -> bool:
        with self._mtx:
            return self._removed

    def next(self) -> Optional["CElement"]:
        with self._mtx:
            return self._next

    def next_wait(self, timeout: Optional[float] = None) -> Optional["CElement"]:
        """Block until a next element exists or this one is removed."""
        with self._mtx:
            if self._next is None and not self._removed:
                self._next_wait.wait(timeout)
            return self._next

    def _set_next(self, nxt: Optional["CElement"]) -> None:
        with self._mtx:
            self._next = nxt
            if nxt is not None:
                self._next_wait.notify_all()

    def _mark_removed(self) -> None:
        with self._mtx:
            self._removed = True
            self._next_wait.notify_all()


class CList:
    def __init__(self):
        self._mtx = threading.RLock()
        self._head: Optional[CElement] = None
        self._tail: Optional[CElement] = None
        self._len = 0
        self._wait = threading.Condition(self._mtx)

    def __len__(self) -> int:
        with self._mtx:
            return self._len

    def front(self) -> Optional[CElement]:
        with self._mtx:
            return self._head

    def front_wait(self, timeout: Optional[float] = None) -> Optional[CElement]:
        with self._mtx:
            if self._head is None:
                self._wait.wait(timeout)
            return self._head

    def back(self) -> Optional[CElement]:
        with self._mtx:
            return self._tail

    def push_back(self, value: Any) -> CElement:
        el = CElement(value)
        with self._mtx:
            if self._tail is None:
                self._head = self._tail = el
            else:
                el._prev = self._tail
                self._tail._set_next(el)
                self._tail = el
            self._len += 1
            self._wait.notify_all()
        return el

    def remove(self, el: CElement) -> Any:
        with self._mtx:
            prev, nxt = el._prev, el._next
            if prev is not None:
                prev._set_next(nxt)
            else:
                self._head = nxt
            if nxt is not None:
                nxt._prev = prev
            else:
                self._tail = prev
            self._len -= 1
            el._mark_removed()
        return el.value

    def __iter__(self) -> Iterator[Any]:
        el = self.front()
        while el is not None:
            if not el.removed:
                yield el.value
            el = el.next()
