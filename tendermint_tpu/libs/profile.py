"""Device-dispatch cost ledger: where wall time goes inside a batched
verify dispatch (host pack vs. compile vs. device run vs. transfer).

`parallel/planner.py` and `parallel/commit_verify.py` record one entry per
dispatch into a process-global ring buffer.  Each entry carries the
(window, bucket) coordinates plus the four costs the ROADMAP north star
pays for:

- ``pack_seconds``   host-side SHA-512/decompress/limb packing time
- ``run_seconds``    device dispatch wall time (includes compile when
                     ``compiled`` is True — XLA compiles on first call)
- ``bytes_to_device`` padded tensor bytes shipped across the transfer seam
- ``lanes_present`` / ``lanes_dispatched``  occupancy of the padded bucket

Callers that know which heights a window covers annotate the current thread
with ``window(height_base)`` so entries can be grouped into a per-height
ledger (`ledger()`), queryable via the unsafe-gated ``dump_profile`` RPC.

Entry ``kind`` names the dispatch site: ``"device"`` / ``"host"`` from the
planner's execute paths, ``"frontend.verify_batch"`` for flushes of the
light-client frontend's cross-client aggregator (`parallel/planner.py
LaneFeed` as wired by `frontend/frontend.py`) — there ``heights`` counts
the client rows folded into the flush, not consecutive block heights —
``"consensus.vote_batch"`` for flushes of the live-vote micro-batcher
(`parallel/planner.VoteFeed`), where ``heights`` counts the vote-set rows
the flush packed and ``n_windows`` the ≤max_rows windows folded into the
superdispatch, and ``"mempool.tx_batch"`` for flushes of the CheckTx
signature-ingest feed (`parallel/planner.TxFeed`) with the same row/window
accounting — annotated with the mempool's current height so the critpath
analyzer's ``verify_dispatch`` overlay picks the flush up in that height's
commit waterfall.

Like libs/trace.py this is deliberately dependency-free and cheap when
idle: recording is a dict append under a lock, and the ring buffer bounds
memory no matter how long the node runs.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

_DEFAULT_CAPACITY = 4096
_EVENT_CAPACITY = 512

_tls = threading.local()


class Profiler:
    """Bounded ring buffer of dispatch-cost entries."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 event_capacity: int = _EVENT_CAPACITY):
        self._mtx = threading.Lock()
        self._capacity = max(1, int(capacity))
        self._entries: List[dict] = []
        self._dropped = 0
        self._seq = 0
        # separate ring for rare, schema-free health events (breaker
        # transitions, audit verdicts, fallbacks) so they survive long
        # after the high-churn dispatch entries have rotated out
        self._event_capacity = max(1, int(event_capacity))
        self._events: List[dict] = []
        self._events_dropped = 0

    # recording ---------------------------------------------------------------

    @contextmanager
    def window(self, height_base: int, heights: int = 0) -> Iterator[None]:
        """Annotate dispatches on this thread with the window's first height.

        Nesting restores the outer annotation on exit, so a syncer backfill
        inside a fast-sync window doesn't mislabel the outer dispatches.
        """
        prev = getattr(_tls, "window", None)
        _tls.window = (int(height_base), int(heights))
        try:
            yield
        finally:
            _tls.window = prev

    def record(
        self,
        kind: str,
        *,
        bucket: tuple = (),
        lanes_present: int = 0,
        lanes_dispatched: int = 0,
        heights: int = 0,
        pack_seconds: float = 0.0,
        run_seconds: float = 0.0,
        compiled: bool = False,
        bytes_to_device: int = 0,
        fe_backend: str = "",
        carry_mode: str = "",
        ed25519_path: str = "",
        n_windows: int = 1,
        n_devices: int = 1,
    ) -> None:
        win = getattr(_tls, "window", None)
        entry = {
            "kind": kind,
            # superdispatch shape: independent windows folded into this
            # dispatch and mesh devices the lane tile sharded across
            "n_windows": int(n_windows),
            "n_devices": int(n_devices),
            # limb-multiplier backend that served this dispatch
            # (ops/fe_common: vpu | mxu | mxu16; "" = host / not applicable)
            "fe_backend": str(fe_backend),
            # carry schedule the dispatch traced with (eager | lazy;
            # "" = host / not applicable) — the effective mode after
            # fe_common.effective_carry_mode's mxu16 degrade
            "carry_mode": str(carry_mode),
            # verify strategy (ladder | msm; "" = host / not applicable):
            # msm = one RLC Pippenger MSM per window (ops/ed25519_msm)
            "ed25519_path": str(ed25519_path),
            "height_base": win[0] if win else None,
            "heights": heights or (win[1] if win else 0),
            "bucket": list(bucket),
            "lanes_present": int(lanes_present),
            "lanes_dispatched": int(lanes_dispatched),
            "occupancy": (
                round(lanes_present / lanes_dispatched, 4)
                if lanes_dispatched else 0.0
            ),
            "pack_seconds": float(pack_seconds),
            "run_seconds": float(run_seconds),
            # XLA compiles inside the first traced call, so a compiled
            # entry's run_seconds is compile + run; steady-state cost is
            # the non-compiled entries for the same bucket
            "compile_seconds": float(run_seconds) if compiled else 0.0,
            "compiled": bool(compiled),
            "bytes_to_device": int(bytes_to_device),
        }
        with self._mtx:
            entry["seq"] = self._seq
            self._seq += 1
            self._entries.append(entry)
            if len(self._entries) > self._capacity:
                del self._entries[0]
                self._dropped += 1

    def record_event(self, kind: str, **fields) -> None:
        """One health/state event (breaker transition, audit verdict,
        host fallback) into the bounded event ring.  Unlike ``record``
        the schema is free-form: kind plus whatever the event carries."""
        entry = {"kind": kind, "wall_time": time.time()}
        entry.update(fields)
        win = getattr(_tls, "window", None)
        if win is not None and "height_base" not in entry:
            entry["height_base"] = win[0]
        with self._mtx:
            entry["seq"] = self._seq
            self._seq += 1
            self._events.append(entry)
            if len(self._events) > self._event_capacity:
                del self._events[0]
                self._events_dropped += 1

    # querying ----------------------------------------------------------------

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._mtx:
            out = [dict(e) for e in self._events]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out

    @property
    def events_dropped(self) -> int:
        with self._mtx:
            return self._events_dropped

    def entries(self) -> List[dict]:
        with self._mtx:
            return [dict(e) for e in self._entries]

    @property
    def dropped(self) -> int:
        with self._mtx:
            return self._dropped

    def ledger(self) -> List[dict]:
        """Per-window cost rows, newest last.  Entries recorded with the
        same window annotation fold into one row; un-annotated entries
        (bench harnesses, direct calls) each get their own row."""
        rows: Dict[object, dict] = {}
        order: List[object] = []
        for e in self.entries():
            key = e["height_base"] if e["height_base"] is not None else (
                "seq", e["seq"]
            )
            row = rows.get(key)
            if row is None:
                row = {
                    "height_base": e["height_base"],
                    "heights": e["heights"],
                    "dispatches": 0,
                    "windows": 0,
                    "n_devices": 1,
                    "kinds": [],
                    "fe_backends": [],
                    "carry_modes": [],
                    "ed25519_paths": [],
                    "buckets": [],
                    "lanes_present": 0,
                    "lanes_dispatched": 0,
                    "pack_seconds": 0.0,
                    "run_seconds": 0.0,
                    "compile_seconds": 0.0,
                    "compiles": 0,
                    "bytes_to_device": 0,
                }
                rows[key] = row
                order.append(key)
            row["dispatches"] += 1
            row["windows"] += e.get("n_windows", 1)
            row["n_devices"] = max(row["n_devices"], e.get("n_devices", 1))
            if e["kind"] not in row["kinds"]:
                row["kinds"].append(e["kind"])
            fb = e.get("fe_backend", "")
            if fb and fb not in row["fe_backends"]:
                row["fe_backends"].append(fb)
            cm = e.get("carry_mode", "")
            if cm and cm not in row["carry_modes"]:
                row["carry_modes"].append(cm)
            ep = e.get("ed25519_path", "")
            if ep and ep not in row["ed25519_paths"]:
                row["ed25519_paths"].append(ep)
            if e["bucket"] and e["bucket"] not in row["buckets"]:
                row["buckets"].append(e["bucket"])
            row["lanes_present"] += e["lanes_present"]
            row["lanes_dispatched"] += e["lanes_dispatched"]
            row["heights"] = max(row["heights"], e["heights"])
            row["pack_seconds"] += e["pack_seconds"]
            row["run_seconds"] += e["run_seconds"]
            row["compile_seconds"] += e["compile_seconds"]
            row["compiles"] += 1 if e["compiled"] else 0
            row["bytes_to_device"] += e["bytes_to_device"]
        out = []
        for key in order:
            row = rows[key]
            ld = row["lanes_dispatched"]
            row["occupancy"] = round(row["lanes_present"] / ld, 4) if ld else 0.0
            out.append(row)
        return out

    def reset(self, capacity: Optional[int] = None) -> None:
        with self._mtx:
            self._entries.clear()
            self._dropped = 0
            self._seq = 0
            self._events.clear()
            self._events_dropped = 0
            if capacity is not None:
                self._capacity = max(1, int(capacity))


_profiler: Optional[Profiler] = None
_profiler_mtx = threading.Lock()


def get_profiler() -> Profiler:
    global _profiler
    with _profiler_mtx:
        if _profiler is None:
            _profiler = Profiler()
        return _profiler
