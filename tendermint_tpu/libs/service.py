"""BaseService — start/stop/reset lifecycle every long-running component uses
(ref: libs/common/service.go).

Python rendition: idempotent start/stop with threading.Event quit signaling;
subclasses override on_start/on_stop/on_reset.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class NotStartedError(ServiceError):
    pass


class BaseService:
    def __init__(self, name: str = "", logger: Optional[logging.Logger] = None):
        self.name = name or type(self).__name__
        self.logger = logger or logging.getLogger(self.name)
        self._started = False
        self._stopped = False
        self._mtx = threading.Lock()
        self._quit = threading.Event()

    # lifecycle ------------------------------------------------------------
    def start(self) -> None:
        with self._mtx:
            if self._started:
                raise AlreadyStartedError(self.name)
            if self._stopped:
                raise AlreadyStoppedError(
                    f"{self.name}: cannot start a stopped service; use reset()"
                )
            self._started = True
        self.logger.debug("starting %s", self.name)
        try:
            self.on_start()
        except Exception:
            with self._mtx:
                self._started = False
            raise

    def stop(self) -> None:
        with self._mtx:
            if self._stopped:
                raise AlreadyStoppedError(self.name)
            if not self._started:
                raise NotStartedError(self.name)
            self._stopped = True
        self.logger.debug("stopping %s", self.name)
        self._quit.set()
        self.on_stop()

    def reset(self) -> None:
        with self._mtx:
            if not self._stopped:
                raise ServiceError(f"{self.name}: can only reset a stopped service")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
        self.on_reset()

    # state ----------------------------------------------------------------
    @property
    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until stop() is called."""
        self._quit.wait(timeout)

    @property
    def quit_event(self) -> threading.Event:
        return self._quit

    # overridables ---------------------------------------------------------
    def on_start(self) -> None: ...

    def on_stop(self) -> None: ...

    def on_reset(self) -> None: ...
