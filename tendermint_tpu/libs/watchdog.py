"""Liveness watchdog — detects a consensus height that stopped advancing.

Tendermint's worst production failures are liveness failures: the chain
simply stops because the proposer is slow, >1/3 of voting power went
silent, or gossip is partitioned.  Nothing crashes, so nothing pages.

The watchdog samples the consensus (height, round) at a fixed interval and
keeps an EWMA of recent block intervals.  When no (height, round) progress
has happened for `stall_factor` × that EWMA (floored at
`min_stall_seconds`), it:

  * increments `tendermint_consensus_stalls_total` (once per stall onset),
  * publishes the live stall age in `tendermint_consensus_stall_seconds`
    (reset to 0 on recovery),
  * logs + retains a structured stall report: current h/r/s, which
    validators are missing from the round's prevote/precommit sets and
    their cumulative voting power, and per-peer last-message ages from the
    switch — everything an operator needs to tell "slow proposer" from
    ">1/3 silent" from "partition".

The report is served by `health`, `dump_consensus_state`, and the
unsafe-gated `dump_flight` RPC (rpc/core/env.py).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

DEFAULT_INTERVAL = 1.0  # seconds between checks
DEFAULT_STALL_FACTOR = 5.0  # stall when idle > factor × block-interval EWMA
DEFAULT_MIN_STALL_SECONDS = 10.0  # ...but never sooner than this
DEFAULT_EWMA_ALPHA = 0.3
# A single block-interval sample never contributes more than this multiple
# of the current EWMA.  One pathological gap (a frozen-then-resumed clock,
# a multi-minute snapshot restore) would otherwise poison the EWMA and
# inflate the stall threshold for many blocks afterwards.
DEFAULT_MAX_SAMPLE_FACTOR = 10.0


class LivenessWatchdog:
    """Watches one ConsensusState.  `switch` (optional) contributes per-peer
    last-receive ages to the stall report; `metrics` (optional NodeMetrics)
    receives the stall counter/gauge."""

    def __init__(
        self,
        consensus_state,
        switch=None,
        metrics=None,
        interval: float = DEFAULT_INTERVAL,
        stall_factor: float = DEFAULT_STALL_FACTOR,
        min_stall_seconds: float = DEFAULT_MIN_STALL_SECONDS,
        ewma_alpha: float = DEFAULT_EWMA_ALPHA,
        max_sample_factor: float = DEFAULT_MAX_SAMPLE_FACTOR,
        logger: Optional[logging.Logger] = None,
        now_ns=None,
    ):
        self.cons = consensus_state
        self.switch = switch
        self.metrics = metrics
        # wall-clock source stamped into stall reports (cross-node fusable);
        # sampling/thresholds stay on time.monotonic. The sim harness injects
        # each node's skewed clock here so reports land on its timeline.
        self.now_ns = now_ns or time.time_ns
        self.interval = interval
        self.stall_factor = stall_factor
        self.min_stall_seconds = min_stall_seconds
        self.ewma_alpha = ewma_alpha
        self.max_sample_factor = max_sample_factor
        self.logger = logger or logging.getLogger("watchdog")

        self._mtx = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        now = time.monotonic()
        self._last_hr = (-1, -1)
        self._last_progress = now
        self._last_height_at = now
        self._ewma: Optional[float] = None  # block-interval EWMA, seconds
        self._stalled = False
        self._stalls_total = 0
        self._report: Optional[dict] = None

    # lifecycle --------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="liveness-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval + 2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception:
                self.logger.exception("watchdog check failed")

    # core -------------------------------------------------------------------
    def check(self, now: Optional[float] = None) -> Optional[dict]:
        """One sample.  Returns the current stall report (None when healthy).
        Exposed for tests — production calls come from the thread."""
        now = time.monotonic() if now is None else now
        rs = self.cons.rs
        hr = (rs.height, rs.round)

        with self._mtx:
            if hr != self._last_hr:
                if hr[0] > self._last_hr[0] >= 0:
                    # several heights may land between two samples (fast
                    # blocks, slow sampling): amortize, or one long gap
                    # poisons the EWMA and inflates the stall threshold
                    dt = (now - self._last_height_at) / (hr[0] - self._last_hr[0])
                    if self._ewma is None:
                        self._ewma = dt
                    else:
                        # clamp the sample: a frozen-then-resumed clock (or
                        # any single multi-minute gap) must not swamp the
                        # average — the stall threshold would stay inflated
                        # long after blocks resumed at normal pace
                        if self.max_sample_factor > 0:
                            dt = min(dt, self.max_sample_factor * self._ewma)
                        self._ewma = (
                            self.ewma_alpha * dt
                            + (1 - self.ewma_alpha) * self._ewma
                        )
                if hr[0] != self._last_hr[0]:
                    self._last_height_at = now
                self._last_hr = hr
                self._last_progress = now
                if self._stalled:
                    self._stalled = False
                    self._report = None
                    self.logger.warning(
                        "consensus recovered at h=%d r=%d", hr[0], hr[1]
                    )
                if self.metrics is not None:
                    self.metrics.stall_seconds.set(0.0)
                return None

            idle = now - self._last_progress
            threshold = self.threshold()
            if idle <= threshold:
                return None

            onset = not self._stalled
            if onset:
                self._stalled = True
                self._stalls_total += 1
            report = self._build_report(idle, threshold)
            self._report = report
            if self.metrics is not None:
                if onset:
                    self.metrics.stalls.add(1.0)
                self.metrics.stall_seconds.set(idle)

        if onset:
            self.logger.warning("consensus stall detected: %s", json.dumps(report))
        return report

    def threshold(self) -> float:
        ewma = self._ewma
        if ewma is None:
            return self.min_stall_seconds
        return max(self.stall_factor * ewma, self.min_stall_seconds)

    # reporting --------------------------------------------------------------
    def _missing_votes(self, rs, vote_set) -> dict:
        """Validators absent from `vote_set` and their cumulative power."""
        vals = rs.validators
        total_power = vals.total_voting_power()
        missing = []
        missing_power = 0
        ba = vote_set.bit_array() if vote_set is not None else None
        for i in range(vals.size):
            if ba is not None and ba.get_index(i):
                continue
            addr, val = vals.get_by_index(i)
            power = val.voting_power if val is not None else 0
            missing_power += power
            missing.append(
                {
                    "index": i,
                    "address": (addr or b"").hex().upper(),
                    "voting_power": power,
                }
            )
        return {
            "validators": missing,
            "power": missing_power,
            "total_power": total_power,
        }

    def _peer_ages(self) -> list:
        if self.switch is None:
            return []
        out = []
        try:
            peers = self.switch.peers.list()
        except Exception:
            return []
        for p in peers:
            age = None
            try:
                st = p.status()
                age = st.get("last_recv_age")
            except Exception:
                pass
            out.append({"id": p.id, "last_recv_age_seconds": age})
        return out

    def _build_report(self, idle: float, threshold: float) -> dict:
        rs = self.cons.rs
        try:
            prevotes = rs.votes.prevotes(rs.round)
        except Exception:
            prevotes = None
        try:
            precommits = rs.votes.precommits(rs.round)
        except Exception:
            precommits = None
        return {
            "stalled": True,
            "wall_time_ns": self.now_ns(),
            "height": rs.height,
            "round": rs.round,
            "step": rs.step.name,
            "stall_seconds": round(idle, 3),
            "threshold_seconds": round(threshold, 3),
            "block_interval_ewma_seconds": (
                round(self._ewma, 3) if self._ewma is not None else None
            ),
            "missing_prevotes": self._missing_votes(rs, prevotes),
            "missing_precommits": self._missing_votes(rs, precommits),
            "peers": self._peer_ages(),
            "stalls_total": self._stalls_total,
        }

    def report(self) -> Optional[dict]:
        """The retained stall report; None while healthy."""
        with self._mtx:
            return self._report

    def status(self) -> dict:
        """Compact health summary (always available, stalled or not)."""
        with self._mtx:
            return {
                "stalled": self._stalled,
                "stall_seconds": (
                    round(time.monotonic() - self._last_progress, 3)
                    if self._stalled
                    else 0.0
                ),
                "stalls_total": self._stalls_total,
                "block_interval_ewma_seconds": (
                    round(self._ewma, 3) if self._ewma is not None else None
                ),
            }
