"""Minimal Prometheus-style metrics: Counter/Gauge/Histogram + registry +
text exposition (ref: the go-kit prometheus metrics used at
consensus/metrics.go:14, p2p/metrics.go, mempool/metrics.go,
state/metrics.go, served at node/node.go:698).

No external client library — exposition format is plain text v0.0.4, which
is all Prometheus needs to scrape.  `scripts/metrics_lint.py` holds a strict
parser for that format and `make metrics-lint` checks every registry this
module builds against it.

Beyond the four reference families, `VerifyMetrics` covers the TPU-specific
seams the reference never had: the BatchVerifier boundary (crypto/batch.py),
the sharded window step (parallel/commit_verify.py), and fast sync's
speculative double-buffering (blockchain/reactor.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


def _fmt_value(v: float) -> str:
    """Full precision: %g truncates to 6 significant digits, silently
    corrupting counters past ~1e6 (real client libs emit repr-style)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label_value(v: str) -> str:
    """Text-format v0.0.4 label-value escaping: backslash, double-quote and
    newline must be escaped or the series line is unparseable/corrupts the
    scrape (prometheus docs "text-based format", escaping rules)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(h: str) -> str:
    """HELP lines escape backslash and newline (a raw newline would start a
    bogus sample line mid-scrape)."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._mtx = threading.Lock()

    def expose(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, *values: str) -> "_BoundCounter":
        return _BoundCounter(self, tuple(str(v) for v in values))

    def add(self, v: float = 1.0, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = self._values.get(_labels, 0.0) + v

    def remove_matching(self, label_name: str, value: str) -> int:
        """Drop every series whose `label_name` equals `value` — the
        cardinality-hygiene hook for per-peer labels on disconnect."""
        if label_name not in self.label_names:
            return 0
        i = self.label_names.index(label_name)
        with self._mtx:
            doomed = [lv for lv in self._values if lv[i] == value]
            for lv in doomed:
                del self._values[lv]
        return len(doomed)

    def expose(self) -> List[str]:
        with self._mtx:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            return [f"{self.name} 0"]
        return [
            f"{self.name}{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}"
            for lv, v in items
        ]


class _BoundCounter:
    def __init__(self, parent: Counter, labels: Tuple[str, ...]):
        self._p, self._l = parent, labels

    def add(self, v: float = 1.0) -> None:
        self._p.add(v, self._l)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {} if label_names else {(): 0.0}

    def labels(self, *values: str) -> "_BoundGauge":
        return _BoundGauge(self, tuple(str(v) for v in values))

    def set(self, v: float, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = float(v)

    def add(self, v: float = 1.0, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = self._values.get(_labels, 0.0) + v

    def remove_matching(self, label_name: str, value: str) -> int:
        """Drop every series whose `label_name` equals `value` (see
        Counter.remove_matching)."""
        if label_name not in self.label_names:
            return 0
        i = self.label_names.index(label_name)
        with self._mtx:
            doomed = [lv for lv in self._values if lv[i] == value]
            for lv in doomed:
                del self._values[lv]
        return len(doomed)

    def expose(self) -> List[str]:
        with self._mtx:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}"
            for lv, v in items
        ]


class _BoundGauge:
    def __init__(self, parent: Gauge, labels: Tuple[str, ...]):
        self._p, self._l = parent, labels

    def set(self, v: float) -> None:
        self._p.set(v, self._l)

    def add(self, v: float = 1.0) -> None:
        self._p.add(v, self._l)


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)

# power-of-two ladder for batch sizes (1 .. 64k signatures per dispatch)
_SIZE_BUCKETS = tuple(float(1 << i) for i in range(17))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", buckets: Sequence[float] = _DEFAULT_BUCKETS,
                 label_names: Sequence[str] = ()):
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))
        # per-labelset series: labels -> [bucket counts (+Inf last), sum, n]
        self._series: Dict[Tuple[str, ...], list] = {}
        if not self.label_names:
            # an unlabeled histogram exposes its zero series immediately
            # (back-compat with the pre-labeled exposition)
            self._series[()] = [[0] * (len(self.buckets) + 1), 0.0, 0]

    def labels(self, *values: str) -> "_BoundHistogram":
        return _BoundHistogram(self, tuple(str(v) for v in values))

    def observe(self, v: float, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            s = self._series.get(_labels)
            if s is None:
                s = self._series[_labels] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0
                ]
            s[1] += v
            s[2] += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s[0][i] += 1
                    return
            s[0][-1] += 1

    def expose(self) -> List[str]:
        with self._mtx:
            series = [
                (lv, list(s[0]), s[1], s[2])
                for lv, s in sorted(self._series.items())
            ]
        out: List[str] = []
        bucket_names = self.label_names + ("le",)
        for lv, counts, total_sum, n in series:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(bucket_names, lv + (f'{b:g}',))} {cum}"
                )
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(bucket_names, lv + ('+Inf',))} {n}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, lv)} "
                f"{_fmt_value(total_sum)}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.label_names, lv)} {n}"
            )
        return out


class _BoundHistogram:
    def __init__(self, parent: Histogram, labels: Tuple[str, ...]):
        self._p, self._l = parent, labels

    def observe(self, v: float) -> None:
        self._p.observe(v, self._l)


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._attached: List["Registry"] = []
        self._mtx = threading.Lock()

    def _register(self, m: _Metric) -> _Metric:
        with self._mtx:
            self._metrics.append(m)
        return m

    def counter(self, name, help="", label_names=()) -> Counter:
        return self._register(
            Counter(f"{self.namespace}_{name}", help, label_names)
        )

    def gauge(self, name, help="", label_names=()) -> Gauge:
        return self._register(Gauge(f"{self.namespace}_{name}", help, label_names))

    def histogram(self, name, help="", buckets=_DEFAULT_BUCKETS,
                  label_names=()) -> Histogram:
        return self._register(
            Histogram(f"{self.namespace}_{name}", help, buckets, label_names)
        )

    def attach(self, other: "Registry") -> None:
        """Expose another registry's metrics through this one's scrape.
        The process-wide VerifyMetrics registry rides every node's /metrics
        this way (the batch verifier is process-global, so per-node
        registration would double count)."""
        with self._mtx:
            if other is not self and other not in self._attached:
                self._attached.append(other)

    def expose_text(self) -> str:
        lines: List[str] = []
        with self._mtx:
            metrics = list(self._metrics)
            attached = list(self._attached)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.expose())
        text = "\n".join(lines) + "\n" if lines else ""
        for reg in attached:
            text += reg.expose_text()
        return text


# -- the per-subsystem metric sets the reference defines -----------------------


class VerifyMetrics:
    """Verification-pipeline telemetry — the TPU batch boundary.

    Recorded inside crypto/batch.py (every BatchVerifier dispatch),
    parallel/commit_verify.py (the sharded window step) and
    blockchain/reactor.py (fast sync's speculative double-buffering).
    Labels stay low-cardinality: backend in {host, xla, pallas, window,
    window_mesh}, algo in {ed25519, secp256k1}.
    """

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.batch_size = r.histogram(
            "verify_batch_size", "Signatures per batch-verify dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.dispatch_seconds = r.histogram(
            "verify_dispatch_seconds",
            "Batch-verify dispatch wall seconds by backend",
            label_names=("backend",),
        )
        self.compile_seconds = r.histogram(
            "verify_compile_seconds",
            "First-dispatch (compile/warm-up) wall seconds by backend",
            buckets=(0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
            label_names=("backend",),
        )
        self.calls = r.counter(
            "verify_calls_total", "Batch-verify dispatches",
            label_names=("backend", "algo"),
        )
        self.sigs = r.counter(
            "verify_sigs_total", "Signatures verified in batch dispatches",
            label_names=("backend", "algo"),
        )
        self.rejects = r.counter(
            "verify_rejects_total", "Signatures that failed verification",
            label_names=("backend", "algo"),
        )
        self.host_fallback = r.counter(
            "verify_host_fallback_total",
            "Items diverted from the device batch to the host path",
            label_names=("reason",),
        )
        self.speculative = r.counter(
            "verify_speculative_total",
            "Speculative (double-buffered) fast-sync window verifies by outcome",
            label_names=("outcome",),
        )
        self.window_heights = r.histogram(
            "verify_window_heights", "Heights per fast-sync verify window",
            buckets=tuple(float(1 << i) for i in range(11)),
        )
        # verification planner (parallel/planner.py): ragged lane packing
        self.lane_occupancy = r.histogram(
            "verify_lane_occupancy",
            "Present lanes / dispatched lanes per planner dispatch",
            buckets=(0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0),
        )
        self.lanes = r.counter(
            "verify_lanes_total",
            "Planner device lanes dispatched by kind (present|padded)",
            label_names=("kind",),
        )
        self.planner_bucket = r.counter(
            "verify_planner_bucket_total",
            "Planner (lane, segment) bucket lookups by event (hit|compile)",
            label_names=("event",),
        )
        # device dispatch guard (libs/breaker.py): breaker state + the
        # fallback/retry/audit outcomes of every guarded device dispatch
        self.device_breaker_state = r.gauge(
            "verify_device_breaker_state",
            "Device verify circuit-breaker state "
            "(0=closed 1=open 2=half_open 3=quarantined)",
        )
        self.device_fallback = r.counter(
            "verify_device_fallback_total",
            "Device dispatches completed on the host path instead, by reason",
            label_names=("reason",),
        )
        self.device_retries = r.counter(
            "verify_device_retries_total",
            "Device dispatches retried after a transient failure",
        )
        self.device_audit = r.counter(
            "verify_device_audit_total",
            "Silent-corruption audit lane cross-checks by outcome "
            "(ok|mismatch)",
            label_names=("outcome",),
        )
        # limb-multiplier attribution: which fe backend (ops/fe_common)
        # served each device window — vpu | mxu | mxu16 — which carry
        # schedule it traced with (eager | lazy), and which verify
        # strategy decided the window (ladder | msm; ops/ed25519_msm);
        # host dispatches carry no fe backend and are not recorded here
        self.fe_dispatch = r.counter(
            "verify_fe_backend_total",
            "Batch-verify device dispatches by limb-multiplier backend, "
            "carry schedule and ed25519 verify path",
            label_names=("backend", "fe_backend", "carry_mode",
                         "ed25519_path"),
        )
        # per-device attribution of mesh superdispatches: which devices the
        # lane tile sharded across and how many lanes each shard carried.
        # Label cardinality is capped like NodeMetrics peer labels — at most
        # MAX_DEVICE_LABELS distinct device ids ever get their own value,
        # the rest fold into "overflow"
        self.device_lanes = r.counter(
            "verify_device_lanes_total",
            "Lanes dispatched per mesh device (lane-tile shard size)",
            label_names=("device",),
        )
        self.device_dispatches = r.counter(
            "verify_device_dispatch_total",
            "Device dispatches that included each mesh device",
            label_names=("device",),
        )
        self._device_label_ids: set = set()
        self._device_label_mtx = threading.Lock()

    MAX_DEVICE_LABELS = 16

    def _device_label(self, device_id: str) -> str:
        with self._device_label_mtx:
            if device_id in self._device_label_ids:
                return device_id
            if len(self._device_label_ids) < self.MAX_DEVICE_LABELS:
                self._device_label_ids.add(device_id)
                return device_id
        return "overflow"

    def record_device_shards(self, device_ids, lanes_per_device: int) -> None:
        """One mesh (or single-device) dispatch: every participating device
        gets a dispatch tick and its lane-tile shard size attributed."""
        for d in device_ids:
            lbl = self._device_label(str(d))
            self.device_dispatches.add(1.0, (lbl,))
            self.device_lanes.add(float(lanes_per_device), (lbl,))

    def record_dispatch(self, backend: str, algo: str, n: int,
                        seconds: float, rejects: int = 0,
                        first: bool = False, fe_backend: str = "",
                        carry_mode: str = "",
                        ed25519_path: str = "") -> None:
        """One batch dispatch: size + latency + outcome in one call so the
        instrumented hot paths stay one-liners."""
        self.batch_size.observe(float(n))
        self.dispatch_seconds.observe(seconds, (backend,))
        if first:
            self.compile_seconds.observe(seconds, (backend,))
        self.calls.add(1.0, (backend, algo))
        self.sigs.add(float(n), (backend, algo))
        if rejects:
            self.rejects.add(float(rejects), (backend, algo))
        if fe_backend:
            self.fe_dispatch.add(
                1.0,
                (backend, fe_backend, carry_mode, ed25519_path or "ladder"),
            )

    def record_planner(self, present: int, dispatched: int,
                       compiled: bool = False) -> None:
        """One planner device dispatch: lane occupancy (present vs padded)
        and the compile-cache outcome for its (lane, segment) bucket."""
        if dispatched > 0:
            self.lane_occupancy.observe(present / dispatched)
            self.lanes.add(float(present), ("present",))
            self.lanes.add(float(dispatched - present), ("padded",))
        self.planner_bucket.add(1.0, ("compile" if compiled else "hit",))


_verify_mtx = threading.Lock()
_verify_metrics: Optional[VerifyMetrics] = None


def get_verify_metrics() -> VerifyMetrics:
    """Process-wide VerifyMetrics singleton — mirrors the process-wide
    default BatchVerifier (crypto/batch.get_batch_verifier)."""
    global _verify_metrics
    with _verify_mtx:
        if _verify_metrics is None:
            _verify_metrics = VerifyMetrics()
        return _verify_metrics


class StateSyncMetrics:
    """State-sync telemetry: snapshot restore progress on the client side
    (chunk fetch outcomes, restore latency, backfill window size) and
    serving counters on the provider side. Process-wide like VerifyMetrics —
    the reactor can outlive a node object across restore retries."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.syncing = r.gauge(
            "statesync_syncing", "1 while a snapshot restore is in progress"
        )
        self.snapshot_height = r.gauge(
            "statesync_snapshot_height", "Height of the snapshot being restored"
        )
        self.chunks_expected = r.gauge(
            "statesync_chunks_expected", "Chunks in the snapshot being restored"
        )
        self.chunks_applied = r.gauge(
            "statesync_chunks_applied", "Chunks applied so far"
        )
        self.chunk_fetch = r.counter(
            "statesync_chunk_fetch_total",
            "Chunk fetch attempts by outcome (ok|bad|timeout|missing)",
            label_names=("outcome",),
        )
        self.chunk_bytes = r.counter(
            "statesync_chunk_bytes_total", "Verified chunk bytes received"
        )
        self.served = r.counter(
            "statesync_served_total",
            "Requests served to restoring peers by message type",
            label_names=("msg",),
        )
        self.chunk_fetch_seconds = r.histogram(
            "statesync_chunk_fetch_seconds", "Per-chunk fetch wall seconds"
        )
        self.backfill_heights = r.histogram(
            "statesync_backfill_heights",
            "Heights in the trailing commit backfill window",
            buckets=tuple(float(1 << i) for i in range(11)),
        )
        self.restore_seconds = r.histogram(
            "statesync_restore_seconds",
            "End-to-end snapshot restore wall seconds",
            buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
        )


_statesync_mtx = threading.Lock()
_statesync_metrics: Optional[StateSyncMetrics] = None


def get_statesync_metrics() -> StateSyncMetrics:
    """Process-wide StateSyncMetrics singleton (mirrors get_verify_metrics)."""
    global _statesync_metrics
    with _statesync_mtx:
        if _statesync_metrics is None:
            _statesync_metrics = StateSyncMetrics()
        return _statesync_metrics


class FrontendMetrics:
    """Light-client frontend telemetry (frontend/): request outcomes per
    route, verified-header cache effectiveness, aggregator batch shape, and
    end-to-end certification latency.  Process-wide like VerifyMetrics —
    one frontend serves every client of the process."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.requests = r.counter(
            "lite_frontend_requests_total",
            "Frontend requests by route and outcome (ok|error)",
            label_names=("route", "outcome"),
        )
        self.cache_events = r.counter(
            "lite_frontend_cache_events_total",
            "Verified-header cache lookups by outcome (hit|miss|wait)",
            label_names=("outcome",),
        )
        self.cache_size = r.gauge(
            "lite_frontend_cache_size", "Verified headers currently cached"
        )
        self.heights_verified = r.counter(
            "lite_frontend_heights_verified_total",
            "Trust-extension operations actually performed — cache +"
            " single-flight keep this well below requests under fan-in",
        )
        self.batch_rows = r.histogram(
            "lite_frontend_batch_rows",
            "Commit rows folded into one aggregated planner dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.batch_occupancy = r.histogram(
            "lite_frontend_batch_occupancy",
            "Lane occupancy (present/dispatched) of aggregated dispatches",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self.verify_seconds = r.histogram(
            "lite_frontend_verify_seconds",
            "End-to-end certification latency per frontend request",
        )


_frontend_mtx = threading.Lock()
_frontend_metrics: Optional[FrontendMetrics] = None


def get_frontend_metrics() -> FrontendMetrics:
    """Process-wide FrontendMetrics singleton (mirrors get_verify_metrics)."""
    global _frontend_metrics
    with _frontend_mtx:
        if _frontend_metrics is None:
            _frontend_metrics = FrontendMetrics()
        return _frontend_metrics


class VoteBatchMetrics:
    """Live-vote micro-batcher telemetry (parallel/planner.VoteFeed): how
    many vote-set rows fold into each flush, how full the lane tile is, and
    what triggered the flush (deadline|quorum|close).  Process-wide like
    VerifyMetrics — the feed is one worker per process regardless of how
    many vote sets feed it."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.batch_rows = r.histogram(
            "consensus_vote_batch_rows",
            "Vote-set rows folded into one batched vote-verify dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.batch_lanes = r.histogram(
            "consensus_vote_batch_lanes",
            "Votes (present lanes) per batched vote-verify dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.lane_occupancy = r.histogram(
            "consensus_vote_batch_lane_occupancy",
            "Lane occupancy (present/dispatched) of batched vote dispatches",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self.flushes = r.counter(
            "consensus_vote_batch_flush_total",
            "Vote micro-batcher flushes by trigger (deadline|quorum|close)",
            label_names=("reason",),
        )
        self.batch_wait = r.histogram(
            "consensus_vote_batch_wait_seconds",
            "Queue wait a vote spent parked in the micro-batcher between "
            "ticket submit and flush (batching-added latency, separable "
            "from network propagation in the quorum reports)",
            buckets=[b / 100 for b in _DEFAULT_BUCKETS],
        )

    def record_flush(self, reason: str, rows: int, lanes: int,
                     occupancy: float) -> None:
        """One VoteFeed flush: shape + trigger in one call."""
        self.batch_rows.observe(float(rows))
        self.batch_lanes.observe(float(lanes))
        self.lane_occupancy.observe(float(occupancy))
        self.flushes.add(1.0, (reason,))

    def record_wait(self, seconds: float) -> None:
        """One ticket's submit->flush queue wait."""
        if seconds >= 0.0:
            self.batch_wait.observe(seconds)


_vote_batch_mtx = threading.Lock()
_vote_batch_metrics: Optional[VoteBatchMetrics] = None


def get_vote_batch_metrics() -> VoteBatchMetrics:
    """Process-wide VoteBatchMetrics singleton (mirrors get_verify_metrics)."""
    global _vote_batch_metrics
    with _vote_batch_mtx:
        if _vote_batch_metrics is None:
            _vote_batch_metrics = VoteBatchMetrics()
        return _vote_batch_metrics


class MempoolBatchMetrics:
    """Ingest micro-batcher telemetry (parallel/planner.TxFeed): how many
    CheckTx-window rows fold into each flush, how full the lane tile is,
    and what triggered the flush (deadline|quorum|close).  Process-wide
    like VoteBatchMetrics — the feed is one worker per process regardless
    of how many CheckTx windows feed it."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.batch_rows = r.histogram(
            "mempool_batch_rows",
            "CheckTx-window rows folded into one batched tx-verify dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.batch_lanes = r.histogram(
            "mempool_batch_lanes",
            "Txs (present lanes) per batched tx-verify dispatch",
            buckets=_SIZE_BUCKETS,
        )
        self.lane_occupancy = r.histogram(
            "mempool_batch_lane_occupancy",
            "Lane occupancy (present/dispatched) of batched tx dispatches",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self.flushes = r.counter(
            "mempool_batch_flush_total",
            "Tx micro-batcher flushes by trigger (deadline|quorum|close)",
            label_names=("reason",),
        )

    def record_flush(self, reason: str, rows: int, lanes: int,
                     occupancy: float) -> None:
        """One TxFeed flush: shape + trigger in one call."""
        self.batch_rows.observe(float(rows))
        self.batch_lanes.observe(float(lanes))
        self.lane_occupancy.observe(float(occupancy))
        self.flushes.add(1.0, (reason,))


class TelemetryMetrics:
    """Soak-telemetry spool health (libs/telemetry.TelemetrySpool) plus
    ring-eviction visibility across the bounded observability stores.
    Per-node (constructed and attached by NodeMetrics, NOT a process
    singleton): each node owns one spool, and in-process sim nets must
    not pool their spool byte gauges."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        self.snapshots = r.counter(
            "telemetry_snapshots_total",
            "Telemetry snapshots appended to the on-disk spool",
        )
        self.spool_bytes = r.gauge(
            "telemetry_spool_bytes",
            "On-disk size of the telemetry spool across all segments",
        )
        self.write_errors = r.counter(
            "telemetry_write_errors_total",
            "Telemetry snapshot appends that failed (disk errors)",
        )
        self.dropped = r.counter(
            "telemetry_dropped_snapshots_total",
            "Telemetry snapshots dropped before reaching the spool "
            "(serialization failures / flusher shutdown races)",
        )
        # ring-eviction visibility: the flight recorder, profile ledger
        # and CritPath/QuorumTrace rings all silently evict under soak
        # load — soak_report flags lossy legs off these counters
        self.evicted = r.counter(
            "observability_evicted_total",
            "Records evicted from bounded observability stores",
            label_names=("store",),
        )


_mempool_batch_mtx = threading.Lock()
_mempool_batch_metrics: Optional[MempoolBatchMetrics] = None


def get_mempool_batch_metrics() -> MempoolBatchMetrics:
    """Process-wide MempoolBatchMetrics singleton (mirrors
    get_vote_batch_metrics)."""
    global _mempool_batch_metrics
    with _mempool_batch_mtx:
        if _mempool_batch_metrics is None:
            _mempool_batch_metrics = MempoolBatchMetrics()
        return _mempool_batch_metrics


class NodeMetrics:
    """All four reference metric families on one registry
    (consensus/metrics.go:14, p2p/metrics.go, mempool/metrics.go,
    state/metrics.go), plus the process-wide verify family attached."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        # consensus
        self.height = r.gauge("consensus_height", "Height of the chain")
        self.rounds = r.gauge("consensus_rounds", "Round of the current height")
        self.validators = r.gauge("consensus_validators", "Number of validators")
        self.validators_power = r.gauge(
            "consensus_validators_power", "Total voting power of validators"
        )
        self.missing_validators = r.gauge(
            "consensus_missing_validators", "Validators missing from the last commit"
        )
        self.byzantine_validators = r.gauge(
            "consensus_byzantine_validators", "Validators that double-signed"
        )
        self.block_interval_seconds = r.histogram(
            "consensus_block_interval_seconds", "Time between this and the last block"
        )
        self.num_txs = r.gauge("consensus_num_txs", "Txs in the latest block")
        self.block_size_bytes = r.gauge(
            "consensus_block_size_bytes", "Size of the latest block"
        )
        self.total_txs = r.gauge("consensus_total_txs", "Total txs committed")
        self.fast_syncing = r.gauge("consensus_fast_syncing", "1 while fast syncing")
        self.step_duration = r.histogram(
            "consensus_step_duration_seconds",
            "Wall seconds spent in each consensus step (labeled by the step "
            "being left)",
            label_names=("step",),
        )
        self.vote_arrival_latency = r.histogram(
            "consensus_vote_arrival_latency_seconds",
            "Wall-clock delay between a vote's signed timestamp and its "
            "arrival at the state machine",
            label_names=("type",),
        )
        self.wal_append_seconds = r.histogram(
            "consensus_wal_append_seconds", "WAL buffered-append wall seconds",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
        )
        self.wal_fsync_seconds = r.histogram(
            "consensus_wal_fsync_seconds", "WAL fsync wall seconds",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
        )
        # commit-latency waterfall (libs/critpath.py): wall seconds each
        # committed height spent in each phase of the commit path
        self.height_phase_seconds = r.histogram(
            "consensus_height_phase_seconds",
            "Per-committed-height wall seconds attributed to each "
            "commit-path phase by the critical-path analyzer",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
            label_names=("phase",),
        )
        # quorum formation (libs/quorumtrace.py): wall seconds from round
        # entry until arriving voting power crossed 1/3 and 2/3 of total
        self.quorum_time_to_third = r.histogram(
            "consensus_quorum_time_to_third_seconds",
            "Per-height wall seconds from round entry until arriving votes "
            "crossed 1/3 of total voting power",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
            label_names=("type",),
        )
        self.quorum_time_to_two_thirds = r.histogram(
            "consensus_quorum_time_to_two_thirds_seconds",
            "Per-height wall seconds from round entry until arriving votes "
            "crossed 2/3 of total voting power (quorum)",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
            label_names=("type",),
        )
        # liveness watchdog (libs/watchdog.py)
        self.stalls = r.counter(
            "consensus_stalls_total",
            "Distinct consensus stalls detected by the liveness watchdog",
        )
        self.stall_seconds = r.gauge(
            "consensus_stall_seconds",
            "Age of the current consensus stall (0 when progressing)",
        )
        # pubsub (libs/pubsub.py slow-subscriber drops)
        self.pubsub_dropped = r.counter(
            "pubsub_dropped_events_total",
            "Events dropped because a subscriber's buffer was full",
            label_names=("client_id",),
        )
        # p2p
        self.peers = r.gauge("p2p_peers", "Connected peers")
        self.peer_receive_bytes = r.counter(
            "p2p_peer_receive_bytes_total",
            "Wire bytes received from a peer by channel (packet framing "
            "included; sourced from the same stream the flowrate recv "
            "monitor measures)",
            label_names=("peer_id", "chID"),
        )
        self.peer_send_bytes = r.counter(
            "p2p_peer_send_bytes_total",
            "Wire bytes sent to a peer by channel (packet framing included)",
            label_names=("peer_id", "chID"),
        )
        self.peer_pending_send_bytes = r.gauge(
            "p2p_peer_pending_send_bytes",
            "Bytes queued (not yet on the wire) toward a peer",
            label_names=("peer_id",),
        )
        self.messages_received = r.counter(
            "p2p_messages_received_total",
            "Complete messages delivered to reactors by channel",
            label_names=("chID",),
        )
        self.messages_sent = r.counter(
            "p2p_messages_sent_total",
            "Messages queued toward peers by channel",
            label_names=("chID",),
        )
        # vote-gossip efficiency at the consensus reactor receive seam
        # (BEFORE VoteSet dedup): every VoteMessage increments exactly one
        # of these two, so their sum is total votes received
        self.vote_first_sighting = r.counter(
            "p2p_vote_first_sighting_total",
            "Votes received that were the node's first sighting of that "
            "(height, round, type, validator) vote, by gossiping peer",
            label_names=("peer_id", "chID"),
        )
        self.duplicate_votes = r.counter(
            "p2p_duplicate_votes_total",
            "Votes received that the node had already seen (gossip "
            "amplification waste), by gossiping peer",
            label_names=("peer_id", "chID"),
        )
        # mempool
        self.mempool_size = r.gauge("mempool_size", "Unconfirmed txs in the mempool")
        self.mempool_tx_size_bytes = r.histogram(
            "mempool_tx_size_bytes", "Size of accepted mempool txs",
            buckets=_SIZE_BUCKETS,
        )
        self.mempool_failed_txs = r.counter(
            "mempool_failed_txs", "Txs rejected by CheckTx"
        )
        self.mempool_recheck_times = r.counter(
            "mempool_recheck_times", "Txs re-checked after a commit"
        )
        # mempool QoS / admission control (mempool/qos.py)
        self.mempool_qos_admitted_total = r.counter(
            "mempool_qos_admitted_total",
            "Peer txs admitted past the QoS layer",
        )
        self.mempool_qos_dropped_total = r.counter(
            "mempool_qos_dropped_total",
            "Peer txs dropped by the QoS layer",
            label_names=("reason",),
        )
        self.mempool_qos_muted_peers = r.gauge(
            "mempool_qos_muted_peers", "Peers currently muted by QoS"
        )
        self.mempool_qos_mutes_total = r.counter(
            "mempool_qos_mutes_total", "Repeat-offender mutes issued"
        )
        self.mempool_qos_shed_total = r.counter(
            "mempool_qos_shed_total",
            "RPC broadcast_tx_* requests shed under overload",
            label_names=("route",),
        )
        self.mempool_qos_evicted_total = r.counter(
            "mempool_qos_evicted_total",
            "Txs evicted from lower lanes to admit higher-priority txs",
            label_names=("lane",),
        )
        self.mempool_lane_txs = r.gauge(
            "mempool_lane_txs", "Unconfirmed txs per priority lane",
            label_names=("lane",),
        )
        self.mempool_checktx_batch_size = r.histogram(
            "mempool_checktx_batch_size",
            "Txs coalesced per CheckTx/recheck app-conn window",
            buckets=_SIZE_BUCKETS,
        )
        # state
        self.block_processing_time = r.histogram(
            "state_block_processing_time", "ApplyBlock seconds",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
        )
        # verify pipeline + state sync (process-global; attached, not
        # re-registered)
        self.verify = get_verify_metrics()
        r.attach(self.verify.registry)
        self.statesync = get_statesync_metrics()
        r.attach(self.statesync.registry)
        self.frontend = get_frontend_metrics()
        r.attach(self.frontend.registry)
        self.vote_batch = get_vote_batch_metrics()
        r.attach(self.vote_batch.registry)
        self.mempool_batch = get_mempool_batch_metrics()
        r.attach(self.mempool_batch.registry)
        # per-node telemetry spool family (see TelemetryMetrics docstring
        # for why this one is NOT a process singleton)
        self.telemetry = TelemetryMetrics()
        r.attach(self.telemetry.registry)
        self._last_block_time: Optional[float] = None
        # cardinality hygiene: at most MAX_PEER_LABELS distinct peer ids ever
        # get their own label value; the rest collapse into "overflow"
        self._peer_label_ids: set = set()
        self._peer_label_mtx = threading.Lock()

    # called from the consensus event path -------------------------------------
    def record_block(self, block, valset) -> None:
        now = time.monotonic()
        self.height.set(block.height)
        self.num_txs.set(len(block.data.txs))
        self.total_txs.add(len(block.data.txs))
        self.block_size_bytes.set(len(block.marshal()))
        if valset is not None:
            self.validators.set(valset.size)
            self.validators_power.set(valset.total_voting_power())
            if block.height > 1:
                # height 1 has no LastCommit — counting "missing" precommits
                # there reports the whole valset absent
                missing = sum(
                    1 for pc in block.last_commit.precommits if pc is None
                )
                self.missing_validators.set(missing)
        # double-sign evidence included in this block (metrics.go
        # ByzantineValidators is computed from block evidence)
        self.byzantine_validators.set(len(block.evidence.evidence))
        if self._last_block_time is not None:
            dt = now - self._last_block_time
            # monotonic() is process-local: a restart (or a timer reset at
            # fast-sync exit) leaves no usable previous timestamp, and a
            # non-positive delta means the clock basis changed under us
            if dt > 0:
                self.block_interval_seconds.observe(dt)
        self._last_block_time = now

    def reset_block_timer(self) -> None:
        """Forget the last block timestamp.  Called at fast-sync exit: the
        synced blocks arrived at replay speed, so the next live block's
        interval measured against them would be garbage."""
        self._last_block_time = None

    # per-peer traffic ----------------------------------------------------------
    MAX_PEER_LABELS = 64

    def _peer_label(self, peer_id: str) -> str:
        with self._peer_label_mtx:
            if peer_id in self._peer_label_ids:
                return peer_id
            if len(self._peer_label_ids) < self.MAX_PEER_LABELS:
                self._peer_label_ids.add(peer_id)
                return peer_id
        return "overflow"

    def record_peer_traffic(self, peer_id: str, chan_id: int,
                            sent: int = 0, received: int = 0) -> None:
        pid = self._peer_label(peer_id)
        ch = f"{chan_id:#x}"
        if sent:
            self.peer_send_bytes.add(sent, (pid, ch))
        if received:
            self.peer_receive_bytes.add(received, (pid, ch))

    def set_peer_pending(self, peer_id: str, pending: int) -> None:
        self.peer_pending_send_bytes.set(float(pending),
                                         (self._peer_label(peer_id),))

    def record_vote_sighting(self, peer_id: str, chan_id: int,
                             first: bool) -> None:
        """One VoteMessage at the reactor receive seam: first sighting or
        duplicate (same 64-peer label fold as the traffic counters)."""
        pid = self._peer_label(peer_id)
        ch = f"{chan_id:#x}"
        if first:
            self.vote_first_sighting.add(1.0, (pid, ch))
        else:
            self.duplicate_votes.add(1.0, (pid, ch))

    def forget_peer(self, peer_id: str) -> None:
        """Drop every per-peer series for a disconnected peer so label
        cardinality tracks the live peer set, not its history."""
        with self._peer_label_mtx:
            self._peer_label_ids.discard(peer_id)
        self.peer_send_bytes.remove_matching("peer_id", peer_id)
        self.peer_receive_bytes.remove_matching("peer_id", peer_id)
        self.peer_pending_send_bytes.remove_matching("peer_id", peer_id)
        self.vote_first_sighting.remove_matching("peer_id", peer_id)
        self.duplicate_votes.remove_matching("peer_id", peer_id)
