"""Minimal Prometheus-style metrics: Counter/Gauge/Histogram + registry +
text exposition (ref: the go-kit prometheus metrics used at
consensus/metrics.go:14, p2p/metrics.go, mempool/metrics.go,
state/metrics.go, served at node/node.go:698).

No external client library — exposition format is plain text v0.0.4, which
is all Prometheus needs to scrape.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


def _fmt_value(v: float) -> str:
    """Full precision: %g truncates to 6 significant digits, silently
    corrupting counters past ~1e6 (real client libs emit repr-style)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_labels(names: Sequence[str], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._mtx = threading.Lock()

    def expose(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, *values: str) -> "_BoundCounter":
        return _BoundCounter(self, tuple(str(v) for v in values))

    def add(self, v: float = 1.0, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = self._values.get(_labels, 0.0) + v

    def expose(self) -> List[str]:
        with self._mtx:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            return [f"{self.name} 0"]
        return [
            f"{self.name}{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}"
            for lv, v in items
        ]


class _BoundCounter:
    def __init__(self, parent: Counter, labels: Tuple[str, ...]):
        self._p, self._l = parent, labels

    def add(self, v: float = 1.0) -> None:
        self._p.add(v, self._l)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {} if label_names else {(): 0.0}

    def labels(self, *values: str) -> "_BoundGauge":
        return _BoundGauge(self, tuple(str(v) for v in values))

    def set(self, v: float, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = float(v)

    def add(self, v: float = 1.0, _labels: Tuple[str, ...] = ()) -> None:
        with self._mtx:
            self._values[_labels] = self._values.get(_labels, 0.0) + v

    def expose(self) -> List[str]:
        with self._mtx:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_fmt_labels(self.label_names, lv)} {_fmt_value(v)}"
            for lv, v in items
        ]


class _BoundGauge:
    def __init__(self, parent: Gauge, labels: Tuple[str, ...]):
        self._p, self._l = parent, labels

    def set(self, v: float) -> None:
        self._p.set(v, self._l)

    def add(self, v: float = 1.0) -> None:
        self._p.add(v, self._l)


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        with self._mtx:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose(self) -> List[str]:
        with self._mtx:
            counts, total, s = list(self._counts), self._count, self._sum
        out, cum = [], 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b:g}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {_fmt_value(s)}")
        out.append(f"{self.name}_count {total}")
        return out


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._mtx = threading.Lock()

    def _register(self, m: _Metric) -> _Metric:
        with self._mtx:
            self._metrics.append(m)
        return m

    def counter(self, name, help="", label_names=()) -> Counter:
        return self._register(Counter(f"{self.namespace}_{name}", help, label_names))

    def gauge(self, name, help="", label_names=()) -> Gauge:
        return self._register(Gauge(f"{self.namespace}_{name}", help, label_names))

    def histogram(self, name, help="", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(f"{self.namespace}_{name}", help, buckets))

    def expose_text(self) -> str:
        lines: List[str] = []
        with self._mtx:
            metrics = list(self._metrics)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# -- the per-subsystem metric sets the reference defines -----------------------


class NodeMetrics:
    """All four reference metric families on one registry
    (consensus/metrics.go:14, p2p/metrics.go, mempool/metrics.go,
    state/metrics.go)."""

    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        # consensus
        self.height = r.gauge("consensus_height", "Height of the chain")
        self.rounds = r.gauge("consensus_rounds", "Round of the current height")
        self.validators = r.gauge("consensus_validators", "Number of validators")
        self.validators_power = r.gauge(
            "consensus_validators_power", "Total voting power of validators"
        )
        self.missing_validators = r.gauge(
            "consensus_missing_validators", "Validators missing from the last commit"
        )
        self.byzantine_validators = r.gauge(
            "consensus_byzantine_validators", "Validators that double-signed"
        )
        self.block_interval_seconds = r.histogram(
            "consensus_block_interval_seconds", "Time between this and the last block"
        )
        self.num_txs = r.gauge("consensus_num_txs", "Txs in the latest block")
        self.block_size_bytes = r.gauge(
            "consensus_block_size_bytes", "Size of the latest block"
        )
        self.total_txs = r.gauge("consensus_total_txs", "Total txs committed")
        self.fast_syncing = r.gauge("consensus_fast_syncing", "1 while fast syncing")
        # p2p
        self.peers = r.gauge("p2p_peers", "Connected peers")
        # mempool
        self.mempool_size = r.gauge("mempool_size", "Unconfirmed txs in the mempool")
        # state
        self.block_processing_time = r.histogram(
            "state_block_processing_time", "ApplyBlock seconds",
            buckets=[b / 10 for b in _DEFAULT_BUCKETS],
        )
        self._last_block_time: Optional[float] = None

    # called from the consensus event path -------------------------------------
    def record_block(self, block, valset) -> None:
        now = time.monotonic()
        self.height.set(block.height)
        self.num_txs.set(len(block.data.txs))
        self.total_txs.add(len(block.data.txs))
        self.block_size_bytes.set(len(block.marshal()))
        if valset is not None:
            self.validators.set(valset.size)
            self.validators_power.set(valset.total_voting_power())
            missing = sum(1 for pc in block.last_commit.precommits if pc is None)
            if block.height > 1:
                self.missing_validators.set(missing)
        # double-sign evidence included in this block (metrics.go
        # ByzantineValidators is computed from block evidence)
        self.byzantine_validators.set(len(block.evidence.evidence))
        if self._last_block_time is not None:
            self.block_interval_seconds.observe(now - self._last_block_time)
        self._last_block_time = now
