"""Append-only file groups with size-based rotation — the WAL substrate
(ref: libs/autofile/group.go, 763 LoC).

A Group owns <head> plus rotated chunks <head>.000, <head>.001, ...
Write() appends to head; when head exceeds head_size_limit it rotates; when
total exceeds total_size_limit the oldest chunks are pruned.  GroupReader
scans from any chunk index forward — consensus WAL replay reads through it.
"""

from __future__ import annotations

import os
import re
import threading
from typing import BinaryIO, List, Optional, Tuple

DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # 10MB (group.go:25)
DEFAULT_TOTAL_SIZE_LIMIT = 1024 * 1024 * 1024  # 1GB (group.go:26)


class Group:
    def __init__(
        self,
        head_path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_TOTAL_SIZE_LIMIT,
    ):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self._mtx = threading.RLock()
        os.makedirs(os.path.dirname(os.path.abspath(head_path)), exist_ok=True)
        self._head: BinaryIO = open(head_path, "ab")
        self._min_index, self._max_index = self._scan_indices()

    def _scan_indices(self) -> Tuple[int, int]:
        """Chunk files are '<head>.NNN'; returns (min, max) where max is the
        index the head will take on next rotation."""
        d = os.path.dirname(os.path.abspath(self.head_path))
        base = os.path.basename(self.head_path)
        pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
        idxs = []
        for fn in os.listdir(d):
            m = pat.match(fn)
            if m:
                idxs.append(int(m.group(1)))
        if not idxs:
            return 0, 0
        return min(idxs), max(idxs) + 1

    @property
    def min_index(self) -> int:
        return self._min_index

    @property
    def max_index(self) -> int:
        """Index of the head (rotated chunks are min_index..max_index-1)."""
        return self._max_index

    def chunk_path(self, index: int) -> str:
        if index == self._max_index:
            return self.head_path
        return f"{self.head_path}.{index:03d}"

    # writing --------------------------------------------------------------
    def write(self, data: bytes) -> None:
        with self._mtx:
            self._head.write(data)

    def flush(self) -> None:
        with self._mtx:
            self._head.flush()

    def sync(self) -> None:
        with self._mtx:
            self._head.flush()
            os.fsync(self._head.fileno())

    def head_size(self) -> int:
        with self._mtx:
            self._head.flush()
            return os.path.getsize(self.head_path)

    def total_size(self) -> int:
        with self._mtx:
            total = self.head_size()
            for i in range(self._min_index, self._max_index):
                p = self.chunk_path(i)
                if os.path.exists(p):
                    total += os.path.getsize(p)
            return total

    def maybe_rotate(self) -> bool:
        """Rotate head into a numbered chunk if over the size limit; prune
        oldest chunks while over the total limit."""
        with self._mtx:
            rotated = False
            if self.head_size() >= self.head_size_limit:
                self.rotate()
                rotated = True
            while (
                self.total_size() > self.total_size_limit
                and self._min_index < self._max_index
            ):
                p = self.chunk_path(self._min_index)
                if os.path.exists(p):
                    os.remove(p)
                self._min_index += 1
            return rotated

    def rotate(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()
            os.rename(self.head_path, f"{self.head_path}.{self._max_index:03d}")
            self._max_index += 1
            self._head = open(self.head_path, "ab")

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()

    # reading --------------------------------------------------------------
    def new_reader(self, start_index: Optional[int] = None) -> "GroupReader":
        return GroupReader(self, start_index if start_index is not None else self._min_index)


class GroupReader:
    """Sequential reader across chunk boundaries (ref group.go GroupReader)."""

    def __init__(self, group: Group, start_index: int):
        self._group = group
        self._index = start_index
        self._file: Optional[BinaryIO] = None
        self._open_current()

    @property
    def cur_index(self) -> int:
        return self._index

    def _open_current(self) -> bool:
        if self._file:
            self._file.close()
            self._file = None
        while self._index <= self._group.max_index:
            p = self._group.chunk_path(self._index)
            if os.path.exists(p):
                self._group.flush()
                self._file = open(p, "rb")
                return True
            self._index += 1
        return False

    def read(self, n: int = -1) -> bytes:
        """Read up to n bytes, advancing across chunks; b'' at true EOF."""
        out = b""
        while n < 0 or len(out) < n:
            if self._file is None:
                break
            chunk = self._file.read(n - len(out) if n >= 0 else -1)
            if chunk:
                out += chunk
            else:
                self._index += 1
                if not self._open_current():
                    break
        return out

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None
