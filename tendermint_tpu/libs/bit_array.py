"""BitArray — vote/part bitmaps gossiped between peers
(ref: libs/common/bit_array.go).

Backed by a Python int (arbitrary-precision bitmask): sub/or/and/pick become
single integer ops instead of word loops — the batch-friendly representation
that also converts to numpy masks for the device tally path.
"""

from __future__ import annotations

import random
from typing import List, Optional

from tendermint_tpu.encoding.codec import Reader, Writer


class BitArray:
    def __init__(self, bits: int, value: int = 0):
        if bits < 0:
            raise ValueError("negative bits")
        self.bits = bits
        self._v = value & ((1 << bits) - 1)

    # element ops ----------------------------------------------------------
    def get_index(self, i: int) -> bool:
        if i >= self.bits or i < 0:
            return False
        return bool((self._v >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i >= self.bits or i < 0:
            return False
        if v:
            self._v |= 1 << i
        else:
            self._v &= ~(1 << i)
        return True

    # set ops --------------------------------------------------------------
    def copy(self) -> "BitArray":
        return BitArray(self.bits, self._v)

    def or_(self, other: "BitArray") -> "BitArray":
        return BitArray(max(self.bits, other.bits), self._v | other._v)

    def and_(self, other: "BitArray") -> "BitArray":
        return BitArray(min(self.bits, other.bits), self._v & other._v)

    def not_(self) -> "BitArray":
        return BitArray(self.bits, ~self._v)

    def sub(self, other: "BitArray") -> "BitArray":
        """bits set in self but not in other (ref bit_array.go Sub)."""
        return BitArray(self.bits, self._v & ~other._v)

    def is_empty(self) -> bool:
        return self._v == 0

    def is_full(self) -> bool:
        return self._v == (1 << self.bits) - 1

    def num_true(self) -> int:
        return bin(self._v).count("1")

    def pick_random(self) -> Optional[int]:
        """Index of a random set bit, or None (ref PickRandom)."""
        n = self.num_true()
        if n == 0:
            return None
        k = random.randrange(n)
        v = self._v
        for _ in range(k):
            v &= v - 1  # drop lowest set bit
        return (v & -v).bit_length() - 1

    def true_indices(self) -> List[int]:
        out = []
        v = self._v
        while v:
            low = v & -v
            out.append(low.bit_length() - 1)
            v ^= low
        return out

    def update(self, other: "BitArray") -> None:
        """Copy other's contents into self (ref Update)."""
        self._v = other._v & ((1 << self.bits) - 1)

    # codec ----------------------------------------------------------------
    def encode(self, w: Writer) -> None:
        w.uvarint(self.bits)
        nbytes = (self.bits + 7) // 8
        w.bytes(self._v.to_bytes(nbytes, "little"))

    def marshal(self) -> bytes:
        w = Writer()
        self.encode(w)
        return w.build()

    # wire-decode bound: bitmaps index validators or block parts, both far
    # below 16M; an unbounded peer-supplied `bits` would let one message
    # materialize a giant int (memory-exhaustion DoS)
    MAX_DECODE_BITS = 1 << 24

    @classmethod
    def decode(cls, r: Reader) -> "BitArray":
        bits = r.uvarint()
        if bits > cls.MAX_DECODE_BITS:
            raise ValueError(f"BitArray bits {bits} exceeds decode bound")
        return cls(bits, int.from_bytes(r.bytes(), "little"))

    @classmethod
    def unmarshal(cls, data: bytes) -> "BitArray":
        return cls.decode(Reader(data))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self.bits == other.bits
            and self._v == other._v
        )

    def __str__(self) -> str:
        return "".join("x" if self.get_index(i) else "_" for i in range(self.bits))
