"""Light client: trust-minimized header verification over the batched
commit-verify path (ref: /root/reference/lite/)."""

from tendermint_tpu.lite.provider import DBProvider, NodeProvider, Provider, ProviderError
from tendermint_tpu.lite.types import FullCommit, LiteError, SignedHeader
from tendermint_tpu.lite.verifier import BaseVerifier, DynamicVerifier

__all__ = [
    "BaseVerifier",
    "DBProvider",
    "DynamicVerifier",
    "FullCommit",
    "LiteError",
    "NodeProvider",
    "Provider",
    "ProviderError",
    "SignedHeader",
]
