"""Light-client verifiers (ref: lite/base_verifier.go:18,
dynamic_verifier.go:21).

BaseVerifier certifies headers against ONE known validator set.
DynamicVerifier tracks validator-set changes: it keeps a persistent store of
trusted FullCommits and hops trust forward — directly when the valset hash
chains (header.next_validators_hash), via VerifyFutureCommit when it
changed, and by BISECTION when the change is too large for one hop
(dynamic_verifier.go:195 updateToHeight, TooMuchChange → halve the jump).

Every signature check inside rides the batched device verify path
(ValidatorSet.verify_commit / verify_future_commit).
"""

from __future__ import annotations

from typing import Optional

from tendermint_tpu.lite.provider import DBProvider, Provider, ProviderError
from tendermint_tpu.lite.types import FullCommit, LiteError, SignedHeader
from tendermint_tpu.types.validator_set import (
    CommitError,
    TooMuchChangeError,
    ValidatorSet,
)


class BaseVerifier:
    """Static-valset certifier (base_verifier.go)."""

    def __init__(self, chain_id: str, height: int, valset: ValidatorSet):
        self.chain_id = chain_id
        self.initial_height = height
        self.valset = valset

    def verify(self, signed_header: SignedHeader, verifier=None) -> None:
        """base_verifier.go Verify: height in range, valset hash matches,
        +2/3 of the set signed it."""
        if signed_header.height < self.initial_height:
            raise LiteError(
                f"height {signed_header.height} below initial {self.initial_height}"
            )
        signed_header.validate_basic(self.chain_id)
        if signed_header.header.validators_hash != self.valset.hash():
            raise LiteError("header validators_hash != trusted valset")
        self.valset.verify_commit(
            self.chain_id,
            signed_header.commit.block_id,
            signed_header.height,
            signed_header.commit,
            verifier=verifier,
        )


class DynamicVerifier:
    """Valset-tracking certifier with a persistent trust store
    (dynamic_verifier.go)."""

    def __init__(
        self,
        chain_id: str,
        trusted: DBProvider,
        source: Provider,
        batch_verifier=None,
    ):
        self.chain_id = chain_id
        self.trusted = trusted
        self.source = source
        self.batch_verifier = batch_verifier

    # -- bootstrap ---------------------------------------------------------------
    def init_from_full_commit(self, fc: FullCommit) -> None:
        """Seed trust (e.g. from a social-consensus genesis/checkpoint)."""
        fc.validate_full(self.chain_id)
        self.trusted.save_full_commit(fc)

    # -- certify -----------------------------------------------------------------
    def verify(self, signed_header: SignedHeader) -> None:
        """dynamic_verifier.go Verify: ensure a trusted FullCommit for
        exactly this height, then certify against its valset."""
        h = signed_header.height
        tfc = self._trusted_at_or_below(h)
        if tfc.height != h:
            self._update_to_height(h)
            tfc = self._trusted_at_or_below(h)
            if tfc.height != h:
                raise LiteError(f"could not establish trust at height {h}")
        BaseVerifier(self.chain_id, tfc.height, tfc.validators).verify(
            signed_header, verifier=self.batch_verifier
        )

    # -- trust propagation ----------------------------------------------------------
    def _trusted_at_or_below(self, h: int) -> FullCommit:
        try:
            return self.trusted.latest_full_commit(self.chain_id, 1, h)
        except ProviderError as e:
            raise LiteError(
                "no trusted full commit — seed with init_from_full_commit"
            ) from e

    def _update_to_height(self, h: int) -> None:
        """dynamic_verifier.go:195 updateToHeight — fetch FullCommit(h) from
        the source and extend trust to it, bisecting on TooMuchChange."""
        fc = self.source.full_commit_at(self.chain_id, h)
        while True:
            tfc = self._trusted_at_or_below(h)
            if tfc.height == h:
                return
            try:
                self._verify_and_save(tfc, fc)
                return
            except TooMuchChangeError:
                # too much valset churn in one hop: trust a midpoint first
                mid = (tfc.height + h) // 2
                if mid in (tfc.height, h):
                    raise
                self._update_to_height(mid)

    def _verify_and_save(self, tfc: FullCommit, fc: FullCommit) -> None:
        """One trust hop tfc -> fc (dynamic_verifier.go verifyAndSave)."""
        if fc.height <= tfc.height:
            raise LiteError("hop must move forward")
        fc.validate_full(self.chain_id)
        if tfc.next_validators.hash() == fc.validators.hash():
            # unchanged valset: ordinary certify
            fc.validators.verify_commit(
                self.chain_id,
                fc.signed_header.commit.block_id,
                fc.height,
                fc.signed_header.commit,
                verifier=self.batch_verifier,
            )
        else:
            # changed: new set must sign AND +2/3 of the old next-set must
            # overlap (validator_set.go:339 VerifyFutureCommit; raises
            # TooMuchChangeError when overlap is insufficient)
            tfc.next_validators.verify_future_commit(
                fc.validators,
                self.chain_id,
                fc.signed_header.commit.block_id,
                fc.height,
                fc.signed_header.commit,
                verifier=self.batch_verifier,
            )
        self.trusted.save_full_commit(fc)
