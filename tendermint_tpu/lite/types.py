"""Light-client records (ref: lite/commit.go:16 FullCommit, types/block.go
SignedHeader).

A FullCommit is everything needed to trust one height without replaying the
chain: the signed header, the validator set that signed it, and the next
validator set (whose hash the header commits to — the hand-off for trust
propagation).
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import ValidatorSet


class LiteError(Exception):
    pass


@dataclass
class SignedHeader:
    """Header + the commit that signed it (types/block.go:458)."""

    header: Header
    commit: Commit

    @property
    def height(self) -> int:
        return self.header.height

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None or self.commit is None:
            raise LiteError("incomplete signed header")
        if self.header.chain_id != chain_id:
            raise LiteError(
                f"wrong chain id: {self.header.chain_id} != {chain_id}"
            )
        if self.commit.height() != self.header.height:
            raise LiteError(
                f"commit height {self.commit.height()} != header {self.header.height}"
            )
        if self.commit.block_id.hash != self.header.hash():
            raise LiteError("commit signs a different header")

    def encode(self, w: Writer) -> None:
        self.header.encode(w)
        self.commit.encode(w)

    @classmethod
    def decode(cls, r: Reader) -> "SignedHeader":
        return cls(Header.decode(r), Commit.decode(r))


@dataclass
class FullCommit:
    """SignedHeader + its validator sets (lite/commit.go:16)."""

    signed_header: SignedHeader
    validators: ValidatorSet
    next_validators: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.height

    def validate_full(self, chain_id: str) -> None:
        """lite/commit.go ValidateFull: internal consistency only — signature
        checks are the verifiers' job."""
        self.signed_header.validate_basic(chain_id)
        if self.signed_header.header.validators_hash != self.validators.hash():
            raise LiteError("header validators_hash != validators")
        if (
            self.signed_header.header.next_validators_hash
            != self.next_validators.hash()
        ):
            raise LiteError("header next_validators_hash != next_validators")

    def encode(self, w: Writer) -> None:
        self.signed_header.encode(w)
        self.validators.encode(w)
        self.next_validators.encode(w)

    def marshal(self) -> bytes:
        w = Writer()
        self.encode(w)
        return w.build()

    @classmethod
    def decode(cls, r: Reader) -> "FullCommit":
        return cls(
            SignedHeader.decode(r), ValidatorSet.decode(r), ValidatorSet.decode(r)
        )

    @classmethod
    def unmarshal(cls, data: bytes) -> "FullCommit":
        return cls.decode(Reader(data))
