"""FullCommit providers (ref: lite/provider.go, dbprovider.go:16,
lite/client/provider.go).

* ``DBProvider`` — the persistent trust store the DynamicVerifier saves
  verified commits into (and reloads across restarts);
* ``NodeProvider`` — a source reading from a live/full node's block store +
  state DB (the in-proc equivalent of the reference's HTTP client provider;
  the RPC-backed variant plugs the same interface).
"""

from __future__ import annotations

import struct
from typing import Optional

from tendermint_tpu.lite.types import FullCommit, LiteError, SignedHeader
from tendermint_tpu.state import store as sm_store


class ProviderError(LiteError):
    """Commit not found (lite/errors.go ErrCommitNotFound)."""


class Provider:
    def latest_full_commit(
        self, chain_id: str, min_height: int, max_height: int
    ) -> FullCommit:
        """The tallest FullCommit within [min_height, max_height]."""
        raise NotImplementedError

    def full_commit_at(self, chain_id: str, height: int) -> FullCommit:
        return self.latest_full_commit(chain_id, height, height)


class DBProvider(Provider):
    """Trust store over the KV abstraction (lite/dbprovider.go)."""

    _PREFIX = b"lite:fc:"

    def __init__(self, db):
        self._db = db

    def _key(self, chain_id: str, height: int) -> bytes:
        # big-endian height so the iterator orders numerically
        return self._PREFIX + chain_id.encode() + b":" + struct.pack(">q", height)

    def save_full_commit(self, fc: FullCommit) -> None:
        chain_id = fc.signed_header.header.chain_id
        self._db.set_sync(self._key(chain_id, fc.height), fc.marshal())

    def latest_full_commit(
        self, chain_id: str, min_height: int, max_height: int
    ) -> FullCommit:
        lo = self._key(chain_id, min_height)
        hi = self._key(chain_id, max_height + 1)
        # reverse iterator: decode only the tallest entry (bisection calls
        # this on every hop — decoding the whole range would be O(N²))
        for _, v in self._db.iterator(lo, hi, reverse=True):
            return FullCommit.unmarshal(v)
        raise ProviderError(
            f"no full commit for {chain_id} in [{min_height},{max_height}]"
        )


class NodeProvider(Provider):
    """Source provider over a full node's stores (block store + state DB) —
    what the reference's lite/client fetches over RPC, served in-proc."""

    def __init__(self, block_store, state_db):
        self._store = block_store
        self._state_db = state_db

    def latest_full_commit(
        self, chain_id: str, min_height: int, max_height: int
    ) -> FullCommit:
        top = min(max_height, self._store.height())
        for h in range(top, min_height - 1, -1):
            try:
                return self.full_commit_at(chain_id, h)
            except ProviderError:
                continue
        raise ProviderError(
            f"no full commit for {chain_id} in [{min_height},{max_height}]"
        )

    def full_commit_at(self, chain_id: str, height: int) -> FullCommit:
        meta = self._store.load_block_meta(height)
        commit = self._store.load_block_commit(height) or self._store.load_seen_commit(
            height
        )
        if meta is None or commit is None:
            raise ProviderError(f"height {height} not in store")
        try:
            vals = sm_store.load_validators(self._state_db, height)
            next_vals = sm_store.load_validators(self._state_db, height + 1)
        except Exception as e:
            raise ProviderError(f"no validators for height {height}: {e}") from e
        return FullCommit(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validators=vals,
            next_validators=next_vals,
        )
