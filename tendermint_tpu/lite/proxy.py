"""Light-client verifying proxy (ref: lite/proxy/proxy.go, wrapper.go and
the `lite` CLI command, cmd/tendermint/commands/lite.go).

``RPCProvider`` feeds the verifier FullCommits fetched from an UNTRUSTED
full node over RPC (codec-exact bytes via /lite_full_commit), with request
timeouts and bounded retry — a hung upstream surfaces as ``ProviderError``
so the frontend sheds load instead of queueing behind a dead socket.

``LiteProxy`` is the multi-client server: certification is delegated to a
shared ``frontend.LiteFrontend`` (verified-header cache, single-flight
dedup, cross-client lane aggregation), replacing the old per-instance
``DynamicVerifier`` loop.  ``run_lite_proxy`` serves /status, /commit,
/verify_commit and /light_block whose responses are only ever derived
from headers the frontend certified — a caller needs no trust in the
backing node.  A full node can pass its own ``block_store``/``state_db``
(the ``NodeProvider`` path) and serve light clients without an RPC hop.
"""

from __future__ import annotations

import base64
import http.client
import json
import socket
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from tendermint_tpu.encoding.codec import Reader
from tendermint_tpu.libs.db.kv import new_db
from tendermint_tpu.lite.provider import (
    DBProvider,
    NodeProvider,
    Provider,
    ProviderError,
)
from tendermint_tpu.lite.types import FullCommit, LiteError, SignedHeader
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import ValidatorSet

# transport-level failures worth a bounded retry; an RPC-level error
# (RPCClientError) is the upstream *answering* "no" and never retried
_TRANSIENT = (OSError, socket.timeout, http.client.HTTPException)


class RPCProvider(Provider):
    """Source provider over an untrusted node's RPC (lite/client/provider.go).

    Every upstream call is bounded: `timeout` seconds per attempt, at most
    `retries` retries (with linear backoff) on transport failures.  The
    old behavior — an HTTPClient with no explicit deadline discipline and
    no retry — let one hung upstream socket park a proxy worker thread
    forever."""

    def __init__(self, addr: str, timeout: float = 5.0, retries: int = 2,
                 backoff: float = 0.05):
        self._client = HTTPClient(addr, timeout=timeout)
        self.retries = max(0, int(retries))
        self.backoff = max(0.0, float(backoff))

    def _call(self, what: str, fn):
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except RPCClientError as e:
                raise ProviderError(f"{what}: {e}") from e
            except _TRANSIENT as e:
                last = e
                if attempt < self.retries:
                    time.sleep(self.backoff * (attempt + 1))
        raise ProviderError(
            f"{what}: upstream unreachable after {self.retries + 1} "
            f"attempts: {last}"
        ) from last

    def latest_full_commit(
        self, chain_id: str, min_height: int, max_height: int
    ) -> FullCommit:
        status = self._call("status", self._client.status)
        top = min(max_height, int(status["sync_info"]["latest_block_height"]))
        for h in range(top, min_height - 1, -1):
            try:
                return self.full_commit_at(chain_id, h)
            except ProviderError:
                continue
        raise ProviderError(f"no full commit in [{min_height},{max_height}]")

    def full_commit_at(self, chain_id: str, height: int) -> FullCommit:
        raw = self._call(
            f"lite_full_commit({height})",
            lambda: self._client.call("lite_full_commit", height=height),
        )
        header = Header.decode(Reader(base64.b64decode(raw["header"])))
        commit = Commit.unmarshal(base64.b64decode(raw["commit"]))
        vals = ValidatorSet.unmarshal(base64.b64decode(raw["validators"]))
        next_vals = ValidatorSet.unmarshal(base64.b64decode(raw["next_validators"]))
        return FullCommit(
            signed_header=SignedHeader(header=header, commit=commit),
            validators=vals,
            next_validators=next_vals,
        )


class LiteProxy:
    """Multi-client certification server (lite/proxy/proxy.go), backed by
    the shared frontend: N concurrent callers of certified_commit share a
    verified-header cache, per-height single-flight, and lane-aggregated
    planner dispatches."""

    def __init__(
        self,
        chain_id: str,
        node_addr: Optional[str] = None,
        trust_db=None,
        trusted_height: Optional[int] = None,
        trusted_hash: Optional[bytes] = None,
        *,
        block_store=None,
        state_db=None,
        source: Optional[Provider] = None,
        provider_timeout: float = 5.0,
        provider_retries: int = 2,
        batch_window_s: float = 0.002,
        batch_max_rows: int = 64,
        cache_size: int = 4096,
        mesh=None,
        use_device: Optional[bool] = None,
    ):
        """trusted_height/trusted_hash: an explicit root of trust — the
        header hash the operator verified out of band. Without it, first
        run falls back to trust-on-first-use: the UNTRUSTED backing node's
        height-1 FullCommit defines the chain permanently (the trust DB
        persists it), which a malicious first contact can exploit.

        Source resolution: an explicit `source` wins; else a full node's
        own `block_store` + `state_db` serve in-proc (NodeProvider — no
        RPC hop); else `node_addr` over RPC."""
        from tendermint_tpu.frontend import LiteFrontend

        self.chain_id = chain_id
        if source is not None:
            self.source = source
        elif block_store is not None and state_db is not None:
            self.source = NodeProvider(block_store, state_db)
        elif node_addr:
            self.source = RPCProvider(
                node_addr, timeout=provider_timeout, retries=provider_retries
            )
        else:
            raise ValueError(
                "need a source: node_addr, block_store+state_db, or source"
            )
        self.frontend = LiteFrontend(
            chain_id,
            self.source,
            trust_db=trust_db,
            mesh=mesh,
            use_device=use_device,
            batch_window_s=batch_window_s,
            batch_max_rows=batch_max_rows,
            cache_size=cache_size,
        )
        self.trusted = self.frontend.trusted  # the shared trust store
        if (trusted_height is None) != (trusted_hash is None):
            # height without hash would silently trust the untrusted node's
            # header at that height — the exact TOFU hole the pin exists to
            # close; hash without height is a dropped pin
            raise ValueError(
                "trusted_height and trusted_hash must be given together"
            )
        self.trusted_height = trusted_height
        self.trusted_hash = trusted_hash
        self._seeded = False

    def _ensure_seed(self) -> None:
        if self._seeded:
            return
        store_has_chain = self.frontend.has_trust()

        if store_has_chain:
            # the persistent store already has a chain: an explicit pin must
            # still be honored — a store seeded by TOFU from a malicious
            # first contact would otherwise silently win over the pin
            if self.trusted_height is not None:
                at_pin = None
                try:
                    at_pin = self.trusted.latest_full_commit(
                        self.chain_id, self.trusted_height, self.trusted_height
                    )
                except ProviderError:
                    pass
                if at_pin is None:
                    # an unverifiable pin must FAIL, not warn: the very
                    # threat the pin exists for is a TOFU-poisoned store,
                    # and proceeding would serve that chain as verified
                    raise ProviderError(
                        f"trust store has no entry at pinned height "
                        f"{self.trusted_height}, so the pin cannot be "
                        f"verified against it — reset the lite trust DB to "
                        f"re-anchor from the pin"
                    )
                if at_pin.signed_header.header.hash() != self.trusted_hash:
                    raise ProviderError(
                        f"trust store conflicts with the pinned hash at "
                        f"height {self.trusted_height} — reset the lite "
                        f"trust DB (it may have been TOFU-seeded from a "
                        f"malicious node)"
                    )
            self._seeded = True
            return

        if self.trusted_height is not None:
            # operator-supplied root of trust: fetch that height and check
            # the header hash matches before anchoring on it
            fc = self.source.full_commit_at(self.chain_id, self.trusted_height)
            got = fc.signed_header.header.hash()
            if got != self.trusted_hash:
                raise ProviderError(
                    f"trusted header mismatch at height {self.trusted_height}: "
                    f"node serves {got.hex()}, operator pinned "
                    f"{self.trusted_hash.hex()}"
                )
        else:
            # TOFU seed at the node's earliest available height (commands/
            # lite.go trusts the first fetch; operators can pre-seed the
            # DB or pass trusted_height/hash instead)
            import logging

            logging.getLogger("lite.proxy").warning(
                "TRUST-ON-FIRST-USE: seeding the light-client trust store "
                "from the UNTRUSTED node at height 1 — a malicious first "
                "contact defines the chain permanently; pass "
                "trusted_height/trusted_hash (or --trusted-height/"
                "--trusted-hash) to pin a verified root of trust"
            )
            fc = self.source.full_commit_at(self.chain_id, 1)
        self.frontend.init_trust(fc)
        self._seeded = True

    def certified_commit(self, height: Optional[int] = None) -> FullCommit:
        """FullCommit for `height` (default: source tip), verified through
        the shared frontend."""
        self._ensure_seed()
        if height is None:
            tip = self.source.latest_full_commit(
                self.chain_id, 1, 1 << 60
            ).height
            # the tip's canonical commit may not be stored yet; step back
            height = max(1, tip - 1)
        return self.frontend.certified_commit(height)

    def status(self) -> dict:
        fc = self.certified_commit()
        h = fc.signed_header.header
        return {
            "verified": True,
            "chain_id": h.chain_id,
            "latest_block_height": h.height,
            "latest_app_hash": h.app_hash.hex().upper(),
            "latest_block_time_ns": h.time_ns,
        }

    def commit(self, height: Optional[int] = None) -> dict:
        fc = self.certified_commit(height)
        h = fc.signed_header.header
        return {
            "verified": True,
            "header": {
                "chain_id": h.chain_id,
                "height": h.height,
                "app_hash": h.app_hash.hex().upper(),
                "validators_hash": h.validators_hash.hex().upper(),
                "time_ns": h.time_ns,
            },
            "commit": {
                "block_id_hash": fc.signed_header.commit.block_id.hash.hex().upper(),
                "precommits": sum(
                    1 for pc in fc.signed_header.commit.precommits if pc
                ),
            },
        }

    def verify_commit(self, height: Optional[int] = None) -> dict:
        """Certification verdict for `height`: block id, valset hash and
        quorum facts a thin client can anchor on."""
        fc = self.certified_commit(height)
        h = fc.signed_header.header
        return {
            "verified": True,
            "height": h.height,
            "block_id_hash": fc.signed_header.commit.block_id.hash.hex().upper(),
            "validators_hash": h.validators_hash.hex().upper(),
            "next_validators_hash": h.next_validators_hash.hex().upper(),
            "total_voting_power": fc.validators.total_voting_power(),
        }

    def light_block(self, height: Optional[int] = None) -> dict:
        """Codec-exact certified FullCommit bytes (b64) — what a thin
        client or restoring peer feeds straight into FullCommit.unmarshal."""
        self._ensure_seed()
        raw = self.frontend.light_block(height)
        return {
            "verified": True,
            "full_commit": base64.b64encode(raw).decode(),
        }

    def stats(self) -> dict:
        return self.frontend.stats()

    def close(self) -> None:
        self.frontend.close()


def _memdb():
    from tendermint_tpu.libs.db.kv import MemDB

    return MemDB()


def run_lite_proxy(
    chain_id: str,
    node_addr: str,
    laddr: str,
    home: str,
    trusted_height: Optional[int] = None,
    trusted_hash: Optional[bytes] = None,
) -> int:
    """Serve /status, /commit, /verify_commit and /light_block (all
    ?height=N) with verified-only data; concurrent requests batch through
    the shared frontend."""
    import os

    trust_db = new_db("lite_trust", "sqlite", os.path.join(home, "data"))
    proxy = LiteProxy(
        chain_id, node_addr, trust_db,
        trusted_height=trusted_height, trusted_hash=trusted_hash,
    )
    httpd = serve_proxy(proxy, laddr)
    print(f"lite proxy verifying {node_addr} (chain {chain_id}) on {laddr}", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def serve_proxy(proxy: LiteProxy, laddr: str) -> ThreadingHTTPServer:
    """Build the HTTP server for a LiteProxy (callers own serve_forever —
    the node embeds this to serve its own block store)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            parsed = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            height = None
            if "height" in q:
                try:
                    height = int(q["height"])
                except ValueError:
                    body = json.dumps({"error": "bad height"}).encode()
                    self.send_response(400)
                    self._finish(body)
                    return
            try:
                if parsed.path == "/status":
                    out = proxy.status()
                elif parsed.path == "/commit":
                    out = proxy.commit(height)
                elif parsed.path == "/verify_commit":
                    out = proxy.verify_commit(height)
                elif parsed.path == "/light_block":
                    out = proxy.light_block(height)
                elif parsed.path == "/frontend_stats":
                    out = proxy.stats()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps({"result": out}).encode()
                self.send_response(200)
            except (LiteError, ProviderError) as e:
                # certification failed or the upstream shed us — tell the
                # client to back off rather than queue behind a dead path
                body = json.dumps({"error": str(e)}).encode()
                self.send_response(502)
            except Exception as e:
                # anything else (a dead backing node mid-read, codec
                # surprises) — callers must get an HTTP error, not a reset
                # connection
                body = json.dumps({"error": str(e)}).encode()
                self.send_response(502)
            self._finish(body)

        def _finish(self, body: bytes) -> None:
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    host, _, port = laddr.replace("tcp://", "").rpartition(":")
    return ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
