"""Light-client verifying proxy (ref: lite/proxy/proxy.go, wrapper.go and
the `lite` CLI command, cmd/tendermint/commands/lite.go).

``RPCProvider`` feeds the DynamicVerifier FullCommits fetched from an
UNTRUSTED full node over RPC (codec-exact bytes via /lite_full_commit).
``run_lite_proxy`` serves a local HTTP endpoint whose /commit and /status
responses are only ever derived from headers the verifier certified —
a caller of the proxy needs no trust in the backing node.
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from tendermint_tpu.encoding.codec import Reader
from tendermint_tpu.libs.db.kv import new_db
from tendermint_tpu.lite.provider import DBProvider, Provider, ProviderError
from tendermint_tpu.lite.types import FullCommit, LiteError, SignedHeader
from tendermint_tpu.lite.verifier import DynamicVerifier
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import ValidatorSet


class RPCProvider(Provider):
    """Source provider over an untrusted node's RPC (lite/client/provider.go)."""

    def __init__(self, addr: str):
        self._client = HTTPClient(addr)

    def latest_full_commit(
        self, chain_id: str, min_height: int, max_height: int
    ) -> FullCommit:
        status = self._client.status()
        top = min(max_height, int(status["sync_info"]["latest_block_height"]))
        for h in range(top, min_height - 1, -1):
            try:
                return self.full_commit_at(chain_id, h)
            except ProviderError:
                continue
        raise ProviderError(f"no full commit in [{min_height},{max_height}]")

    def full_commit_at(self, chain_id: str, height: int) -> FullCommit:
        try:
            raw = self._client.call("lite_full_commit", height=height)
        except RPCClientError as e:
            raise ProviderError(str(e)) from e
        header = Header.decode(Reader(base64.b64decode(raw["header"])))
        commit = Commit.unmarshal(base64.b64decode(raw["commit"]))
        vals = ValidatorSet.unmarshal(base64.b64decode(raw["validators"]))
        next_vals = ValidatorSet.unmarshal(base64.b64decode(raw["next_validators"]))
        return FullCommit(
            signed_header=SignedHeader(header=header, commit=commit),
            validators=vals,
            next_validators=next_vals,
        )


class LiteProxy:
    """Certifies heights on demand and serves them (lite/proxy/proxy.go)."""

    def __init__(
        self,
        chain_id: str,
        node_addr: str,
        trust_db=None,
        trusted_height: Optional[int] = None,
        trusted_hash: Optional[bytes] = None,
    ):
        """trusted_height/trusted_hash: an explicit root of trust — the
        header hash the operator verified out of band. Without it, first
        run falls back to trust-on-first-use: the UNTRUSTED backing node's
        height-1 FullCommit defines the chain permanently (the trust DB
        persists it), which a malicious first contact can exploit."""
        self.chain_id = chain_id
        self.source = RPCProvider(node_addr)
        self.trusted = DBProvider(trust_db if trust_db is not None else _memdb())
        self.verifier = DynamicVerifier(chain_id, self.trusted, self.source)
        self._client = HTTPClient(node_addr)
        if (trusted_height is None) != (trusted_hash is None):
            # height without hash would silently trust the untrusted node's
            # header at that height — the exact TOFU hole the pin exists to
            # close; hash without height is a dropped pin
            raise ValueError(
                "trusted_height and trusted_hash must be given together"
            )
        self.trusted_height = trusted_height
        self.trusted_hash = trusted_hash
        self._seeded = False

    def _ensure_seed(self) -> None:
        if self._seeded:
            return
        store_has_chain = True
        try:
            self.trusted.latest_full_commit(self.chain_id, 1, 1 << 60)
        except ProviderError:
            store_has_chain = False

        if store_has_chain:
            # the persistent store already has a chain: an explicit pin must
            # still be honored — a store seeded by TOFU from a malicious
            # first contact would otherwise silently win over the pin
            if self.trusted_height is not None:
                at_pin = None
                try:
                    at_pin = self.trusted.latest_full_commit(
                        self.chain_id, self.trusted_height, self.trusted_height
                    )
                except ProviderError:
                    pass
                if at_pin is None:
                    # an unverifiable pin must FAIL, not warn: the very
                    # threat the pin exists for is a TOFU-poisoned store,
                    # and proceeding would serve that chain as verified
                    raise ProviderError(
                        f"trust store has no entry at pinned height "
                        f"{self.trusted_height}, so the pin cannot be "
                        f"verified against it — reset the lite trust DB to "
                        f"re-anchor from the pin"
                    )
                if at_pin.signed_header.header.hash() != self.trusted_hash:
                    raise ProviderError(
                        f"trust store conflicts with the pinned hash at "
                        f"height {self.trusted_height} — reset the lite "
                        f"trust DB (it may have been TOFU-seeded from a "
                        f"malicious node)"
                    )
            self._seeded = True
            return

        if self.trusted_height is not None:
            # operator-supplied root of trust: fetch that height and check
            # the header hash matches before anchoring on it
            fc = self.source.full_commit_at(self.chain_id, self.trusted_height)
            got = fc.signed_header.header.hash()
            if got != self.trusted_hash:
                raise ProviderError(
                    f"trusted header mismatch at height {self.trusted_height}: "
                    f"node serves {got.hex()}, operator pinned "
                    f"{self.trusted_hash.hex()}"
                )
        else:
            # TOFU seed at the node's earliest available height (commands/
            # lite.go trusts the first fetch; operators can pre-seed the
            # DB or pass trusted_height/hash instead)
            import logging

            logging.getLogger("lite.proxy").warning(
                "TRUST-ON-FIRST-USE: seeding the light-client trust store "
                "from the UNTRUSTED node at height 1 — a malicious first "
                "contact defines the chain permanently; pass "
                "trusted_height/trusted_hash (or --trusted-height/"
                "--trusted-hash) to pin a verified root of trust"
            )
            fc = self.source.full_commit_at(self.chain_id, 1)
        self.verifier.init_from_full_commit(fc)
        self._seeded = True

    def certified_commit(self, height: Optional[int] = None) -> FullCommit:
        """FullCommit for `height` (default: node tip), verified."""
        self._ensure_seed()
        if height is None:
            status = self._client.status()
            height = int(status["sync_info"]["latest_block_height"])
            # the tip's canonical commit may not be stored yet; step back
            height = max(1, height - 1)
        fc = self.source.full_commit_at(self.chain_id, height)
        self.verifier.verify(fc.signed_header)
        return fc

    def status(self) -> dict:
        fc = self.certified_commit()
        h = fc.signed_header.header
        return {
            "verified": True,
            "chain_id": h.chain_id,
            "latest_block_height": h.height,
            "latest_app_hash": h.app_hash.hex().upper(),
            "latest_block_time_ns": h.time_ns,
        }

    def commit(self, height: Optional[int] = None) -> dict:
        fc = self.certified_commit(height)
        h = fc.signed_header.header
        return {
            "verified": True,
            "header": {
                "chain_id": h.chain_id,
                "height": h.height,
                "app_hash": h.app_hash.hex().upper(),
                "validators_hash": h.validators_hash.hex().upper(),
                "time_ns": h.time_ns,
            },
            "commit": {
                "block_id_hash": fc.signed_header.commit.block_id.hash.hex().upper(),
                "precommits": sum(
                    1 for pc in fc.signed_header.commit.precommits if pc
                ),
            },
        }


def _memdb():
    from tendermint_tpu.libs.db.kv import MemDB

    return MemDB()


def run_lite_proxy(
    chain_id: str,
    node_addr: str,
    laddr: str,
    home: str,
    trusted_height: Optional[int] = None,
    trusted_hash: Optional[bytes] = None,
) -> int:
    """Serve /status and /commit?height=N with verified-only data."""
    import os

    trust_db = new_db("lite_trust", "sqlite", os.path.join(home, "data"))
    proxy = LiteProxy(
        chain_id, node_addr, trust_db,
        trusted_height=trusted_height, trusted_hash=trusted_hash,
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            parsed = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            try:
                if parsed.path == "/status":
                    out = proxy.status()
                elif parsed.path == "/commit":
                    try:
                        height = int(q["height"]) if "height" in q else None
                    except ValueError:
                        body = json.dumps({"error": "bad height"}).encode()
                        self.send_response(400)
                        self._finish(body)
                        return
                    out = proxy.commit(height)
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps({"result": out}).encode()
                self.send_response(200)
            except Exception as e:
                # LiteError/ProviderError, but also a dead backing node
                # (socket errors) — callers must get an HTTP error, not a
                # reset connection
                body = json.dumps({"error": str(e)}).encode()
                self.send_response(502)
            self._finish(body)

        def _finish(self, body: bytes) -> None:
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    host, _, port = laddr.replace("tcp://", "").rpartition(":")
    httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
    print(f"lite proxy verifying {node_addr} (chain {chain_id}) on {laddr}", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0
