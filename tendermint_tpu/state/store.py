"""State persistence (ref: state/store.go:29-300).

Keys mirror the reference schema: the State snapshot under 'stateKey',
per-height validator sets ('validatorsKey:<H>'), per-height consensus params
('consensusParamsKey:<H>'), and per-height ABCIResponses ('abciResponsesKey:<H>').
Validator/params records are only written at change heights; lookups chase the
'last changed' pointer exactly like the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.libs.db.kv import DB
from tendermint_tpu.state.state_types import State, state_from_genesis
from tendermint_tpu.types import ConsensusParams, GenesisDoc, ValidatorSet

_STATE_KEY = b"stateKey"


def _validators_key(height: int) -> bytes:
    return b"validatorsKey:%d" % height


def _params_key(height: int) -> bytes:
    return b"consensusParamsKey:%d" % height


def _abci_responses_key(height: int) -> bytes:
    return b"abciResponsesKey:%d" % height


class NoValSetForHeightError(Exception):
    pass


class NoABCIResponsesForHeightError(Exception):
    pass


@dataclass
class ABCIResponses:
    """Responses from executing a block, persisted for replay/indexing
    (ref state.go ABCIResponses)."""

    deliver_tx: List[abci.ResponseDeliverTx] = field(default_factory=list)
    end_block: Optional[abci.ResponseEndBlock] = None
    begin_block: Optional[abci.ResponseBeginBlock] = None

    def results_hash(self) -> bytes:
        from tendermint_tpu.types import ABCIResults

        return ABCIResults.from_deliver_txs(self.deliver_tx).hash()

    def marshal(self) -> bytes:
        return abci.msg_to_json(
            [self.deliver_tx, self.end_block, self.begin_block]
        )

    @classmethod
    def unmarshal(cls, data: bytes) -> "ABCIResponses":
        dtxs, eb, bb = abci.msg_from_json(data)
        return cls(deliver_tx=dtxs, end_block=eb, begin_block=bb)


# ---------------------------------------------------------------------------
# load/save
# ---------------------------------------------------------------------------


def load_state(db: DB) -> Optional[State]:
    raw = db.get(_STATE_KEY)
    return State.unmarshal(raw) if raw else None


def save_state(db: DB, state: State) -> None:
    """Persist the snapshot + the next height's validators/params records
    (ref store.go saveState)."""
    next_height = state.last_block_height + 1
    if next_height == 1:
        # bootstrap: genesis validators recorded for height 1 (the params
        # record for height 1 is written by the unconditional save below)
        save_validators_info(db, next_height, state.last_height_validators_changed,
                             state.validators)
    save_validators_info(db, next_height + 1, state.last_height_validators_changed,
                         state.next_validators)
    save_consensus_params_info(
        db, next_height, state.last_height_consensus_params_changed,
        state.consensus_params,
    )
    db.set_sync(_STATE_KEY, state.marshal())


def load_state_from_db_or_genesis(db: DB, genesis: GenesisDoc) -> State:
    state = load_state(db)
    if state is None or state.is_empty():
        state = state_from_genesis(genesis)
    return state


# validators per height ------------------------------------------------------


def save_validators_info(
    db: DB, height: int, last_changed: int, vals: Optional[ValidatorSet]
) -> None:
    """Write a record at `height`; the full set is stored only at change
    heights, otherwise just the pointer (ref store.go:149-170)."""
    w = Writer()
    w.svarint(last_changed)
    if height == last_changed and vals is not None:
        w.bool(True)
        vals.encode(w)
    else:
        w.bool(False)
    db.set(_validators_key(height), w.build())


def load_validators(db: DB, height: int) -> ValidatorSet:
    raw = db.get(_validators_key(height))
    if raw is None:
        raise NoValSetForHeightError(height)
    r = Reader(raw)
    last_changed = r.svarint()
    if r.bool():
        return ValidatorSet.decode(r)
    # chase the pointer to the change height
    raw2 = db.get(_validators_key(last_changed))
    if raw2 is None:
        raise NoValSetForHeightError(height)
    r2 = Reader(raw2)
    r2.svarint()
    if not r2.bool():
        raise NoValSetForHeightError(height)
    return ValidatorSet.decode(r2)


# consensus params per height ------------------------------------------------


def save_consensus_params_info(
    db: DB, height: int, last_changed: int, params: ConsensusParams
) -> None:
    w = Writer()
    w.svarint(last_changed)
    if height == last_changed:
        w.bool(True)
        params.encode(w)
    else:
        w.bool(False)
    db.set(_params_key(height), w.build())


def load_consensus_params(db: DB, height: int) -> ConsensusParams:
    raw = db.get(_params_key(height))
    if raw is None:
        raise NoValSetForHeightError(f"params @ {height}")
    r = Reader(raw)
    last_changed = r.svarint()
    if r.bool():
        return ConsensusParams.decode(r)
    raw2 = db.get(_params_key(last_changed))
    if raw2 is None:
        raise NoValSetForHeightError(f"params @ {height}")
    r2 = Reader(raw2)
    r2.svarint()
    if not r2.bool():
        raise NoValSetForHeightError(f"params @ {height}")
    return ConsensusParams.decode(r2)


# ABCI responses -------------------------------------------------------------


def save_abci_responses(db: DB, height: int, responses: ABCIResponses) -> None:
    db.set(_abci_responses_key(height), responses.marshal())


def load_abci_responses(db: DB, height: int) -> ABCIResponses:
    raw = db.get(_abci_responses_key(height))
    if raw is None:
        raise NoABCIResponsesForHeightError(height)
    return ABCIResponses.unmarshal(raw)
