"""State — everything needed to validate the next block
(ref: state/state.go:51).

MedianTime implements BFT time (state.go:167): the voting-power-weighted
median of LastCommit timestamps, tamper-proof as long as <1/3 is byzantine.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.types import (
    Block,
    BlockID,
    Commit,
    ConsensusParams,
    GenesisDoc,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.block import Version


@dataclass
class State:
    chain_id: str = ""
    version: Version = field(default_factory=Version)

    last_block_height: int = 0
    last_block_total_tx: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time_ns: int = 0

    next_validators: Optional[ValidatorSet] = None
    validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            version=self.version,
            last_block_height=self.last_block_height,
            last_block_total_tx=self.last_block_total_tx,
            last_block_id=self.last_block_id,
            last_block_time_ns=self.last_block_time_ns,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
            last_height_validators_changed=self.last_height_validators_changed,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.last_height_consensus_params_changed,
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    # block construction ---------------------------------------------------
    def make_block(
        self,
        height: int,
        txs: List[bytes],
        commit: Commit,
        evidence: Optional[list] = None,
        proposer_address: bytes = b"",
        time_ns: Optional[int] = None,
    ) -> Block:
        """Build the next proposal block filled with state-derived header data
        (ref state.go:132).  Block time = BFT MedianTime of the commit for
        height > 1; proposer's clock at height 1."""
        block = Block.make_block(height, txs, commit, evidence)
        if height == 1:
            t = self.last_block_time_ns  # genesis time (state.go:144)
        else:
            t = median_time(commit, self.last_validators)
        h = block.header
        h.version = self.version
        h.chain_id = self.chain_id
        h.time_ns = t
        h.total_txs = self.last_block_total_tx + len(txs)
        h.last_block_id = self.last_block_id
        h.validators_hash = self.validators.hash()
        h.next_validators_hash = self.next_validators.hash()
        h.consensus_hash = self.consensus_params.hash()
        h.app_hash = self.app_hash
        h.last_results_hash = self.last_results_hash
        h.proposer_address = proposer_address
        return block

    # codec ----------------------------------------------------------------
    def marshal(self) -> bytes:
        w = Writer()
        w.string(self.chain_id)
        self.version.encode(w)
        w.svarint(self.last_block_height).svarint(self.last_block_total_tx)
        self.last_block_id.encode(w)
        w.fixed64(self.last_block_time_ns)
        for vs in (self.next_validators, self.validators, self.last_validators):
            if vs is None:
                w.bool(False)
            else:
                # vs.marshal() == the bytes vs.encode(w) would write, but
                # memoized (two of these three sets are unchanged per block)
                w.bool(True)
                w.raw(vs.marshal())
        w.svarint(self.last_height_validators_changed)
        self.consensus_params.encode(w)
        w.svarint(self.last_height_consensus_params_changed)
        w.bytes(self.last_results_hash).bytes(self.app_hash)
        return w.build()

    @classmethod
    def unmarshal(cls, data: bytes) -> "State":
        r = Reader(data)
        chain_id = r.string()
        version = Version.decode(r)
        lbh = r.svarint()
        lbt = r.svarint()
        lbid = BlockID.decode(r)
        lbtime = r.fixed64()
        sets = []
        for _ in range(3):
            sets.append(ValidatorSet.decode(r) if r.bool() else None)
        return cls(
            chain_id=chain_id,
            version=version,
            last_block_height=lbh,
            last_block_total_tx=lbt,
            last_block_id=lbid,
            last_block_time_ns=lbtime,
            next_validators=sets[0],
            validators=sets[1],
            last_validators=sets[2],
            last_height_validators_changed=r.svarint(),
            consensus_params=ConsensusParams.decode(r),
            last_height_consensus_params_changed=r.svarint(),
            last_results_hash=r.bytes(),
            app_hash=r.bytes(),
        )


def median_time(commit: Commit, validators: ValidatorSet) -> int:
    """Voting-power-weighted median of commit vote timestamps (state.go:167).
    Returns unix nanos."""
    weighted: List[Tuple[int, int]] = []  # (time_ns, power)
    total = 0
    vals = validators.validators
    n_vals = len(vals)
    for i, pc in enumerate(commit.precommits):
        if pc is None or i >= n_vals:
            continue
        val = vals[i]  # in-place read; get_by_index would copy per vote
        weighted.append((pc.timestamp_ns, val.voting_power))
        total += val.voting_power
    if not weighted:
        return 0
    weighted.sort()
    half = total // 2
    acc = 0
    for t, p in weighted:
        acc += p
        if acc > half:
            return t
    return weighted[-1][0]


def state_from_genesis(genesis: GenesisDoc) -> State:
    """Bootstrap state at height 0 (ref state.go MakeGenesisState)."""
    genesis.validate_and_complete()
    vals = [Validator(v.pub_key, v.power) for v in genesis.validators]
    vs = ValidatorSet(vals) if vals else None
    next_vs = vs.copy_increment_accum(1) if vs else None
    return State(
        chain_id=genesis.chain_id,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time_ns=genesis.genesis_time_ns,
        validators=vs,
        next_validators=next_vs,
        last_validators=ValidatorSet(),
        last_height_validators_changed=1,
        consensus_params=genesis.consensus_params,
        last_height_consensus_params_changed=1,
        app_hash=genesis.app_hash,
    )
