"""BlockExecutor — validates, executes (ABCI), commits and persists blocks
(ref: state/execution.go).

apply_block is THE state transition of the system: validate (batched
signature check) → stream DeliverTx to the app → EndBlock valset/params
updates → app Commit under mempool lock → save state → fire events.
fail_point() kill-sites mirror the reference's crash-consistency test hooks
(execution.go:102-106, state.go:1284-1341).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.db.kv import DB
from tendermint_tpu.state import store
from tendermint_tpu.state.state_types import State
from tendermint_tpu.state.validation import validate_block
from tendermint_tpu.types import Block, BlockID, Validator, ValidatorSet
from tendermint_tpu.types.events import EventBus
from tendermint_tpu.crypto.keys import PubKeyEd25519, PubKeySecp256k1


class InvalidBlockError(Exception):
    pass


class ProxyAppConnError(Exception):
    pass


class BlockExecutor:
    def __init__(
        self,
        state_db: DB,
        proxy_app,  # AppConnConsensus
        mempool=None,
        evpool=None,
        event_bus: Optional[EventBus] = None,
        verifier=None,
        metrics=None,
        logger=None,
    ):
        from tendermint_tpu.state.services import MockEvidencePool, MockMempool

        self.db = state_db
        self.proxy_app = proxy_app
        self.mempool = mempool if mempool is not None else MockMempool()
        self.evpool = evpool if evpool is not None else MockEvidencePool()
        self.event_bus = event_bus
        self.verifier = verifier  # BatchVerifier for commit checks
        self.metrics = metrics
        import logging

        self.logger = logger or logging.getLogger("tm.state")

    def validate_block(
        self, state: State, block: Block, trusted_last_commit: bool = False
    ) -> None:
        validate_block(
            self.db, state, block, verifier=self.verifier,
            trusted_last_commit=trusted_last_commit,
        )

    def apply_block(
        self,
        state: State,
        block_id: BlockID,
        block: Block,
        trusted_last_commit: bool = False,
    ) -> State:
        """execution.go:88 — returns the new state or raises; the caller dies
        on failure (consensus halts deliberately).

        trusted_last_commit: fast sync's batched window verify already checked
        this block's LastCommit signatures — skip re-verifying them."""
        try:
            self.validate_block(state, block, trusted_last_commit=trusted_last_commit)
        except Exception as e:
            raise InvalidBlockError(str(e)) from e

        t0 = time.monotonic()
        abci_responses = exec_block_on_proxy_app(
            self.proxy_app, block, state.last_validators, self.db, self.logger
        )
        if self.metrics is not None:
            self.metrics.block_processing_time.observe(time.monotonic() - t0)

        fail.fail_point()

        store.save_abci_responses(self.db, block.height, abci_responses)

        fail.fail_point()

        state = update_state(state, block_id, block.header, abci_responses)

        # lock mempool, commit app, update mempool
        app_hash = self.commit(state, block)

        self.evpool.update(block, state)

        fail.fail_point()

        state.app_hash = app_hash
        store.save_state(self.db, state)

        fail.fail_point()

        if self.event_bus is not None:
            fire_events(self.event_bus, block, abci_responses)
        return state

    def commit(self, state: State, block: Block) -> bytes:
        """Mempool locked across app Commit + mempool update
        (execution.go:145-192)."""
        self.mempool.lock()
        try:
            self.mempool.flush_app_conn()
            res = self.proxy_app.commit_sync()
            self.logger.info(
                "committed state height=%d txs=%d app_hash=%s",
                block.height, len(block.data.txs), res.data.hex(),
            )
            self.mempool.update(block.height, block.data.txs)
            return res.data
        finally:
            self.mempool.unlock()

    def create_proposal_block(
        self, height: int, state: State, commit, proposer_address: bytes
    ) -> Tuple[Block, "object"]:
        """Reap mempool + evidence into the next proposal
        (ref execution.go CreateProposalBlock)."""
        max_bytes = state.consensus_params.block_size.max_bytes
        max_gas = state.consensus_params.block_size.max_gas
        evidence = self.evpool.pending_evidence(max_bytes // 10)
        txs = self.mempool.reap_max_bytes_max_gas(max_bytes * 9 // 10, max_gas)
        block = state.make_block(
            height, txs, commit, evidence, proposer_address
        )
        return block, block.make_part_set()


def exec_block_on_proxy_app(
    proxy_app, block: Block, last_val_set: ValidatorSet, state_db: DB, logger
) -> store.ABCIResponses:
    """BeginBlock → DeliverTxAsync×N (pipelined) → EndBlock
    (execution.go:194-264)."""
    deliver_txs: List[Optional[abci.ResponseDeliverTx]] = [None] * len(block.data.txs)
    counted = [0]
    app_err: List[Optional[str]] = [None]

    def on_response(req, res):
        if isinstance(res, abci.ResponseException) and isinstance(
            req, abci.RequestDeliverTx
        ):
            # app crashed on a tx: the block must fail, not silently shift
            # the results array (state-divergence hazard)
            app_err[0] = res.error
            counted[0] += 1
        elif isinstance(res, abci.ResponseDeliverTx):
            deliver_txs[counted[0]] = res
            if res.code != abci.CODE_TYPE_OK:
                logger.debug("invalid tx code=%d log=%s", res.code, res.log)
            counted[0] += 1

    proxy_app.set_response_callback(on_response)

    commit_info, byz_vals = _get_begin_block_validator_info(
        block, last_val_set, state_db
    )

    bb = proxy_app.begin_block_sync(
        abci.RequestBeginBlock(
            hash=block.hash() or b"",
            header=_abci_header(block),
            last_commit_info=commit_info,
            byzantine_validators=byz_vals,
        )
    )
    if isinstance(bb, abci.ResponseException):
        raise ProxyAppConnError(bb.error)

    for tx in block.data.txs:
        proxy_app.deliver_tx_async(bytes(tx))
        err = proxy_app.error()
        if err:
            raise ProxyAppConnError(str(err))

    eb = proxy_app.end_block_sync(abci.RequestEndBlock(height=block.height))
    if isinstance(eb, abci.ResponseException):
        raise ProxyAppConnError(eb.error)

    # end_block_sync flushed the pipeline: every DeliverTx must be accounted for
    if app_err[0] is not None:
        raise ProxyAppConnError(f"DeliverTx failed: {app_err[0]}")
    if counted[0] != len(block.data.txs) or any(r is None for r in deliver_txs):
        raise ProxyAppConnError(
            f"DeliverTx responses missing: got {counted[0]}/{len(block.data.txs)}"
        )

    return store.ABCIResponses(
        deliver_tx=list(deliver_txs),
        end_block=eb,
        begin_block=bb,
    )


def _abci_header(block: Block) -> abci.ABCIHeader:
    h = block.header
    return abci.ABCIHeader(
        chain_id=h.chain_id,
        height=h.height,
        time_ns=h.time_ns,
        num_txs=h.num_txs,
        total_txs=h.total_txs,
        app_hash=h.app_hash,
        proposer_address=h.proposer_address,
    )


def _get_begin_block_validator_info(
    block: Block, last_val_set: ValidatorSet, state_db: DB
):
    votes = []
    if block.height > 1:
        precommits = block.last_commit.precommits
        n_pc = len(precommits)
        # read validators in place — get_by_index's defensive copy is pure
        # allocation on this per-block loop (positional args: this builds
        # |valset| objects per applied block)
        _vi = abci.VoteInfo
        votes = [
            _vi(
                val.address,
                val.voting_power,
                i < n_pc and precommits[i] is not None,
            )
            for i, val in enumerate(last_val_set.validators)
        ]
    byz = []
    for ev in block.evidence.evidence:
        try:
            valset = store.load_validators(state_db, ev.height)
            _, val = valset.get_by_address(ev.address)
            power = val.voting_power if val else 0
            total = valset.total_voting_power()
        except store.NoValSetForHeightError:
            power, total = 0, 0
        byz.append(
            abci.ABCIEvidence(
                type="duplicate/vote",
                validator_address=ev.address,
                validator_power=power,
                height=ev.height,
                total_voting_power=total,
            )
        )
    return abci.LastCommitInfo(round=block.last_commit.round(), votes=votes), byz


def update_validators(current_set: ValidatorSet, updates: List[abci.ValidatorUpdate]) -> None:
    """Apply EndBlock deltas: power 0 removes, unknown adds, known updates
    (execution.go:318)."""
    from tendermint_tpu.types.validator_set import _MAX_TOTAL_POWER

    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative: {vu}")
        if vu.power > _MAX_TOTAL_POWER:
            # the set's arithmetic clips at this bound and its codec packs
            # powers as int64 — an app granting more must be rejected here,
            # not crash the node at the next save_state
            raise ValueError(f"voting power {vu.power} exceeds maximum")
        if vu.pub_key_type == "ed25519":
            pub = PubKeyEd25519(vu.pub_key)
        elif vu.pub_key_type == "secp256k1":
            pub = PubKeySecp256k1(vu.pub_key)
        else:
            raise ValueError(f"unknown pubkey type {vu.pub_key_type!r}")
        address = pub.address()
        _, val = current_set.get_by_address(address)
        if vu.power == 0:
            if current_set.remove(address) is None:
                raise ValueError(f"failed to remove validator {address.hex()}")
        elif val is None:
            if not current_set.add(Validator(pub, vu.power)):
                raise ValueError("failed to add new validator")
        else:
            if not current_set.update(Validator(pub, vu.power)):
                raise ValueError("failed to update validator")


def update_state(
    state: State,
    block_id: BlockID,
    header,
    abci_responses: store.ABCIResponses,
) -> State:
    """execution.go:356 — the pure state transition."""
    n_val_set = state.next_validators.copy()

    last_height_vals_changed = state.last_height_validators_changed
    if abci_responses.end_block and abci_responses.end_block.validator_updates:
        update_validators(n_val_set, abci_responses.end_block.validator_updates)
        # change applies to the height after next
        last_height_vals_changed = header.height + 1 + 1

    n_val_set.increment_accum(1)

    next_params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    if abci_responses.end_block and abci_responses.end_block.consensus_param_updates:
        next_params = state.consensus_params.update(
            abci_responses.end_block.consensus_param_updates
        )
        next_params.validate()
        last_height_params_changed = header.height + 1

    return State(
        chain_id=state.chain_id,
        version=state.version,
        last_block_height=header.height,
        last_block_total_tx=state.last_block_total_tx + header.num_txs,
        last_block_id=block_id,
        last_block_time_ns=header.time_ns,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=next_params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=abci_responses.results_hash(),
        app_hash=b"",  # filled after Commit
    )


def fire_events(event_bus: EventBus, block: Block, abci_responses: store.ABCIResponses) -> None:
    """NewBlock, NewBlockHeader, one TxEvent per tx (execution.go:421)."""
    event_bus.publish_event_new_block(block, abci_responses)
    event_bus.publish_event_new_block_header(block.header)
    for i, tx in enumerate(block.data.txs):
        res = (
            abci_responses.deliver_tx[i]
            if i < len(abci_responses.deliver_tx)
            else None
        )
        event_bus.publish_event_tx(block.height, i, bytes(tx), res)
