"""Tx indexer (ref: state/txindex/): IndexerService subscribes to EventTx and
indexes results by hash + tags; searchable with the pubsub query language.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding.codec import Reader, Writer
from tendermint_tpu.libs.db.kv import DB
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.types.events import EVENT_TX, query_for_event


@dataclass
class TxResult:
    height: int
    index: int
    tx: bytes
    result: Optional[abci.ResponseDeliverTx]

    def hash(self) -> bytes:
        return hashlib.sha256(self.tx).digest()

    def marshal(self) -> bytes:
        w = Writer()
        w.svarint(self.height).svarint(self.index).bytes(self.tx)
        w.bytes(abci.msg_to_json(self.result) if self.result else b"")
        return w.build()

    @classmethod
    def unmarshal(cls, data: bytes) -> "TxResult":
        r = Reader(data)
        height = r.svarint()
        index = r.svarint()
        tx = r.bytes()
        raw = r.bytes()
        return cls(height, index, tx, abci.msg_from_json(raw) if raw else None)


class NullTxIndexer:
    def index(self, tx_result: TxResult) -> None: ...

    def get(self, tx_hash: bytes) -> Optional[TxResult]:
        return None

    def search(self, q: str) -> List[TxResult]:
        return []


class KVTxIndexer:
    """kv backend (txindex/kv/kv.go): primary record by hash + tag rows
    'tag/value/height/index' -> hash."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, tx_result: TxResult) -> None:
        h = tx_result.hash()
        batch = self._db.batch()
        batch.set(h, tx_result.marshal())
        tags = getattr(tx_result.result, "tags", None) or []
        for kv in tags:
            key = b"%s/%s/%d/%d" % (
                kv.key, kv.value, tx_result.height, tx_result.index
            )
            batch.set(key, h)
        # standard height tag
        batch.set(
            b"tx.height/%d/%d/%d" % (tx_result.height, tx_result.height, tx_result.index),
            h,
        )
        batch.write()

    def get(self, tx_hash: bytes) -> Optional[TxResult]:
        raw = self._db.get(tx_hash)
        return TxResult.unmarshal(raw) if raw else None

    def search(self, q: str) -> List[TxResult]:
        """Tag-condition search; supports '=' conditions + tx.height ranges."""
        query = Query(q)
        hashes: Optional[set] = None
        for cond in query.conditions:
            matches = set()
            if cond.tag == "tx.hash" and cond.op == "=":
                h = bytes.fromhex(str(cond.value))
                return [r for r in [self.get(h)] if r is not None]
            prefix = cond.tag.encode() + b"/"
            for k, v in self._db.iterator(prefix, prefix + b"\xff"):
                parts = k.split(b"/")
                if len(parts) < 4:
                    continue
                value = b"/".join(parts[1:-2]).decode(errors="replace")
                if cond.matches({cond.tag: value}):
                    matches.add(bytes(v))
            hashes = matches if hashes is None else (hashes & matches)
        out = []
        for h in hashes or set():
            r = self.get(h)
            if r is not None:
                out.append(r)
        out.sort(key=lambda r: (r.height, r.index))
        return out


class TxIndexerService(BaseService):
    """indexer_service.go:17 — subscribes to EventTx on the bus."""

    def __init__(self, indexer, event_bus):
        super().__init__("TxIndexerService")
        self.indexer = indexer
        self.event_bus = event_bus

    def on_start(self) -> None:
        self._sub = self.event_bus.subscribe(
            "tx_index", query_for_event(EVENT_TX), maxsize=1024
        )
        threading.Thread(target=self._run, daemon=True).start()

    def on_stop(self) -> None:
        try:
            self.event_bus.unsubscribe_all("tx_index")
        except Exception:
            pass

    def _run(self) -> None:
        import queue as _q

        while not self.quit_event.is_set():
            try:
                msg = self._sub.get(timeout=0.1)
            except _q.Empty:
                continue
            d = msg.data
            self.indexer.index(
                TxResult(height=d.height, index=d.index, tx=d.tx, result=d.result)
            )
