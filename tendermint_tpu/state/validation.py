"""Block validation against state (ref: state/validation.go:16-166).

The LastCommit check at the heart of this file (validation.go:102) is the
SIGNATURE HOT SPOT — it goes through ValidatorSet.verify_commit, i.e. one
batched device dispatch per block instead of the reference's serial loop.
"""

from __future__ import annotations

from typing import Optional

from tendermint_tpu.libs.db.kv import DB
from tendermint_tpu.state import store
from tendermint_tpu.state.state_types import State, median_time
from tendermint_tpu.types import Block, DuplicateVoteEvidence

MAX_EVIDENCE_PER_BLOCK = 50


class BlockValidationError(Exception):
    pass


class EvidenceInvalidError(Exception):
    pass


def validate_block(
    state_db: DB,
    state: State,
    block: Block,
    verifier=None,
    trusted_last_commit: bool = False,
) -> None:
    """trusted_last_commit: skip only the LastCommit *signature* verification
    (structural/size/time checks still run) — set by fast sync after its
    batched multi-height window verify already covered those signatures."""
    block.validate_basic()

    # basic info
    if block.header.version != state.version:
        raise BlockValidationError(
            f"wrong Version: expected {state.version}, got {block.header.version}"
        )
    if block.header.chain_id != state.chain_id:
        raise BlockValidationError(
            f"wrong ChainID: expected {state.chain_id}, got {block.header.chain_id}"
        )
    if block.header.height != state.last_block_height + 1:
        raise BlockValidationError(
            f"wrong Height: expected {state.last_block_height + 1}, "
            f"got {block.header.height}"
        )

    # prev block info
    if block.header.last_block_id != state.last_block_id:
        raise BlockValidationError("wrong LastBlockID")
    new_txs = len(block.data.txs)
    if block.header.total_txs != state.last_block_total_tx + new_txs:
        raise BlockValidationError(
            f"wrong TotalTxs: expected {state.last_block_total_tx + new_txs}, "
            f"got {block.header.total_txs}"
        )

    # app info from the previous block
    if block.header.app_hash != state.app_hash:
        raise BlockValidationError("wrong AppHash")
    if block.header.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong ConsensusHash")
    if block.header.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong LastResultsHash")
    if block.header.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong ValidatorsHash")
    if block.header.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong NextValidatorsHash")

    # LastCommit — ★ the batched signature verification boundary
    if block.header.height == 1:
        if len(block.last_commit.precommits) != 0:
            raise BlockValidationError("block at height 1 can't have LastCommit")
    else:
        if len(block.last_commit.precommits) != state.last_validators.size:
            raise BlockValidationError(
                f"invalid commit size: expected {state.last_validators.size}, "
                f"got {len(block.last_commit.precommits)}"
            )
        if not trusted_last_commit:
            state.last_validators.verify_commit(
                state.chain_id, state.last_block_id, block.header.height - 1,
                block.last_commit, verifier=verifier,
            )

    # block time: BFT median of LastCommit (validation.go:117-141)
    if block.header.height > 1:
        if block.header.time_ns <= state.last_block_time_ns:
            raise BlockValidationError("block time not greater than last block time")
        want = median_time(block.last_commit, state.last_validators)
        if block.header.time_ns != want:
            raise BlockValidationError(
                f"invalid block time: expected {want}, got {block.header.time_ns}"
            )
    elif block.header.height == 1:
        if block.header.time_ns != state.last_block_time_ns:
            raise BlockValidationError("block time != genesis time")

    # evidence
    if len(block.evidence.evidence) > MAX_EVIDENCE_PER_BLOCK:
        raise BlockValidationError("too much evidence")
    for ev in block.evidence.evidence:
        try:
            verify_evidence(state_db, state, ev)
        except Exception as e:
            raise EvidenceInvalidError(str(e)) from e

    # proposer must be a known validator
    if (
        len(block.header.proposer_address) != 20
        or not state.validators.has_address(block.header.proposer_address)
    ):
        raise BlockValidationError(
            f"ProposerAddress {block.header.proposer_address.hex()} is not a validator"
        )


def verify_evidence(state_db: DB, state: State, ev: DuplicateVoteEvidence) -> None:
    """validation.go:167: recent enough, from a then-validator, internally
    consistent, properly signed."""
    height = state.last_block_height
    ev_height = ev.height
    max_age = state.consensus_params.evidence.max_age
    if height - ev_height > max_age:
        raise EvidenceInvalidError(
            f"evidence from height {ev_height} is too old (now {height}, max age {max_age})"
        )

    valset = store.load_validators(state_db, ev_height)
    _, val = valset.get_by_address(ev.address)
    if val is None:
        raise EvidenceInvalidError(
            f"address {ev.address.hex()} was not a validator at height {ev_height}"
        )
    ev.verify(state.chain_id)
