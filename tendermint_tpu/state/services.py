"""Service interfaces the executor needs + mocks (ref: state/services.go)."""

from __future__ import annotations

import threading
from typing import List, Optional


class Mempool:
    """Interface the BlockExecutor requires (services.go:34)."""

    def lock(self) -> None: ...

    def unlock(self) -> None: ...

    def size(self) -> int: ...

    def check_tx(self, tx: bytes, callback=None) -> None: ...

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]: ...

    def update(self, height: int, txs, pre_check=None, post_check=None) -> None: ...

    def flush(self) -> None: ...

    def flush_app_conn(self) -> None: ...

    def txs_available(self): ...

    def enable_txs_available(self) -> None: ...


class MockMempool(Mempool):
    def __init__(self):
        self._mtx = threading.Lock()

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def size(self) -> int:
        return 0

    def check_tx(self, tx: bytes, callback=None) -> None:
        pass

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return []

    def update(self, height: int, txs, pre_check=None, post_check=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def flush_app_conn(self) -> None:
        pass

    def txs_available(self):
        return None

    def enable_txs_available(self) -> None:
        pass


class EvidencePool:
    """Interface (services.go:90)."""

    def pending_evidence(self, max_bytes: int) -> list: ...

    def add_evidence(self, ev) -> None: ...

    def update(self, block, state) -> None: ...

    def is_committed(self, ev) -> bool: ...


class MockEvidencePool(EvidencePool):
    def __init__(self):
        self.added: list = []  # recorded for test assertions, never proposed

    def pending_evidence(self, max_bytes: int) -> list:
        return []

    def add_evidence(self, ev) -> None:
        self.added.append(ev)

    def update(self, block, state) -> None:
        pass

    def is_committed(self, ev) -> bool:
        return False


class BlockStoreBase:
    """Interface for the block store (services.go BlockStoreRPC/BlockStore)."""

    def height(self) -> int: ...

    def load_block(self, height: int): ...

    def save_block(self, block, parts, seen_commit) -> None: ...
