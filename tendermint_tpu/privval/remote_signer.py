"""Remote signer — keep the validator key in a separate process
(ref: privval/tcp.go TCPVal, ipc.go IPCVal, remote_signer.go protocol,
wired at node/node.go:225-242).

Topology per the reference: the NODE listens on ``priv_validator_laddr``;
the SIGNER process (which holds the key, e.g. an HSM front) dials in. For
tcp:// addresses the connection is upgraded to a SecretConnection — the
node authenticates itself with an ed25519 conn key and the channel is
AEAD-encrypted; unix:// sockets rely on filesystem permissions (ipc.go).

Protocol: length-prefixed codec frames, request/response:
PubKeyRequest/Response, SignVoteRequest/SignedVoteResponse,
SignProposalRequest/SignedProposalResponse, SignHeartbeatRequest/
SignedHeartbeatResponse, PingRequest/Response. Errors (e.g. the signer's
double-sign protection refusing) travel as RemoteSignerError responses.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional, Tuple

from tendermint_tpu.crypto.keys import PrivKey, PrivKeyEd25519, PubKey, _PUBKEY_TYPES
from tendermint_tpu.encoding.codec import Reader, Writer, length_prefix
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn.secret_connection import (
    RawConn,
    SecretConnection,
    read_length_prefixed_stream,
)
from tendermint_tpu.types import Heartbeat, Proposal, Vote
from tendermint_tpu.types.priv_validator import PrivValidator

MAX_MSG = 1 << 20
ACCEPT_DEADLINE = 30.0  # tcp.go defaultAcceptDeadlineSeconds
CONN_TIMEOUT = 5.0  # per-request read deadline

# message tags
_PUBKEY_REQ = 1
_PUBKEY_RESP = 2
_SIGN_VOTE_REQ = 3
_SIGNED_VOTE_RESP = 4
_SIGN_PROPOSAL_REQ = 5
_SIGNED_PROPOSAL_RESP = 6
_SIGN_HEARTBEAT_REQ = 7
_SIGNED_HEARTBEAT_RESP = 8
_PING_REQ = 9
_PING_RESP = 10
_ERROR_RESP = 11


class RemoteSignerError(Exception):
    pass


def _parse_addr(addr: str) -> Tuple[str, object]:
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://"):]
    if addr.startswith("tcp://"):
        host, _, port = addr[len("tcp://"):].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    raise ValueError(f"unsupported privval address {addr!r}")


class _Conn:
    """One framed connection (SecretConnection for tcp, raw for unix)."""

    def __init__(
        self,
        sock,
        conn_key: Optional[PrivKey],
        is_tcp: bool,
        handshake_timeout: Optional[float] = None,
    ):
        self._raw = RawConn(sock)
        if handshake_timeout is not None:
            # bound the handshake: accept() returns BLOCKING sockets, and an
            # inbound client that sends nothing would wedge the accept loop
            self._raw.set_deadline(time.monotonic() + handshake_timeout)
        try:
            if is_tcp:
                self._io = SecretConnection(
                    self._raw, conn_key or PrivKeyEd25519.generate()
                )
            else:
                self._io = self._raw
        finally:
            self._raw.set_deadline(None)
        self._mtx = threading.Lock()

    def send(self, payload: bytes) -> None:
        self._io.write(length_prefix(payload))

    def recv(self) -> bytes:
        return read_length_prefixed_stream(self._io.read_exactly, MAX_MSG)

    def request(self, payload: bytes, timeout: Optional[float] = None) -> bytes:
        """Round trip under an absolute deadline: a stalled signer must not
        hang the consensus thread forever (tcp.go connTimeout)."""
        with self._mtx:
            if timeout is not None:
                self._raw.set_deadline(time.monotonic() + timeout)
            try:
                self.send(payload)
                return self.recv()
            finally:
                if timeout is not None:
                    self._raw.set_deadline(None)

    def close(self) -> None:
        self._raw.close()


# -- wire helpers -------------------------------------------------------------


def _enc_error(msg: str) -> bytes:
    w = Writer()
    w.uvarint(_ERROR_RESP).string(msg)
    return w.build()


class SignerServiceEndpoint(BaseService):
    """The SIGNER side (holds the key): dials the node and serves sign
    requests forever (remote_signer.go RemoteSigner)."""

    def __init__(self, addr: str, privval: PrivValidator, conn_key: Optional[PrivKey] = None):
        super().__init__(name="SignerService")
        self.addr = addr
        self.privval = privval
        self.conn_key = conn_key or PrivKeyEd25519.generate()
        self._conn: Optional[_Conn] = None

    def _connect(self) -> "_Conn":
        scheme, target = _parse_addr(self.addr)
        if scheme == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(target)
        else:
            sock = socket.create_connection(target, timeout=ACCEPT_DEADLINE)
            # clear the connect timeout: the serve loop must block on recv
            # indefinitely (idle gaps between sign requests are normal)
            sock.settimeout(None)
        return _Conn(sock, self.conn_key, is_tcp=(scheme == "tcp"))

    def on_start(self) -> None:
        self._conn = self._connect()
        threading.Thread(target=self._serve, name="signer-serve", daemon=True).start()

    def on_stop(self) -> None:
        if self._conn is not None:
            self._conn.close()

    def _serve(self) -> None:
        """Serve forever; when the node drops the connection (timeout reset,
        restart), redial — the validator must not lose its signer permanently
        (remote_signer.go reconnects the same way)."""
        conn = self._conn
        while not self._quit.is_set():
            try:
                req = conn.recv()
            except Exception:
                conn.close()
                conn = None
                while conn is None and not self._quit.is_set():
                    time.sleep(0.2)
                    try:
                        conn = self._connect()
                    except Exception:
                        conn = None
                self._conn = conn
                continue
            try:
                resp = self._handle(req)
            except Exception as e:  # double-sign refusal etc.
                resp = _enc_error(str(e))
            try:
                conn.send(resp)
            except Exception:
                continue  # recv will fail next and trigger the redial path

    def _handle(self, data: bytes) -> bytes:
        r = Reader(data)
        tag = r.uvarint()
        if tag == _PUBKEY_REQ:
            pk = self.privval.get_pub_key()
            w = Writer()
            w.uvarint(_PUBKEY_RESP).string(pk.type_name).bytes(pk.bytes())
            return w.build()
        if tag == _PING_REQ:
            w = Writer()
            w.uvarint(_PING_RESP)
            return w.build()
        chain_id = r.string()
        if tag == _SIGN_VOTE_REQ:
            vote = Vote.decode(r)
            signed = self.privval.sign_vote(chain_id, vote)
            w = Writer()
            w.uvarint(_SIGNED_VOTE_RESP)
            signed.encode(w)
            return w.build()
        if tag == _SIGN_PROPOSAL_REQ:
            prop = Proposal.decode(r)
            signed = self.privval.sign_proposal(chain_id, prop)
            w = Writer()
            w.uvarint(_SIGNED_PROPOSAL_RESP)
            signed.encode(w)
            return w.build()
        if tag == _SIGN_HEARTBEAT_REQ:
            hb = Heartbeat.decode(r)
            signed = self.privval.sign_heartbeat(chain_id, hb)
            w = Writer()
            w.uvarint(_SIGNED_HEARTBEAT_RESP)
            signed.encode(w)
            return w.build()
        raise RemoteSignerError(f"unknown request tag {tag}")


class SignerValidatorEndpoint(BaseService, PrivValidator):
    """The NODE side: listens for the signer's dial-in, then IS the node's
    PrivValidator — every sign call becomes a request over the wire
    (tcp.go TCPVal / ipc.go IPCVal)."""

    def __init__(
        self,
        addr: str,
        conn_key: Optional[PrivKey] = None,
        expected_signer_pubkey: Optional[PubKey] = None,
    ):
        """expected_signer_pubkey: pin the signer's SecretConnection identity
        (tcp only). Without it, ANY dialer that completes the handshake
        replaces the active signer — matching the reference's TCPVal, but a
        known weakness there: anyone who can reach priv_validator_laddr can
        evict the real signer or serve a chosen pubkey."""
        BaseService.__init__(self, name="SignerValidator")
        self.addr = addr
        self.conn_key = conn_key or PrivKeyEd25519.generate()
        self.expected_signer_pubkey = expected_signer_pubkey
        self._listener: Optional[socket.socket] = None
        self._conn: Optional[_Conn] = None
        self._connected = threading.Event()
        self._pubkey: Optional[PubKey] = None

    # -- lifecycle -------------------------------------------------------------
    def on_start(self) -> None:
        scheme, target = _parse_addr(self.addr)
        if scheme == "unix":
            import os

            try:
                os.unlink(target)
            except OSError:
                pass
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(target)
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind(target)
        ls.listen(1)
        ls.settimeout(ACCEPT_DEADLINE)
        self._listener = ls
        self._scheme = scheme
        threading.Thread(target=self._accept_loop, name="privval-accept", daemon=True).start()

    def on_stop(self) -> None:
        for closer in (self._conn, ):
            if closer is not None:
                closer.close()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    @property
    def listen_port(self) -> int:
        return self._listener.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._quit.is_set():
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn = _Conn(
                    sock, self.conn_key, is_tcp=(self._scheme == "tcp"),
                    handshake_timeout=CONN_TIMEOUT,
                )
            except Exception as e:
                self.logger.error("signer connection upgrade failed: %s", e)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if self.expected_signer_pubkey is not None:
                remote = getattr(conn._io, "remote_pubkey", None)
                if remote is None or remote.bytes() != self.expected_signer_pubkey.bytes():
                    self.logger.error(
                        "rejecting signer connection: authenticated key %s "
                        "does not match the pinned signer pubkey",
                        remote.address().hex() if remote is not None else "<none>",
                    )
                    conn.close()
                    continue
            old, self._conn = self._conn, conn
            if old is not None:
                # matches the reference's accept-any TCPVal behavior, but an
                # eviction is worth an operator's attention: a dialer just
                # displaced the live signer (pin expected_signer_pubkey to
                # prevent untrusted dialers doing this)
                self.logger.warning(
                    "active remote signer connection displaced by a new dial-in"
                )
                old.close()
            self._pubkey = None  # re-fetch from the (possibly new) signer
            self._connected.set()
            self.logger.info("remote signer connected")

    def wait_for_signer(self, timeout: float = ACCEPT_DEADLINE) -> bool:
        return self._connected.wait(timeout)

    # -- PrivValidator over the wire ---------------------------------------------
    def _request(self, payload: bytes) -> Reader:
        if not self._connected.wait(CONN_TIMEOUT):
            raise RemoteSignerError("no signer connected")
        conn = self._conn
        if conn is None:
            raise RemoteSignerError("signer reconnecting")
        try:
            resp = conn.request(payload, timeout=CONN_TIMEOUT)
        except Exception as e:
            # a timed-out/failed round trip leaves the stream (and with a
            # SecretConnection, the AEAD framing) desynced: drop the conn so
            # the signer redials a fresh one instead of serving stale replies
            if self._conn is conn:
                self._connected.clear()
                self._conn = None
            conn.close()
            raise RemoteSignerError(f"signer connection failed: {e}") from e
        r = Reader(resp)
        tag = r.uvarint()
        if tag == _ERROR_RESP:
            raise RemoteSignerError(r.string())
        return Reader(resp)  # fresh reader incl. tag for callers

    def get_pub_key(self) -> PubKey:
        if self._pubkey is None:
            w = Writer()
            w.uvarint(_PUBKEY_REQ)
            r = self._request(w.build())
            tag = r.uvarint()
            if tag != _PUBKEY_RESP:
                raise RemoteSignerError(f"unexpected response tag {tag}")
            self._pubkey = _PUBKEY_TYPES[r.string()](r.bytes())
        return self._pubkey

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        w = Writer()
        w.uvarint(_SIGN_VOTE_REQ).string(chain_id)
        vote.encode(w)
        r = self._request(w.build())
        if r.uvarint() != _SIGNED_VOTE_RESP:
            raise RemoteSignerError("unexpected response")
        return Vote.decode(r)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        w = Writer()
        w.uvarint(_SIGN_PROPOSAL_REQ).string(chain_id)
        proposal.encode(w)
        r = self._request(w.build())
        if r.uvarint() != _SIGNED_PROPOSAL_RESP:
            raise RemoteSignerError("unexpected response")
        return Proposal.decode(r)

    def sign_heartbeat(self, chain_id: str, heartbeat: Heartbeat) -> Heartbeat:
        w = Writer()
        w.uvarint(_SIGN_HEARTBEAT_REQ).string(chain_id)
        heartbeat.encode(w)
        r = self._request(w.build())
        if r.uvarint() != _SIGNED_HEARTBEAT_RESP:
            raise RemoteSignerError("unexpected response")
        return Heartbeat.decode(r)

    def ping(self) -> bool:
        try:
            w = Writer()
            w.uvarint(_PING_REQ)
            return self._request(w.build()).uvarint() == _PING_RESP
        except Exception:
            return False
